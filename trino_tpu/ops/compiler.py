"""Expression compiler: IR -> XLA-traceable column programs.

Reference blueprint: io.trino.sql.gen.PageFunctionCompiler
(PageFunctionCompiler.java:103, compileProjection:170 / compileFilter:385) — Trino's
query-time JVM-bytecode generator, SURVEY.md §2.4: "this entire layer becomes
'IR -> StableHLO/XLA (jax.jit) + Pallas kernels', the single biggest architectural
substitution."

A compiled expression is a host closure ``fn(env) -> CVal`` where ``env`` maps plan
symbols to :class:`CVal` (data array, validity array) pairs; tracing it under
``jax.jit`` produces fused XLA. Compilation is cached per (expression, input layout)
exactly as PageFunctionCompiler caches generated classes per expression.

Null semantics are mask-based three-valued logic:
- arithmetic/comparisons: valid = AND of input validities
- AND/OR: Kleene logic (false dominates AND, true dominates OR)
- CASE: first WHEN whose condition is definitively true

String semantics ride the sorted-dictionary invariant (spi.page.Dictionary):
- col <op> 'literal'  ->  int32 code comparisons (searchsorted for ranges)
- LIKE / IN / functions over strings -> host-evaluated boolean/code LUTs indexed
  by dictionary code on device (InLut nodes and dictionary transforms)
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..spi.page import Dictionary
from ..spi.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTERVAL_DAY_TIME,
    INTERVAL_YEAR_MONTH,
    UNKNOWN,
    ArrayType,
    DecimalType,
    IntegralType,
    MapType,
    Type,
    is_floating,
    is_integral,
    is_numeric,
    is_string,
)
from ..sql.functions import HIGHER_ORDER_FUNCTIONS as _HO_FUNCS
from ..sql.functions import VECTOR_SCALAR_FUNCTIONS as _VECTOR_FUNCS
from ..sql.ir import Call, Case, CastExpr, Constant, InLut, IrExpr, Reference
from ..sql.ir import Lambda as IrLambda
from ..sql.ir import references as ir_references


import jax as _jax


@_jax.tree_util.register_pytree_node_class
@dataclass
class CVal:
    """A compiled column value: device data + validity (both full-capacity).
    A pytree, so environments of CVals flow through jit.

    Nested values mirror spi.page.Column's pad-and-mask layout: arrays carry
    ``data[cap, W]`` + ``elem_valid`` + ``lengths``; maps/rows carry child
    CVals in ``children``."""

    data: jnp.ndarray
    valid: jnp.ndarray
    dictionary: Optional[Dictionary] = None
    lengths: Optional[jnp.ndarray] = None
    elem_valid: Optional[jnp.ndarray] = None
    children: tuple = ()

    def tree_flatten(self):
        return (
            (self.data, self.valid, self.lengths, self.elem_valid, self.children),
            self.dictionary,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, valid, lengths, elem_valid, kids = children
        return cls(data, valid, aux, lengths, elem_valid, tuple(kids))


@dataclass(frozen=True)
class ColumnLayout:
    """Static per-symbol input description — part of the compilation cache key.

    ``child_dicts`` mirrors a nested column's children: each entry is either a
    Dictionary/None (scalar or array child) or a nested tuple (map/row child),
    so accessors ($field, map element_at) can resolve the static output
    dictionary string functions compile against."""

    type: Type
    dictionary: Optional[Dictionary] = None
    child_dicts: tuple = ()


class CompileError(ValueError):
    pass


Env = Dict[str, CVal]
Compiled = Callable[[Env], CVal]

_NESTED_FUNCS = frozenset(
    {
        "$array", "$row", "$map", "$field", "$subscript", "element_at",
        "cardinality", "contains", "array_position", "array_min", "array_max",
        "array_sort", "array_distinct", "$array_concat", "slice",
        "map_keys", "map_values", "array_remove", "array_except",
        "array_intersect", "arrays_overlap", "trim_array", "repeat",
        "map_concat", "sequence",
    }
)



def _repeat_cval(v: "CVal", w: int) -> "CVal":
    """Broadcast a [cap]-shaped value to the [cap*w] flattened lane grid."""

    def rep(x):
        return None if x is None else jnp.repeat(x, w, axis=0)

    return CVal(
        rep(v.data), rep(v.valid), v.dictionary, rep(v.lengths),
        rep(v.elem_valid), tuple(_repeat_cval(c, w) for c in v.children),
    )


def _merge_dicts(dicts) -> Dictionary:
    """Merge element dictionaries for string-array construction/concat; every
    contributing value must be dictionary-coded."""
    real = [d for d in dicts if d is not None]
    if len(real) != len(dicts):
        raise CompileError("string array elements must be dictionary-coded")
    if len({d.fingerprint() for d in real}) == 1:
        return real[0]
    merged = sorted(set().union(*[list(d.values) for d in real]))
    return Dictionary(np.asarray(merged, dtype=object))


def _remap_codes(data: jnp.ndarray, from_dict: Dictionary, to_dict: Dictionary):
    """Translate codes between dictionaries via a host LUT (absent -> -1, which
    never equals a valid code)."""
    if from_dict is None or from_dict is to_dict:
        return data
    if from_dict.fingerprint() == to_dict.fingerprint():
        return data
    lut = np.array([to_dict.code_of(s) for s in from_dict.values], dtype=np.int32)
    if len(lut) == 0:
        return jnp.full_like(data, -1)
    return jnp.asarray(lut)[jnp.clip(data, 0, len(lut) - 1)]


def _null_cval(type_: Type, cap: int) -> CVal:
    """An all-NULL CVal of ``type_`` (nested types get empty lanes/children)."""
    from ..spi.types import RowType, VectorType

    invalid = jnp.zeros((cap,), dtype=jnp.bool_)
    if isinstance(type_, VectorType):
        return CVal(
            jnp.zeros((cap, type_.dimension), dtype=jnp.float64), invalid
        )
    if isinstance(type_, ArrayType):
        return CVal(
            jnp.zeros((cap, 1), dtype=_dtype_of(type_.element)), invalid,
            lengths=jnp.zeros((cap,), dtype=jnp.int32),
            elem_valid=jnp.zeros((cap, 1), dtype=jnp.bool_),
        )
    if isinstance(type_, MapType):
        kids = tuple(_null_cval(kt, cap) for kt in type_.child_types())
        return CVal(
            jnp.zeros((cap,), dtype=jnp.int8), invalid,
            lengths=jnp.zeros((cap,), dtype=jnp.int32), children=kids,
        )
    if isinstance(type_, RowType):
        kids = tuple(_null_cval(kt, cap) for kt in type_.child_types())
        return CVal(jnp.zeros((cap,), dtype=jnp.int8), invalid, children=kids)
    return CVal(jnp.zeros((cap,), dtype=_dtype_of(type_)), invalid)


def _lane_equals(a: CVal, x: CVal) -> jnp.ndarray:
    """[cap, W] elementwise equality of array lanes against a scalar column,
    translating dictionary codes when the vocabularies differ. Mixed integral
    widths compare in the promoted int64 domain (never narrowing the needle)."""
    xd = x.data
    if a.dictionary is not None and x.dictionary is not None:
        xd = _remap_codes(xd, x.dictionary, a.dictionary)
    if (
        a.data.dtype != xd.dtype
        and jnp.issubdtype(a.data.dtype, jnp.integer)
        and jnp.issubdtype(xd.dtype, jnp.integer)
    ):
        eq = a.data.astype(jnp.int64) == xd.astype(jnp.int64)[:, None]
    else:
        eq = a.data == xd[:, None].astype(a.data.dtype)
    return eq & a.elem_valid & x.valid[:, None]


def _string_cast_lut(d: Dictionary, dst: Type):
    """(values LUT, ok mask) for a dictionary-string cast to ``dst``, or
    (None, None) when the target type has no string parse."""
    import datetime as _dt

    from ..spi.types import BOOLEAN as _B
    from ..spi.types import DATE as _D
    from ..spi.types import is_floating as _isf
    from ..spi.types import is_integral as _isi
    from ..spi.types import is_long_decimal

    if is_long_decimal(dst):
        return None, None  # two-limb lanes: no scalar LUT shape

    def parse(s: str):
        if dst == _D:
            return (_dt.date.fromisoformat(s.strip()) - _dt.date(1970, 1, 1)).days
        if dst.name.startswith("timestamp"):
            return _iso_timestamp_micros(s.strip())
        if dst == _B:
            u = s.strip().lower()
            if u in ("true", "t", "1"):
                return True
            if u in ("false", "f", "0"):
                return False
            raise ValueError(s)
        if isinstance(dst, DecimalType):
            from decimal import Decimal

            return int(Decimal(s.strip()).scaleb(dst.scale))
        if _isi(dst):
            return int(s.strip())
        if _isf(dst):
            return float(s.strip())
        raise KeyError(dst)

    try:
        parse("1970-01-01" if (dst == _D or dst.name.startswith("timestamp")) else "1")
    except KeyError:
        return None, None
    except Exception:  # noqa: BLE001 — probe value mismatch is fine
        pass
    n = max(len(d.values), 1)
    lut = np.zeros((n,), dtype=dst.storage_dtype)
    ok = np.zeros((n,), dtype=np.bool_)
    for i, s in enumerate(d.values):
        try:
            lut[i] = parse(str(s))
            ok[i] = True
        except Exception:  # noqa: BLE001 — malformed value -> NULL rows
            pass
    return lut, ok


def _lane_present(a: CVal) -> jnp.ndarray:
    return jnp.arange(a.data.shape[1])[None, :] < a.lengths[:, None]


def _lane_member(a: CVal, b: CVal) -> jnp.ndarray:
    """[cap, Wa] bool: a's element is present among b's elements (by VALUE —
    dictionary codes remapped when vocabularies differ); NULL elements of a
    match iff b carries a NULL element (SQL set semantics for except/
    intersect treat NULL as one value)."""
    ad, bd = a.data, b.data
    if (
        a.dictionary is not None
        and b.dictionary is not None
        and a.dictionary is not b.dictionary
    ):
        bd = _remap_codes(bd, b.dictionary, a.dictionary)
    if ad.dtype != bd.dtype:
        ad = ad.astype(jnp.int64)
        bd = bd.astype(jnp.int64)
    pb = _lane_present(b)
    eq = (
        (ad[:, :, None] == bd[:, None, :])
        & a.elem_valid[:, :, None]
        & (b.elem_valid & pb)[:, None, :]
    )
    member = jnp.any(eq, axis=2)
    b_has_null = jnp.any(pb & ~b.elem_valid, axis=1)
    return jnp.where(a.elem_valid, member, b_has_null[:, None])


def _lane_compact(a: CVal, keep: jnp.ndarray, distinct: bool, valid=None) -> CVal:
    """Stable lane compaction to the kept elements; ``distinct`` additionally
    drops later duplicates (value-keyed, NULLs collapse to one)."""
    from . import kernels as K

    if distinct:
        key = jnp.where(
            keep & a.elem_valid,
            K.order_key(a.data),
            jnp.where(keep, jnp.int64(K.INT64_MAX - 1), jnp.int64(K.INT64_MAX)),
        )
        order = jnp.argsort(key, axis=1)
        ks = jnp.take_along_axis(key, order, axis=1)
        keep_s = jnp.take_along_axis(keep, order, axis=1)
        dup_s = jnp.zeros_like(keep_s)
        dup_s = dup_s.at[:, 1:].set(keep_s[:, 1:] & (ks[:, 1:] == ks[:, :-1]))
        inv = jnp.argsort(order, axis=1)
        keep = keep & ~jnp.take_along_axis(dup_s, inv, axis=1)
    korder = jnp.argsort(~keep, axis=1, stable=True)
    data = jnp.take_along_axis(a.data, korder, axis=1)
    ev = jnp.take_along_axis(a.elem_valid, korder, axis=1) & jnp.take_along_axis(
        keep, korder, axis=1
    )
    lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
    return CVal(
        data, a.valid if valid is None else valid, a.dictionary, lengths, ev
    )


def _dtype_of(t: Type) -> np.dtype:
    return t.storage_dtype


def _broadcast_const(value, type_: Type, like: Optional[jnp.ndarray], capacity: int) -> jnp.ndarray:
    from ..spi.types import is_long_decimal

    if is_long_decimal(type_):
        from . import int128 as i128

        limbs = i128.np_from_ints([int(value) if value is not None else 0])
        return jnp.broadcast_to(jnp.asarray(limbs[0]), (capacity, 2))
    dt = _dtype_of(type_)
    return jnp.full((capacity,), value if value is not None else 0, dtype=dt)


# --------------------------------------------------------------------------- #
# public API
# --------------------------------------------------------------------------- #

_CACHE: Dict[tuple, Tuple[Compiled, Optional[Dictionary]]] = {}


def compile_expression(
    expr: IrExpr, layout: Dict[str, ColumnLayout], capacity: int
) -> Tuple[Compiled, Optional[Dictionary]]:
    """Compile IR to a closure over an environment of CVals.

    Returns (fn, output_dictionary). output_dictionary is set when the result is
    a dictionary-coded string column.
    """
    key = (expr, tuple(sorted(layout.items(), key=lambda kv: kv[0])), capacity)
    hit = _CACHE.get(key)
    if hit is not None:
        return hit
    c = _Compiler(layout, capacity)
    fn, out_dict = c.compile(expr)
    _CACHE[key] = (fn, out_dict)
    return fn, out_dict


# --------------------------------------------------------------------------- #
# compiler
# --------------------------------------------------------------------------- #


class _Compiler:
    def __init__(self, layout: Dict[str, ColumnLayout], capacity: int):
        self.layout = layout
        self.capacity = capacity
        # per-run subexpression memo: _dict_of peeks at computed string
        # expressions' output dictionaries, and shared subtrees (CASE arms,
        # common operands) must not recompile per use
        self._memo: Dict[int, Tuple[Compiled, Optional[Dictionary]]] = {}

    def compile(self, expr: IrExpr) -> Tuple[Compiled, Optional[Dictionary]]:
        key = id(expr)
        hit = self._memo.get(key)
        if hit is None:
            hit = self._compile_uncached(expr)
            self._memo[key] = hit
        return hit

    def _compile_uncached(self, expr: IrExpr) -> Tuple[Compiled, Optional[Dictionary]]:
        if isinstance(expr, Reference):
            sym = expr.symbol
            lay = self.layout.get(sym)
            d = lay.dictionary if lay else None

            def ref_fn(env: Env, sym=sym, d=d) -> CVal:
                v = env[sym]
                return CVal(
                    v.data, v.valid, v.dictionary or d,
                    v.lengths, v.elem_valid, v.children,
                )

            return ref_fn, d

        if isinstance(expr, Constant):
            type_ = expr.type
            value = expr.value
            if is_string(type_) and isinstance(value, str):
                # A free-standing string constant becomes a 1-entry dictionary col.
                d = Dictionary(np.asarray([value], dtype=object))

                def sconst_fn(env: Env, d=d) -> CVal:
                    data = jnp.zeros((self.capacity,), dtype=jnp.int32)
                    valid = jnp.ones((self.capacity,), dtype=jnp.bool_)
                    return CVal(data, valid, d)

                return sconst_fn, d

            from ..spi.types import is_nested, is_vector as _is_vec

            if _is_vec(type_):
                # vector constant (the ORDER BY similarity query vector):
                # broadcast the host (n,) values to the (cap, n) lane grid —
                # the tensor lowering (ops/tensor.py) reads the HOST value
                # off the Constant for the matvec form, so this path only
                # runs when a vector constant is used as a plain column
                n = type_.dimension
                if value is None:

                    def nullvec_fn(env: Env, type_=type_) -> CVal:
                        return _null_cval(type_, self.capacity)

                    return nullvec_fn, None
                vec_np = np.asarray(value, dtype=np.float64)
                if vec_np.shape != (n,):
                    raise CompileError(
                        f"vector({n}) constant with {vec_np.size} elements"
                    )

                def vec_fn(env: Env, vec_np=vec_np, n=n) -> CVal:
                    data = jnp.broadcast_to(
                        jnp.asarray(vec_np), (self.capacity, n)
                    )
                    return CVal(
                        data, jnp.ones((self.capacity,), dtype=jnp.bool_)
                    )

                return vec_fn, None

            if is_nested(type_):
                if value is not None:
                    raise CompileError(
                        f"non-null {type_.display()} constants are not foldable"
                    )

                def nconst_fn(env: Env, type_=type_) -> CVal:
                    return _null_cval(type_, self.capacity)

                return nconst_fn, None

            def const_fn(env: Env, value=value, type_=type_) -> CVal:
                data = _broadcast_const(value, type_, None, self.capacity)
                valid = jnp.full((self.capacity,), value is not None, dtype=jnp.bool_)
                return CVal(data, valid)

            return const_fn, None

        if isinstance(expr, CastExpr):
            return self._compile_cast(expr)

        if isinstance(expr, Case):
            return self._compile_case(expr)

        if isinstance(expr, InLut):
            inner, _ = self.compile(expr.value)
            # keep host-side; convert inside the closure so cached closures
            # never capture another trace's constants (tracer-leak safe)
            lut_np = np.asarray(expr.lut, dtype=np.bool_)

            def lut_fn(env: Env) -> CVal:
                v = inner(env)
                lut = jnp.asarray(lut_np)
                codes = jnp.clip(v.data, 0, lut.shape[0] - 1)
                return CVal(lut[codes], v.valid)

            return lut_fn, None

        if isinstance(expr, Call):
            return self._compile_call(expr)

        raise CompileError(f"cannot compile {type(expr).__name__}")

    # ------------------------------------------------------------------ casts

    def _compile_cast(self, expr: CastExpr) -> Tuple[Compiled, Optional[Dictionary]]:
        inner, in_dict = self.compile(expr.value)
        src, dst = expr.value.type, expr.type
        cap = self.capacity

        if is_string(src) and is_string(dst):
            return inner, in_dict
        if src == dst:
            return inner, in_dict
        if is_string(src) and in_dict is not None and not is_string(dst):
            # varchar -> date/timestamp/numeric/boolean: one host pass over
            # the dictionary builds a value LUT; malformed values are NULL
            # for THEIR rows (the engine's error channel). ref:
            # scalar/VarcharToDateCast etc. — per-row parsing there,
            # per-dictionary-value here.
            lut_np, ok_np = _string_cast_lut(in_dict, dst)
            if lut_np is not None:

                def dictcast_fn(env: Env) -> CVal:
                    v = inner(env)
                    idx = jnp.clip(v.data, 0, lut_np.shape[0] - 1)
                    return CVal(
                        jnp.asarray(lut_np)[idx],
                        v.valid & jnp.asarray(ok_np)[idx],
                    )

                return dictcast_fn, None

        from ..spi.types import VectorType as _Vec

        if isinstance(dst, _Vec) or isinstance(src, _Vec):
            return self._compile_vector_cast(expr, inner, src, dst)

        def convert(v: CVal) -> CVal:
            from ..spi.types import is_long_decimal

            data = v.data
            if is_long_decimal(src) or is_long_decimal(dst):
                from . import int128 as i128

                if isinstance(src, DecimalType) and isinstance(dst, DecimalType):
                    x = data if is_long_decimal(src) else i128.from_int64(data)
                    diff = dst.scale - src.scale
                    if diff > 0:
                        x = i128.scale_up_pow10(x, diff)
                    elif diff < 0:
                        x = i128.div_round_pow10(x, -diff)
                    if is_long_decimal(dst):
                        return CVal(x, v.valid)
                    # long -> short: low limb (Trino raises on overflow; we
                    # mark out-of-range rows NULL — loud, never silently wrong)
                    return CVal(i128.lo(x), v.valid & i128.fits_int64(x))
                if is_long_decimal(dst):
                    if is_integral(src) or src == BOOLEAN:
                        return CVal(
                            i128.scale_up_pow10(
                                i128.from_int64(data.astype(jnp.int64)), dst.scale
                            ),
                            v.valid,
                        )
                    if is_floating(src):
                        scaled = jnp.round(data.astype(jnp.float64) * float(10**dst.scale))
                        h = jnp.floor(scaled / 2.0**64)
                        l = scaled - h * 2.0**64  # in [0, 2**64): split to
                        # 32-bit halves (a direct int64 cast saturates >= 2**63)
                        lh = jnp.floor(l / 2.0**32)
                        ll = l - lh * 2.0**32
                        lbits = (lh.astype(jnp.int64) << jnp.int64(32)) | ll.astype(
                            jnp.int64
                        )
                        return CVal(
                            i128.make(h.astype(jnp.int64), lbits), v.valid
                        )
                if is_long_decimal(src):
                    if is_floating(dst):
                        return CVal(
                            (i128.to_float64(data) / float(10**src.scale)).astype(
                                _dtype_of(dst)
                            ),
                            v.valid,
                        )
                    if is_integral(dst):
                        x = i128.div_round_pow10(data, src.scale)
                        return CVal(
                            i128.lo(x).astype(_dtype_of(dst)),
                            v.valid & i128.fits_int64(x),
                        )
                raise CompileError(
                    f"cast {src.display()} -> {dst.display()} not supported"
                )
            if isinstance(src, DecimalType) and isinstance(dst, DecimalType):
                diff = dst.scale - src.scale
                if diff > 0:
                    data = data * (10**diff)
                elif diff < 0:
                    data = _div_round(data, 10**-diff)
                return CVal(data.astype(jnp.int64), v.valid)
            if isinstance(dst, DecimalType):
                if is_integral(src):
                    return CVal(data.astype(jnp.int64) * (10**dst.scale), v.valid)
                if is_floating(src):
                    scaled = jnp.round(data * float(10**dst.scale))
                    return CVal(scaled.astype(jnp.int64), v.valid)
                if src == BOOLEAN:
                    return CVal(data.astype(jnp.int64) * (10**dst.scale), v.valid)
            if isinstance(src, DecimalType) and (is_floating(dst)):
                return CVal((data / float(10**src.scale)).astype(_dtype_of(dst)), v.valid)
            if isinstance(src, DecimalType) and is_integral(dst):
                return CVal(
                    _div_round(data, 10**src.scale).astype(_dtype_of(dst)), v.valid
                )
            if is_numeric(src) and is_numeric(dst):
                if is_floating(src) and is_integral(dst):
                    return CVal(jnp.round(data).astype(_dtype_of(dst)), v.valid)
                return CVal(data.astype(_dtype_of(dst)), v.valid)
            if src == BOOLEAN and is_numeric(dst):
                return CVal(data.astype(_dtype_of(dst)), v.valid)
            if is_numeric(src) and dst == BOOLEAN:
                return CVal(data != 0, v.valid)
            from ..spi.types import TimestampWithTimeZoneType as _Ttz
            from ..spi.types import TimeType as _Time
            from ..spi.types import TimeWithTimeZoneType as _Twtz
            from ..spi.types import TimestampType as _Ts

            if isinstance(src, _Twtz) and isinstance(dst, _Time):
                # UTC micros + offset -> local micros-of-day (wrapped)
                local = (data >> 12) + ((data & 0xFFF) - 841) * 60_000_000
                return CVal(jnp.mod(local, 86_400_000_000), v.valid)
            if isinstance(src, _Time) and isinstance(dst, _Twtz):
                # session zone = UTC (matches the TIMESTAMP cast convention)
                return CVal((data.astype(jnp.int64) << 12) | 841, v.valid)

            if isinstance(src, _Ttz) and isinstance(dst, _Ts):
                # instant -> local wall time in the value's zone
                local_millis = (data >> 12) + ((data & 0xFFF) - 841) * 60_000
                return CVal((local_millis * 1000).astype(jnp.int64), v.valid)
            if isinstance(src, _Ts) and isinstance(dst, _Ttz):
                # session zone = UTC (ref: CastFromTimestamp + session zone)
                return CVal(
                    (((data // 1000) << 12) | 841).astype(jnp.int64), v.valid
                )
            if isinstance(src, _Ttz) and dst == DATE:
                return CVal(_days_of(data, src).astype(jnp.int32), v.valid)
            if isinstance(src, (_Ts, _Ttz)) and isinstance(dst, _Time):
                return CVal(_micros_of_day(data, src).astype(jnp.int64), v.valid)
            if isinstance(src, _Time) and isinstance(dst, _Time):
                return CVal(data, v.valid)
            if src == DATE and isinstance(dst, _Ttz):
                millis = data.astype(jnp.int64) * 86_400_000
                return CVal((millis << 12) | 841, v.valid)
            if src == DATE and dst.name.startswith("timestamp"):
                return CVal(data.astype(jnp.int64) * 86_400_000_000, v.valid)
            if src.name.startswith("timestamp") and dst == DATE:
                return CVal(
                    jnp.floor_divide(data, 86_400_000_000).astype(jnp.int32), v.valid
                )
            if src == UNKNOWN:
                return _null_cval(dst, cap)
            raise CompileError(f"unsupported cast {src.display()} -> {dst.display()}")

        def cast_fn(env: Env) -> CVal:
            return convert(inner(env))

        return cast_fn, None

    def _compile_vector_cast(self, expr, inner, src, dst):
        """Casts into/out of the dense VECTOR(n) layout (tensor workload
        plane). array(numeric) -> vector(n): the static lane width is a
        compile-time check; a non-NULL row whose runtime length != n, or one
        carrying a NULL element, degrades to a NULL row (the dense layout
        has no element mask and a traced program has no per-row error
        channel — ingest boundaries raise instead, ops/tensor.py
        column_to_vector). vector(n) -> array(numeric) materializes full
        lanes with length n."""
        from ..spi.types import UnknownType as _Unk
        from ..spi.types import VectorType as _Vec

        cap = self.capacity
        if isinstance(src, _Unk):

            def nullsrc_fn(env: Env) -> CVal:
                return _null_cval(dst, cap)

            return nullsrc_fn, None
        if isinstance(src, _Vec) and isinstance(dst, _Vec):
            raise CompileError(
                f"cannot cast {src.display()} to {dst.display()} "
                "(vector dimensions are fixed)"
            )
        if isinstance(dst, _Vec):
            if not (isinstance(src, ArrayType) and is_numeric(src.element)):
                raise CompileError(
                    f"cannot cast {src.display()} to {dst.display()}"
                )
            n = dst.dimension

            def arr2vec_fn(env: Env) -> CVal:
                v = inner(env)
                data = v.data.astype(jnp.float64)
                w = data.shape[1]
                lengths = (
                    v.lengths
                    if v.lengths is not None
                    else jnp.full((data.shape[0],), w, dtype=jnp.int32)
                )
                ok = v.valid & (lengths == n)
                if w < n:
                    # no row can hold n elements in W < n lanes
                    return CVal(
                        jnp.zeros((data.shape[0], n), dtype=jnp.float64),
                        ok & False,
                    )
                if v.elem_valid is not None:
                    ok = ok & jnp.all(v.elem_valid[:, :n], axis=1)
                out = jnp.where(ok[:, None], data[:, :n], 0.0)
                return CVal(out, ok)

            return arr2vec_fn, None
        # vector -> array(numeric)
        if not (isinstance(dst, ArrayType) and is_numeric(dst.element)):
            raise CompileError(
                f"cannot cast {src.display()} to {dst.display()}"
            )
        n = src.dimension
        el_dt = _dtype_of(dst.element)

        def vec2arr_fn(env: Env) -> CVal:
            v = inner(env)
            data = v.data.astype(el_dt)
            lengths = jnp.where(v.valid, n, 0).astype(jnp.int32)
            ev = jnp.broadcast_to(v.valid[:, None], data.shape)
            return CVal(data, v.valid, None, lengths, ev)

        return vec2arr_fn, None

    # ------------------------------------------------------------------ case

    def _compile_case(self, expr: Case) -> Tuple[Compiled, Optional[Dictionary]]:
        from ..spi.types import is_nested

        if is_nested(expr.type):
            raise CompileError("CASE over array/map/row values not supported yet")
        compiled_whens = [
            (self.compile(c)[0],) + self.compile(r) for c, r in expr.whens
        ]
        default_fn, default_dict = (
            self.compile(expr.default) if expr.default is not None else (None, None)
        )
        dt = _dtype_of(expr.type)

        # string CASE: merge branch dictionaries and remap each branch's codes
        # onto the merged vocabulary (same scheme as $array construction)
        out_dict = None
        if is_string(expr.type):
            branch_dicts = [d for *_rest, d in compiled_whens]
            if default_fn is not None:
                branch_dicts.append(default_dict)
            real = [d for d in branch_dicts if d is not None]
            if real:
                out_dict = _merge_dicts(real)

        def remap(r: CVal, d: Optional[Dictionary]):
            if out_dict is None or d is None:
                return r.data
            return _remap_codes(r.data, d, out_dict)

        from ..spi.types import is_long_decimal

        lanes = is_long_decimal(expr.type)

        def case_fn(env: Env) -> CVal:
            if default_fn is not None:
                acc = default_fn(env)
                acc_data = remap(acc, default_dict).astype(dt)
                acc_valid = acc.valid
            else:
                shape = (self.capacity, 2) if lanes else (self.capacity,)
                acc_data = jnp.zeros(shape, dtype=dt)
                acc_valid = jnp.zeros((self.capacity,), dtype=jnp.bool_)
            # evaluate in reverse: earlier WHENs override later ones
            for cond_fn, res_fn, res_dict in reversed(compiled_whens):
                c = cond_fn(env)
                r = res_fn(env)
                fire = c.valid & c.data.astype(jnp.bool_)
                fire_d = fire[:, None] if lanes else fire
                acc_data = jnp.where(fire_d, remap(r, res_dict).astype(dt), acc_data)
                acc_valid = jnp.where(fire, r.valid, acc_valid)
            return CVal(acc_data, acc_valid, out_dict)

        return case_fn, out_dict

    # ----------------------------------------------------------- nested types

    def _dict_tree(self, expr: IrExpr):
        """Compile-time dictionary info for a (possibly nested) expression:
        a Dictionary/None for scalars and arrays, a tuple of subtrees for
        maps (keys, values) and rows (fields)."""
        if isinstance(expr, Reference):
            lay = self.layout.get(expr.symbol)
            if lay is None:
                return None
            if isinstance(expr.type, (MapType,)) or expr.type.name == "row":
                return lay.child_dicts
            return lay.dictionary
        if isinstance(expr, Call):
            if expr.name == "$row":
                return tuple(self._dict_tree(a) for a in expr.args)
            if expr.name == "$map":
                return (self._dict_tree(expr.args[0]), self._dict_tree(expr.args[1]))
            if expr.name == "$field":
                sub = self._dict_tree(expr.args[0])
                idx = int(expr.args[1].value)
                return sub[idx] if isinstance(sub, tuple) and idx < len(sub) else None
        try:
            return self.compile(expr)[1]
        except CompileError:
            return None

    def _compile_nested(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        """ARRAY/MAP/ROW constructors and accessors over the pad-and-mask
        layout (ref: operator/scalar/ArraySubscriptOperator.java, MapSubscript,
        ArrayFunctions — vectorized here as [cap, W] lane ops; no per-row
        loops, everything traces into one fused XLA program)."""
        name = expr.name
        cap = self.capacity
        arg_fns = [self.compile(a)[0] for a in expr.args]
        arg_types = [a.type for a in expr.args]
        out_t = expr.type

        if name == "$array":
            el_t = out_t.element
            merged = None
            if is_string(el_t):
                # NULL elements contribute no vocabulary; every non-null
                # element must be dictionary-coded
                null_arg = [
                    isinstance(a, Constant) and a.value is None for a in expr.args
                ]
                el_dicts = [
                    self.compile(a)[1]
                    for a, isnull in zip(expr.args, null_arg)
                    if not isnull
                ]
                merged = _merge_dicts(el_dicts) if el_dicts else None

            def array_fn(env: Env) -> CVal:
                vals = [f(env) for f in arg_fns]
                datas, valids = [], []
                for v in vals:
                    d = v.data
                    if merged is not None and v.dictionary is not None:
                        d = _remap_codes(d, v.dictionary, merged)
                    datas.append(d)
                    valids.append(v.valid)
                if not vals:
                    data = jnp.zeros((cap, 1), dtype=_dtype_of(el_t))
                    ev = jnp.zeros((cap, 1), dtype=jnp.bool_)
                else:
                    data = jnp.stack(datas, axis=1)
                    ev = jnp.stack(valids, axis=1)
                lengths = jnp.full((cap,), len(vals), dtype=jnp.int32)
                valid = jnp.ones((cap,), dtype=jnp.bool_)
                return CVal(data, valid, merged, lengths, ev)

            return array_fn, merged

        if name == "$row":

            def row_fn(env: Env) -> CVal:
                kids = tuple(f(env) for f in arg_fns)
                return CVal(
                    jnp.zeros((cap,), dtype=jnp.int8),
                    jnp.ones((cap,), dtype=jnp.bool_),
                    children=kids,
                )

            return row_fn, None

        if name == "$map":

            def map_fn(env: Env) -> CVal:
                k, v = arg_fns[0](env), arg_fns[1](env)
                same_len = k.lengths == v.lengths
                valid = k.valid & v.valid & same_len
                return CVal(
                    jnp.zeros((cap,), dtype=jnp.int8), valid,
                    lengths=k.lengths, children=(k, v),
                )

            return map_fn, None

        if name == "$field":
            idx = expr.args[1].value

            def field_fn(env: Env, idx=int(idx)) -> CVal:
                r = arg_fns[0](env)
                c = r.children[idx]
                return CVal(
                    c.data, c.valid & r.valid, c.dictionary,
                    c.lengths, c.elem_valid, c.children,
                )

            d = self._dict_tree(expr)
            return field_fn, d if isinstance(d, Dictionary) else None

        if name in ("$subscript", "element_at") and isinstance(arg_types[0], ArrayType):
            el_t = arg_types[0].element

            def sub_fn(env: Env) -> CVal:
                a, i = arg_fns[0](env), arg_fns[1](env)
                w = a.data.shape[1]
                pos = i.data.astype(jnp.int64) - 1  # SQL arrays are 1-based
                safe = jnp.clip(pos, 0, w - 1)[:, None]
                data = jnp.take_along_axis(a.data, safe, axis=1)[:, 0]
                ev = jnp.take_along_axis(a.elem_valid, safe, axis=1)[:, 0]
                in_range = (pos >= 0) & (pos < a.lengths.astype(jnp.int64))
                valid = a.valid & i.valid & in_range & ev
                return CVal(data, valid, a.dictionary)

            d = self.compile(expr.args[0])[1]
            return sub_fn, d if is_string(el_t) else None

        if name in ("$subscript", "element_at") and isinstance(arg_types[0], MapType):

            def mapsub_fn(env: Env) -> CVal:
                m, k = arg_fns[0](env), arg_fns[1](env)
                keys, vals = m.children
                eq = _lane_equals(keys, k)
                found = jnp.any(eq, axis=1)
                pos = jnp.argmax(eq, axis=1)[:, None]
                data = jnp.take_along_axis(vals.data, pos, axis=1)[:, 0]
                ev = jnp.take_along_axis(vals.elem_valid, pos, axis=1)[:, 0]
                valid = m.valid & k.valid & found & ev
                return CVal(data, valid, vals.dictionary)

            tree = self._dict_tree(expr.args[0])
            vd = tree[1] if isinstance(tree, tuple) and len(tree) == 2 else None
            return mapsub_fn, vd if isinstance(vd, Dictionary) else None

        if name == "cardinality":

            def card_fn(env: Env) -> CVal:
                v = arg_fns[0](env)
                lengths = v.lengths if v.lengths is not None else v.children[0].lengths
                return CVal(lengths.astype(jnp.int64), v.valid)

            return card_fn, None

        if name == "contains":

            def contains_fn(env: Env) -> CVal:
                a, x = arg_fns[0](env), arg_fns[1](env)
                w = a.data.shape[1]
                present = jnp.arange(w)[None, :] < a.lengths[:, None]
                eq = _lane_equals(a, x) & present
                match = jnp.any(eq, axis=1)
                has_null = jnp.any(present & ~a.elem_valid, axis=1)
                valid = a.valid & x.valid & (match | ~has_null)
                return CVal(match, valid)

            return contains_fn, None

        if name == "array_position":

            def pos_fn(env: Env) -> CVal:
                a, x = arg_fns[0](env), arg_fns[1](env)
                w = a.data.shape[1]
                present = jnp.arange(w)[None, :] < a.lengths[:, None]
                eq = _lane_equals(a, x) & present
                found = jnp.any(eq, axis=1)
                first = jnp.argmax(eq, axis=1).astype(jnp.int64) + 1
                return CVal(jnp.where(found, first, 0), a.valid & x.valid)

            return pos_fn, None

        if name in ("array_min", "array_max"):
            el_t = arg_types[0].element

            def minmax_fn(env: Env, is_min=(name == "array_min")) -> CVal:
                a = arg_fns[0](env)
                w = a.data.shape[1]
                present = jnp.arange(w)[None, :] < a.lengths[:, None]
                mask = present & a.elem_valid
                dt = a.data.dtype
                if jnp.issubdtype(dt, jnp.floating):
                    sent = jnp.array(jnp.inf if is_min else -jnp.inf, dtype=dt)
                elif dt == jnp.bool_:
                    sent = jnp.array(is_min, dtype=dt)
                else:
                    info = jnp.iinfo(dt)
                    sent = jnp.array(info.max if is_min else info.min, dtype=dt)
                masked = jnp.where(mask, a.data, sent)
                data = jnp.min(masked, axis=1) if is_min else jnp.max(masked, axis=1)
                has_null = jnp.any(present & ~a.elem_valid, axis=1)
                valid = a.valid & (a.lengths > 0) & ~has_null
                return CVal(data, valid, a.dictionary)

            d = self.compile(expr.args[0])[1]
            return minmax_fn, d if is_string(el_t) else None

        if name in ("array_sort", "array_distinct"):

            def sort_fn(env: Env, distinct=(name == "array_distinct")) -> CVal:
                from . import kernels as K

                a = arg_fns[0](env)
                w = a.data.shape[1]
                present = jnp.arange(w)[None, :] < a.lengths[:, None]
                # sort lanes: value order, nulls last-within-present, absents last
                key = jnp.where(
                    present & a.elem_valid,
                    K.order_key(a.data),
                    jnp.where(present, jnp.int64(K.INT64_MAX - 1), jnp.int64(K.INT64_MAX)),
                )
                order = jnp.argsort(key, axis=1)
                if not distinct:
                    data = jnp.take_along_axis(a.data, order, axis=1)
                    ev = jnp.take_along_axis(a.elem_valid, order, axis=1)
                    return CVal(data, a.valid, a.dictionary, a.lengths, ev)
                # distinct keeps FIRST occurrences in ORIGINAL order (reference
                # semantics): find dups in value order, map the keep mask back
                # through the inverse permutation, then compact stably
                ks = jnp.take_along_axis(key, order, axis=1)
                pres_s = jnp.take_along_axis(present, order, axis=1)
                dup_s = jnp.zeros_like(pres_s)
                dup_s = dup_s.at[:, 1:].set(pres_s[:, 1:] & (ks[:, 1:] == ks[:, :-1]))
                inv = jnp.argsort(order, axis=1)
                keep = present & ~jnp.take_along_axis(dup_s, inv, axis=1)
                korder = jnp.argsort(~keep, axis=1)  # stable: original order kept
                data = jnp.take_along_axis(a.data, korder, axis=1)
                ev = jnp.take_along_axis(a.elem_valid, korder, axis=1) & (
                    jnp.take_along_axis(keep, korder, axis=1)
                )
                lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
                return CVal(data, a.valid, a.dictionary, lengths, ev)

            d = self.compile(expr.args[0])[1]
            return sort_fn, d

        if name == "$array_concat":
            el_t = out_t.element
            d0 = self.compile(expr.args[0])[1]
            d1 = self.compile(expr.args[1])[1]
            merged = _merge_dicts([d0, d1]) if is_string(el_t) else None

            def concat_fn(env: Env) -> CVal:
                a, b = arg_fns[0](env), arg_fns[1](env)
                wa, wb = a.data.shape[1], b.data.shape[1]
                w = wa + wb
                da, db = a.data, b.data
                if merged is not None:
                    da = _remap_codes(da, a.dictionary, merged)
                    db = _remap_codes(db, b.dictionary, merged)
                j = jnp.arange(w)[None, :]
                la = a.lengths[:, None].astype(jnp.int64)
                from_a = j < la
                ia = jnp.clip(j, 0, wa - 1)
                ib = jnp.clip(j - la, 0, wb - 1)
                ia = jnp.broadcast_to(ia, (cap, w))
                ib = jnp.broadcast_to(ib, (cap, w))
                data = jnp.where(
                    from_a,
                    jnp.take_along_axis(da, ia, axis=1),
                    jnp.take_along_axis(db, ib, axis=1),
                )
                ev = jnp.where(
                    from_a,
                    jnp.take_along_axis(a.elem_valid, ia, axis=1),
                    jnp.take_along_axis(b.elem_valid, ib, axis=1),
                )
                lengths = a.lengths + b.lengths
                present = j < lengths[:, None]
                return CVal(data, a.valid & b.valid, merged, lengths, ev & present)

            return concat_fn, merged

        if name == "slice":

            def slice_fn(env: Env) -> CVal:
                a, s, ln = arg_fns[0](env), arg_fns[1](env), arg_fns[2](env)
                w = a.data.shape[1]
                start = s.data.astype(jnp.int64)
                length = jnp.maximum(ln.data.astype(jnp.int64), 0)
                lens = a.lengths.astype(jnp.int64)
                eff = jnp.where(start > 0, start - 1, lens + start)
                j = jnp.arange(w)[None, :]
                src = eff[:, None] + j
                take = (j < length[:, None]) & (src >= 0) & (src < lens[:, None])
                safe = jnp.clip(src, 0, w - 1)
                data = jnp.take_along_axis(a.data, safe, axis=1)
                ev = jnp.take_along_axis(a.elem_valid, safe, axis=1) & take
                new_len = jnp.sum(take, axis=1).astype(jnp.int32)
                valid = a.valid & s.valid & ln.valid & (start != 0)
                return CVal(data, valid, a.dictionary, new_len, ev)

            d = self.compile(expr.args[0])[1]
            return slice_fn, d

        if name in ("map_keys", "map_values"):
            idx = 0 if name == "map_keys" else 1

            def extract_fn(env: Env, idx=idx) -> CVal:
                m = arg_fns[0](env)
                c = m.children[idx]
                return CVal(
                    c.data, m.valid, c.dictionary, c.lengths, c.elem_valid
                )

            tree = self._dict_tree(expr.args[0])
            cd = tree[idx] if isinstance(tree, tuple) and len(tree) == 2 else None
            return extract_fn, cd if isinstance(cd, Dictionary) else None

        if name == "array_remove":

            def remove_fn(env: Env) -> CVal:
                a, x = arg_fns[0](env), arg_fns[1](env)
                keep = _lane_present(a) & ~_lane_equals(a, x)
                return _lane_compact(
                    a, keep, distinct=False, valid=a.valid & x.valid
                )

            return remove_fn, self.compile(expr.args[0])[1]

        if name in ("array_except", "array_intersect"):

            def setop_fn(env: Env, except_=(name == "array_except")) -> CVal:
                a, b = arg_fns[0](env), arg_fns[1](env)
                member = _lane_member(a, b)
                keep = _lane_present(a) & (~member if except_ else member)
                return _lane_compact(
                    a, keep, distinct=True, valid=a.valid & b.valid
                )

            return setop_fn, self.compile(expr.args[0])[1]

        if name == "arrays_overlap":

            def overlap_fn(env: Env) -> CVal:
                a, b = arg_fns[0](env), arg_fns[1](env)
                pa, pb = _lane_present(a), _lane_present(b)
                member = _lane_member(a, b)
                real = jnp.any(pa & a.elem_valid & member, axis=1)
                a_null = jnp.any(pa & ~a.elem_valid, axis=1)
                b_null = jnp.any(pb & ~b.elem_valid, axis=1)
                # a real match decides TRUE; otherwise a NULL element on
                # either side makes the answer unknown (reference semantics)
                valid = a.valid & b.valid & (real | ~(a_null | b_null))
                return CVal(real, valid)

            return overlap_fn, None

        if name == "trim_array":

            def trim_fn(env: Env) -> CVal:
                a, n = arg_fns[0](env), arg_fns[1](env)
                cut = jnp.clip(n.data.astype(jnp.int64), 0, None)
                new_len = jnp.maximum(
                    a.lengths.astype(jnp.int64) - cut, 0
                ).astype(jnp.int32)
                pres = jnp.arange(a.data.shape[1])[None, :] < new_len[:, None]
                # deviation: the reference raises when n exceeds cardinality;
                # we clamp to empty (NULL-free error channel)
                return CVal(
                    a.data, a.valid & n.valid, a.dictionary,
                    new_len, a.elem_valid & pres,
                )

            return trim_fn, self.compile(expr.args[0])[1]

        if name == "sequence":
            if not all(isinstance(a, Constant) for a in expr.args):
                raise CompileError(
                    "sequence: bounds must be literals (static lane width)"
                )
            start = int(expr.args[0].value)
            stop = int(expr.args[1].value)
            step = int(expr.args[2].value) if len(expr.args) > 2 else (
                1 if stop >= start else -1
            )
            if step == 0:
                raise CompileError("sequence: step must not be zero")
            seq = list(range(start, stop + (1 if step > 0 else -1), step))
            wseq = max(len(seq), 1)
            seq_np = np.array(seq or [0], dtype=np.int64)

            def seq_fn(env: Env) -> CVal:
                data = jnp.broadcast_to(jnp.asarray(seq_np)[None, :], (cap, wseq))
                ev = jnp.full((cap, wseq), bool(seq), dtype=jnp.bool_)
                lengths = jnp.full((cap,), len(seq), dtype=jnp.int32)
                return CVal(
                    data, jnp.ones((cap,), dtype=jnp.bool_), None, lengths, ev
                )

            return seq_fn, None

        if name == "repeat":
            cnt = expr.args[1]
            if not isinstance(cnt, Constant):
                raise CompileError(
                    "repeat: count must be a literal (static lane width)"
                )
            if cnt.value is None:  # NULL count null-propagates
                return (lambda env: _null_cval(out_t, cap)), None
            wn = max(int(cnt.value), 0)

            def repeat_fn(env: Env) -> CVal:
                x = arg_fns[0](env)
                w = max(wn, 1)
                data = jnp.broadcast_to(x.data[:, None], (cap, w))
                ev = jnp.broadcast_to(x.valid[:, None], (cap, w))
                lengths = jnp.full((cap,), wn, dtype=jnp.int32)
                return CVal(
                    data, jnp.ones((cap,), dtype=jnp.bool_), x.dictionary,
                    lengths, ev,
                )

            return repeat_fn, self.compile(expr.args[0])[1]

        if name == "map_concat":
            ktrees = [self._dict_tree(a) for a in expr.args]
            kdicts = [t[0] if isinstance(t, tuple) and len(t) == 2 else None for t in ktrees]
            vdicts = [t[1] if isinstance(t, tuple) and len(t) == 2 else None for t in ktrees]
            mk = _merge_dicts([d for d in kdicts if d is not None]) if any(kdicts) else None
            mv = _merge_dicts([d for d in vdicts if d is not None]) if any(vdicts) else None

            def mapcat_fn(env: Env) -> CVal:
                ms = [f(env) for f in arg_fns]
                kds, vds, evs_k, evs_v, press = [], [], [], [], []
                for m, kd_, vd_ in zip(ms, kdicts, vdicts):
                    k, v = m.children
                    kdat, vdat = k.data, v.data
                    if mk is not None:
                        kdat = _remap_codes(kdat, kd_, mk)
                    if mv is not None:
                        vdat = _remap_codes(vdat, vd_, mv)
                    kds.append(kdat)
                    vds.append(vdat)
                    evs_k.append(k.elem_valid)
                    evs_v.append(v.elem_valid)
                    press.append(
                        jnp.arange(kdat.shape[1])[None, :] < m.lengths[:, None]
                    )
                kd = jnp.concatenate(kds, axis=1)
                vd = jnp.concatenate(vds, axis=1)
                kev = jnp.concatenate(evs_k, axis=1)
                vev = jnp.concatenate(evs_v, axis=1)
                pres = jnp.concatenate(press, axis=1)
                from . import kernels as K

                W = kd.shape[1]
                pos = jnp.broadcast_to(jnp.arange(W)[None, :], (cap, W))
                key = jnp.where(
                    pres & kev, K.order_key(kd), jnp.int64(K.INT64_MAX)
                )
                # keep the LAST occurrence of each key (later maps win):
                # sort by (key asc, pos desc), keep first of each run
                order = jnp.lexsort((-pos, key), axis=1)
                ks = jnp.take_along_axis(key, order, axis=1)
                pres_s = jnp.take_along_axis(pres, order, axis=1)
                dup_s = jnp.zeros_like(pres_s)
                dup_s = dup_s.at[:, 1:].set(
                    pres_s[:, 1:] & (ks[:, 1:] == ks[:, :-1])
                )
                inv = jnp.argsort(order, axis=1)
                keep = pres & kev & ~jnp.take_along_axis(dup_s, inv, axis=1)
                korder = jnp.argsort(~keep, axis=1)
                lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
                keep_s2 = jnp.take_along_axis(keep, korder, axis=1)
                kc = CVal(
                    jnp.take_along_axis(kd, korder, axis=1),
                    jnp.ones((cap,), dtype=jnp.bool_), mk, lengths,
                    jnp.take_along_axis(kev, korder, axis=1) & keep_s2,
                )
                vc = CVal(
                    jnp.take_along_axis(vd, korder, axis=1),
                    jnp.ones((cap,), dtype=jnp.bool_), mv, lengths,
                    jnp.take_along_axis(vev, korder, axis=1) & keep_s2,
                )
                valid = ms[0].valid
                for m in ms[1:]:
                    valid = valid & m.valid
                return CVal(
                    jnp.zeros((cap,), dtype=jnp.int8), valid,
                    lengths=lengths, children=(kc, vc),
                )

            return mapcat_fn, (mk, mv)

        raise CompileError(f"nested function {name} not implemented")

    # ---------------------------------------------------------- higher-order

    def _lambda_layout(self, lam: IrLambda, param_dicts) -> Dict[str, ColumnLayout]:
        lay = dict(self.layout)
        for p, pt, pd in zip(lam.params, lam.param_types, param_dicts):
            lay[p] = ColumnLayout(pt, pd)
        return lay

    def _lambda_free_env(self, lam: IrLambda, env: Env, w: int) -> Env:
        """Outer symbols free in the body, repeated onto the lane grid."""
        free = ir_references(lam.body) - set(lam.params)
        return {s: _repeat_cval(env[s], w) for s in free if s in env}

    def _compile_higher_order(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        """Lambda-taking array/map functions: the body compiles to its own
        vectorized program over the flattened [cap*W] lane grid (W is a static
        lane width at trace time, so each distinct W compiles once and caches).
        ref: operator/scalar/ArrayTransformFunction.java and friends — there
        the lambda becomes a MethodHandle looped per element; here it becomes
        one fused elementwise program over all rows' lanes at once."""
        from ..spi.types import is_nested

        name = expr.name
        cap = self.capacity
        # scalar lanes only: element CVals flattened onto the lane grid carry
        # no children, and nested lambda results would need [cap, W, ...]
        # layouts — reject cleanly instead of dying inside the trace
        for a in expr.args:
            if isinstance(a, IrLambda):
                if is_nested(a.type) or any(is_nested(p) for p in a.param_types):
                    raise CompileError(
                        f"{name} over nested (array/map/row) elements or with "
                        "a nested-returning lambda is not supported yet"
                    )

        if name in ("transform", "filter", "any_match", "all_match", "none_match"):
            arr_fn, arr_dict = self.compile(expr.args[0])
            lam: IrLambda = expr.args[1]
            lay = self._lambda_layout(lam, (arr_dict,))
            body_dict = compile_expression(lam.body, lay, 1)[1]

            def run_body(env: Env):
                a = arr_fn(env)
                w = a.data.shape[1]
                fenv = self._lambda_free_env(lam, env, w)
                fenv[lam.params[0]] = CVal(
                    a.data.reshape(cap * w), a.elem_valid.reshape(cap * w),
                    a.dictionary,
                )
                bfn, _ = compile_expression(lam.body, lay, cap * w)
                r = bfn(fenv)
                present = jnp.arange(w)[None, :] < a.lengths[:, None]
                return a, w, r, present

            if name == "transform":

                def transform_fn(env: Env) -> CVal:
                    a, w, r, present = run_body(env)
                    return CVal(
                        r.data.reshape(cap, w), a.valid, body_dict, a.lengths,
                        r.valid.reshape(cap, w) & present,
                    )

                return transform_fn, body_dict

            if name == "filter":

                def filter_fn(env: Env) -> CVal:
                    a, w, r, present = run_body(env)
                    keep = (
                        r.data.astype(jnp.bool_) & r.valid
                    ).reshape(cap, w) & present
                    order = jnp.argsort(~keep, axis=1, stable=True)
                    data2 = jnp.take_along_axis(a.data, order, axis=1)
                    ev2 = jnp.take_along_axis(a.elem_valid, order, axis=1)
                    new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
                    pres2 = jnp.arange(w)[None, :] < new_len[:, None]
                    return CVal(data2, a.valid, a.dictionary, new_len, ev2 & pres2)

                return filter_fn, arr_dict

            def match_fn(env: Env, mode=name) -> CVal:
                # 3VL: any_match is TRUE if any true, FALSE if all false,
                # NULL if no true but some null (ref ArrayAnyMatchFunction)
                a, w, r, present = run_body(env)
                bd = r.data.astype(jnp.bool_).reshape(cap, w)
                bv = r.valid.reshape(cap, w)
                any_true = jnp.any(bd & bv & present, axis=1)
                any_false = jnp.any(~bd & bv & present, axis=1)
                any_null = jnp.any(~bv & present, axis=1)
                if mode == "any_match":
                    data, det = any_true, any_true | ~any_null
                elif mode == "all_match":
                    data, det = ~any_false, any_false | ~any_null
                else:  # none_match
                    data, det = ~any_true, any_true | ~any_null
                return CVal(data, a.valid & det)

            return match_fn, None

        if name == "zip_with":
            a_fn, a_dict = self.compile(expr.args[0])
            b_fn, b_dict = self.compile(expr.args[1])
            lam = expr.args[2]
            lay = self._lambda_layout(lam, (a_dict, b_dict))
            body_dict = compile_expression(lam.body, lay, 1)[1]

            def zip_fn(env: Env) -> CVal:
                a, b = a_fn(env), b_fn(env)
                wa, wb = a.data.shape[1], b.data.shape[1]
                w = max(wa, wb)

                def pad(x, width):
                    return x if x.shape[1] == width else jnp.pad(
                        x, ((0, 0), (0, width - x.shape[1]))
                    )

                lane = jnp.arange(w)[None, :]
                lengths = jnp.maximum(a.lengths, b.lengths)
                # the shorter array extends with NULLs (ZipWithFunction)
                ea = pad(a.elem_valid, w) & (lane < a.lengths[:, None])
                eb = pad(b.elem_valid, w) & (lane < b.lengths[:, None])
                fenv = self._lambda_free_env(lam, env, w)
                fenv[lam.params[0]] = CVal(
                    pad(a.data, w).reshape(cap * w), ea.reshape(cap * w), a.dictionary
                )
                fenv[lam.params[1]] = CVal(
                    pad(b.data, w).reshape(cap * w), eb.reshape(cap * w), b.dictionary
                )
                bfn, _ = compile_expression(lam.body, lay, cap * w)
                r = bfn(fenv)
                present = lane < lengths[:, None]
                return CVal(
                    r.data.reshape(cap, w), a.valid & b.valid, body_dict,
                    lengths, r.valid.reshape(cap, w) & present,
                )

            return zip_fn, body_dict

        if name == "reduce":
            arr_fn, arr_dict = self.compile(expr.args[0])
            init_fn, init_dict = self.compile(expr.args[1])
            lam_in: IrLambda = expr.args[2]
            lam_out: IrLambda = expr.args[3]
            state_t = lam_in.param_types[0]
            if is_string(state_t):
                raise CompileError("reduce with a string-typed state is not supported")
            lay_in = self._lambda_layout(lam_in, (None, arr_dict))
            lay_out = self._lambda_layout(lam_out, (None,))
            out_dict = compile_expression(lam_out.body, lay_out, 1)[1]

            def reduce_fn(env: Env) -> CVal:
                a = arr_fn(env)
                w = a.data.shape[1]
                s = init_fn(env)
                bfn, _ = compile_expression(lam_in.body, lay_in, cap)
                free_in = ir_references(lam_in.body) - set(lam_in.params)
                base_env = {k: env[k] for k in free_in if k in env}
                for i in range(w):
                    xi = CVal(a.data[:, i], a.elem_valid[:, i], a.dictionary)
                    env2 = dict(base_env)
                    env2[lam_in.params[0]] = s
                    env2[lam_in.params[1]] = xi
                    s2 = bfn(env2)
                    pres = (i < a.lengths) & a.valid
                    s = CVal(
                        jnp.where(pres, s2.data, s.data),
                        jnp.where(pres, s2.valid, s.valid),
                    )
                ofn, _ = compile_expression(lam_out.body, lay_out, cap)
                free_out = ir_references(lam_out.body) - set(lam_out.params)
                env3 = {k: env[k] for k in free_out if k in env}
                env3[lam_out.params[0]] = s
                r = ofn(env3)
                return CVal(r.data, r.valid & a.valid, out_dict)

            return reduce_fn, out_dict

        if name in ("transform_values", "map_filter"):
            m_fn, _ = self.compile(expr.args[0])
            lam = expr.args[1]
            tree = self._dict_tree(expr.args[0])
            kd, vd = tree if isinstance(tree, tuple) and len(tree) == 2 else (None, None)
            kd = kd if isinstance(kd, Dictionary) else None
            vd = vd if isinstance(vd, Dictionary) else None
            lay = self._lambda_layout(lam, (kd, vd))
            body_dict = compile_expression(lam.body, lay, 1)[1]

            def run_map_body(env: Env):
                m = m_fn(env)
                k, v = m.children
                w = k.data.shape[1]
                present = jnp.arange(w)[None, :] < m.lengths[:, None]
                fenv = self._lambda_free_env(lam, env, w)
                fenv[lam.params[0]] = CVal(
                    k.data.reshape(cap * w), k.elem_valid.reshape(cap * w),
                    k.dictionary,
                )
                fenv[lam.params[1]] = CVal(
                    v.data.reshape(cap * w), v.elem_valid.reshape(cap * w),
                    v.dictionary,
                )
                bfn, _ = compile_expression(lam.body, lay, cap * w)
                return m, k, v, w, bfn(fenv), present

            if name == "transform_values":

                def tv_fn(env: Env) -> CVal:
                    m, k, v, w, r, present = run_map_body(env)
                    nv = CVal(
                        r.data.reshape(cap, w), m.valid, body_dict,
                        k.lengths, r.valid.reshape(cap, w) & present,
                    )
                    return CVal(
                        jnp.zeros((cap,), dtype=jnp.int8), m.valid,
                        lengths=m.lengths, children=(k, nv),
                    )

                return tv_fn, None

            def mf_fn(env: Env) -> CVal:
                m, k, v, w, r, present = run_map_body(env)
                keep = (r.data.astype(jnp.bool_) & r.valid).reshape(cap, w) & present
                order = jnp.argsort(~keep, axis=1, stable=True)
                new_len = jnp.sum(keep, axis=1).astype(jnp.int32)
                pres2 = jnp.arange(w)[None, :] < new_len[:, None]

                def reorder(c: CVal) -> CVal:
                    return CVal(
                        jnp.take_along_axis(c.data, order, axis=1), c.valid,
                        c.dictionary, new_len,
                        jnp.take_along_axis(c.elem_valid, order, axis=1) & pres2,
                    )

                return CVal(
                    jnp.zeros((cap,), dtype=jnp.int8), m.valid,
                    lengths=new_len, children=(reorder(k), reorder(v)),
                )

            return mf_fn, None

        raise CompileError(f"higher-order function {name} not implemented")

    # ------------------------------------------------------------------ calls

    def _compile_call(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        name = expr.name
        if name in _HO_FUNCS:
            return self._compile_higher_order(expr)
        if name in _VECTOR_FUNCS or name in ("$linear_model", "$gbdt_model"):
            # tensor workload plane (ops/tensor.py): similarity family ->
            # MXU matmul forms; model calls -> stacked-feature matmul/GBDT
            from . import tensor as _tensor

            if name in _VECTOR_FUNCS:
                return _tensor.compile_vector_call(self, expr)
            return _tensor.compile_model_call(self, expr)
        if name in _NESTED_FUNCS:
            return self._compile_nested(expr)
        # string-aware operators first
        if name in ("$eq", "$ne", "$lt", "$lte", "$gt", "$gte") and any(
            is_string(a.type) for a in expr.args
        ):
            return self._compile_string_comparison(expr)
        if name == "$like":
            return self._compile_like(expr)
        if name in _STRING_FUNCS:
            return self._compile_string_function(expr)

        arg_fns = [self.compile(a)[0] for a in expr.args]
        arg_types = [a.type for a in expr.args]
        out_dt = _dtype_of(expr.type)
        cap = self.capacity

        # logical (Kleene)
        if name == "$and":

            def and_fn(env: Env) -> CVal:
                a, b = arg_fns[0](env), arg_fns[1](env)
                af = a.valid & ~a.data.astype(jnp.bool_)
                bf = b.valid & ~b.data.astype(jnp.bool_)
                res_false = af | bf
                res_true = (a.valid & a.data.astype(jnp.bool_)) & (
                    b.valid & b.data.astype(jnp.bool_)
                )
                return CVal(res_true, res_false | res_true)

            return and_fn, None
        if name == "$or":

            def or_fn(env: Env) -> CVal:
                a, b = arg_fns[0](env), arg_fns[1](env)
                at = a.valid & a.data.astype(jnp.bool_)
                bt = b.valid & b.data.astype(jnp.bool_)
                res_true = at | bt
                res_false = (a.valid & ~a.data.astype(jnp.bool_)) & (
                    b.valid & ~b.data.astype(jnp.bool_)
                )
                return CVal(res_true, res_false | res_true)

            return or_fn, None
        if name == "$not":

            def not_fn(env: Env) -> CVal:
                a = arg_fns[0](env)
                return CVal(~a.data.astype(jnp.bool_), a.valid)

            return not_fn, None
        if name == "$is_null":

            def isnull_fn(env: Env) -> CVal:
                a = arg_fns[0](env)
                return CVal(~a.valid, jnp.ones((cap,), dtype=jnp.bool_))

            return isnull_fn, None
        if name == "$not_null":

            def notnull_fn(env: Env) -> CVal:
                a = arg_fns[0](env)
                return CVal(a.valid, jnp.ones((cap,), dtype=jnp.bool_))

            return notnull_fn, None

        if name == "coalesce":
            if is_string(expr.type):
                # dictionary-coded strings: codes are only comparable within
                # ONE dictionary — merge the argument vocabularies and remap
                # every branch before selecting
                dicts = [self._dict_tree(a) for a in expr.args]
                dicts = [d if isinstance(d, Dictionary) else None for d in dicts]
                merged = _merge_dicts([d for d in dicts if d is not None])

                def coalesce_str_fn(env: Env) -> CVal:
                    vals = [f(env) for f in arg_fns]
                    datas = [
                        _remap_codes(v.data, d, merged) if d is not None else v.data
                        for v, d in zip(vals, dicts)
                    ]
                    data = datas[-1]
                    valid = vals[-1].valid
                    for v, dd in zip(reversed(vals[:-1]), reversed(datas[:-1])):
                        data = jnp.where(v.valid, dd, data)
                        valid = valid | v.valid
                    return CVal(data, valid, merged)

                return coalesce_str_fn, merged

            def coalesce_fn(env: Env) -> CVal:
                vals = [f(env) for f in arg_fns]
                data = vals[-1].data.astype(out_dt)
                valid = vals[-1].valid
                for v in reversed(vals[:-1]):
                    ok = v.valid[:, None] if v.data.ndim == 2 else v.valid
                    data = jnp.where(ok, v.data.astype(out_dt), data)
                    valid = valid | v.valid
                return CVal(data, valid)

            return coalesce_fn, None

        if name == "nullif":

            def nullif_fn(env: Env) -> CVal:
                a, b = arg_fns[0](env), arg_fns[1](env)
                same = (
                    (a.data == b.data).all(axis=-1)
                    if a.data.ndim == 2
                    else (a.data == b.data)
                )
                eq = same & a.valid & b.valid
                return CVal(a.data, a.valid & ~eq)

            return nullif_fn, None

        if name == "value_at_quantile":
            # tdigest lane data: [means..., weights...] per row; walk the
            # cumulative weight to the first centroid covering q (ref:
            # TDigest.valueAt — fully vectorized over rows AND centroids)
            q_type = expr.args[1].type
            out_type_ = expr.type
            from ..spi.types import is_integral as _is_int

            round_out = _is_int(out_type_)
            # digests store VALUE-space means (the aggregate descales decimal
            # inputs); a decimal element rescales back to storage before the
            # generic int64 cast
            out_scale = (
                10 ** out_type_.scale
                if isinstance(out_type_, DecimalType)
                else None
            )

            def vaq_fn(env: Env) -> CVal:
                td, q = arg_fns[0](env), arg_fns[1](env)
                kc = td.data.shape[1] // 2
                means, wts = td.data[:, :kc], td.data[:, kc:]
                total = jnp.sum(wts, axis=-1)
                qv = q.data.astype(jnp.float64)
                if isinstance(q_type, DecimalType):
                    qv = qv / float(10**q_type.scale)  # storage -> value space
                target = jnp.clip(qv, 0.0, 1.0) * total
                cum = jnp.cumsum(wts, axis=-1)
                okb = (cum >= target[:, None]) & (wts > 0)
                idx = jnp.argmax(okb, axis=-1)
                has = jnp.any(okb, axis=-1)
                val = jnp.take_along_axis(means, idx[:, None], axis=-1)[:, 0]
                if out_scale is not None:
                    val = jnp.round(val * out_scale)
                elif round_out:
                    # qdigest(bigint): centroid means round to the element
                    val = jnp.round(val)
                return CVal(val, td.valid & q.valid & has)

            return vaq_fn, None

        if name == "$dec_limb":
            # Int128 -> one of four 32-bit limbs as BIGINT (l3 keeps the
            # sign). The long-decimal aggregation decomposition: sums of
            # limbs are exact int64 for < 2**31 rows/group, so the whole
            # agg/exchange machinery stays scalar int64
            # (planner/rules.py decompose_long_decimal_aggregates).
            idx = expr.args[1].value
            src_t = expr.args[0].type

            def limb_fn(env: Env) -> CVal:
                from ..spi.types import is_long_decimal as _ild

                from . import int128 as i128

                v = arg_fns[0](env)
                x = v.data if _ild(src_t) else i128.from_int64(v.data)
                h, l = i128.hi(x), i128.lo(x)
                m32 = jnp.int64(0xFFFFFFFF)
                if idx == 0:
                    out = l & m32
                elif idx == 1:
                    out = jax.lax.shift_right_logical(l, jnp.int64(32))
                elif idx == 2:
                    out = h & m32
                else:
                    out = h >> jnp.int64(32)  # arithmetic: signed top limb
                return CVal(out, v.valid)

            return limb_fn, None

        if name in ("$i128_recombine", "$i128_avg"):
            nsums = 4

            def recombine_fn(env: Env) -> CVal:
                from . import int128 as i128

                vs = [f(env) for f in arg_fns]
                acc = i128.from_int64(vs[0].data)
                for i in range(1, nsums):
                    term = i128.from_int64(vs[i].data)
                    for _ in range(i):
                        term = i128.mul_int64(term, jnp.int64(1 << 32))
                    acc = i128.add(acc, term)
                valid = vs[0].valid
                for v in vs[1:nsums]:
                    valid = valid & v.valid
                if name == "$i128_avg":
                    cnt = vs[nsums]
                    acc = i128.div_int(acc, jnp.maximum(cnt.data, 1))
                    valid = valid & cnt.valid & (cnt.data > 0)
                return CVal(acc, valid)

            return recombine_fn, None

        if name == "$avg_combine":
            # final-stage avg = total_sum / total_count (fragmenter split);
            # result is NULL when no rows aggregated
            out_type_ = expr.type

            def avgc_fn(env: Env) -> CVal:
                s, c = arg_fns[0](env), arg_fns[1](env)
                cnt = jnp.maximum(c.data, 1)
                if isinstance(out_type_, DecimalType) and isinstance(
                    expr.args[0].type, DecimalType
                ):
                    half = cnt // 2
                    data = jnp.where(
                        s.data >= 0, (s.data + half) // cnt, -((-s.data + half) // cnt)
                    )
                else:
                    data = s.data.astype(jnp.float64) / cnt
                    if isinstance(expr.args[0].type, DecimalType):
                        data = data / float(10 ** expr.args[0].type.scale)
                return CVal(data.astype(out_dt), s.valid & c.valid & (c.data > 0))

            return avgc_fn, None

        if name.endswith("_combine") and name.startswith("$"):
            # $<stddev|variance...>_combine(s1, s2, n)
            stat = name[1:].rsplit("_combine", 1)[0]

            def varc_fn(env: Env) -> CVal:
                s1, s2, cn = (f(env) for f in arg_fns)
                n = jnp.maximum(cn.data, 1).astype(jnp.float64)
                mean = s1.data / n
                var_pop = jnp.maximum(s2.data / n - mean * mean, 0.0)
                if stat in ("var_pop", "stddev_pop"):
                    var = var_pop
                    valid = cn.data > 0
                else:
                    var = var_pop * n / jnp.maximum(n - 1, 1)
                    valid = cn.data > 1
                data = jnp.sqrt(var) if stat.startswith("stddev") else var
                return CVal(data, s1.valid & s2.valid & valid)

            return varc_fn, None

        if name in ("date_trunc", "date_add", "date_diff"):
            return self._compile_datetime_fn(expr)

        if name in ("pi", "e", "nan", "infinity") and not expr.args:
            import math as _math

            constv = {"pi": _math.pi, "e": _math.e, "nan": float("nan"),
                      "infinity": float("inf")}[name]
            cap0 = self.capacity

            def const_fn(env: Env) -> CVal:
                return CVal(
                    jnp.full((cap0,), constv, dtype=jnp.float64),
                    jnp.ones((cap0,), dtype=jnp.bool_),
                )

            return const_fn, None
        if name in ("random", "rand"):
            # per-row uniform via a mixed row index with a per-compilation
            # salt. Deviation, declared: a CACHED program replays its
            # sequence (the reference reseeds per call); fine for sampling.
            import random as _random

            salt = _random.getrandbits(63)
            cap0 = self.capacity
            hi = None
            if expr.args:
                inner_r, _ = self.compile(expr.args[0])
                hi = inner_r

            def random_fn(env: Env) -> CVal:
                from . import kernels as _K

                idx = jnp.arange(cap0, dtype=jnp.int64) + jnp.int64(salt)
                u = (
                    jax.lax.shift_right_logical(_K.splitmix64(idx), jnp.int64(11))
                ).astype(jnp.float64) / float(1 << 53)
                if hi is None:
                    return CVal(u, jnp.ones((cap0,), dtype=jnp.bool_))
                b = hi(env)
                n = jnp.maximum(b.data, 1)
                return CVal(
                    jnp.floor(u * n.astype(jnp.float64)).astype(jnp.int64),
                    b.valid & (b.data > 0),
                )

            return random_fn, None

        impl = _SIMPLE_FUNCS.get(name)
        if impl is None:
            raise CompileError(f"no device lowering for function {name}")

        def call_fn(env: Env) -> CVal:
            vals = [f(env) for f in arg_fns]
            data = impl([v.data for v in vals], arg_types, expr.type)
            valid = None
            for v in vals:
                valid = v.valid if valid is None else (valid & v.valid)
            if valid is None:
                valid = jnp.ones((cap,), dtype=jnp.bool_)
            return CVal(data.astype(out_dt) if data.dtype != out_dt else data, valid)

        return call_fn, None

    def _compile_datetime_fn(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        """date_trunc/date_add/date_diff with a constant unit (ref:
        operator/scalar/DateTimeFunctions.java). Calendar math runs on-device
        via the civil-date conversions (_civil_from_days/_days_from_civil)."""
        name = expr.name
        unit_arg = expr.args[0]
        if not isinstance(unit_arg, Constant) or not isinstance(unit_arg.value, str):
            raise CompileError(f"{name}: unit must be a string literal")
        unit = unit_arg.value.lower().rstrip("s")
        out_dt = _dtype_of(expr.type)

        if name == "date_trunc":
            inner, _ = self.compile(expr.args[1])
            src_t = expr.args[1].type

            def trunc_fn(env: Env) -> CVal:
                v = inner(env)
                days = _days_of(v.data, src_t)
                if unit == "day":
                    out_days = days
                elif unit == "week":  # ISO week starts Monday; epoch was a Thursday
                    out_days = days - jnp.remainder(days.astype(jnp.int64) + 3, 7)
                elif unit in ("month", "quarter", "year"):
                    y, m, _d = _civil_from_days(days)
                    if unit == "quarter":
                        m = ((m - 1) // 3) * 3 + 1
                    elif unit == "year":
                        m = jnp.ones_like(m)
                    out_days = _days_from_civil(y, m, jnp.ones_like(m))
                else:
                    raise CompileError(f"date_trunc unit {unit!r} not supported")
                if src_t == DATE:
                    return CVal(out_days.astype(out_dt), v.valid)
                return CVal((out_days * 86_400_000_000).astype(out_dt), v.valid)

            return trunc_fn, None

        if name == "date_add":
            amount_fn, _ = self.compile(expr.args[1])
            inner, _ = self.compile(expr.args[2])
            src_t = expr.args[2].type

            def add_fn(env: Env) -> CVal:
                amt = amount_fn(env)
                v = inner(env)
                days = _days_of(v.data, src_t)
                n = amt.data.astype(jnp.int64)
                if unit == "day":
                    out_days = days.astype(jnp.int64) + n
                elif unit == "week":
                    out_days = days.astype(jnp.int64) + 7 * n
                elif unit in ("month", "year", "quarter"):
                    k = n * {"month": 1, "quarter": 3, "year": 12}[unit]
                    y, m, d = _civil_from_days(days)
                    total = y * 12 + (m - 1) + k
                    ny = jnp.floor_divide(total, 12)
                    nm = jnp.remainder(total, 12) + 1
                    # clamp day to the target month's length
                    month_start = _days_from_civil(ny, nm, jnp.ones_like(nm))
                    next_start = _days_from_civil(
                        ny + (nm == 12), jnp.where(nm == 12, 1, nm + 1), jnp.ones_like(nm)
                    )
                    dim = next_start - month_start
                    out_days = month_start + jnp.minimum(d, dim) - 1
                else:
                    raise CompileError(f"date_add unit {unit!r} not supported")
                if src_t == DATE:
                    return CVal(out_days.astype(out_dt), v.valid & amt.valid)
                return CVal((out_days * 86_400_000_000).astype(out_dt), v.valid & amt.valid)

            return add_fn, None

        # date_diff(unit, a, b) = number of unit boundaries from a to b
        a_fn, _ = self.compile(expr.args[1])
        b_fn, _ = self.compile(expr.args[2])
        at, bt = expr.args[1].type, expr.args[2].type

        def diff_fn(env: Env) -> CVal:
            va, vb = a_fn(env), b_fn(env)
            da = _days_of(va.data, at).astype(jnp.int64)
            db = _days_of(vb.data, bt).astype(jnp.int64)
            if unit == "day":
                out = db - da
            elif unit == "week":
                out = (db - da) // 7
            elif unit in ("month", "quarter", "year"):
                ya, ma, _ = _civil_from_days(da)
                yb, mb, _ = _civil_from_days(db)
                months = (yb * 12 + mb) - (ya * 12 + ma)
                out = months // {"month": 1, "quarter": 3, "year": 12}[unit]
            else:
                raise CompileError(f"date_diff unit {unit!r} not supported")
            return CVal(out.astype(out_dt), va.valid & vb.valid)

        return diff_fn, None

    # ------------------------------------------------ string specializations

    def _dict_of(self, expr: IrExpr) -> Optional[Dictionary]:
        if isinstance(expr, Reference):
            lay = self.layout.get(expr.symbol)
            return lay.dictionary if lay else None
        if isinstance(expr, CastExpr):
            return self._dict_of(expr.value)
        if isinstance(expr, (Call, Case, Constant)):
            # computed string expressions (substr(col, ...), CASE ... END) and
            # string literals carry their output dictionary from compilation
            from ..spi.types import is_string

            if is_string(expr.type):
                _, out_dict = self.compile(expr)
                return out_dict
        return None

    def _compile_string_comparison(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        name = expr.name
        a, b = expr.args
        # normalize: column <op> constant
        if isinstance(a, Constant) and not isinstance(b, Constant):
            flip = {"$lt": "$gt", "$lte": "$gte", "$gt": "$lt", "$gte": "$lte"}
            name = flip.get(name, name)
            a, b = b, a
        if isinstance(b, Constant):
            d = self._dict_of(a)
            if d is None:
                raise CompileError("string comparison requires a dictionary column")
            inner, _ = self.compile(a)
            s = b.value
            if name in ("$eq", "$ne"):
                code = d.code_of(s) if s is not None else -1

                def eq_fn(env: Env) -> CVal:
                    v = inner(env)
                    if s is None:
                        return CVal(
                            jnp.zeros((self.capacity,), dtype=jnp.bool_),
                            jnp.zeros((self.capacity,), dtype=jnp.bool_),
                        )
                    res = v.data == code
                    if name == "$ne":
                        res = ~res
                    return CVal(res, v.valid)

                return eq_fn, None
            # range ops via sorted-dictionary searchsorted
            lo_left = d.searchsorted(s, "left")
            lo_right = d.searchsorted(s, "right")

            def range_fn(env: Env) -> CVal:
                v = inner(env)
                if name == "$lt":
                    res = v.data < lo_left
                elif name == "$lte":
                    res = v.data < lo_right
                elif name == "$gt":
                    res = v.data >= lo_right
                else:  # $gte
                    res = v.data >= lo_left
                return CVal(res, v.valid)

            return range_fn, None

        # column vs column
        da, db = self._dict_of(a), self._dict_of(b)
        fa, _ = self.compile(a)
        fb, _ = self.compile(b)
        if da is None or db is None:
            raise CompileError("string comparison requires dictionary columns")
        if da is db:

            def samecmp_fn(env: Env) -> CVal:
                va, vb = fa(env), fb(env)
                res = _compare(name, va.data, vb.data)
                return CVal(res, va.valid & vb.valid)

            return samecmp_fn, None
        if name in ("$eq", "$ne"):
            # translate codes of A into codes of B (exact-match LUT, -1 = no match)
            lut = np.array([db.code_of(s) for s in da.values], dtype=np.int32)

            def xdict_eq_fn(env: Env) -> CVal:
                va, vb = fa(env), fb(env)
                lut_dev = jnp.asarray(lut)
                mapped = lut_dev[jnp.clip(va.data, 0, lut_dev.shape[0] - 1)]
                res = (mapped == vb.data) & (mapped >= 0)
                if name == "$ne":
                    res = ~res
                return CVal(res, va.valid & vb.valid)

            return xdict_eq_fn, None
        raise CompileError(
            "ordering comparison across different dictionaries not supported yet"
        )

    def _compile_like(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        value = expr.args[0]
        pattern = expr.args[1]
        escape = expr.args[2].value if len(expr.args) > 2 else None
        if not isinstance(pattern, Constant):
            raise CompileError("LIKE pattern must be constant")
        d = self._dict_of(value)
        if d is None:
            raise CompileError("LIKE requires a dictionary column")
        inner, _ = self.compile(value)
        rx = _like_to_regex(pattern.value, escape)
        lut_np = np.fromiter(
            (rx.fullmatch(s) is not None for s in d.values), dtype=np.bool_, count=len(d)
        )

        def like_fn(env: Env) -> CVal:
            v = inner(env)
            lut_dev = jnp.asarray(lut_np)
            codes = jnp.clip(v.data, 0, lut_dev.shape[0] - 1)
            return CVal(lut_dev[codes], v.valid)

        return like_fn, None

    def _compile_concat(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        """String concat over constants + up to two dictionary columns: the
        output vocabulary is the (product) dictionary, computed host-side once;
        the device maps codes through an int LUT (ref operator/scalar/
        ConcatFunction — per-row Slice concat becomes O(|vocab|))."""
        dyn = [i for i, a in enumerate(expr.args) if not isinstance(a, Constant)]
        consts = {
            i: a.value for i, a in enumerate(expr.args) if isinstance(a, Constant)
        }
        if len(dyn) == 0:
            # all-constant: fold here (the planner's constant folder covers
            # arithmetic only)
            if any(v is None for v in consts.values()):
                return self.compile(Constant(expr.type, None))
            folded = "".join(str(consts[i]) for i in range(len(expr.args)))
            return self.compile(Constant(expr.type, folded))
        dicts = {i: self._dict_of(expr.args[i]) for i in dyn}
        if any(d is None for d in dicts.values()):
            raise CompileError("concat requires dictionary-coded string columns")
        if len(dyn) > 2:
            raise CompileError("concat over 3+ non-constant strings not supported yet")
        sizes = [len(dicts[i]) for i in dyn]
        if len(dyn) == 2 and sizes[0] * sizes[1] > 1 << 16:
            raise CompileError(
                f"concat product vocabulary too large ({sizes[0]}x{sizes[1]})"
            )

        def render(codes_vals):  # dict arg-index -> string value
            parts = []
            for i in range(len(expr.args)):
                v = codes_vals.get(i) if i in dicts else consts.get(i)
                if v is None:
                    return None
                parts.append(str(v))
            return "".join(parts)

        if len(dyn) == 1:
            i0 = dyn[0]
            new_values = [render({i0: s}) for s in dicts[i0].values]
        else:
            i0, i1 = dyn
            new_values = [
                render({i0: s0, i1: s1})
                for s0 in dicts[i0].values
                for s1 in dicts[i1].values
            ]
        out_dict, lut_np = _build_code_lut(new_values)
        fns = [self.compile(expr.args[i])[0] for i in dyn]
        n1 = sizes[1] if len(dyn) == 2 else 1

        def concat_fn(env: Env) -> CVal:
            vals = [f(env) for f in fns]
            lut = jnp.asarray(lut_np)
            if len(vals) == 1:
                pair = vals[0].data
                valid = vals[0].valid
            else:
                pair = vals[0].data.astype(jnp.int32) * n1 + vals[1].data
                valid = vals[0].valid & vals[1].valid
            codes = lut[jnp.clip(pair, 0, lut.shape[0] - 1)]
            return CVal(jnp.maximum(codes, 0), valid & (codes >= 0), out_dict)

        return concat_fn, out_dict

    def _compile_string_function(self, expr: Call) -> Tuple[Compiled, Optional[Dictionary]]:
        """String functions via host dictionary transform + device code remap.

        The transform runs once per (function, dictionary) at compile time:
        new_values = f(dict.values); output dictionary is the sorted unique set and
        a code LUT maps old codes -> new codes. (Trino evaluates per row via Slice
        ops — operator/scalar/StringFunctions.java; dictionaries make it O(|dict|).)
        """
        name = expr.name
        if name == "concat":
            return self._compile_concat(expr)
        value = expr.args[0]
        d = self._dict_of(value)
        if name in ("length", "char_length", "character_length") and d is not None:
            inner, _ = self.compile(value)
            lut_np = np.array([len(s) for s in d.values], dtype=np.int64)

            def length_fn(env: Env) -> CVal:
                v = inner(env)
                lut = jnp.asarray(lut_np)
                return CVal(lut[jnp.clip(v.data, 0, lut.shape[0] - 1)], v.valid)

            return length_fn, None
        if name == "codepoint" and d is not None:
            inner, _ = self.compile(value)
            lut_np = np.array(
                [ord(s[0]) if s else 0 for s in d.values], dtype=np.int64
            )

            def codepoint_fn(env: Env) -> CVal:
                v = inner(env)
                lut = jnp.asarray(lut_np)
                return CVal(lut[jnp.clip(v.data, 0, lut.shape[0] - 1)], v.valid)

            return codepoint_fn, None
        if name in _STRING_ARRAY_LUTS and d is not None:
            # string -> array<varchar> via a [vocab, W] code LUT: the parts
            # of every dictionary value are computed once on host, a child
            # dictionary is built from their union, and each row gathers its
            # value's code lanes (ref: StringFunctions.split / regexp family
            # — per-row loops there, one dictionary pass here)
            fn_ = _STRING_ARRAY_LUTS[name]
            cargs = []
            for a in expr.args[1:]:
                if not isinstance(a, Constant):
                    raise CompileError(f"{name}: arguments must be constant")
                cargs.append(a.value)
            parts: List[Optional[List[str]]] = []
            for s in d.values:
                try:
                    parts.append([p for p in fn_(s, *cargs)])
                except Exception:  # noqa: BLE001 — per-value failure -> NULL
                    parts.append(None)
            w = max((len(p) for p in parts if p is not None), default=1) or 1
            vocab = sorted({p for ps in parts if ps is not None for p in ps})
            child = Dictionary(np.asarray(vocab, dtype=object))
            code_of = {s: c for c, s in enumerate(vocab)}
            codes_np = np.zeros((len(parts), w), dtype=np.int32)
            len_np = np.zeros((len(parts),), dtype=np.int32)
            ok_np = np.zeros((len(parts),), dtype=np.bool_)
            for i, ps in enumerate(parts):
                if ps is None:
                    continue
                ok_np[i] = True
                len_np[i] = len(ps)
                for j, p in enumerate(ps):
                    codes_np[i, j] = code_of[p]
            inner, _ = self.compile(value)

            def split_fn(env: Env) -> CVal:
                v = inner(env)
                idx = jnp.clip(v.data, 0, len(parts) - 1)
                data = jnp.asarray(codes_np)[idx]
                lengths = jnp.asarray(len_np)[idx]
                ok = jnp.asarray(ok_np)[idx]
                ev = jnp.arange(w)[None, :] < lengths[:, None]
                return CVal(data, v.valid & ok, child, lengths, ev)

            return split_fn, child
        if name in _STRING_INT_LUTS and d is not None:
            fn_, dtype_ = _STRING_INT_LUTS[name]
            cargs = []
            for a in expr.args[1:]:
                if not isinstance(a, Constant):
                    raise CompileError(f"{name}: non-leading args must be constant")
                cargs.append(a.value)
            vals = []
            for sv in d.values:
                try:
                    vals.append(fn_(sv, *cargs))
                except Exception:  # noqa: BLE001 — per-value failures -> NULL
                    vals.append(None)
            lut_np = np.array(
                [(-1 if v is None else (int(v) if dtype_ != np.bool_ else bool(v)))
                 for v in vals],
                dtype=np.int64 if dtype_ != np.bool_ else np.bool_,
            )
            null_np = np.array([v is None for v in vals], dtype=np.bool_)
            inner, _ = self.compile(value)

            def slut_fn(env: Env) -> CVal:
                v = inner(env)
                codes = jnp.clip(v.data, 0, lut_np.shape[0] - 1)
                out = jnp.asarray(lut_np)[codes]
                bad = jnp.asarray(null_np)[codes]
                return CVal(out, v.valid & ~bad)

            return slut_fn, None
        if name in ("levenshtein_distance", "hamming_distance") and d is not None:
            other = expr.args[1]
            if not isinstance(other, Constant):
                raise CompileError(f"{name}: second argument must be constant")
            ref = other.value or ""

            def lev(a: str, b: str) -> int:
                prev = list(range(len(b) + 1))
                for i, ca in enumerate(a, 1):
                    cur = [i]
                    for j, cb in enumerate(b, 1):
                        cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                                       prev[j - 1] + (ca != cb)))
                    prev = cur
                return prev[-1]

            if name == "hamming_distance":
                vals = [
                    sum(a != b for a, b in zip(s, ref)) if len(s) == len(ref) else -1
                    for s in d.values
                ]
            else:
                vals = [lev(s, ref) for s in d.values]
            lut_np = np.array(vals, dtype=np.int64)
            inner, _ = self.compile(value)

            def dist_fn(env: Env) -> CVal:
                v = inner(env)
                lut = jnp.asarray(lut_np)
                out = lut[jnp.clip(v.data, 0, lut.shape[0] - 1)]
                # hamming over unequal lengths raises in the reference; NULL here
                return CVal(out, v.valid & (out >= 0))

            return dist_fn, None
        if name == "strpos" and d is not None:
            sub = expr.args[1]
            if not isinstance(sub, Constant):
                raise CompileError("strpos needle must be constant")
            inner, _ = self.compile(value)
            lut_np = np.array([s.find(sub.value) + 1 for s in d.values], dtype=np.int64)

            def strpos_fn(env: Env) -> CVal:
                v = inner(env)
                lut = jnp.asarray(lut_np)
                return CVal(lut[jnp.clip(v.data, 0, lut.shape[0] - 1)], v.valid)

            return strpos_fn, None
        if name == "starts_with" and d is not None:
            prefix = expr.args[1]
            if not isinstance(prefix, Constant):
                raise CompileError("starts_with prefix must be constant")
            inner, _ = self.compile(value)
            # prefix predicate == one searchsorted range on the sorted dictionary
            lo = d.searchsorted(prefix.value, "left")
            hi = d.searchsorted(prefix.value + "￿", "right")

            def sw_fn(env: Env) -> CVal:
                v = inner(env)
                return CVal((v.data >= lo) & (v.data < hi), v.valid)

            return sw_fn, None
        if name == "regexp_like" and d is not None:
            # regex predicate as a boolean LUT over the dictionary — the host
            # regex engine runs O(|dict|) once at compile time (ref: Trino's
            # joni matcher runs per ROW; dictionaries collapse that)
            pattern = expr.args[1]
            if not isinstance(pattern, Constant):
                raise CompileError("regexp_like pattern must be constant")
            rx = re.compile(pattern.value)
            inner, _ = self.compile(value)
            lut_np = np.fromiter(
                (rx.search(s) is not None for s in d.values),
                dtype=np.bool_,
                count=len(d),
            )

            def rxlike_fn(env: Env) -> CVal:
                v = inner(env)
                lut = jnp.asarray(lut_np)
                return CVal(lut[jnp.clip(v.data, 0, lut.shape[0] - 1)], v.valid)

            return rxlike_fn, None
        if name in ("json_array_length", "json_size", "json_array_contains") and d is not None:
            # JSON functions with non-string outputs: a typed LUT + a validity
            # LUT (NULL results) computed once over the dictionary
            import json as _json

            cargs = []
            for a in expr.args[1:]:
                if not isinstance(a, Constant):
                    raise CompileError(f"{name}: arguments must be constant")
                val = a.value
                if isinstance(a.type, DecimalType) and val is not None:
                    val = val / 10**a.type.scale
                cargs.append(val)
            if any(v is None for v in cargs):
                return self.compile(Constant(expr.type, None))  # SQL NULL arg

            def compute(s):
                if name == "json_array_length":
                    try:
                        v = _json.loads(s)
                    except (ValueError, TypeError):
                        return None
                    return len(v) if isinstance(v, list) else None
                if name == "json_size":
                    v = _json_eval(s, _parse_json_path(cargs[0]))
                    if v is _MISSING:
                        return None
                    return len(v) if isinstance(v, (dict, list)) else 0
                # json_array_contains — type-strict per the reference: JSON
                # true is not the number 1, and bigints compare exactly
                try:
                    v = _json.loads(s)
                except (ValueError, TypeError):
                    return None
                if not isinstance(v, list):
                    return None
                needle = cargs[0]

                def hit(x):
                    if isinstance(needle, bool):
                        return isinstance(x, bool) and x == needle
                    if isinstance(needle, (int, float)):
                        return (
                            isinstance(x, (int, float))
                            and not isinstance(x, bool)
                            and x == needle
                        )
                    return isinstance(x, str) and x == needle

                return any(hit(x) for x in v)

            results = [compute(s) for s in d.values]
            out_np_t = np.bool_ if name == "json_array_contains" else np.int64
            lut_np = np.array([r if r is not None else 0 for r in results], dtype=out_np_t)
            ok_np = np.array([r is not None for r in results], dtype=np.bool_)
            inner, _ = self.compile(value)

            def jsonlut_fn(env: Env) -> CVal:
                v = inner(env)
                lut = jnp.asarray(lut_np)
                ok = jnp.asarray(ok_np)
                codes = jnp.clip(v.data, 0, lut.shape[0] - 1)
                return CVal(lut[codes], v.valid & ok[codes])

            return jsonlut_fn, None

        if d is None:
            raise CompileError(f"{name} requires a dictionary column")

        transform = _STRING_FUNCS[name]
        args = []
        for a in expr.args[1:]:
            if not isinstance(a, Constant):
                raise CompileError(f"{name}: non-leading arguments must be constant")
            args.append(a.value)
        if any(v is None for v in args):
            return self.compile(Constant(expr.type, None))  # SQL NULL argument
        new_values = [transform(s, *args) for s in d.values]
        # transforms may produce SQL NULL (e.g. regexp_extract with no match):
        # those map to code -1 and invalidate the row
        out_dict, lut_np = _build_code_lut(new_values)
        inner, _ = self.compile(value)

        def transform_fn(env: Env) -> CVal:
            v = inner(env)
            lut = jnp.asarray(lut_np)
            codes = lut[jnp.clip(v.data, 0, lut.shape[0] - 1)]
            return CVal(
                jnp.maximum(codes, 0), v.valid & (codes >= 0), out_dict
            )

        return transform_fn, out_dict


# --------------------------------------------------------------------------- #
# lowering tables
# --------------------------------------------------------------------------- #


def _cmp_norm(x, t: Type):
    """Comparison key: TIMESTAMP WITH TIME ZONE compares by INSTANT — strip
    the packed zone key (the reference's TTZ comparison operators likewise
    operate on unpackMillisUtc)."""
    from ..spi.types import TimestampWithTimeZoneType, TimeWithTimeZoneType

    if isinstance(t, (TimestampWithTimeZoneType, TimeWithTimeZoneType)):
        return x >> 12  # both pack the UTC-normalized instant in the high bits
    return x


def _cmp_op(name: str):
    """Comparison lowering; long decimals (Int128 limbs) compare limb-wise
    (planner coercions guarantee both sides share type + scale)."""

    def impl(datas, arg_types, out_type):
        from ..spi.types import is_long_decimal

        a, b = datas
        at, bt = arg_types
        if is_long_decimal(at) or is_long_decimal(bt):
            from . import int128 as i128

            A = a if is_long_decimal(at) else i128.from_int64(a)
            B = b if is_long_decimal(bt) else i128.from_int64(b)
            return {
                "$eq": lambda: i128.eq(A, B),
                "$ne": lambda: ~i128.eq(A, B),
                "$lt": lambda: i128.lt(A, B),
                "$lte": lambda: i128.lte(A, B),
                "$gt": lambda: i128.lt(B, A),
                "$gte": lambda: i128.lte(B, A),
            }[name]()
        return _compare(name, _cmp_norm(a, at), _cmp_norm(b, bt))

    return impl


def _compare(name: str, a, b):
    return {
        "$eq": lambda: a == b,
        "$ne": lambda: a != b,
        "$lt": lambda: a < b,
        "$lte": lambda: a <= b,
        "$gt": lambda: a > b,
        "$gte": lambda: a >= b,
    }[name]()


def _wilson(d, lower: bool):
    """Wilson score interval bound (ref: scalar/WilsonInterval.java)."""
    n_s = d[0].astype(jnp.float64)
    n = d[1].astype(jnp.float64)
    z = d[2].astype(jnp.float64)
    p = n_s / jnp.maximum(n, 1.0)
    z2 = z * z
    denom = 1.0 + z2 / jnp.maximum(n, 1.0)
    center = p + z2 / (2.0 * jnp.maximum(n, 1.0))
    spread = z * jnp.sqrt(
        (p * (1.0 - p) + z2 / (4.0 * jnp.maximum(n, 1.0))) / jnp.maximum(n, 1.0)
    )
    return (center - spread if lower else center + spread) / denom


def _lane_aware_negate(d, t, o):
    from ..spi.types import is_long_decimal

    if is_long_decimal(t[0]):
        from . import int128 as i128

        return i128.negate(d[0])
    return -d[0]


def _lane_aware_abs(d, t, o):
    from ..spi.types import is_long_decimal

    if is_long_decimal(t[0]):
        from . import int128 as i128

        return i128.abs_(d[0])
    return jnp.abs(d[0])


def _div_round(x, divisor: int):
    """Round-half-up integer division (Trino decimal rescale semantics)."""
    half = divisor // 2
    return jnp.where(x >= 0, (x + half) // divisor, -((-x + half) // divisor))


def _civil_from_days(z):
    """days-since-epoch -> (year, month, day); Howard Hinnant's algorithm,
    branch-free and integer-only (MXU/VPU friendly)."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _arith(name):
    def impl(datas, arg_types, out_type):
        from ..spi.types import is_long_decimal

        a, b = datas
        at, bt = arg_types
        if is_long_decimal(out_type) or is_long_decimal(at) or is_long_decimal(bt):
            from . import int128 as i128

            A = a if is_long_decimal(at) else i128.from_int64(a)
            B = b if is_long_decimal(bt) else i128.from_int64(b)
            if name == "$add":
                return i128.add(A, B)
            if name == "$subtract":
                return i128.sub(A, B)
            if name == "$multiply":
                return i128.mul(A, B)
            raise CompileError(
                f"{name} on DECIMAL(p>18) not supported yet "
                "(ref Int128Math.divideRoundUp)"
            )
        # date/timestamp +- interval
        if at == DATE and bt == INTERVAL_DAY_TIME:
            days = b // 86_400_000_000
            return (a + days if name == "$add" else a - days).astype(jnp.int32)
        if at == DATE and bt == DATE and name == "$subtract":
            return (a.astype(jnp.int64) - b.astype(jnp.int64)) * 86_400_000_000
        if at == DATE and bt == INTERVAL_YEAR_MONTH:
            raise CompileError(
                "date +/- year-month interval over columns not supported yet "
                "(constant-folded when both sides are literals)"
            )
        if name == "$add":
            return a + b
        if name == "$subtract":
            return a - b
        if name == "$multiply":
            out = a * b
            # decimal x decimal already correct: scales add
            return out
        if name == "$divide":
            if is_integral(out_type):
                return jnp.floor_divide(jnp.abs(a), jnp.abs(b).clip(1)) * (
                    jnp.sign(a) * jnp.sign(b)
                )
            return a / b
        if name == "$modulus":
            if isinstance(out_type, DecimalType) or is_integral(out_type):
                m = jnp.remainder(jnp.abs(a), jnp.abs(b).clip(1))
                return m * jnp.sign(a)
            return jnp.remainder(a, b)
        raise CompileError(name)

    return impl


def _binomial_cdf(trials, p, k):
    # P(X <= k) = I_{1-p}(n - k, k + 1)
    n = trials.astype(jnp.float64)
    kk = jnp.clip(jnp.floor(k.astype(jnp.float64)), -1.0, n)
    a = jnp.maximum(n - kk, 1e-12)
    b = kk + 1.0
    out = jax.scipy.special.betainc(a, b, 1.0 - p)
    return jnp.where(kk < 0, 0.0, jnp.where(kk >= n, 1.0, out))


def _f_cdf(df1, df2, x):
    return jax.scipy.special.betainc(
        df1 / 2.0, df2 / 2.0, df1 * x / (df1 * x + df2)
    )


def _laplace_cdf(mean, scale, x):
    z = (x - mean) / scale
    return jnp.where(z < 0, 0.5 * jnp.exp(z), 1.0 - 0.5 * jnp.exp(-z))


def _inverse_laplace_cdf(mean, scale, p):
    return jnp.where(
        p < 0.5,
        mean + scale * jnp.log(2.0 * p),
        mean - scale * jnp.log(2.0 - 2.0 * p),
    )


def _t_cdf(df, x):
    ib = jax.scipy.special.betainc(df / 2.0, 0.5, df / (df + x * x))
    return jnp.where(x < 0, 0.5 * ib, 1.0 - 0.5 * ib)


def _t_pdf(df, x):
    from jax.scipy.special import gammaln

    logc = (
        gammaln((df + 1.0) / 2.0)
        - gammaln(df / 2.0)
        - 0.5 * jnp.log(df * jnp.pi)
    )
    return jnp.exp(logc - ((df + 1.0) / 2.0) * jnp.log1p(x * x / df))


def _inverse_beta_cdf(a, b, p):
    if not hasattr(jax.scipy.special, "betaincinv"):
        raise CompileError(
            "inverse_beta_cdf needs jax.scipy.special.betaincinv "
            "(unavailable in this jax build)"
        )
    return jax.scipy.special.betaincinv(a, b, p)


_SIMPLE_FUNCS: Dict[str, Callable] = {
    "$add": _arith("$add"),
    "$subtract": _arith("$subtract"),
    "$multiply": _arith("$multiply"),
    "$divide": _arith("$divide"),
    "$modulus": _arith("$modulus"),
    "$negate": _lane_aware_negate,
    "$eq": _cmp_op("$eq"),
    "$ne": _cmp_op("$ne"),
    "$lt": _cmp_op("$lt"),
    "$lte": _cmp_op("$lte"),
    "$gt": _cmp_op("$gt"),
    "$gte": _cmp_op("$gte"),
    "abs": _lane_aware_abs,
    "log": lambda d, t, o: jnp.log(_to_f64(d[1], t[1])) / jnp.log(_to_f64(d[0], t[0])),
    "normal_cdf": lambda d, t, o: 0.5 * (
        1.0 + jax.scipy.special.erf(
            (_to_f64(d[2], t[2]) - _to_f64(d[0], t[0]))
            / (_to_f64(d[1], t[1]) * jnp.sqrt(2.0))
        )
    ),
    "inverse_normal_cdf": lambda d, t, o: _to_f64(d[0], t[0]) + _to_f64(d[1], t[1])
    * jax.scipy.special.ndtri(_to_f64(d[2], t[2])),
    "beta_cdf": lambda d, t, o: jax.scipy.special.betainc(
        _to_f64(d[0], t[0]), _to_f64(d[1], t[1]), _to_f64(d[2], t[2])
    ),
    "wilson_interval_lower": lambda d, t, o: _wilson(
        [_to_f64(x, tt) for x, tt in zip(d, t)], lower=True
    ),
    "wilson_interval_upper": lambda d, t, o: _wilson(
        [_to_f64(x, tt) for x, tt in zip(d, t)], lower=False
    ),
    "timezone_hour": lambda d, t, o: jax.lax.div(
        ((d[0] & 0xFFF) - 841).astype(jnp.int64), jnp.int64(60)
    ),
    "timezone_minute": lambda d, t, o: jax.lax.rem(
        ((d[0] & 0xFFF) - 841).astype(jnp.int64), jnp.int64(60)
    ),
    "ceiling": lambda d, t, o: _decimal_ceil(d[0], t[0]) if isinstance(t[0], DecimalType) else jnp.ceil(d[0]),
    "ceil": lambda d, t, o: _decimal_ceil(d[0], t[0]) if isinstance(t[0], DecimalType) else jnp.ceil(d[0]),
    "floor": lambda d, t, o: _decimal_floor(d[0], t[0]) if isinstance(t[0], DecimalType) else jnp.floor(d[0]),
    "round": lambda d, t, o: jnp.round(d[0]) if len(d) == 1 else _round_n(d[0], d[1]),
    "sqrt": lambda d, t, o: jnp.sqrt(_to_f64(d[0], t[0])),
    "cbrt": lambda d, t, o: jnp.cbrt(_to_f64(d[0], t[0])),
    "exp": lambda d, t, o: jnp.exp(_to_f64(d[0], t[0])),
    "ln": lambda d, t, o: jnp.log(_to_f64(d[0], t[0])),
    "log2": lambda d, t, o: jnp.log2(_to_f64(d[0], t[0])),
    "log10": lambda d, t, o: jnp.log10(_to_f64(d[0], t[0])),
    "power": lambda d, t, o: jnp.power(_to_f64(d[0], t[0]), _to_f64(d[1], t[1])),
    "pow": lambda d, t, o: jnp.power(_to_f64(d[0], t[0]), _to_f64(d[1], t[1])),
    "mod": _arith("$modulus"),
    "sign": lambda d, t, o: jnp.sign(d[0]),
    "sin": lambda d, t, o: jnp.sin(_to_f64(d[0], t[0])),
    "cos": lambda d, t, o: jnp.cos(_to_f64(d[0], t[0])),
    "tan": lambda d, t, o: jnp.tan(_to_f64(d[0], t[0])),
    "asin": lambda d, t, o: jnp.arcsin(_to_f64(d[0], t[0])),
    "acos": lambda d, t, o: jnp.arccos(_to_f64(d[0], t[0])),
    "atan": lambda d, t, o: jnp.arctan(_to_f64(d[0], t[0])),
    "atan2": lambda d, t, o: jnp.arctan2(_to_f64(d[0], t[0]), _to_f64(d[1], t[1])),
    "greatest": lambda d, t, o: _nary(jnp.maximum, d),
    "least": lambda d, t, o: _nary(jnp.minimum, d),
    "year": lambda d, t, o: _civil_from_days(_days_of(d[0], t[0]))[0],
    "month": lambda d, t, o: _civil_from_days(_days_of(d[0], t[0]))[1],
    "day": lambda d, t, o: _civil_from_days(_days_of(d[0], t[0]))[2],
    "quarter": lambda d, t, o: (_civil_from_days(_days_of(d[0], t[0]))[1] + 2) // 3,
    "day_of_week": lambda d, t, o: jnp.remainder(_days_of(d[0], t[0]) + 3, 7) + 1,
    "day_of_year": lambda d, t, o: _day_of_year(_days_of(d[0], t[0])),
    "hour": lambda d, t, o: _micros_of_day(d[0], t[0]) // 3_600_000_000,
    "minute": lambda d, t, o: (_micros_of_day(d[0], t[0]) // 60_000_000) % 60,
    "second": lambda d, t, o: (_micros_of_day(d[0], t[0]) // 1_000_000) % 60,
    "millisecond": lambda d, t, o: (_micros_of_day(d[0], t[0]) // 1000) % 1000,
    "hash64": lambda d, t, o: _hash64_combine(d),
    # math long tail (operator/scalar/MathFunctions.java)
    "cot": lambda d, t, o: 1.0 / jnp.tan(_to_f64(d[0], t[0])),
    "bitwise_right_shift_arithmetic": lambda d, t, o: d[0].astype(jnp.int64)
    >> jnp.clip(d[1].astype(jnp.int64), 0, 63),
    "to_milliseconds": lambda d, t, o: d[0].astype(jnp.int64) // 1000,
    "date": lambda d, t, o: _days_of(d[0], t[0]).astype(jnp.int32),
    "from_unixtime_nanos": lambda d, t, o: d[0].astype(jnp.int64) // 1000,
    # try(): the engine's error channel is already NULL-on-failure
    # (division guards, LUT per-value exceptions), so try is a passthrough
    "try": lambda d, t, o: d[0],
    # probability distributions (MathFunctions.java CDF family; closed
    # forms / regularized incomplete gamma+beta via jax.scipy.special)
    "binomial_cdf": lambda d, t, o: _binomial_cdf(
        d[0], _to_f64(d[1], t[1]), d[2]
    ),
    "cauchy_cdf": lambda d, t, o: 0.5
    + jnp.arctan(
        (_to_f64(d[2], t[2]) - _to_f64(d[0], t[0])) / _to_f64(d[1], t[1])
    )
    / jnp.pi,
    "inverse_cauchy_cdf": lambda d, t, o: _to_f64(d[0], t[0])
    + _to_f64(d[1], t[1]) * jnp.tan(jnp.pi * (_to_f64(d[2], t[2]) - 0.5)),
    "chi_squared_cdf": lambda d, t, o: jax.scipy.special.gammainc(
        _to_f64(d[0], t[0]) / 2.0, _to_f64(d[1], t[1]) / 2.0
    ),
    "f_cdf": lambda d, t, o: _f_cdf(
        _to_f64(d[0], t[0]), _to_f64(d[1], t[1]), _to_f64(d[2], t[2])
    ),
    "gamma_cdf": lambda d, t, o: jax.scipy.special.gammainc(
        _to_f64(d[0], t[0]), _to_f64(d[2], t[2]) / _to_f64(d[1], t[1])
    ),
    "laplace_cdf": lambda d, t, o: _laplace_cdf(
        _to_f64(d[0], t[0]), _to_f64(d[1], t[1]), _to_f64(d[2], t[2])
    ),
    "inverse_laplace_cdf": lambda d, t, o: _inverse_laplace_cdf(
        _to_f64(d[0], t[0]), _to_f64(d[1], t[1]), _to_f64(d[2], t[2])
    ),
    "poisson_cdf": lambda d, t, o: jax.scipy.special.gammaincc(
        _to_f64(d[1], t[1]) + 1.0, _to_f64(d[0], t[0])
    ),
    "weibull_cdf": lambda d, t, o: 1.0
    - jnp.exp(
        -jnp.power(
            _to_f64(d[2], t[2]) / _to_f64(d[1], t[1]), _to_f64(d[0], t[0])
        )
    ),
    "inverse_weibull_cdf": lambda d, t, o: _to_f64(d[1], t[1])
    * jnp.power(
        -jnp.log1p(-_to_f64(d[2], t[2])), 1.0 / _to_f64(d[0], t[0])
    ),
    "t_cdf": lambda d, t, o: _t_cdf(_to_f64(d[0], t[0]), _to_f64(d[1], t[1])),
    "t_pdf": lambda d, t, o: _t_pdf(_to_f64(d[0], t[0]), _to_f64(d[1], t[1])),
    "inverse_beta_cdf": lambda d, t, o: _inverse_beta_cdf(
        _to_f64(d[0], t[0]), _to_f64(d[1], t[1]), _to_f64(d[2], t[2])
    ),
    "degrees": lambda d, t, o: jnp.degrees(_to_f64(d[0], t[0])),
    "radians": lambda d, t, o: jnp.radians(_to_f64(d[0], t[0])),
    "cosh": lambda d, t, o: jnp.cosh(_to_f64(d[0], t[0])),
    "sinh": lambda d, t, o: jnp.sinh(_to_f64(d[0], t[0])),
    "tanh": lambda d, t, o: jnp.tanh(_to_f64(d[0], t[0])),
    "is_nan": lambda d, t, o: jnp.isnan(_to_f64(d[0], t[0])),
    "is_finite": lambda d, t, o: jnp.isfinite(_to_f64(d[0], t[0])),
    "is_infinite": lambda d, t, o: jnp.isinf(_to_f64(d[0], t[0])),
    "truncate": lambda d, t, o: jnp.trunc(_to_f64(d[0], t[0])) if len(d) == 1
    else _truncate_n(d[0], d[1], t[0]),
    "width_bucket": lambda d, t, o: _width_bucket(
        _to_f64(d[0], t[0]), _to_f64(d[1], t[1]), _to_f64(d[2], t[2]), d[3]
    ),
    # bitwise family (operator/scalar/BitwiseFunctions.java; two's-complement
    # int64 semantics like the reference)
    "bitwise_and": lambda d, t, o: d[0].astype(jnp.int64) & d[1].astype(jnp.int64),
    "bitwise_or": lambda d, t, o: d[0].astype(jnp.int64) | d[1].astype(jnp.int64),
    "bitwise_xor": lambda d, t, o: d[0].astype(jnp.int64) ^ d[1].astype(jnp.int64),
    "bitwise_not": lambda d, t, o: ~d[0].astype(jnp.int64),
    "bitwise_left_shift": lambda d, t, o: d[0].astype(jnp.int64)
    << jnp.clip(d[1].astype(jnp.int64), 0, 63),
    "bitwise_right_shift": lambda d, t, o: jax.lax.shift_right_logical(
        d[0].astype(jnp.int64), jnp.clip(d[1].astype(jnp.int64), 0, 63)
    ),
    "bit_count": lambda d, t, o: _bit_count(d[0].astype(jnp.int64), d[1] if len(d) > 1 else None),
    # datetime long tail (operator/scalar/DateTimeFunctions.java)
    "day_of_month": lambda d, t, o: _civil_from_days(_days_of(d[0], t[0]))[2],
    "dow": lambda d, t, o: jnp.remainder(_days_of(d[0], t[0]) + 3, 7) + 1,
    "doy": lambda d, t, o: _day_of_year(_days_of(d[0], t[0])),
    "week": lambda d, t, o: _iso_week_year(_days_of(d[0], t[0]))[0],
    "week_of_year": lambda d, t, o: _iso_week_year(_days_of(d[0], t[0]))[0],
    "year_of_week": lambda d, t, o: _iso_week_year(_days_of(d[0], t[0]))[1],
    "yow": lambda d, t, o: _iso_week_year(_days_of(d[0], t[0]))[1],
    "last_day_of_month": lambda d, t, o: _last_day_of_month(_days_of(d[0], t[0])),
}


def _truncate_n(x, n, t):
    scale = jnp.power(10.0, n.astype(jnp.float64))
    return jnp.trunc(_to_f64(x, t) * scale) / scale


def _width_bucket(x, lo, hi, n):
    nb = jnp.maximum(n.astype(jnp.int64), 1)
    frac = (x - lo) / jnp.where(hi != lo, hi - lo, 1.0)
    b = jnp.floor(frac * nb.astype(jnp.float64)).astype(jnp.int64) + 1
    return jnp.clip(b, 0, nb + 1)


def _bit_count(x, bits):
    # popcount via the SWAR ladder (no scalar loop — VPU friendly)
    v = x
    if bits is not None:
        width = jnp.clip(bits.astype(jnp.int64), 2, 64)
        mask = jnp.where(
            width >= 64, jnp.int64(-1), (jnp.int64(1) << width) - 1
        )
        v = v & mask
    c = v - (jax.lax.shift_right_logical(v, jnp.int64(1)) & jnp.int64(0x5555555555555555))
    c = (c & jnp.int64(0x3333333333333333)) + (
        jax.lax.shift_right_logical(c, jnp.int64(2)) & jnp.int64(0x3333333333333333)
    )
    c = (c + jax.lax.shift_right_logical(c, jnp.int64(4))) & jnp.int64(0x0F0F0F0F0F0F0F0F)
    return jax.lax.shift_right_logical(c * jnp.int64(0x0101010101010101), jnp.int64(56))


def _iso_week_year(days):
    """ISO-8601 week number and week-year (WeekOfWeekBasedYear/WeekBasedYear)."""
    y, m, d = _civil_from_days(days)
    doy = _day_of_year(days)
    dow = jnp.remainder(days.astype(jnp.int64) + 3, 7) + 1  # Mon=1..Sun=7
    w = (doy - dow + 10) // 7

    def weeks_in(yy):
        jan1 = _days_from_civil(yy, jnp.ones_like(yy), jnp.ones_like(yy))
        jd = jnp.remainder(jan1 + 3, 7) + 1
        leap = ((yy % 4 == 0) & (yy % 100 != 0)) | (yy % 400 == 0)
        return 52 + ((jd == 4) | (leap & (jd == 3))).astype(jnp.int64)

    week = jnp.where(w < 1, weeks_in(y - 1), jnp.where(w > weeks_in(y), 1, w))
    wyear = jnp.where(w < 1, y - 1, jnp.where(w > weeks_in(y), y + 1, y))
    return week, wyear


def _last_day_of_month(days):
    y, m, _ = _civil_from_days(days)
    ny = jnp.where(m == 12, y + 1, y)
    nm = jnp.where(m == 12, 1, m + 1)
    return (_days_from_civil(ny, nm, jnp.ones_like(nm)) - 1).astype(jnp.int32)


def _to_f64(x, t: Type):
    if isinstance(t, DecimalType):
        return x / float(10**t.scale)
    return x.astype(jnp.float64)


def _days_of(x, t: Type):
    from ..spi.types import TimestampWithTimeZoneType

    if t == DATE:
        return x
    if isinstance(t, TimestampWithTimeZoneType):
        # packed (utc_millis << 12 | zone_key): calendar fields read in the
        # value's own zone (the reference's unpackMillisUtc + zone rules)
        local_millis = (x >> 12) + ((x & 0xFFF) - 841) * 60_000
        return jnp.floor_divide(local_millis, 86_400_000)
    # timestamp micros -> days
    return jnp.floor_divide(x, 86_400_000_000)


def _micros_of_day(x, t: Type):
    from ..spi.types import TimeType, TimestampWithTimeZoneType, TimeWithTimeZoneType

    if isinstance(t, TimeType):
        return x
    if isinstance(t, TimeWithTimeZoneType):
        # packed UTC micros + offset -> LOCAL micros of day
        local = (x >> 12) + ((x & 0xFFF) - 841) * 60_000_000
        return jnp.remainder(local, 86_400_000_000)
    if isinstance(t, TimestampWithTimeZoneType):
        local_millis = (x >> 12) + ((x & 0xFFF) - 841) * 60_000
        return jnp.remainder(local_millis, 86_400_000) * 1000
    return jnp.remainder(x, 86_400_000_000)


def _day_of_year(days):
    y, _, _ = _civil_from_days(days)
    jan1 = _days_from_civil(y, 1, 1)
    return days.astype(jnp.int64) - jan1 + 1


def _days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _decimal_ceil(x, t: DecimalType):
    f = 10**t.scale
    return jnp.where(x >= 0, (x + f - 1) // f, -((-x) // f)) * f


def _decimal_floor(x, t: DecimalType):
    f = 10**t.scale
    return jnp.where(x >= 0, x // f, -((-x + f - 1) // f)) * f


def _round_n(x, n):
    p = jnp.power(10.0, n.astype(jnp.float64))
    return jnp.round(x * p) / p


def _nary(op, datas):
    out = datas[0]
    for d in datas[1:]:
        out = op(out, d)
    return out


def _hash64_combine(datas):
    """xxhash-style 64-bit mix for partitioning/join keys (the analogue of
    Trino's TypeOperators hash used by FlatHash/PagesHash)."""
    acc = jnp.uint64(0x9E3779B97F4A7C15)
    for d in datas:
        x = d.astype(jnp.uint64)
        x = (x ^ (x >> 33)) * jnp.uint64(0xFF51AFD7ED558CCD)
        x = (x ^ (x >> 33)) * jnp.uint64(0xC4CEB9FE1A85EC53)
        x = x ^ (x >> 33)
        acc = (acc ^ x) * jnp.uint64(0x100000001B3)
    return acc.astype(jnp.int64)


def _java_replacement_to_python(repl: str) -> str:
    """Java-style regex replacement ($N groups, backslash escapes the next
    char) -> Python re.sub template (backslash-group refs, literal backslashes
    doubled). A raw backslash handed to re.sub would raise 'bad escape'."""
    out = []
    i = 0
    while i < len(repl):
        ch = repl[i]
        if ch == "\\" and i + 1 < len(repl):
            nxt = repl[i + 1]
            out.append("\\\\" if nxt == "\\" else nxt)
            i += 2
            continue
        if ch == "$" and i + 1 < len(repl) and repl[i + 1].isdigit():
            j = i + 1
            while j < len(repl) and repl[j].isdigit():
                j += 1
            out.append("\\" + repl[i + 1 : j])
            i = j
            continue
        out.append("\\\\" if ch == "\\" else ch)
        i += 1
    return "".join(out)


# --------------------------------------------------------------------------- #
# JSON (ref: io.trino.operator.scalar.JsonFunctions + io.trino.jsonpath — the
# per-row jsonpath VM becomes a once-per-dictionary host transform here)
# --------------------------------------------------------------------------- #

_MISSING = object()


def _urlparse(s: str):
    from urllib.parse import urlparse

    try:
        return urlparse(s)
    except ValueError:
        return urlparse("")


import functools


@functools.lru_cache(maxsize=1024)
def _parse_json_path(path: str):
    """Parse the supported jsonpath subset: $, .field, ['field'], ["field"],
    [index]. Returns a list of ('field', name) / ('index', i) steps.
    Cached: transforms call this once per dictionary VALUE."""
    if not path.startswith("$"):
        raise CompileError(f"unsupported json path (must start with $): {path!r}")
    steps = []
    rest = path[1:]
    step_rx = re.compile(
        r"""^(?:
              \.(?P<dotted>[A-Za-z_][A-Za-z0-9_]*)
            | \[\s*(?P<index>-?\d+)\s*\]
            | \[\s*'(?P<sq>[^']*)'\s*\]
            | \[\s*"(?P<dq>[^"]*)"\s*\]
        )""",
        re.VERBOSE,
    )
    while rest:
        m = step_rx.match(rest)
        if m is None:
            raise CompileError(f"unsupported json path step at {rest!r}")
        if m.group("index") is not None:
            steps.append(("index", int(m.group("index"))))
        else:
            steps.append(
                ("field", m.group("dotted") or m.group("sq") or m.group("dq"))
            )
        rest = rest[m.end():]
    return tuple(steps)


def _build_code_lut(new_values):
    """Transformed dictionary values -> (output Dictionary, old-code -> new-code
    int32 LUT with -1 for SQL-NULL results). Shared by every dictionary
    transform (string functions, concat)."""
    uniq = sorted({s for s in new_values if s is not None})
    out_dict = Dictionary(np.asarray(uniq, dtype=object))
    code_map = {s: i for i, s in enumerate(uniq)}
    lut_np = np.array(
        [-1 if s is None else code_map[s] for s in new_values], dtype=np.int32
    )
    return out_dict, lut_np


def _json_eval(text, steps):
    """Evaluate parsed jsonpath steps; returns the python value or _MISSING."""
    import json as _json

    try:
        v = _json.loads(text)
    except (ValueError, TypeError):
        return _MISSING
    for kind, arg in steps:
        if kind == "field":
            if not isinstance(v, dict) or arg not in v:
                return _MISSING
            v = v[arg]
        else:
            if not isinstance(v, list):
                return _MISSING
            i = arg if arg >= 0 else len(v) + arg
            if not 0 <= i < len(v):
                return _MISSING
            v = v[i]
    return v


def _json_dumps(v) -> str:
    import json as _json

    return _json.dumps(v, separators=(",", ":"), ensure_ascii=False)


def _json_extract(s, path):
    v = _json_eval(s, _parse_json_path(path))
    return None if v is _MISSING else _json_dumps(v)


def _json_extract_scalar(s, path):
    v = _json_eval(s, _parse_json_path(path))
    if v is _MISSING or v is None or isinstance(v, (dict, list)):
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        return v
    return _json_dumps(v)


def _json_parse(s):
    import json as _json

    try:
        return _json_dumps(_json.loads(s))
    except (ValueError, TypeError):
        return None  # deviation: the reference raises on malformed JSON


def _json_array_get(s, idx):
    v = _json_eval(s, [("index", int(idx))])
    return None if v is _MISSING else _json_dumps(v)


def _json_eval_exists(s: str, path: str) -> bool:
    return _json_extract(s, path) is not None


def _null_on_error(fn):
    """Per-dictionary-value transform guard: a malformed value anywhere in
    the column must yield NULL for ITS rows, not abort the query (filtered
    rows still share the dictionary). Strictness note: from_base64 validates
    the alphabet — corrupt input becomes NULL, never silently-decoded data
    (the reference raises; NULL is this engine's documented error channel)."""

    def wrapped(s, *args):
        try:
            return fn(s, *args)
        except Exception:  # noqa: BLE001 — per-value failure -> NULL
            return None

    return wrapped


_STRING_FUNCS: Dict[str, Callable] = {
    "upper": lambda s: s.upper(),
    "lower": lambda s: s.lower(),
    "trim": lambda s: s.strip(),
    "ltrim": lambda s: s.lstrip(),
    "rtrim": lambda s: s.rstrip(),
    "substring": lambda s, start, length=None: (
        s[int(start) - 1 :] if length is None else s[int(start) - 1 : int(start) - 1 + int(length)]
    ),
    "substr": lambda s, start, length=None: (
        s[int(start) - 1 :] if length is None else s[int(start) - 1 : int(start) - 1 + int(length)]
    ),
    "replace": lambda s, find, repl="": s.replace(find, repl),
    "reverse": lambda s: s[::-1],
    "split_part": lambda s, delim, index: (
        (lambda parts, i: parts[i - 1] if 1 <= i <= len(parts) else None)(
            s.split(delim) if delim else [s], int(index)
        )
    ),
    "translate": lambda s, frm, to: s.translate(
        {ord(c): (to[i] if i < len(to) else None) for i, c in enumerate(frm)}
    ),
    "lpad": lambda s, n, fill=" ": (
        (fill * int(n))[: max(int(n) - len(s), 0)] + s if len(s) < int(n) else s[: int(n)]
    ),
    "rpad": lambda s, n, fill=" ": (
        s + (fill * int(n))[: max(int(n) - len(s), 0)] if len(s) < int(n) else s[: int(n)]
    ),
    "regexp_extract": lambda s, pattern, group=0: (
        (lambda m: m.group(int(group)) if m else None)(re.search(pattern, s))
    ),
    "regexp_replace": lambda s, pattern, repl="": re.sub(
        pattern, _java_replacement_to_python(repl), s
    ),
    "url_extract_protocol": lambda s: (_urlparse(s).scheme or None),
    "url_extract_host": lambda s: (_urlparse(s).hostname or None),
    "url_extract_path": lambda s: _urlparse(s).path,
    "url_extract_query": lambda s: (_urlparse(s).query or None),
    "url_extract_fragment": lambda s: (_urlparse(s).fragment or None),
    "url_extract_parameter": lambda s, name: (
        (lambda q: q.get(name, [None])[0])(
            __import__("urllib.parse", fromlist=["parse_qs"]).parse_qs(
                _urlparse(s).query, keep_blank_values=True
            )
        )
    ),
    "url_encode": lambda s: __import__("urllib.parse", fromlist=["quote"]).quote(
        s, safe=""
    ),
    # binary-family functions surface as lowercase-hex strings (the engine
    # has no varbinary lane; documented deviation from the reference's
    # varbinary returns in scalar/VarbinaryFunctions.java)
    "md5": lambda s: __import__("hashlib").md5(s.encode()).hexdigest(),
    "sha1": lambda s: __import__("hashlib").sha1(s.encode()).hexdigest(),
    "sha256": lambda s: __import__("hashlib").sha256(s.encode()).hexdigest(),
    "sha512": lambda s: __import__("hashlib").sha512(s.encode()).hexdigest(),
    "to_hex": lambda s: s.encode().hex().upper(),
    "from_hex": _null_on_error(
        lambda s: bytes.fromhex(s).decode("utf-8", "replace")
    ),
    "to_base64": lambda s: __import__("base64").b64encode(s.encode()).decode(),
    "from_base64": _null_on_error(
        lambda s: __import__("base64").b64decode(s, validate=True).decode(
            "utf-8", "replace"
        )
    ),
    "normalize": lambda s, form="NFC": __import__("unicodedata").normalize(
        str(form).upper(), s
    ),
    "url_decode": lambda s: __import__("urllib.parse", fromlist=["unquote"]).unquote(s),
    "soundex": lambda s: _soundex(s),
    "word_stem": lambda s, lang="en": _word_stem(s),
    "to_utf8": lambda s: s.encode().hex(),   # varbinary-as-hex (documented)
    "from_utf8": _null_on_error(lambda s: bytes.fromhex(s).decode("utf-8", "replace")),
    "xxhash64": lambda s: format(_xxhash64(s.encode()), "016x"),
    "murmur3": lambda s: _murmur3_128_hex(s.encode()),
    "hmac_md5": lambda s, key: __import__("hmac").new(
        str(key).encode(), s.encode(), "md5"
    ).hexdigest(),
    "hmac_sha1": lambda s, key: __import__("hmac").new(
        str(key).encode(), s.encode(), "sha1"
    ).hexdigest(),
    "hmac_sha256": lambda s, key: __import__("hmac").new(
        str(key).encode(), s.encode(), "sha256"
    ).hexdigest(),
    "hmac_sha512": lambda s, key: __import__("hmac").new(
        str(key).encode(), s.encode(), "sha512"
    ).hexdigest(),
    "json_value": _json_extract_scalar,
    "json_extract": _json_extract,
    "json_extract_scalar": _json_extract_scalar,
    "json_parse": _json_parse,
    "json_format": _json_parse,  # canonical re-rendering
    "json_array_get": _json_array_get,
    "concat": None,   # specialized (product-dictionary LUT)
    "length": None,   # specialized
    "char_length": None,       # length alias
    "character_length": None,  # length alias
    "strpos": None,   # specialized
    "ends_with": None,         # LUT (const suffix)
    "strrpos": None,           # LUT (const needle)
    "from_base": None,         # LUT (const radix)
    "date_parse": None,        # LUT (const mysql format) -> timestamp
    "parse_datetime": None,    # LUT (const joda format) -> timestamp
    "from_iso8601_timestamp": None,  # LUT -> timestamp
    "parse_duration": None,    # LUT -> interval micros
    "json_exists": None,       # boolean LUT (const path)
    "is_json_scalar": None,    # boolean LUT
    "split": None,             # array LUT (const delimiter)
    "regexp_split": None,      # array LUT (const pattern)
    "regexp_extract_all": None,  # array LUT (const pattern)
    "json_query": _json_extract,
    "codepoint": None,  # specialized (bigint LUT)
    "levenshtein_distance": None,  # specialized (bigint LUT, const 2nd arg)
    "hamming_distance": None,  # specialized (bigint LUT, const 2nd arg)
    "starts_with": None,  # specialized
    "regexp_like": None,  # specialized (boolean LUT)
    "json_array_length": None,  # specialized (bigint LUT)
    "json_size": None,  # specialized (bigint LUT)
    "json_array_contains": None,  # specialized (boolean LUT)
    "regexp_count": None,   # specialized (generic string->int LUT)
    "regexp_position": None,  # specialized
    "crc32": None,          # specialized
    "luhn_check": None,     # specialized
    "from_iso8601_date": None,  # specialized
}


def _luhn_check(s: str) -> bool:
    digits = [int(c) for c in s if c.isdigit()]
    if len(digits) != len(s) or not digits:
        raise ValueError("non-digit input")
    total = 0
    for i, dgt in enumerate(reversed(digits)):
        if i % 2 == 1:
            dgt *= 2
            if dgt > 9:
                dgt -= 9
        total += dgt
    return total % 10 == 0


def _soundex(s: str) -> str:
    """American Soundex (ref: operator/scalar/StringFunctions soundex)."""
    codes = {
        **dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
        **dict.fromkeys("DT", "3"), "L": "4", **dict.fromkeys("MN", "5"),
        "R": "6",
    }
    u = [c for c in s.upper() if c.isalpha()]
    if not u:
        return ""
    out = [u[0]]
    prev = codes.get(u[0], "")
    for c in u[1:]:
        code = codes.get(c, "")
        if code and code != prev:
            out.append(code)
        if c not in "HW":
            prev = code
        if len(out) == 4:
            break
    return "".join(out).ljust(4, "0")


def _word_stem(s: str) -> str:
    """Light English suffix stripper (deviation: the reference embeds the
    full Porter stemmer via Lucene; this covers the common inflections)."""
    w = s.lower()
    for suf, repl in (
        ("ies", "y"), ("sses", "ss"), ("ing", ""), ("edly", ""), ("ed", ""),
        ("ly", ""), ("es", ""), ("s", ""),
    ):
        if w.endswith(suf) and len(w) - len(suf) >= 2:
            return w[: len(w) - len(suf)] + repl
    return w


def _xxhash64(data: bytes, seed: int = 0) -> int:
    """Pure-python XXH64 (public algorithm; ref uses airlift XxHash64)."""
    P1, P2, P3, P4, P5 = (
        0x9E3779B185EBCA87, 0xC2B2AE3D27D4EB4F, 0x165667B19E3779F9,
        0x85EBCA77C2B2AE63, 0x27D4EB2F165667C5,
    )
    M = (1 << 64) - 1

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + P1 + P2) & M
        v2 = (seed + P2) & M
        v3 = seed & M
        v4 = (seed - P1) & M
        while i <= n - 32:
            v1 = (rotl((v1 + int.from_bytes(data[i:i+8], "little") * P2) & M, 31) * P1) & M
            v2 = (rotl((v2 + int.from_bytes(data[i+8:i+16], "little") * P2) & M, 31) * P1) & M
            v3 = (rotl((v3 + int.from_bytes(data[i+16:i+24], "little") * P2) & M, 31) * P1) & M
            v4 = (rotl((v4 + int.from_bytes(data[i+24:i+32], "little") * P2) & M, 31) * P1) & M
            i += 32
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
        for v in (v1, v2, v3, v4):
            h = ((h ^ (rotl((v * P2) & M, 31) * P1) & M) * P1 + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while i <= n - 8:
        h = (rotl(h ^ ((rotl((int.from_bytes(data[i:i+8], "little") * P2) & M, 31) * P1) & M), 27) * P1 + P4) & M
        i += 8
    if i <= n - 4:
        h = (rotl(h ^ (int.from_bytes(data[i:i+4], "little") * P1) & M, 23) * P2 + P3) & M
        i += 4
    while i < n:
        h = (rotl(h ^ (data[i] * P5) & M, 11) * P1) & M
        i += 1
    h ^= h >> 33
    h = (h * P2) & M
    h ^= h >> 29
    h = (h * P3) & M
    h ^= h >> 32
    return h


def _murmur3_128_hex(data: bytes, seed: int = 0) -> str:
    """MurmurHash3 x64_128 (public algorithm; ref io.airlift.slice.Murmur3)."""
    M = (1 << 64) - 1
    c1, c2 = 0x87C37B91114253D5, 0x4CF5AD432745937F

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def fmix(k):
        k ^= k >> 33
        k = (k * 0xFF51AFD7ED558CCD) & M
        k ^= k >> 33
        k = (k * 0xC4CEB9FE1A85EC53) & M
        k ^= k >> 33
        return k

    h1 = h2 = seed
    n = len(data)
    nblocks = n // 16
    for b in range(nblocks):
        k1 = int.from_bytes(data[b*16:b*16+8], "little")
        k2 = int.from_bytes(data[b*16+8:b*16+16], "little")
        k1 = (rotl((k1 * c1) & M, 31) * c2) & M
        h1 = ((rotl(h1 ^ k1, 27) + h2) * 5 + 0x52DCE729) & M
        k2 = (rotl((k2 * c2) & M, 33) * c1) & M
        h2 = ((rotl(h2 ^ k2, 31) + h1) * 5 + 0x38495AB5) & M
    tail = data[nblocks*16:]
    k1 = k2 = 0
    for j in range(len(tail) - 1, 7, -1):
        k2 |= tail[j] << ((j - 8) * 8)
    for j in range(min(len(tail), 8) - 1, -1, -1):
        k1 |= tail[j] << (j * 8)
    if len(tail) > 8:
        k2 = (rotl((k2 * c2) & M, 33) * c1) & M
        h2 ^= k2
    if len(tail) > 0:
        k1 = (rotl((k1 * c1) & M, 31) * c2) & M
        h1 ^= k1
    h1 ^= n
    h2 ^= n
    h1 = (h1 + h2) & M
    h2 = (h2 + h1) & M
    h1 = fmix(h1)
    h2 = fmix(h2)
    h1 = (h1 + h2) & M
    h2 = (h2 + h1) & M
    return h1.to_bytes(8, "little").hex() + h2.to_bytes(8, "little").hex()


_MYSQL_TO_STRPTIME = {
    "%i": "%M", "%s": "%S", "%h": "%I", "%r": "%I:%M:%S %p", "%T": "%H:%M:%S",
    "%e": "%d", "%c": "%m",
}

_JODA_TO_STRPTIME = [
    ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
    ("hh", "%I"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"), ("a", "%p"),
]


def _mysql_format(fmt: str) -> str:
    for k, v in _MYSQL_TO_STRPTIME.items():
        fmt = fmt.replace(k, v)
    return fmt


def _joda_format(fmt: str) -> str:
    for k, v in _JODA_TO_STRPTIME:
        fmt = fmt.replace(k, v)
    return fmt


def _strptime_micros(s: str, fmt: str) -> int:
    import datetime as _dt

    d = _dt.datetime.strptime(s, fmt)
    return (d - _dt.datetime(1970, 1, 1)) // _dt.timedelta(microseconds=1)


_DURATION_UNITS = {
    "ns": 1e-3, "us": 1.0, "ms": 1e3, "s": 1e6, "m": 60e6, "h": 3600e6,
    "d": 86400e6,
}


def _parse_duration_micros(s: str) -> int:
    m = re.fullmatch(r"\s*([\d.]+)\s*(ns|us|ms|s|m|h|d)\s*", s)
    if not m:
        raise ValueError(f"bad duration: {s!r}")
    return int(float(m.group(1)) * _DURATION_UNITS[m.group(2)])


def _iso_timestamp_micros(s: str) -> int:
    import datetime as _dt

    d = _dt.datetime.fromisoformat(s)
    if d.tzinfo is not None:
        d = d.astimezone(_dt.timezone.utc).replace(tzinfo=None)
    return (d - _dt.datetime(1970, 1, 1)) // _dt.timedelta(microseconds=1)


def _is_json_scalar(s: str) -> bool:
    import json as _json

    try:
        v = _json.loads(s)
    except (ValueError, TypeError):
        raise ValueError("not json")
    return not isinstance(v, (dict, list))


# string -> array<varchar> dictionary LUTs (trailing args constant)
_STRING_ARRAY_LUTS: Dict[str, Callable] = {
    "split": lambda s, delim, limit=None: (
        s.split(delim, int(limit) - 1) if limit is not None else s.split(delim)
    )
    if delim
    else [s],
    "regexp_split": lambda s, pattern: re.split(pattern, s),
    "regexp_extract_all": lambda s, pattern, group=0: [
        m.group(int(group)) for m in re.finditer(pattern, s)
    ],
}

# string -> numeric/boolean dictionary LUTs (trailing args constant);
# per-value exceptions become NULL
_STRING_INT_LUTS: Dict[str, tuple] = {
    "ends_with": (lambda s, suffix: s.endswith(suffix), np.bool_),
    "strrpos": (lambda s, sub: s.rfind(sub) + 1, np.int64),
    "from_base": (lambda s, radix: int(s, int(radix)), np.int64),
    "date_parse": (
        lambda s, fmt: _strptime_micros(s, _mysql_format(fmt)), np.int64
    ),
    "parse_datetime": (
        lambda s, fmt: _strptime_micros(s, _joda_format(fmt)), np.int64
    ),
    "from_iso8601_timestamp": (_iso_timestamp_micros, np.int64),
    "parse_duration": (_parse_duration_micros, np.int64),
    "json_exists": (
        lambda s, path: _json_eval_exists(s, path), np.bool_
    ),
    "is_json_scalar": (_is_json_scalar, np.bool_),
    "regexp_count": (lambda s, pat: len(re.findall(pat, s)), np.int64),
    "regexp_position": (
        lambda s, pat: (lambda m: m.start() + 1 if m else -1)(re.search(pat, s)),
        np.int64,
    ),
    "crc32": (lambda s: __import__("zlib").crc32(s.encode()), np.int64),
    "luhn_check": (_luhn_check, np.bool_),
    "from_iso8601_date": (
        lambda s: (
            __import__("datetime").date.fromisoformat(s)
            - __import__("datetime").date(1970, 1, 1)
        ).days,
        np.int64,
    ),
}


def _like_to_regex(pattern: str, escape: Optional[str] = None) -> "re.Pattern":
    """SQL LIKE -> compiled regex (ref: io.trino.likematcher; ours runs on the
    host over dictionary values, so a plain regex engine is plenty)."""
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile("".join(out), re.DOTALL)


# --------------------------------------------------------------------------- #
# megakernel shape recognition (ops/megakernels.py)
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class MegakernelSpec:
    """A fragment shape the fused Pallas megakernel path accepts.

    Produced by :func:`plan_megakernel` — the compiler-layer half of the
    megakernel plane. The executor layers the aggregation spec (direct-
    indexed domains) and the repartition epilogue on top; this spec answers
    only "is the JOIN itself expressible as the hash-probe kernel".
    """

    left_outer: bool


def plan_megakernel(kind, criteria, has_filter: bool,
                    probe_page, build_page) -> Tuple[Optional[MegakernelSpec], str]:
    """Recognize a join fragment for the fused hash-join megakernel.

    Returns ``(spec, reason)``: a spec when the shape is fused-eligible, or
    ``(None, reason)`` with a stable fallback label (the
    ``trino_tpu_pallas_fallbacks_total{reason=}`` vocabulary). Recognition
    rules (the ARCHITECTURE.md "Megakernel plane" fallback matrix):

    - equi-join with at least one criterion (CROSS has no keys to bucket)
    - INNER or LEFT after the executor's RIGHT-swap; FULL's unmatched-build
      tail needs the anti-set pass the kernel does not carry yet
    - no non-equi residual (the serial path owns ON-clause residuals)
    - single-lane key columns (int128 limb keys order on two words — the
      kernel compares one normalized word per column)

    Payload columns are unconstrained: the expansion gathers whole columns
    through the same ``_permute_column`` body the serial join uses, so
    multi-lane (int128 limb) and nested payloads ride along identically.
    """
    from ..planner.plan import JoinKind as _JK

    if not criteria:
        return None, "cross_join"
    if kind not in (_JK.INNER, _JK.LEFT):
        return None, "join_kind"
    if has_filter:
        return None, "residual_filter"
    for page in (probe_page, build_page):
        if page.capacity < 1:
            return None, "empty_layout"
    return MegakernelSpec(left_outer=(kind == _JK.LEFT)), "ok"


def megakernel_key_check(key_cols) -> Tuple[bool, str]:
    """Physical key-column check: every join key must be a single-lane
    column (``data.ndim == 1``); multi-lane (int128) keys fall back."""
    for d, _v in key_cols:
        if d.ndim != 1:
            return False, "key_ndim"
    return True, "ok"
