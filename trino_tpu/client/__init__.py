from .client import StatementClient, ClientError
