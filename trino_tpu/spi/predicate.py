"""Predicate pushdown domain model.

Reference blueprint: core/trino-spi/src/main/java/io/trino/spi/predicate/
(TupleDomain, Domain, ValueSet/Ranges; SURVEY.md §2.1). Simplified to the shapes the
round-1 optimizer extracts: per-column range + in-list + null admission. Used for
connector split pruning and (later) dynamic filtering.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, Optional, Tuple


@dataclass(frozen=True)
class Range:
    """[low, high] with open/closed bounds; None bound = unbounded."""

    low: Any = None
    high: Any = None
    low_inclusive: bool = True
    high_inclusive: bool = True

    def intersect(self, other: "Range") -> Optional["Range"]:
        low, low_inc = self.low, self.low_inclusive
        if other.low is not None and (low is None or other.low > low or (other.low == low and not other.low_inclusive)):
            low, low_inc = other.low, other.low_inclusive
        high, high_inc = self.high, self.high_inclusive
        if other.high is not None and (high is None or other.high < high or (other.high == high and not other.high_inclusive)):
            high, high_inc = other.high, other.high_inclusive
        if low is not None and high is not None:
            if low > high or (low == high and not (low_inc and high_inc)):
                return None
        return Range(low, high, low_inc, high_inc)

    def contains_value(self, v: Any) -> bool:
        if self.low is not None:
            if v < self.low or (v == self.low and not self.low_inclusive):
                return False
        if self.high is not None:
            if v > self.high or (v == self.high and not self.high_inclusive):
                return False
        return True


ALL_RANGE = Range()


@dataclass(frozen=True)
class Domain:
    """Admissible values for one column (ref: spi/predicate/Domain.java)."""

    range: Range = ALL_RANGE
    in_values: Optional[FrozenSet[Any]] = None  # None = unconstrained by IN
    nulls_allowed: bool = False
    none: bool = False  # contradiction: no value passes

    @staticmethod
    def all() -> "Domain":
        return Domain(nulls_allowed=True)

    @staticmethod
    def single(value: Any) -> "Domain":
        return Domain(range=Range(value, value))

    def intersect(self, other: "Domain") -> "Domain":
        if self.none or other.none:
            return Domain(none=True)
        r = self.range.intersect(other.range)
        iv = self.in_values
        if other.in_values is not None:
            iv = other.in_values if iv is None else frozenset(iv & other.in_values)
        nulls = self.nulls_allowed and other.nulls_allowed
        if r is None or (iv is not None and not iv):
            return Domain(none=True, nulls_allowed=nulls)
        return Domain(range=r, in_values=iv, nulls_allowed=nulls)

    def contains_value(self, v: Any) -> bool:
        if self.none:
            return False
        if v is None:
            return self.nulls_allowed
        if self.in_values is not None and v not in self.in_values:
            return False
        return self.range.contains_value(v)

    def overlaps_range(self, low: Any, high: Any) -> bool:
        """Can any value in [low, high] satisfy this domain? (split pruning)."""
        if self.none:
            return False
        r = self.range.intersect(Range(low, high))
        if r is None:
            return False
        if self.in_values is not None:
            return any(Range(low, high).contains_value(v) and self.range.contains_value(v) for v in self.in_values)
        return True


@dataclass(frozen=True)
class TupleDomain:
    """Conjunction of per-column domains (ref: spi/predicate/TupleDomain.java)."""

    domains: Tuple[Tuple[str, Domain], ...] = ()  # sorted items, hashable

    @staticmethod
    def all() -> "TupleDomain":
        return TupleDomain()

    @staticmethod
    def from_dict(d: Dict[str, Domain]) -> "TupleDomain":
        return TupleDomain(tuple(sorted(d.items())))

    def as_dict(self) -> Dict[str, Domain]:
        return dict(self.domains)

    @property
    def is_none(self) -> bool:
        return any(dom.none for _, dom in self.domains)

    def intersect(self, other: "TupleDomain") -> "TupleDomain":
        merged = self.as_dict()
        for col, dom in other.domains:
            merged[col] = merged[col].intersect(dom) if col in merged else dom
        return TupleDomain.from_dict(merged)

    def domain_for(self, column: str) -> Domain:
        for col, dom in self.domains:
            if col == column:
                return dom
        return Domain.all()
