"""SQL type system mapped onto TPU-friendly physical layouts.

Reference blueprint: core/trino-spi/src/main/java/io/trino/spi/type/Type.java:31 and
the concrete types under spi/type/ (BigintType, DoubleType, DecimalType, VarcharType,
DateType, BooleanType, ...). Trino maps each SQL type onto a physical Block layout;
here each SQL type maps onto a *device array dtype* plus (optionally) host-side
metadata — most importantly VARCHAR, which is dictionary-encoded so the device only
ever sees int32 codes (SURVEY.md §7: "strings -> dictionary-encode at ingest,
operate on codes").

Physical mapping:

| SQL type       | device dtype | notes                                             |
|----------------|--------------|---------------------------------------------------|
| BOOLEAN        | bool_        |                                                   |
| TINYINT        | int8         |                                                   |
| SMALLINT       | int16        |                                                   |
| INTEGER        | int32        |                                                   |
| BIGINT         | int64        |                                                   |
| REAL           | float32      |                                                   |
| DOUBLE         | float64      |                                                   |
| DECIMAL(p, s)  | int64        | scaled integer (value * 10**s), p <= 18           |
| VARCHAR(n)     | int32        | codes into a sorted host-side dictionary          |
| CHAR(n)        | int32        | same as VARCHAR                                   |
| DATE           | int32        | days since 1970-01-01 (same as Trino DateType)    |
| TIMESTAMP(p)   | int64        | microseconds since epoch (p <= 6)                 |
| UNKNOWN        | bool_        | the type of NULL literals                         |

Sorted dictionaries are load-bearing: because each VARCHAR column's dictionary is
lexicographically sorted at ingest, code order == string order, so <, <=, =, BETWEEN
and LIKE-prefix predicates evaluate directly on int32 codes on device.
"""

from __future__ import annotations


from dataclasses import dataclass
from typing import Optional

import numpy as np


@dataclass(frozen=True)
class Type:
    """Base class for SQL types. Immutable and hashable (used as cache keys)."""

    name: str

    @property
    def storage_dtype(self) -> np.dtype:
        raise NotImplementedError

    @property
    def storage_lanes(self):
        """Trailing storage lanes per row (None = scalar). Long decimals
        (p > 18) carry 2 int64 limbs [hi, lo] — ref spi/type/Int128.java:23."""
        return None

    @property
    def is_orderable(self) -> bool:
        return True

    @property
    def is_comparable(self) -> bool:
        return True

    def display(self) -> str:
        return self.name

    def __repr__(self) -> str:  # pragma: no cover
        return self.display()


@dataclass(frozen=True)
class BooleanType(Type):
    name: str = "boolean"

    @property
    def storage_dtype(self):
        return np.dtype(np.bool_)


@dataclass(frozen=True)
class IntegralType(Type):
    bits: int = 64

    @property
    def storage_dtype(self):
        return np.dtype({8: np.int8, 16: np.int16, 32: np.int32, 64: np.int64}[self.bits])


@dataclass(frozen=True)
class DoubleType(Type):
    name: str = "double"

    @property
    def storage_dtype(self):
        return np.dtype(np.float64)


@dataclass(frozen=True)
class RealType(Type):
    name: str = "real"

    @property
    def storage_dtype(self):
        return np.dtype(np.float32)


@dataclass(frozen=True)
class DecimalType(Type):
    """Fixed-point decimal stored as a scaled integer (ref:
    spi/type/DecimalType.java). p <= 18: one int64 per row (short decimal);
    p > 18: TWO int64 limbs [hi, lo] per row on a trailing axis — the
    TPU-native Int128 (spi/type/Int128.java:23, Int128Math.java; kernels in
    ops/int128.py). Long-decimal aggregation decomposes into 32-bit limb
    sums at plan time (planner/rules.py decompose_long_decimal_aggregates)
    so the whole agg/exchange machinery stays int64."""

    name: str = "decimal"
    precision: int = 18
    scale: int = 0

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)

    @property
    def storage_lanes(self):
        return 2 if self.precision > 18 else None

    def display(self) -> str:
        return f"decimal({self.precision},{self.scale})"


@dataclass(frozen=True)
class VarcharType(Type):
    """Variable-width string, dictionary-encoded (codes into a sorted host dict)."""

    name: str = "varchar"
    length: Optional[int] = None  # None == unbounded

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def display(self) -> str:
        return self.name if self.length is None else f"varchar({self.length})"


@dataclass(frozen=True)
class JsonType(VarcharType):
    """JSON values stored as canonical-text dictionary strings (ref:
    io/trino/type/JsonType.java — Trino stores JSON as canonicalized UTF-8
    Slices; here the canonical text rides the sorted-dictionary machinery, so
    jsonpath extraction becomes an O(|dict|) host transform)."""

    name: str = "json"

    def display(self) -> str:
        return "json"


JSON = JsonType()


@dataclass(frozen=True)
class CharType(Type):
    name: str = "char"
    length: int = 1

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)

    def display(self) -> str:
        return f"char({self.length})"


@dataclass(frozen=True)
class DateType(Type):
    """Days since the epoch, int32 (ref: spi/type/DateType.java)."""

    name: str = "date"

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)


@dataclass(frozen=True)
class TimestampType(Type):
    """Microseconds since the epoch, int64 (Trino supports p<=12 via Int128; we do p<=6)."""

    name: str = "timestamp"
    precision: int = 6

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)

    def display(self) -> str:
        return f"timestamp({self.precision})"


@dataclass(frozen=True)
class TimeType(Type):
    """Microseconds of day, int64 (ref: spi/type/TimeType.java; Trino stores
    picos-of-day — p<=6 here, same ceiling as TIMESTAMP)."""

    name: str = "time"
    precision: int = 3

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)

    def display(self) -> str:
        return f"time({self.precision})"


@dataclass(frozen=True)
class TimeWithTimeZoneType(Type):
    """TIME(p) WITH TIME ZONE: packed int64 — micros-of-day << 12 | (zone
    offset minutes + 841), the same packing scheme as TIMESTAMP W/ TZ (ref:
    spi/type/TimeWithTimeZoneType.java packs picos-of-day + offset).
    Comparison/ordering normalize to the UTC instant (value minus offset),
    matching the reference's comparison operators."""

    name: str = "time with time zone"
    precision: int = 3

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)

    def display(self) -> str:
        return f"time({self.precision}) with time zone"


def twtz_pack(local_micros_of_day: int, offset_minutes: int) -> int:
    """Packs the UTC-NORMALIZED micros (local - offset) in the high bits so
    raw int64 order == instant order, exactly like ttz_pack's UTC millis."""
    utc = int(local_micros_of_day) - int(offset_minutes) * 60_000_000
    return (utc << 12) | (int(offset_minutes) + 841)


def twtz_unpack(v: int):
    """-> (local_micros_of_day wrapped to [0, day), offset_minutes)."""
    utc = int(v) >> 12
    offset = (int(v) & 0xFFF) - 841
    return (utc + offset * 60_000_000) % 86_400_000_000, offset


@dataclass(frozen=True)
class TimestampWithTimeZoneType(Type):
    """Packed ``(utc_millis << 12) | zone_key`` in one int64 — the reference's
    representation exactly (spi/type/TimestampWithTimeZoneType.java,
    DateTimeEncoding.java packDateTimeWithZone; p<=3 rides the packed form
    there too). Zone keys encode FIXED offsets: key = offset_minutes + 841
    (0 = UTC alias); named zones resolve to their offset at the value's
    instant when parsed (correct for literals; arithmetic across a DST
    transition keeps the original offset — documented deviation)."""

    name: str = "timestamp with time zone"
    precision: int = 3

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)

    def display(self) -> str:
        return f"timestamp({self.precision}) with time zone"


# zone-key helpers (DateTimeEncoding.java analogues)
TTZ_UTC_KEY = 841  # offset 0


def ttz_pack(utc_millis: int, offset_minutes: int) -> int:
    return (int(utc_millis) << 12) | (int(offset_minutes) + 841)


def ttz_millis(packed: int) -> int:
    return int(packed) >> 12


def ttz_offset_minutes(packed: int) -> int:
    return (int(packed) & 0xFFF) - 841


@dataclass(frozen=True)
class IntervalDayTimeType(Type):
    """Interval day-to-second, microseconds as int64."""

    name: str = "interval day to second"

    @property
    def storage_dtype(self):
        return np.dtype(np.int64)


@dataclass(frozen=True)
class IntervalYearMonthType(Type):
    name: str = "interval year to month"

    @property
    def storage_dtype(self):
        return np.dtype(np.int32)


TDIGEST_CENTROIDS = 64


@dataclass(frozen=True)
class TDigestType(Type):
    """Quantile sketch value (ref: core/trino-spi .../type/TDigestType +
    operator/aggregation/TDigestAggregationFunction.java:33). TPU-native
    representation: a FIXED K-centroid equi-rank sketch with the t-digest k1
    (arcsine) scale biasing resolution toward the tails — 2K float64 lanes
    per row ([means..., weights...]), so digests are plain pad-and-mask
    columns and every op on them is elementwise/segment XLA."""

    name: str = "tdigest"

    @property
    def storage_dtype(self):
        return np.dtype(np.float64)

    @property
    def storage_lanes(self):
        return 2 * TDIGEST_CENTROIDS

    @property
    def is_orderable(self) -> bool:
        return False

    @property
    def is_comparable(self) -> bool:
        return False


@dataclass(frozen=True)
class QDigestType(Type):
    """qdigest(T): typed quantile sketch (ref: spi/type/QuantileDigestType +
    operator/aggregation/QuantileDigestAggregationFunction). Shares the
    fixed-K centroid-lane representation with TDIGEST; ``value_at_quantile``
    returns the ELEMENT type (rounded for integral elements)."""

    element: Type = None
    name: str = "qdigest"

    @property
    def storage_dtype(self):
        return np.dtype(np.float64)

    @property
    def storage_lanes(self):
        return 2 * TDIGEST_CENTROIDS

    @property
    def is_orderable(self) -> bool:
        return False

    @property
    def is_comparable(self) -> bool:
        return False

    def display(self) -> str:
        return f"qdigest({self.element.display()})"


@dataclass(frozen=True)
class UnknownType(Type):
    """The type of a bare NULL literal (ref: io/trino/type/UnknownType.java)."""

    name: str = "unknown"

    @property
    def storage_dtype(self):
        return np.dtype(np.bool_)


@dataclass(frozen=True)
class VectorType(Type):
    """VECTOR(n) — a dense fixed-dimension embedding column (the tensor
    workload plane, ref arXiv:2306.08367 "Accelerating ML Queries with
    Linear Algebra Query Processing").

    Physical layout: the multi-lane scalar discipline TDIGEST pioneered —
    one contiguous ``data[cap, n]`` float64 device buffer with the ordinary
    row ``valid`` mask carrying NULLs (no per-element masks, no lengths: a
    vector either exists whole or is NULL). Because the column is just a
    trailing-lanes array, it flows through Page/serde/spill/exchange and
    the capstore capacity classes UNCHANGED, and batched similarity
    evaluation over a page is literally ``data @ query`` — the
    ``(rows, n) x (n,)`` matvec the MXU exists for."""

    name: str = "vector"
    dimension: int = 0

    @property
    def storage_dtype(self):
        return np.dtype(np.float64)

    @property
    def storage_lanes(self):
        return self.dimension

    @property
    def is_orderable(self) -> bool:
        return False

    @property
    def is_comparable(self) -> bool:
        return False

    def display(self) -> str:
        return f"vector({self.dimension})"


@dataclass(frozen=True)
class ArrayType(Type):
    """ARRAY(E) — fixed-width pad-and-mask layout (ref: spi/type/ArrayType.java,
    spi/block/ArrayBlock.java).

    Trino stores arrays as offsets into a flat element block; under XLA's
    static-shape regime the TPU-first layout is ``data[cap, W]`` (W = the
    column's max element count) + ``elem_valid[cap, W]`` + ``lengths[cap]`` —
    the row-mask philosophy applied to the element axis.
    """

    name: str = "array"
    element: Type = None

    @property
    def storage_dtype(self):
        return self.element.storage_dtype

    @property
    def is_orderable(self) -> bool:
        return False

    def display(self) -> str:
        return f"array({self.element.display()})"


@dataclass(frozen=True)
class MapType(Type):
    """MAP(K, V) — two aligned array-layout children (ref: spi/type/MapType.java,
    spi/block/MapBlock.java; Trino's per-entry hash tables become elementwise
    key-compare selects on the [cap, W] key lanes)."""

    name: str = "map"
    key: Type = None
    value: Type = None

    @property
    def storage_dtype(self):
        return np.dtype(np.int8)  # parent carries no data; children do

    @property
    def is_orderable(self) -> bool:
        return False

    @property
    def is_comparable(self) -> bool:
        return False

    def child_types(self) -> tuple:
        """Physical child-column types: aligned key/value array lanes."""
        return (ArrayType(element=self.key), ArrayType(element=self.value))

    def display(self) -> str:
        return f"map({self.key.display()}, {self.value.display()})"


@dataclass(frozen=True)
class RowType(Type):
    """ROW(name type, ...) — struct-of-columns (ref: spi/type/RowType.java,
    spi/block/RowBlock.java: child blocks per field)."""

    name: str = "row"
    fields: tuple = ()  # ((name|None, Type), ...)

    @property
    def storage_dtype(self):
        return np.dtype(np.int8)

    @property
    def is_orderable(self) -> bool:
        return False

    def display(self) -> str:
        parts = [
            (f"{n} {t.display()}" if n else t.display()) for n, t in self.fields
        ]
        return f"row({', '.join(parts)})"

    def child_types(self) -> tuple:
        """Physical child-column types: one per field."""
        return tuple(ft for _, ft in self.fields)

    def field_index(self, name: str):
        for i, (n, _) in enumerate(self.fields):
            if n is not None and n.lower() == name.lower():
                return i
        return None


# Singleton instances (Trino exposes these as static fields on the type classes).
BOOLEAN = BooleanType()
TINYINT = IntegralType("tinyint", 8)
SMALLINT = IntegralType("smallint", 16)
INTEGER = IntegralType("integer", 32)
BIGINT = IntegralType("bigint", 64)
REAL = RealType()
DOUBLE = DoubleType()
VARCHAR = VarcharType()
DATE = DateType()
TIMESTAMP = TimestampType()
TIME = TimeType()
TIMESTAMP_TZ = TimestampWithTimeZoneType()
INTERVAL_DAY_TIME = IntervalDayTimeType()
INTERVAL_YEAR_MONTH = IntervalYearMonthType()
UNKNOWN = UnknownType()


def decimal_type(precision: int, scale: int) -> DecimalType:
    if precision > 38:
        raise NotImplementedError(
            f"decimal({precision},{scale}): precision above 38 exceeds the "
            "Int128 representation (ref: spi/type/DecimalType.java MAX_PRECISION)"
        )
    return DecimalType(precision=precision, scale=scale)


def is_long_decimal(t) -> bool:
    """DECIMAL(p>18): two-limb Int128 storage (spi/type/Int128.java:23)."""
    return isinstance(t, DecimalType) and t.precision > 18


def varchar_type(length: Optional[int] = None) -> VarcharType:
    return VarcharType(length=length)


_INTEGRAL_ORDER = {"tinyint": 0, "smallint": 1, "integer": 2, "bigint": 3}


def is_integral(t: Type) -> bool:
    return isinstance(t, IntegralType)


def is_numeric(t: Type) -> bool:
    return isinstance(t, (IntegralType, DoubleType, RealType, DecimalType))


def is_string(t: Type) -> bool:
    return isinstance(t, (VarcharType, CharType))


def is_floating(t: Type) -> bool:
    return isinstance(t, (DoubleType, RealType))


def is_nested(t: Type) -> bool:
    return isinstance(t, (ArrayType, MapType, RowType))


def is_vector(t: Type) -> bool:
    return isinstance(t, VectorType)


def vector_type(dimension: int) -> VectorType:
    if dimension < 1:
        raise ValueError(f"vector({dimension}): dimension must be positive")
    return VectorType(dimension=dimension)


def integral_precision(t: IntegralType) -> int:
    # Max decimal digits representable — used for decimal promotion.
    return {8: 3, 16: 5, 32: 10, 64: 19}[t.bits]


def common_super_type(a: Type, b: Type) -> Optional[Type]:
    """Least common type for comparisons/set ops (ref: io/trino/type/TypeCoercion.java)."""
    if a == b:
        return a
    if isinstance(a, UnknownType):
        return b
    if isinstance(b, UnknownType):
        return a
    if is_integral(a) and is_integral(b):
        return a if _INTEGRAL_ORDER[a.name] >= _INTEGRAL_ORDER[b.name] else b
    if is_numeric(a) and is_numeric(b):
        # Any float involved -> double; decimal+integral -> decimal with enough scale.
        if is_floating(a) or is_floating(b):
            return DOUBLE
        da = a if isinstance(a, DecimalType) else None
        db = b if isinstance(b, DecimalType) else None
        # precision stays clamped to the 18-digit short representation while
        # both sides are short (documented deviation: one-int64 storage on
        # the hot path); a DECLARED long operand widens to the Int128 cap
        cap = 38 if ((da and da.precision > 18) or (db and db.precision > 18)) else 18
        if da and db:
            scale = max(da.scale, db.scale)
            prec = max(da.precision - da.scale, db.precision - db.scale) + scale
            return decimal_type(min(prec, cap), scale)
        d = da or db
        other = b if da else a
        assert d is not None and isinstance(other, IntegralType)
        prec = max(integral_precision(other), d.precision - d.scale) + d.scale
        return decimal_type(min(prec, cap), d.scale)
    if is_string(a) and is_string(b):
        la = getattr(a, "length", None)
        lb = getattr(b, "length", None)
        if la is None or lb is None:
            return VARCHAR
        return varchar_type(max(la, lb))
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return b
    if isinstance(a, TimestampType) and isinstance(b, DateType):
        return a
    if isinstance(a, TimestampType) and isinstance(b, TimestampType):
        return a if a.precision >= b.precision else b
    return None


def can_coerce(from_t: Type, to_t: Type) -> bool:
    if from_t == to_t:
        return True
    c = common_super_type(from_t, to_t)
    return c == to_t


def _split_type_args(rest: str):
    """Split 'a, b' at top-level commas (nested parens stay intact)."""
    parts, depth, cur = [], 0, []
    for ch in rest:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts]


def parse_type(text: str) -> Type:
    """Parse a SQL type name, e.g. 'decimal(12,2)', 'array(bigint)',
    'map(varchar, bigint)', 'row(a bigint, b varchar)'."""
    text = text.strip().lower()
    base = text.split("(", 1)[0].strip()
    if base == "qdigest" and "(" in text:
        inner = text.split("(", 1)[1].rstrip()
        if not inner.endswith(")"):
            raise ValueError(f"unbalanced type: {text!r}")
        return QDigestType(element=parse_type(inner[:-1]))
    if base in ("array", "map", "row") and "(" in text:
        inner = text.split("(", 1)[1].rstrip()
        if not inner.endswith(")"):
            raise ValueError(f"unbalanced type: {text!r}")
        args_s = _split_type_args(inner[:-1])
        if base == "array":
            return ArrayType(element=parse_type(args_s[0]))
        if base == "map":
            return MapType(key=parse_type(args_s[0]), value=parse_type(args_s[1]))
        fields = []
        for f in args_s:
            bits = f.split(None, 1)
            if len(bits) == 2:
                fields.append((bits[0], parse_type(bits[1])))
            else:
                fields.append((None, parse_type(bits[0])))
        return RowType(fields=tuple(fields))
    if text.endswith("with time zone"):
        head = text[: -len("with time zone")].strip()
        p = 3
        if "(" in head:
            head, rest = head.split("(", 1)
            p = int(rest.rstrip(") "))
        if head.strip() == "timestamp":
            return TimestampWithTimeZoneType(precision=p)
        if head.strip() == "time":
            return TimeWithTimeZoneType(precision=p)
        raise ValueError(f"unknown type: {text!r}")
    base, args = text, []
    if "(" in text:
        base, rest = text.split("(", 1)
        base = base.strip()
        args = [int(x.strip()) for x in rest.rstrip(")").split(",")]
    simple = {
        "boolean": BOOLEAN,
        "tinyint": TINYINT,
        "smallint": SMALLINT,
        "integer": INTEGER,
        "int": INTEGER,
        "bigint": BIGINT,
        "real": REAL,
        "double": DOUBLE,
        "date": DATE,
        "json": JSON,
        "unknown": UNKNOWN,
        "tdigest": TDigestType(),
    }
    if base in simple:
        return simple[base]
    if base == "decimal":
        p = args[0] if args else 18
        s = args[1] if len(args) > 1 else 0
        return decimal_type(p, s)
    if base == "varchar":
        return varchar_type(args[0] if args else None)
    if base == "vector":
        if not args:
            raise ValueError("vector requires a dimension: vector(n)")
        return vector_type(args[0])
    if base == "char":
        return CharType(length=args[0] if args else 1)
    if base == "timestamp":
        p = args[0] if args else 6
        if p > 6:
            raise NotImplementedError(
                f"timestamp({p}): precision > 6 exceeds int64-microsecond storage"
            )
        return TimestampType(precision=p)
    if base == "time":
        return TimeType(precision=args[0] if args else 3)
    raise ValueError(f"unknown type: {text!r}")
