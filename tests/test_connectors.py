"""Memory/blackhole connector + DML tests (ref: plugin/trino-memory tests +
BaseConnectorTest smoke coverage, SURVEY.md §4)."""

import pytest

from trino_tpu.connectors.memory import BlackHoleConnector, MemoryConnector
from trino_tpu.metadata import Session
from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture()
def runner():
    from trino_tpu.connectors.tpch import TpchConnector

    r = LocalQueryRunner(Session(catalog="memory", schema="default"))
    r.register_catalog("memory", MemoryConnector())
    r.register_catalog("blackhole", BlackHoleConnector())
    r.register_catalog("tpch", TpchConnector(scale=0.0005))
    return r


class TestMemoryConnector:
    def test_ctas_and_select(self, runner):
        res = runner.execute("CREATE TABLE t AS SELECT 1 a, 'x' b")
        assert res.rows == [(1,)]
        assert runner.execute("SELECT a, b FROM t").rows == [(1, "x")]

    def test_insert_appends(self, runner):
        runner.execute("CREATE TABLE nums AS SELECT 1 n")
        runner.execute("INSERT INTO nums SELECT 2")
        runner.execute("INSERT INTO nums VALUES (3), (4)")
        res = runner.execute("SELECT n FROM nums ORDER BY n")
        assert [r[0] for r in res.rows] == [1, 2, 3, 4]

    def test_ctas_from_tpch(self, runner):
        res = runner.execute(
            "CREATE TABLE top_orders AS "
            "SELECT o_orderkey, o_totalprice FROM tpch.sf0_0005.orders "
            "ORDER BY o_totalprice DESC LIMIT 10"
        )
        assert res.rows == [(10,)]
        out = runner.execute("SELECT count(*), max(o_totalprice) FROM top_orders")
        assert out.rows[0][0] == 10

    def test_aggregate_over_memory_table(self, runner):
        runner.execute("CREATE TABLE v AS SELECT * FROM (VALUES (1, 10), (1, 20), (2, 5)) x(k, v)")
        res = runner.execute("SELECT k, sum(v) FROM v GROUP BY k ORDER BY k")
        assert res.rows == [(1, 30), (2, 5)]

    def test_drop_table(self, runner):
        runner.execute("CREATE TABLE d AS SELECT 1 x")
        runner.execute("DROP TABLE d")
        with pytest.raises(Exception):
            runner.execute("SELECT * FROM d")
        runner.execute("DROP TABLE IF EXISTS d")  # no error

    def test_create_existing_fails(self, runner):
        runner.execute("CREATE TABLE e AS SELECT 1 x")
        with pytest.raises(ValueError):
            runner.execute("CREATE TABLE e AS SELECT 2 y")
        res = runner.execute("CREATE TABLE IF NOT EXISTS e AS SELECT 2 y")
        assert res.rows == [(0,)]

    def test_show_tables_memory(self, runner):
        runner.execute("CREATE TABLE listed AS SELECT 1 x")
        names = [r[0] for r in runner.execute("SHOW TABLES").rows]
        assert "listed" in names

    def test_insert_arity_mismatch(self, runner):
        runner.execute("CREATE TABLE two AS SELECT 1 a, 2 b")
        with pytest.raises(ValueError):
            runner.execute("INSERT INTO two SELECT 1")


class TestBlackHole:
    def test_swallow_writes(self, runner):
        runner.execute("CREATE TABLE blackhole.default.sink AS SELECT 1 x")
        res = runner.execute("INSERT INTO blackhole.default.sink VALUES (42)")
        assert res.rows == [(1,)]
        out = runner.execute("SELECT count(*) FROM blackhole.default.sink")
        assert out.rows == [(0,)]
