"""Object-store substrate (runtime/objectstore.py): honest rename-free
semantics under every TrinoFileSystem implementation.

Acceptance contracts (ISSUE 20):
- ONE shared semantics checklist every filesystem passes: conditional puts
  (If-None-Match / If-Match CAS) admit exactly one winner under racing
  threads, etags name content (md5), listings see committed keys;
- the retrying layer disambiguates torn puts (write landed, response lost)
  by re-reading the key — never a duplicate, never a lost ack;
- listings may LAG writes (and are paginated); per-key GETs stay strong,
  so every discovery path that probes keys directly is lag-proof;
- throttles retry under backoff + budget and classify EXTERNAL (an FTE
  task killed by one never burns its attempt budget);
- the journal / exchange planes keep their local-substrate contracts
  (sequenced appends, marker-last commits, quarantine) without rename;
- capstore/statstore CAS merge-on-write never drops a concurrent writer.
"""

import hashlib
import json
import os
import threading

import pytest

from trino_tpu.fs import LocalFileSystem, Location
from trino_tpu.runtime.failure import (
    ChaosInjector,
    ErrorCategory,
    classify_error,
)
from trino_tpu.runtime.metrics import REGISTRY
from trino_tpu.runtime.objectstore import (
    CAS_CONFLICTS_HELP,
    REQUESTS_HELP,
    RETRIES_HELP,
    THROTTLES_HELP,
    ObjectExchange,
    ObjectFileSystem,
    ObjectJournal,
    ObjectStoreThrottled,
    RetryBudgetExhausted,
    RetryingFileSystem,
    _BUDGETS,
    backend_for_root,
    is_object_uri,
    object_journal_queries,
    object_remove_query,
)


def _md5(data: bytes) -> str:
    return hashlib.md5(data).hexdigest()


def _counter(name: str, help_: str):
    return REGISTRY.counter(name, help=help_)


@pytest.fixture(params=["local", "object", "retrying"])
def anyfs(request, tmp_path):
    """Every TrinoFileSystem implementation through ONE checklist: the
    POSIX-backed local fs, the raw S3-shaped emulator, and the retrying
    layer the durable planes actually mount."""
    root = str(tmp_path / "store")
    os.makedirs(root, exist_ok=True)
    if request.param == "local":
        return LocalFileSystem(root)
    if request.param == "object":
        return ObjectFileSystem(root)
    return RetryingFileSystem(ObjectFileSystem(root))


# --------------------------------------------------------------------------- #
# the shared semantics checklist
# --------------------------------------------------------------------------- #


class TestFileSystemContract:
    def test_write_read_exists_delete(self, anyfs):
        loc = Location("object", "a/b/key")
        assert not anyfs.exists(loc)
        anyfs.write(loc, b"payload")
        assert anyfs.exists(loc)
        assert anyfs.read(loc) == b"payload"
        anyfs.write(loc, b"replaced")  # unconditional put overwrites
        assert anyfs.read(loc) == b"replaced"
        anyfs.delete(loc)
        assert not anyfs.exists(loc)
        anyfs.delete(loc)  # idempotent on a missing key

    def test_etag_names_content(self, anyfs):
        loc = Location("object", "etag/key")
        anyfs.write(loc, b"versioned")
        data, etag = anyfs.read_with_etag(loc)
        assert data == b"versioned"
        assert etag == _md5(b"versioned")  # both backends agree: md5

    def test_write_if_absent_exactly_one_winner(self, anyfs):
        """8 racing threads, one key: exactly one If-None-Match succeeds
        and the stored object is the winner's COMPLETE payload — the
        losers' bytes never tear into it."""
        loc = Location("object", "claim/key")
        payloads = [f"writer-{i}".encode() * 256 for i in range(8)]
        wins = {}
        barrier = threading.Barrier(8)

        def race(i):
            barrier.wait()
            wins[i] = anyfs.write_if_absent(loc, payloads[i])

        ts = [threading.Thread(target=race, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        winners = [i for i, won in wins.items() if won]
        assert len(winners) == 1
        assert anyfs.read(loc) == payloads[winners[0]]
        # a duplicate claim against the settled key also loses
        assert anyfs.write_if_absent(loc, b"late") is False

    def test_write_if_match_cas(self, anyfs):
        loc = Location("object", "cas/key")
        anyfs.write(loc, b"v0")
        _, etag = anyfs.read_with_etag(loc)
        new = anyfs.write_if_match(loc, b"v1", etag)
        assert new == _md5(b"v1")
        # the consumed etag is now stale
        assert anyfs.write_if_match(loc, b"v2", etag) is None
        assert anyfs.read(loc) == b"v1"
        # CAS against a missing key is a conflict, not a create
        assert anyfs.write_if_match(
            Location("object", "cas/missing"), b"x", etag
        ) is None

    def test_write_if_match_exactly_one_winner(self, anyfs):
        loc = Location("object", "cas/race")
        anyfs.write(loc, b"base")
        _, etag = anyfs.read_with_etag(loc)
        results = {}
        barrier = threading.Barrier(6)

        def race(i):
            barrier.wait()
            results[i] = anyfs.write_if_match(loc, f"w{i}".encode(), etag)

        ts = [threading.Thread(target=race, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        winners = [i for i, new in results.items() if new is not None]
        assert len(winners) == 1
        assert anyfs.read(loc) == f"w{winners[0]}".encode()

    def test_listing_sees_committed_keys(self, anyfs):
        for name in ("l/a", "l/b", "l/sub/c"):
            anyfs.write(Location("object", name), b"x")
        names = sorted(
            e.location.path for e in anyfs.list_files(Location("object", "l"))
        )
        assert names == ["l/a", "l/b", "l/sub/c"]
        # no tmp/lock sidecar of the write machinery ever lists
        assert not any(n.endswith((".tmp", ".lck")) for n in names)

    def test_concurrent_unconditional_writes_never_tear(self, anyfs):
        """The shared-tmp-name regression: racing whole-object puts to one
        key must settle on exactly ONE writer's complete payload."""
        loc = Location("object", "tear/key")
        payloads = [f"w{i}-".encode() * 512 for i in range(8)]
        barrier = threading.Barrier(8)

        def race(i):
            barrier.wait()
            anyfs.write(loc, payloads[i])

        ts = [threading.Thread(target=race, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert anyfs.read(loc) in payloads


# --------------------------------------------------------------------------- #
# local-fs satellite regressions
# --------------------------------------------------------------------------- #


class TestLocalFileSystemRegressions:
    def test_write_if_absent_leaves_no_tmp_residue(self, tmp_path):
        fs = LocalFileSystem(str(tmp_path))
        loc = Location("local", "key")
        assert fs.write_if_absent(loc, b"first")
        assert fs.write_if_absent(loc, b"loser") is False
        residue = [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]
        assert residue == []
        assert fs.read(loc) == b"first"

    def test_losing_claim_never_blocks_with_partial_object(self, tmp_path):
        """The O_EXCL-then-write regression: the key must appear complete
        or not at all — a claim is never an empty/partial object."""
        fs = LocalFileSystem(str(tmp_path))
        loc = Location("local", "claim")
        seen = []
        stop = threading.Event()

        def watch():
            p = os.path.join(str(tmp_path), "claim")
            while not stop.is_set():
                try:
                    with open(p, "rb") as f:
                        seen.append(f.read())
                except FileNotFoundError:
                    pass

        t = threading.Thread(target=watch)
        t.start()
        payload = b"full-claim-body" * 1024
        try:
            for i in range(20):
                assert fs.write_if_absent(loc, payload)
                fs.delete(loc)
        finally:
            stop.set()
            t.join()
        assert all(s == payload for s in seen)

    def test_list_files_skips_vanished_entries(self, tmp_path, monkeypatch):
        """The TOCTOU regression: a concurrent evictor deleting a file
        between walk and stat must not blow up the listing."""
        fs = LocalFileSystem(str(tmp_path))
        fs.write(Location("local", "keep"), b"x")
        fs.write(Location("local", "gone"), b"y")
        real_getsize = os.path.getsize

        def racing_getsize(p):
            if p.endswith("gone"):
                raise FileNotFoundError(p)  # deleted mid-walk
            return real_getsize(p)

        monkeypatch.setattr(os.path, "getsize", racing_getsize)
        names = [e.location.path for e in fs.list_files(Location("local", ""))]
        assert names == ["keep"]


# --------------------------------------------------------------------------- #
# object semantics: lag, pagination, multipart
# --------------------------------------------------------------------------- #


class TestObjectSemantics:
    def test_list_lag_hides_fresh_keys_but_gets_stay_strong(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("TRINO_TPU_OBJECT_LIST_LAG_MS", "60000")
        fs = ObjectFileSystem(str(tmp_path))
        loc = Location("object", "fresh")
        fs.write(loc, b"data")
        # the asymmetry every discovery scan must tolerate:
        assert list(fs.list_files(Location("object", ""))) == []  # LIST lags
        assert fs.read(loc) == b"data"  # GET is read-after-write
        assert fs.exists(loc)
        monkeypatch.setenv("TRINO_TPU_OBJECT_LIST_LAG_MS", "0")
        assert [e.location.path for e in fs.list_files(Location("object", ""))] \
            == ["fresh"]

    def test_list_lag_chaos_site_forces_one_lagging_listing(self, tmp_path):
        fs = ObjectFileSystem(str(tmp_path))
        fs.write(Location("object", "k"), b"x")
        with ChaosInjector() as chaos:
            chaos.arm("object_store_list_lag", times=1)
            assert list(fs.list_files(Location("object", ""))) == []
            # the site fired once; the next listing converges
            assert [e.location.path for e in fs.list_files(Location("object", ""))] \
                == ["k"]
            assert chaos.fired["object_store_list_lag"] == 1

    def test_listing_paginates(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TRINO_TPU_OBJECT_LIST_PAGE", "2")
        fs = ObjectFileSystem(str(tmp_path))
        for i in range(5):
            fs.write(Location("object", f"k{i}"), b"x")
        page, truncated = fs.list_page(Location("object", ""))
        assert [e.location.path for e in page] == ["k0", "k1"]
        assert truncated
        page2, _ = fs.list_page(Location("object", ""), start_after="k1")
        assert [e.location.path for e in page2] == ["k2", "k3"]
        # the full iterator stitches pages back into every key
        assert len(list(fs.list_files(Location("object", "")))) == 5

    def test_multipart_write_over_threshold(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TRINO_TPU_OBJECT_MULTIPART_THRESHOLD", "4096")
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        blob = os.urandom(10_000)  # 3 parts at a 4 KiB part size
        fs.write(Location("object", "big/blob"), blob)
        assert fs.read(Location("object", "big/blob")) == blob
        # the staging area is cleaned up and never leaks into listings
        uploads = os.path.join(str(tmp_path), ".uploads")
        assert not os.path.isdir(uploads) or os.listdir(uploads) == []
        assert [e.location.path for e in fs.list_files(Location("object", ""))] \
            == ["big/blob"]


# --------------------------------------------------------------------------- #
# the retrying layer
# --------------------------------------------------------------------------- #


@pytest.fixture
def fast_retry(monkeypatch):
    monkeypatch.setenv("TRINO_TPU_OBJECT_RETRY_INITIAL_MS", "1")
    monkeypatch.setenv("TRINO_TPU_OBJECT_RETRY_CAP_MS", "5")


class TestRetryingLayer:
    def test_throttle_retries_to_success_and_counts(self, tmp_path, fast_retry):
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        loc = Location("object", "k")
        fs.write(loc, b"v")
        retries = _counter(
            "trino_tpu_object_store_retries_total", RETRIES_HELP
        )
        throttles = _counter(
            "trino_tpu_object_store_throttles_total", THROTTLES_HELP
        )
        r0, t0 = retries.value, throttles.value
        with ChaosInjector() as chaos:
            chaos.arm("object_store_throttle", times=2)
            assert fs.read(loc) == b"v"
        assert throttles.value == t0 + 2
        assert retries.value == r0 + 2

    def test_retry_max_exhaustion_classifies_external(
        self, tmp_path, fast_retry, monkeypatch
    ):
        monkeypatch.setenv("TRINO_TPU_OBJECT_RETRY_MAX", "1")
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        fs.write(Location("object", "k"), b"v")
        with ChaosInjector() as chaos:
            chaos.arm("object_store_throttle", times=10)
            with pytest.raises(ObjectStoreThrottled) as ei:
                fs.read(Location("object", "k"))
        # the store, not the query, is the faulting component: an FTE task
        # killed by this reschedules without burning its attempt budget
        assert classify_error(ei.value) is ErrorCategory.EXTERNAL

    def test_retry_budget_degrades_storm_to_first_failure(
        self, tmp_path, fast_retry, monkeypatch
    ):
        monkeypatch.setenv("TRINO_TPU_OBJECT_RETRY_BUDGET", "2")
        _BUDGETS.pop(2, None)  # a fresh bucket for this capacity
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        fs.write(Location("object", "k"), b"v")
        with ChaosInjector() as chaos:
            chaos.arm("object_store_throttle", times=10)
            with pytest.raises(RetryBudgetExhausted) as ei:
                fs.read(Location("object", "k"))
        assert classify_error(ei.value) is ErrorCategory.EXTERNAL

    def test_torn_put_recovered_by_rereading_key(self, tmp_path, fast_retry):
        """The ambiguous-timeout case: the put LANDED, the response was
        lost. The layer re-reads the key, proves its bytes are on store,
        and reports success — no duplicate object, no spurious failure."""
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        loc = Location("object", "torn/put")
        with ChaosInjector() as chaos:
            chaos.arm("object_store_torn_put", times=1)
            fs.write(loc, b"landed")
            assert chaos.fired["object_store_torn_put"] == 1
        assert fs.read(loc) == b"landed"

    def test_torn_conditional_put_still_reports_win(self, tmp_path, fast_retry):
        """write_if_absent whose response was lost: the key exists with
        OUR bytes, so the claim is a win — a naive retry would see the key
        and wrongly report a lost race (double-dispatch in the journal)."""
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        loc = Location("object", "torn/claim")
        with ChaosInjector() as chaos:
            chaos.arm("object_store_torn_put", times=1)
            assert fs.write_if_absent(loc, b"mine") is True
        assert fs.read(loc) == b"mine"
        # ...and a genuine lost race still reports the loss
        assert fs.write_if_absent(loc, b"other") is False

    def test_torn_cas_recovers_new_etag(self, tmp_path, fast_retry):
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        loc = Location("object", "torn/cas")
        fs.write(loc, b"v0")
        _, etag = fs.read_with_etag(loc)
        with ChaosInjector() as chaos:
            chaos.arm("object_store_torn_put", times=1)
            new = fs.write_if_match(loc, b"v1", etag)
        assert new == _md5(b"v1")
        assert fs.read(loc) == b"v1"

    def test_cas_conflicts_counted(self, tmp_path):
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        conflicts = _counter(
            "trino_tpu_object_store_cas_conflicts_total", CAS_CONFLICTS_HELP
        )
        c0 = conflicts.value
        loc = Location("object", "k")
        assert fs.write_if_absent(loc, b"v")
        assert fs.write_if_absent(loc, b"w") is False
        assert fs.write_if_match(loc, b"x", "stale-etag") is None
        assert conflicts.value == c0 + 2

    def test_every_request_is_counted(self, tmp_path):
        fs = RetryingFileSystem(ObjectFileSystem(str(tmp_path)))
        requests = _counter(
            "trino_tpu_object_store_requests_total", REQUESTS_HELP
        )
        n0 = requests.value
        loc = Location("object", "k")
        fs.write(loc, b"v")
        fs.read(loc)
        fs.exists(loc)
        assert requests.value == n0 + 3


# --------------------------------------------------------------------------- #
# sequenced-record journal
# --------------------------------------------------------------------------- #


class TestObjectJournal:
    def _journal(self, tmp_path):
        return ObjectJournal("object://" + str(tmp_path / "q1" / "journal"))

    def test_append_read_round_trip(self, tmp_path):
        j = self._journal(tmp_path)
        for i in range(5):
            j.append({"kind": "rec", "i": i})
        records, torn = j.read()
        assert torn == 0
        assert [r["i"] for r in records] == [0, 1, 2, 3, 4]

    def test_concurrent_appends_all_land_in_unique_slots(self, tmp_path):
        j = self._journal(tmp_path)
        seqs = []
        lock = threading.Lock()
        barrier = threading.Barrier(4)

        def writer(wid):
            barrier.wait()
            mine = [j.append({"w": wid, "n": n}) for n in range(5)]
            with lock:
                seqs.extend(mine)

        ts = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(seqs) == list(range(20))  # no slot claimed twice
        records, torn = j.read()
        assert torn == 0
        assert sorted((r["w"], r["n"]) for r in records) == sorted(
            (w, n) for w in range(4) for n in range(5)
        )

    def test_undecodable_record_counts_torn(self, tmp_path):
        j = self._journal(tmp_path)
        j.append({"kind": "ok0"})
        j.append({"kind": "ok1"})
        # a record object damaged on store (the torn-put analogue)
        j.fs.write(Location("object", "00000001.json"), b'{"kind": "ok1')
        records, torn = j.read()
        assert torn == 1
        assert [r["kind"] for r in records] == ["ok0"]

    def test_record_past_lost_tail_cas_is_recovered(self, tmp_path):
        """A writer whose record landed but whose tail CAS never finished:
        readers probe past the tail and still see the append."""
        j = self._journal(tmp_path)
        j.append({"kind": "acked"})
        j.fs.write_if_absent(
            Location("object", "00000001.json"),
            json.dumps({"kind": "orphan"}).encode(),
        )  # tail still says next=1
        records, torn = j.read()
        assert torn == 0
        assert [r["kind"] for r in records] == ["acked", "orphan"]

    def test_discovery_lists_journals_by_tail_marker(self, tmp_path):
        base = "object://" + str(tmp_path)
        ObjectJournal(f"{base}/qa/journal").append({"kind": "begin"})
        ObjectJournal(f"{base}/qb/journal").append({"kind": "begin"})
        assert object_journal_queries(base) == [
            ("qa", f"{base}/qa/journal"),
            ("qb", f"{base}/qb/journal"),
        ]


# --------------------------------------------------------------------------- #
# rename-free durable exchange
# --------------------------------------------------------------------------- #


class TestObjectExchange:
    def _frames(self, n):
        return [f"frame-{i}".encode() * 32 for i in range(n)]

    def test_marker_last_torn_commit_invisible(self, tmp_path):
        """A producer crash between the part puts and the marker: the
        attempt's bytes are on store, but no consumer can select it."""
        from trino_tpu.runtime.failure import InjectedFailure

        ex = ObjectExchange("object://" + str(tmp_path / "q" / "f0"))
        sink = ex.part_sink(0, 0)
        for f in self._frames(3):
            sink.add_part(0, f, rows=1)
        with ChaosInjector() as chaos:
            chaos.arm("exchange_torn_commit", times=1)
            with pytest.raises(InjectedFailure):
                sink.commit()
        assert ex.committed_parts_attempt(0) is None  # invisible forever
        # the retry commits attempt 1 and becomes the selected winner
        retry = ex.part_sink(0, 1)
        for f in self._frames(3):
            retry.add_part(0, f, rows=1)
        retry.commit()
        assert ex.committed_parts_attempt(0) == 1
        assert ex.source_part(0, 0) == self._frames(3)

    def test_selection_never_consults_the_lagging_listing(self, tmp_path):
        ex = ObjectExchange("object://" + str(tmp_path / "q" / "f0"))
        sink = ex.part_sink(0, 0)
        for f in self._frames(2):
            sink.add_part(0, f, rows=1)
        sink.commit()
        with ChaosInjector() as chaos:
            chaos.arm("object_store_list_lag", times=100)
            assert ex.committed_parts_attempt(0) == 0
            assert ex.source_part(0, 0) == self._frames(2)
            # proof: attempt selection fired zero LIST requests
            assert chaos.fired.get("object_store_list_lag") is None

    def test_corrupt_frame_quarantine_and_recommit(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import ExchangeDataCorruption

        ex = ObjectExchange("object://" + str(tmp_path / "q" / "f0"))
        with ChaosInjector() as chaos:
            sink = ex.part_sink(0, 0)
            for f in self._frames(2):
                sink.add_part(0, f, rows=1)
            chaos.arm("exchange_corrupt_frame", times=1)
            sink.commit()  # commits, then the chaos site damages a part
        with pytest.raises(ExchangeDataCorruption):
            ex.source_part(0, 0)
        assert ex.quarantine_attempt(0, 0)
        assert ex.committed_parts_attempt(0) is None  # hidden by the marker
        recommit = ex.part_sink(0, 1)
        for f in self._frames(2):
            recommit.add_part(0, f, rows=1)
        recommit.commit()
        assert ex.source_part(0, 0) == self._frames(2)

    def test_remove_query_tombstone_fences_zombie_commit(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import QueryExchangeRemoved

        base = "object://" + str(tmp_path)
        ex = ObjectExchange(f"{base}/q9/f0")
        sink = ex.part_sink(0, 0)
        sink.add_part(0, b"zombie-frame" * 16, rows=1)
        object_remove_query(base, "q9")  # the sweep lands first
        with pytest.raises(QueryExchangeRemoved):
            sink.commit()
        assert ex.committed_parts_attempt(0) is None

    def test_single_blob_sink_round_trip(self, tmp_path):
        ex = ObjectExchange("object://" + str(tmp_path / "q" / "f1"))
        sink = ex.sink(2, 0)
        for f in self._frames(4):
            sink.add(f)
        sink.commit()
        assert ex.committed_attempt(2) == 0
        assert ex.source(2) == self._frames(4)


# --------------------------------------------------------------------------- #
# single-object stores: capstore / statstore CAS merge
# --------------------------------------------------------------------------- #


class TestSingleObjectStores:
    def test_capstore_concurrent_writers_merge(self, tmp_path, monkeypatch):
        from trino_tpu.runtime import capstore

        uri = "object://" + str(tmp_path / "caps.json")
        monkeypatch.setenv(capstore.ENV_VAR, uri)
        barrier = threading.Barrier(4)

        def writer(i):
            barrier.wait()
            capstore.save(f"fp{i}", [1024 * (i + 1), None])

        ts = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # CAS merge-on-write: NO writer's fingerprint was clobbered
        for i in range(4):
            assert capstore.load(f"fp{i}") == [1024 * (i + 1), None]

    def test_statstore_object_round_trip(self, tmp_path, monkeypatch):
        from trino_tpu.runtime import statstore

        uri = "object://" + str(tmp_path / "stats.json")
        monkeypatch.setenv(statstore.ENV_VAR, uri)
        statstore.record_history({"s:abc": {"rows": 42}})
        statstore.record_history({"s:def": {"rows": 7}})  # merges, not clobbers
        hist = statstore.load_history()
        assert hist["s:abc"]["rows"] == 42
        assert hist["s:def"]["rows"] == 7


# --------------------------------------------------------------------------- #
# dispatch helpers
# --------------------------------------------------------------------------- #


class TestBackendDispatch:
    def test_backend_for_root_routes_by_scheme(self, tmp_path):
        fs, root = backend_for_root(str(tmp_path / "plain"))
        assert isinstance(fs, LocalFileSystem)
        obj_fs, obj_root = backend_for_root("object://" + str(tmp_path / "obj"))
        assert isinstance(obj_fs, RetryingFileSystem)
        assert is_object_uri(obj_root)
        assert not is_object_uri(root)
