#!/usr/bin/env python
"""Benchmark: the BASELINE.json TPC-H ladder through the full engine.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N, "detail": {...}}

- Primary metric: tpch_q6_sf{N}_rows_per_sec — lineitem rows/s through the
  compiled scan->filter->project->sum pipeline (steady-state, data resident in
  device memory; BASELINE.json config #1).
- detail.queries: per-query ladder results (Q1 group-by, Q3/Q14 joins, Q18
  having+semi-join).
- vs_baseline: speedup vs single-thread numpy computing the identical Q6 over
  identical host arrays (stand-in for the JVM operator pipeline; BASELINE.md
  records that the reference publishes no absolute numbers).

Isolation model (benchto's fixed-runs discipline hardened for a remote-TPU
tunnel, ref testing/trino-benchto-benchmarks/.../tpch.yaml): EVERY measurement
runs in its OWN child process with its own hard timeout, streaming its record
to a results file the moment it lands. A device call wedged in native code
(where SIGALRM cannot fire) kills exactly one query's child; every other
number survives. The parent traps SIGTERM/SIGINT and emits the assembled JSON
line from whatever has been streamed — a partial number always beats a lost
round. Children share compiled programs through the persistent XLA cache
(.jax_cache_tpu), the analogue of PageFunctionCompiler's generated-class cache.

Timing strategy (remote-TPU tunnel, see BASELINE.md): block_until_ready
returns before compute finishes and any host fetch forces input re-upload on
later dispatches. Traced (join-free) queries therefore run K chained
iterations inside ONE device program (data-dependent carry defeats CSE) and
take the slope between two K values. Join queries are timed end-to-end
wall-clock through the operator engine (honest for what the engine delivers),
then upgraded in the same child to the traced single-program formulation.
"""

import json
import os
import signal
import sys
import time

import numpy as np

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

# BASELINE ladder config #2: multi-key group-by (direct-indexed aggregation)
Q1 = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty, avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc, count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

# config #3: join + grouped agg + TopN
Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

# config #4: join + conditional aggregation
Q14 = """
SELECT 100.00 * sum(CASE WHEN p_type LIKE 'PROMO%'
                         THEN l_extendedprice * (1 - l_discount) ELSE 0 END)
       / sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
FROM lineitem, part
WHERE l_partkey = p_partkey
  AND l_shipdate >= DATE '1995-09-01' AND l_shipdate < DATE '1995-10-01'
"""

# config #5: semi-join + big group-by + TopN
Q18 = """
SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity)
FROM customer, orders, lineitem
WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                     GROUP BY l_orderkey HAVING sum(l_quantity) > 300)
  AND c_custkey = o_custkey AND o_orderkey = l_orderkey
GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate LIMIT 100
"""

JOIN_QUERIES = {"q3": Q3, "q14": Q14, "q18": Q18}


def numpy_baseline(scale: float):
    """Single-thread numpy Q6 over the same generated data; (result, secs, rows)."""
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.connectors.tpch import generator as g

    conn = TpchConnector(scale=scale)
    total = conn.split_count("lineitem", scale)
    cols = {"l_shipdate": [], "l_discount": [], "l_quantity": [], "l_extendedprice": []}
    for s in range(total):
        data = g.generate_split("lineitem", scale, s, total)
        for k in cols:
            cols[k].append(data.columns[k])
    arrs = {k: np.concatenate(v) for k, v in cols.items()}
    lo = (np.datetime64("1994-01-01") - np.datetime64("1970-01-01")).astype(int)
    hi = (np.datetime64("1995-01-01") - np.datetime64("1970-01-01")).astype(int)

    def run():
        m = (
            (arrs["l_shipdate"] >= lo)
            & (arrs["l_shipdate"] < hi)
            & (arrs["l_discount"] >= 5)
            & (arrs["l_discount"] <= 7)
            & (arrs["l_quantity"] < 2400)
        )
        return np.sum(arrs["l_extendedprice"][m] * arrs["l_discount"][m])

    run()  # warm page cache
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        result = run()
        times.append(time.perf_counter() - t0)
    return result, min(times), len(arrs["l_shipdate"])


def device_healthcheck(timeout_secs: int = 60) -> bool:
    """The remote-TPU tunnel can wedge, and a hung device call blocks in
    native code where signals can't interrupt it — probe in a subprocess with
    a hard timeout. Returns True when the device answers."""
    import subprocess

    probe = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "np.asarray(jax.jit(lambda a: a * 2 + 1)(jnp.ones(8)))"
    )
    try:
        subprocess.run(
            [sys.executable, "-c", probe],
            timeout=timeout_secs,
            check=True,
            capture_output=True,
        )
        return True
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        return False


def measure_traced_loop(runner, sql, probe_col: int, ks=(8, 72), runs=3):
    """Slope timing for a traced (join-free) query: chained fori_loop
    iterations in one program; per-query secs = slope between two K values."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from trino_tpu.runtime.traced import compile_query

    plan = runner.plan_sql(sql)
    fn, pages, _ = compile_query(plan, runner.metadata, runner.session)

    def make_looped(k: int):
        def looped(*scan_pages):
            def body(i, carry):
                bit = carry >= jnp.int64(-(10**18))
                perturbed = [type(p)(p.columns, p.active & bit) for p in scan_pages]
                out = fn(*perturbed)
                return carry + out.columns[probe_col].data[0].astype(jnp.int64)

            return lax.fori_loop(0, k, body, jnp.int64(0))

        return jax.jit(looped)

    k1, k2 = ks
    f1, f2 = make_looped(k1), make_looped(k2)
    t0 = time.time()
    _ = np.asarray(f1(*pages))  # compile + run
    _ = np.asarray(f2(*pages))
    compile_secs = time.time() - t0

    def timed(f):
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            _ = np.asarray(f(*pages))
            best = min(best, time.perf_counter() - t0)
        return best

    t1, t2 = timed(f1), timed(f2)
    secs = max((t2 - t1) / (k2 - k1), 1e-9)
    return {"secs": round(secs, 9), "compile_secs": round(compile_secs, 2),
            "loop_secs": [round(t1, 6), round(t2, 6)]}


def measure_traced_join_loop(runner, sql, ks=(2, 6), runs=3):
    """Join queries as ONE traced XLA program (static join capacities +
    overflow retry) timed with the chained-loop slope — no mid-plan host
    syncs, one tunnel compile per K instead of dozens per operator."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from trino_tpu.runtime.traced import compile_query_joins

    plan = runner.plan_sql(sql)
    factor = 1.0
    rows = None
    for _ in range(4):
        fn, pages, names = compile_query_joins(
            plan, runner.metadata, runner.session, factor
        )
        out, ovf = jax.jit(fn)(*pages)
        if int(np.asarray(ovf)) == 0:
            rows = int(np.asarray(jnp.sum(out.active.astype(jnp.int32))))
            break
        factor *= 2.0
    else:
        raise RuntimeError("join capacity overflow after 4 retries")

    def make_looped(k: int):
        def looped(*scan_pages):
            def body(i, carry):
                bit = carry >= jnp.int64(-(10**18))
                perturbed = [type(p)(p.columns, p.active & bit) for p in scan_pages]
                page, ov = fn(*perturbed)
                return carry + jnp.sum(page.active.astype(jnp.int64)) + ov

            return lax.fori_loop(0, k, body, jnp.int64(0))

        return jax.jit(looped)

    k1, k2 = ks
    f1, f2 = make_looped(k1), make_looped(k2)
    t0 = time.time()
    _ = np.asarray(f1(*pages))
    _ = np.asarray(f2(*pages))
    compile_secs = time.time() - t0

    def timed(f):
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            _ = np.asarray(f(*pages))
            best = min(best, time.perf_counter() - t0)
        return best

    t1, t2 = timed(f1), timed(f2)
    secs = max((t2 - t1) / (k2 - k1), 1e-9)
    return {
        "secs": round(secs, 9),
        "compile_secs": round(compile_secs, 2),
        "loop_secs": [round(t1, 6), round(t2, 6)],
        "result_rows": rows,
        "join_capacity_factor": factor,
    }


def measure_traced_join_single(runner, sql, runs=3):
    """Single-dispatch timing for join queries whose chained-loop form cannot
    compile (Q3: Mosaic scoped-VMEM limit under fori_loop; Q18: the looped
    program is fresh HLO and recompiles for tens of minutes through the
    tunnel). Each timed run is dispatch + compute + host fetch of the full
    result — the fetch WAITS for completion, and the post-fetch re-upload
    penalty (~0.45s at SF1) lands inside our time, so this method can only
    OVERSTATE the engine's latency. Honest, just coarser than the slope."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from trino_tpu.runtime.traced import compile_query_joins

    plan = runner.plan_sql(sql)
    factor = 1.0
    for _ in range(4):
        fn, pages, names = compile_query_joins(
            plan, runner.metadata, runner.session, factor
        )
        jfn = jax.jit(fn)
        t0 = time.time()
        out, ovf = jfn(*pages)
        if int(np.asarray(ovf)) == 0:
            compile_secs = time.time() - t0
            break
        factor *= 2.0
    else:
        raise RuntimeError("join capacity overflow after 4 retries")
    rows = int(np.asarray(jnp.sum(out.active.astype(jnp.int32))))
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        out, ovf = jfn(*pages)
        _ = np.asarray(out.active)  # full-result fetch: waits for compute
        _ = int(np.asarray(ovf))
        best = min(best, time.perf_counter() - t0)
    return {
        "secs": round(best, 6),
        "method": "single_dispatch_fetch",
        "compile_secs": round(compile_secs, 2),
        "result_rows": rows,
        "join_capacity_factor": factor,
    }


def measure_adaptive(runner, sql, runs=3):
    """The round-4 join path: one whole-query program with CBO-seeded,
    actuals-tuned per-stage capacities (runtime/adaptive.py). Steady-state
    timing is dispatch + full-result fetch — the fetch waits for completion
    and the post-fetch re-upload penalty lands inside our time, so this can
    only OVERSTATE latency."""
    import time as _t

    import numpy as np

    from trino_tpu.runtime.adaptive import AdaptiveQuery

    plan = runner.plan_sql(sql)
    q = AdaptiveQuery(plan, runner.metadata, runner.session)
    t0 = _t.time()
    page, names = q.tune()
    tune_secs = _t.time() - t0
    best = float("inf")
    for _ in range(runs):
        t0 = _t.perf_counter()
        out, ovf, _acts = q.jfn(*q.pages)
        _ = np.asarray(out.active)  # waits for compute
        _ = int(np.asarray(ovf))
        best = min(best, _t.perf_counter() - t0)
    rows = int(np.asarray(page.active).sum())
    return {
        "secs": round(best, 6),
        "method": "adaptive_single_dispatch_fetch",
        "tune_secs": round(tune_secs, 2),
        "compiles": q.compiles,
        "capacities_from_store": q.seeded_from_store,
        "result_rows": rows,
    }


def measure_ooc(sql: str, scale: float, prefetch_depth: int = 2):
    """One query through the out-of-core tier at ``scale``: wall time incl.
    host datagen (dominant on CPU; the v5e's per-unit device work is
    microseconds-to-ms at these unit sizes). Reports the pipeline's overlap
    evidence: seconds the main loop spent inside device dispatch+sync
    (device_busy) vs blocked on prefetch results (host_wait), prefetch
    hit/miss counts, canonical shape classes, and total XLA compiles — the
    compile count must NOT scale with the bucket count."""
    import time as _t

    import numpy as np

    runner = _make_runner(scale)
    from trino_tpu.runtime.ooc import OutOfCoreRunner

    t0 = _t.time()
    plan = runner.plan_sql(sql)
    ooc = OutOfCoreRunner(
        plan, runner.metadata, runner.session, n_buckets=32, split_batch=8,
        prefetch_depth=prefetch_depth,
    )
    names, page = ooc.execute()
    wall = _t.time() - t0
    rows = int(np.asarray(page.active).sum())
    units = {k: v for k, v in ooc.stats.items() if str(k).endswith("_units")}
    s = ooc.stats
    # time attribution + counters come from the observability plane
    # (runtime/observability.QueryStatsCollector), not private OOC timers —
    # the same numbers EXPLAIN ANALYZE VERBOSE and /v1/query report
    plane = ooc.collector.snapshot()
    times, counts = plane["times"], plane["counts"]
    device_busy = float(times.get("device_busy_secs", 0.0))
    host_wait = float(times.get("host_wait_secs", 0.0))
    return {
        "secs": round(wall, 2),
        "method": "out_of_core_pipelined",
        "result_rows": rows,
        "units": units,
        "spilled_bytes": s.get("spilled_bytes", 0),
        "overlap": {
            "device_busy_secs": round(device_busy, 2),
            "compile_secs": round(float(times.get("compile_secs", 0.0)), 2),
            "fallback_secs": round(float(times.get("fallback_secs", 0.0)), 2),
            "host_wait_secs": round(host_wait, 2),
            "emit_secs": round(float(times.get("emit_secs", 0.0)), 2),
            # fraction of the wall the device was kept busy: the pipeline's
            # whole point is pushing this toward 1.0
            "device_busy_frac": round(device_busy / wall, 3) if wall else 0.0,
            "prefetch_hits": counts.get("prefetch_hits", 0),
            "prefetch_misses": counts.get("prefetch_misses", 0),
            "prefetch_max_inflight_bytes": s.get("prefetch_max_inflight_bytes", 0),
        },
        "per_fragment": plane["fragments"],
        "h2d_bytes": counts.get("h2d_bytes", 0),
        "spill_write_bytes": counts.get("spill_write_bytes", 0),
        "spill_read_bytes": counts.get("spill_read_bytes", 0),
        "compiles": s.get("compiles", 0),
        "shape_classes": s.get("shape_classes", 0),
        "caps_from_store": counts.get("caps_from_store", 0),
        "prefetch_depth": prefetch_depth,
    }


def measure_streaming_q6(scale: float, runs: int = 2):
    """Out-of-core proof: Q6 streamed split-at-a-time with a bounded device
    carry (runtime/streaming.py) — data size decoupled from HBM. Wall time
    includes host datagen (dominant) — engine_secs approximates device-side
    time as wall minus a datagen-only pass."""
    import time as _t

    import numpy as np

    runner = _make_runner(scale)
    from trino_tpu.runtime.streaming import StreamingAggQuery

    plan = runner.plan_sql(Q6)
    q = StreamingAggQuery(plan, runner.metadata, runner.session)
    t0 = _t.time()
    names, page = q.execute()
    wall = _t.time() - t0
    total_rows = 0
    from trino_tpu.connectors.tpch import generator as g

    conn = runner.catalogs.get("tpch")
    nsplits = conn.split_count("lineitem", scale)
    total_rows = sum(g.lineitem_split_rows(scale, s, nsplits) for s in range(nsplits))
    act = np.asarray(page.active)
    revenue = page.to_pylist()[0][0] if act.any() else None
    return {
        "wall_secs": round(wall, 2),
        "splits": q.splits_processed,
        "rows": total_rows,
        "rows_per_sec_wall": round(total_rows / wall, 1),
        "revenue": float(revenue) if revenue is not None else None,
    }


def measure_exchange(scale: float = 1.0, n_parts: int = 16, runs: int = 3):
    """A/B the repartition edge of a TPC-H join at ``scale``: the legacy
    fully host-side path (whole-page D2H -> numpy row hashing -> one boolean
    selection pass + Page object + v1 frame PER partition) vs the device
    repartition epilogue (ops/repartition.py: compiled hash + stable cosort
    + offsets/counts, ONE D2H, v2 frames sliced from the contiguous buffers
    with LZ4 on the shared I/O pool).

    The payload is the Q3 probe-side exchange shape — lineitem keyed by
    l_orderkey with the revenue columns riding along — and both paths'
    partition frames are decoded and compared for BIT-IDENTICAL contents
    (same rows, same order, same masks) before any number is reported."""
    import time as _t

    import numpy as np

    import trino_tpu  # noqa: F401  (enables x64)
    import jax.numpy as jnp
    from trino_tpu.connectors.tpch import generator as g
    from trino_tpu.ops.repartition import repartition_frames
    from trino_tpu.runtime.serde import deserialize_page, serialize_page
    from trino_tpu.runtime.spiller import io_pool
    from trino_tpu.spi.host_pages import (
        host_partition_targets,
        page_to_host,
        pages_from_host_rows,
    )
    from trino_tpu.spi.page import Column, Page
    from trino_tpu.spi.types import parse_type

    nsplits = max(1, int(scale * 4))
    cols = {"l_orderkey": [], "l_extendedprice": [], "l_discount": [],
            "l_shipdate": []}
    for s in range(nsplits):
        data = g.generate_split("lineitem", scale, s, nsplits)
        for k in cols:
            cols[k].append(data.columns[k])
    arrs = {k: np.concatenate(v) for k, v in cols.items()}
    rows = len(arrs["l_orderkey"])
    cap = 1 << max(10, (rows - 1).bit_length())  # canonical shape class
    types = {"l_orderkey": "bigint", "l_extendedprice": "decimal(12,2)",
             "l_discount": "decimal(12,2)", "l_shipdate": "date"}
    page = Page(
        tuple(
            Column.from_numpy(parse_type(types[k]), arrs[k], capacity=cap)
            for k in types
        ),
        jnp.asarray(np.arange(cap) < rows),
    )
    key_idx = [0]  # l_orderkey

    def run_host():
        hc = page_to_host(page)
        target = host_partition_targets(hc, key_idx, n_parts)
        return [
            serialize_page(pages_from_host_rows(hc, target == b))
            for b in range(n_parts)
        ]

    def run_device():
        return repartition_frames(page, key_idx, n_parts, pool=io_pool())[0]

    t0 = _t.time()
    device_blobs = run_device()  # compile + warm
    compile_secs = _t.time() - t0
    host_blobs = run_host()

    # bit-identity gate: every partition must decode to the same rows in the
    # same order with the same validity, on both paths
    identical = True
    for b in range(n_parts):
        hp = deserialize_page(host_blobs[b])
        dp = deserialize_page(device_blobs[b])
        ha, da = np.asarray(hp.active), np.asarray(dp.active)
        if int(ha.sum()) != int(da.sum()):
            identical = False
            break
        for hc_, dc_ in zip(hp.columns, dp.columns):
            hd = np.asarray(hc_.data)[ha]
            dd = np.asarray(dc_.data)[da]
            hv = np.asarray(hc_.valid)[ha]
            dv = np.asarray(dc_.valid)[da]
            if not (np.array_equal(hd, dd) and np.array_equal(hv, dv)):
                identical = False
                break

    def timed(fn):
        best = float("inf")
        for _ in range(runs):
            t0 = _t.perf_counter()
            fn()
            best = min(best, _t.perf_counter() - t0)
        return best

    host_secs = timed(run_host)
    device_secs = timed(run_device)
    return {
        "rows": rows,
        "n_parts": n_parts,
        "capacity": cap,
        "columns": list(types),
        "partition_key": "l_orderkey",
        "identical": identical,
        "host_secs": round(host_secs, 4),
        "device_secs": round(device_secs, 4),
        "device_compile_secs": round(compile_secs, 2),
        "speedup": round(host_secs / device_secs, 2) if device_secs else 0.0,
        "host_wire_bytes": sum(len(b) for b in host_blobs),
        "device_wire_bytes": sum(len(b) for b in device_blobs),
        "runs": runs,
    }


def _nearest_rank_percentile(sorted_vals, q):
    """Nearest-rank percentile: ceil(q*n)-1 (the FTE straggler-quantile
    convention) — shared by the multi-client replay benches."""
    import math

    n = len(sorted_vals)
    if not n:
        return 0.0
    return sorted_vals[max(0, min(n - 1, math.ceil(q * n) - 1))]


# the r09/r13/r16 saturation-replay workload: a mixed Q1/Q3/Q6/Q13 class
# set (shared by measure_concurrency and the r19 hostpath attribution pass)
CONCURRENCY_MIX = {
    "q1": """
        SELECT l_returnflag, l_linestatus, sum(l_quantity), count(*)
        FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus""",
    "q3": """
        SELECT o_orderkey, sum(l_extendedprice)
        FROM lineitem JOIN orders ON l_orderkey = o_orderkey
        WHERE o_orderdate < DATE '1995-03-15'
        GROUP BY o_orderkey ORDER BY 2 DESC, 1 LIMIT 10""",
    "q6": """
        SELECT sum(l_extendedprice * l_discount)
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1995-01-01'
          AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24""",
    "q13": """
        SELECT c_custkey, count(o_orderkey)
        FROM customer LEFT JOIN orders ON c_custkey = o_custkey
        GROUP BY c_custkey ORDER BY 2 DESC, 1 LIMIT 10""",
}


def measure_concurrency(
    scale: float = 0.01,
    clients=(1, 2, 4, 8, 16),
    per_client: int = 6,
    pool_factor: float = 8.0,
    device_batching: bool = False,
):
    """ROADMAP sustained-concurrency benchmark: N client threads replaying a
    mixed Q1/Q3/Q6/Q13 TPC-H workload through a QueryManager over one
    runner, against a memory pool sized ``pool_factor`` x the largest
    single-query reservation (the arbitration plane is ON: blocking
    backpressure + the low-memory killer). Per concurrency level: pooled
    AND per-query-class p50/p99 latency, throughput, and the device program
    launch count (``trino_tpu_device_programs_total`` delta — the number
    the batching A/B attributes its win to); ``saturation_qps`` is the best
    level's queries/sec. Queries shed by the killer under overload are
    counted, not errors — that is the plane doing its job.
    ``device_batching=True`` runs the same replay with the device-batching
    plane on (ragged multi-query packing + shared-scan elimination);
    per-query result fingerprints ride every level so A/B runs can assert
    bit-identity."""
    import hashlib as _hl
    import threading as _th
    import time as _t

    from trino_tpu.runtime.device_scheduler import program_launches
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.memory import (
        ClusterMemoryManager,
        MemoryPool,
        memory_scope,
    )
    from trino_tpu.runtime.query_manager import QueryManager, QueryState

    mix = CONCURRENCY_MIX
    runner = LocalQueryRunner.tpch(scale=scale)
    if device_batching:
        runner.session.set("device_batching", True)
    names = sorted(mix)
    sqls = [mix[n] for n in names]
    # warm every shape (JIT compile) + size the pool from measured peaks
    peaks = []
    for i, sql in enumerate(sqls):
        probe = MemoryPool(0, name=f"bench_probe{i}")
        with memory_scope(f"p{i}", probe):
            runner.execute(sql)
        peaks.append(probe.peak_bytes)
    pool_bytes = int(pool_factor * max(peaks))

    percentile = _nearest_rank_percentile

    def rows_fingerprint(rows) -> str:
        return _hl.sha256(repr(rows).encode()).hexdigest()[:16]

    levels = []
    fingerprints: dict = {}  # class -> {fingerprint, ...} across ALL levels
    for n_clients in clients:
        # each level is an independent experiment: a cold batching window
        # (no shared-scan/subsumption carry-over from the previous level),
        # so every level's first wave pays the same compute and the p99s
        # are comparable across levels
        from trino_tpu.runtime.device_scheduler import SCHEDULER

        SCHEDULER.reset_stats()
        pool = MemoryPool(pool_bytes, name=f"bench{n_clients}")
        cm = ClusterMemoryManager(pool, spill_after=0.01, kill_after=0.1)
        mgr = QueryManager(
            runner.execute, max_workers=max(4, n_clients), cluster_memory=cm
        )
        latencies = []
        by_class: dict = {n: [] for n in names}
        outcomes = {"finished": 0, "killed": 0, "failed": 0}
        lock = _th.Lock()

        def client(cid):
            for j in range(per_client):
                cls = names[(cid + j) % len(names)]
                t0 = _t.perf_counter()
                q = mgr.submit(mix[cls])
                q.wait_done(600)
                dt = _t.perf_counter() - t0
                with lock:
                    latencies.append(dt)
                    by_class[cls].append(dt)
                    if q.state is QueryState.FINISHED:
                        outcomes["finished"] += 1
                        fingerprints.setdefault(cls, set()).add(
                            rows_fingerprint(q.rows)
                        )
                    elif q.error_type == "AdministrativelyKilled":
                        outcomes["killed"] += 1
                    else:
                        outcomes["failed"] += 1

        threads = [
            _th.Thread(
                target=client, args=(c,), name=f"bench-client-{c}"
            )
            for c in range(n_clients)
        ]
        launches0 = program_launches()
        t0 = _t.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _t.perf_counter() - t0
        launches = program_launches() - launches0
        lat = sorted(latencies)
        levels.append({
            "clients": n_clients,
            "queries": len(lat),
            "wall_secs": round(wall, 3),
            "qps": round(len(lat) / wall, 2) if wall else 0.0,
            "p50_ms": round(percentile(lat, 0.50) * 1000, 2),
            "p95_ms": round(percentile(lat, 0.95) * 1000, 2),
            "p99_ms": round(percentile(lat, 0.99) * 1000, 2),
            # raw per-query latencies: the v3 sample vector the hostpath
            # A/B (and any future consumer) computes median/MAD from
            "latency_samples": [round(x, 6) for x in lat],
            "device_program_launches": int(launches),
            "per_class": {
                n: {
                    "queries": len(ls),
                    "p50_ms": round(percentile(sorted(ls), 0.50) * 1000, 2),
                    "p99_ms": round(percentile(sorted(ls), 0.99) * 1000, 2),
                }
                for n, ls in by_class.items() if ls
            },
            "low_memory_kills": cm.kills_total,
            **outcomes,
        })
    best = max(levels, key=lambda r: r["qps"])
    return {
        "scale": scale,
        "mix": names,
        "per_client": per_client,
        "pool_bytes": pool_bytes,
        "pool_factor": pool_factor,
        "killer": "total-reservation-on-blocked-nodes",
        "device_batching": device_batching,
        "levels": levels,
        # one fingerprint per class across every level and client = every
        # finished execution of a class produced the same bytes
        "result_fingerprints": {
            n: sorted(fps) for n, fps in sorted(fingerprints.items())
        },
        "internally_consistent": all(
            len(fps) == 1 for fps in fingerprints.values()
        ),
        "saturation_qps": best["qps"],
        "saturation_clients": best["clients"],
    }


def measure_batching_ab(
    scale: float = 0.01, clients=(1, 2, 4, 8, 16), per_client: int = 6
):
    """Device-batching A/B (ISSUE 11 acceptance, BENCH_r13_batching_ab.json):
    the BENCH_r09 mixed replay with ``device_batching`` off vs on at every
    concurrency level. The claims the record carries:

    - ``bit_identical``: every finished query of a class produced one
      result fingerprint, within each mode and ACROSS the two modes;
    - ``launches_strictly_fewer``: the on-mode replay dispatched strictly
      fewer device programs at every multi-client level (the packed ragged
      launches + shared scans are where the time goes);
    - ``saturation_speedup`` and per-level p99s for the latency story.
    """
    off = measure_concurrency(
        scale=scale, clients=clients, per_client=per_client,
        device_batching=False,
    )
    on = measure_concurrency(
        scale=scale, clients=clients, per_client=per_client,
        device_batching=True,
    )
    identical = off["internally_consistent"] and on["internally_consistent"]
    for cls, fps in off["result_fingerprints"].items():
        if on["result_fingerprints"].get(cls) != fps:
            identical = False
    fewer = all(
        lon["device_program_launches"] < loff["device_program_launches"]
        for loff, lon in zip(off["levels"], on["levels"])
        if lon["clients"] > 1
    )
    p99_by_clients = {l["clients"]: l["p99_ms"] for l in on["levels"]}
    return {
        "scale": scale,
        "mix": off["mix"],
        "per_client": per_client,
        "off": off,
        "on": on,
        "bit_identical": identical,
        "launches_strictly_fewer": fewer,
        "saturation_qps_off": off["saturation_qps"],
        "saturation_qps_on": on["saturation_qps"],
        "saturation_speedup": round(
            on["saturation_qps"] / off["saturation_qps"], 2
        ) if off["saturation_qps"] else 0.0,
        "p99_16c_vs_4c_on": (
            round(p99_by_clients.get(16, 0.0) / p99_by_clients[4], 3)
            if p99_by_clients.get(4) else None
        ),
    }


def measure_megakernel_ab(scale: float = 0.01, runs: int = 5):
    """Megakernel-plane A/B (ISSUE 12 acceptance, BENCH_r14_megakernel_ab
    .json): the join-heavy TPC-H shapes (Q3 / Q5 / Q13) with
    ``pallas_fusion`` off vs on. Per fragment class the record carries:

    - ``device_program_launches``: plan-node program dispatches
      (trino_tpu_device_programs_total delta) — the fused path must be
      STRICTLY fewer on every join+agg shape (one megakernel replaces the
      join-node program + the aggregation-node program);
    - ``pallas_launches`` / ``pallas_fallbacks``: how many fused kernels
      actually ran and how many fragments declined (fallback matrix);
    - ``bit_identical``: fused rows == serial rows per query;
    - a composition level with ``device_batching`` ON TOO: fused fragments
      must coexist with the ragged-lane batching plane (batchable chains
      are join-free, so the planes serve disjoint fragments), results
      bit-identical across all four knob combinations.

    CPU-labeled like every BENCH number since round 5 (ROADMAP item 2's
    hardware-verified ladder): interpret-mode kernels measure the DISPATCH
    structure — strictly fewer device programs per fragment — not TPU
    kernel wall-clock; wall times here are CPU interpret times and carry
    no speed claim.
    """
    import statistics

    from trino_tpu.ops import megakernels as MK
    from trino_tpu.runtime.device_scheduler import program_launches
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.metrics import REGISTRY

    mix = {
        "q3": """
            SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
                   o_orderdate, o_shippriority
            FROM customer, orders, lineitem
            WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
              AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
              AND l_shipdate > DATE '1995-03-15'
            GROUP BY l_orderkey, o_orderdate, o_shippriority
            ORDER BY revenue DESC, o_orderdate, l_orderkey LIMIT 10""",
        "q5": """
            SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
            FROM customer, orders, lineitem, supplier, nation, region
            WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
              AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey
              AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
              AND r_name = 'ASIA'
              AND o_orderdate >= DATE '1994-01-01'
              AND o_orderdate < DATE '1995-01-01'
            GROUP BY n_name ORDER BY revenue DESC, n_name""",
        "q13": """
            SELECT c_custkey, count(o_orderkey) AS c_count
            FROM customer LEFT JOIN orders ON c_custkey = o_custkey
            GROUP BY c_custkey ORDER BY c_count DESC, c_custkey LIMIT 20""",
    }

    def fallbacks_total() -> float:
        return sum(
            m["value"] for m in REGISTRY.collect()
            if m["name"] == "trino_tpu_pallas_fallbacks_total"
        )

    runner = LocalQueryRunner.tpch(scale=scale)
    per_query = {}
    serial_rows = {}
    for name, sql in sorted(mix.items()):
        entry = {}
        rows_by_mode = {}
        for mode in ("off", "on"):
            runner.session.set("pallas_fusion", mode == "on")
            runner.execute(sql)  # warm the compile caches for this mode
            n0, p0, f0 = program_launches(), MK.pallas_launches(), fallbacks_total()
            rows_by_mode[mode] = runner.execute(sql).rows
            launches = program_launches() - n0
            samples = []
            for _ in range(runs):
                t0 = time.perf_counter()
                runner.execute(sql)
                samples.append(time.perf_counter() - t0)
            entry[mode] = {
                "device_program_launches": int(launches),
                "pallas_launches": int(MK.pallas_launches() - p0),
                "pallas_fallbacks": int(fallbacks_total() - f0),
                "median_secs": round(statistics.median(samples), 4),
            }
        runner.session.set("pallas_fusion", False)
        serial_rows[name] = rows_by_mode["off"]
        entry["bit_identical"] = rows_by_mode["off"] == rows_by_mode["on"]
        entry["launches_strictly_fewer"] = (
            entry["on"]["device_program_launches"]
            < entry["off"]["device_program_launches"]
        )
        per_query[name] = entry

    # composition: device_batching on in BOTH modes — the planes serve
    # disjoint fragment shapes of the same query and must not interfere;
    # rows in every knob combination must equal the plain serial rows
    composed = {}
    for name, sql in sorted(mix.items()):
        runner.session.set("device_batching", True)
        rows = {}
        for mode in ("off", "on"):
            runner.session.set("pallas_fusion", mode == "on")
            rows[mode] = runner.execute(sql).rows
        runner.session.set("device_batching", False)
        runner.session.set("pallas_fusion", False)
        composed[name] = {
            "bit_identical_across_4_knob_combos": (
                rows["off"] == serial_rows[name]
                and rows["on"] == serial_rows[name]
            ),
        }
    return {
        "scale": scale,
        "runs": runs,
        "caveat": (
            "CPU backend, interpret-mode kernels: launch counts are the "
            "measured claim; wall times carry no TPU speed claim "
            "(hardware-verified ladder = ROADMAP item 2)"
        ),
        "queries": per_query,
        "composed_with_device_batching": composed,
        "all_bit_identical": all(
            e["bit_identical"] for e in per_query.values()
        ) and all(
            c["bit_identical_across_4_knob_combos"] for c in composed.values()
        ),
        "agg_fused_shapes_strictly_fewer": all(
            per_query[q]["launches_strictly_fewer"] for q in ("q3", "q5", "q13")
        ),
    }


def measure_vector_ab(rows: int = 150_000, dim: int = 64, k: int = 10,
                      runs: int = 5):
    """Tensor-plane A/B (ISSUE 13 acceptance, BENCH_r15_vector_ab.json):
    ORDER BY cosine_similarity LIMIT k over a memory-resident VECTOR(dim)
    table at a customer-SF1-shaped row count (150k), fused
    (``vector_topk_fusion``) vs the serial Project + TopN oracle, plus
    linear/GBDT model scoring through the table-function path vs the
    equivalent hand-expanded SQL arithmetic.

    The measured CLAIMS are structural: strictly fewer device-program
    launches on the fused path and bit-identical rows; wall times are
    CPU-labeled like every BENCH number since round 5 (the
    hardware-verified ladder = ROADMAP item 2) and carry no TPU speed
    claim — on a chip the (rows, dim) @ (dim,) matvec is the MXU's home
    shape.
    """
    import statistics

    import jax.numpy as jnp
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.ops import tensor as T
    from trino_tpu.runtime.device_scheduler import program_launches
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.spi.connector import ColumnMetadata, SchemaTableName
    from trino_tpu.spi.page import Page, Column
    from trino_tpu.spi.types import BIGINT, vector_type

    runner = LocalQueryRunner.tpch(scale=0.001)
    mem = MemoryConnector()
    runner.register_catalog("memory", mem)
    name = SchemaTableName("default", "bench_emb")
    vtype = vector_type(dim)
    mem.create_table(name, [
        ColumnMetadata("id", BIGINT), ColumnMetadata("v", vtype),
    ])
    rng = np.random.RandomState(42)
    t0 = time.perf_counter()
    ids = np.arange(rows, dtype=np.int64)
    vecs = rng.standard_normal((rows, dim))
    page = Page(
        (
            Column.from_numpy(BIGINT, ids),
            Column.from_numpy(vtype, vecs),
        ),
        jnp.ones((rows,), dtype=bool),
    )
    mem.insert(name, page)
    ingest_secs = time.perf_counter() - t0
    q = ", ".join(f"{x:.6f}" for x in rng.standard_normal(dim))
    topk_sql = (
        "SELECT id FROM memory.default.bench_emb "
        f"ORDER BY cosine_similarity(v, ARRAY[{q}]) DESC, id LIMIT {k}"
    )

    def run_mode(on: bool):
        runner.session.set("tensor_plane", on)
        runner.session.set("vector_topk_fusion", on)
        runner.execute(topk_sql)  # warm the compile caches for this mode
        n0, v0 = program_launches(), T.vector_launches()
        rows_out = runner.execute(topk_sql).rows
        launches = program_launches() - n0
        vector_launches = T.vector_launches() - v0
        samples = []
        for _ in range(runs):
            t1 = time.perf_counter()
            runner.execute(topk_sql)
            samples.append(time.perf_counter() - t1)
        return rows_out, {
            "device_program_launches": int(launches),
            "vector_kernel_launches": int(vector_launches),
            "median_secs": round(statistics.median(samples), 4),
        }

    serial_rows, serial = run_mode(False)
    fused_rows, fused = run_mode(True)
    runner.session.set("tensor_plane", False)
    runner.session.set("vector_topk_fusion", False)

    # model scoring: table function (one matmul) vs hand-expanded arithmetic
    runner.session.set("tensor_plane", True)
    runner.session.set("model_scoring", True)
    feat_dim = 8
    w = rng.standard_normal(feat_dim)
    # features derived from id so both formulations see identical inputs
    feat_exprs = ", ".join(
        f"CAST(id % {13 + i} AS double) AS f{i}" for i in range(feat_dim)
    )
    weights_sql = ", ".join(f"{x:.6f}" for x in w)
    scored_tf = (
        "SELECT max(score) FROM TABLE(linear_score("
        f" input => TABLE(SELECT id, {feat_exprs} FROM"
        "   memory.default.bench_emb),"
        f" features => DESCRIPTOR({', '.join(f'f{i}' for i in range(feat_dim))}),"
        f" weights => ARRAY[{weights_sql}], bias => 0.5))"
    )
    arith = " + ".join(
        f"({x:.6f} * CAST(id % {13 + i} AS double))"
        for i, x in enumerate(w)
    )
    scored_sql = (
        f"SELECT max(0.5 + {arith}) FROM memory.default.bench_emb"
    )

    def timed_median(sql):
        runner.execute(sql)
        samples = []
        for _ in range(max(3, runs // 2)):
            t1 = time.perf_counter()
            out = runner.execute(sql).rows
            samples.append(time.perf_counter() - t1)
        return out, round(statistics.median(samples), 4)

    tf_rows, tf_secs = timed_median(scored_tf)
    sql_rows, sql_secs = timed_median(scored_sql)
    runner.session.set("tensor_plane", False)
    runner.session.set("model_scoring", False)
    score_match = abs(tf_rows[0][0] - sql_rows[0][0]) <= 1e-9 * max(
        1.0, abs(sql_rows[0][0])
    )
    return {
        "rows": rows,
        "dim": dim,
        "k": k,
        "runs": runs,
        "ingest_secs": round(ingest_secs, 3),
        "caveat": (
            "CPU backend: launch counts and bit-identity are the measured "
            "claims; wall times carry no TPU speed claim (the matvec shape "
            "is measured on-chip under ROADMAP item 2's ladder)"
        ),
        "topk": {
            "off": serial,
            "on": fused,
            "bit_identical": fused_rows == serial_rows,
            "launches_strictly_fewer": (
                fused["device_program_launches"]
                < serial["device_program_launches"]
            ),
        },
        "scoring": {
            "table_function_median_secs": tf_secs,
            "sql_arithmetic_median_secs": sql_secs,
            "results_match": bool(score_match),
        },
    }


def measure_vector_serving_ab(rows: int = 50_000, dim: int = 32, k: int = 10,
                              levels=(1, 4, 16, 64), n_clusters: int = 16):
    """Vector-serving A/B (ISSUE 16 acceptance, BENCH_r18_vector_serving_ab
    .json): concurrent vector top-k clients — each with its OWN query
    constant — replayed at 1/4/16/64 clients with ``vector_query_batching``
    off vs on, plus the IVF ANN ladder (recall@k and pruned splits per
    nprobe, nprobe=n_clusters bit-identical to exact).

    The measured CLAIMS are structural: per-level result fingerprints
    identical off vs on, fewer device-program launches under batching at
    every concurrent level, and the recall ladder monotone. Wall times are
    CPU-labeled like every BENCH number since round 5 and carry no TPU
    speed claim — on a chip the stacked (rows, dim) lanes are the MXU's
    home shape.
    """
    import hashlib
    import statistics
    import tempfile
    import threading

    import jax.numpy as jnp
    import numpy as np

    from trino_tpu.connectors.memory import MemoryConnector
    from trino_tpu.connectors.vector_index import IvfVectorConnector
    from trino_tpu.fs import FileSystemManager, LocalFileSystem
    from trino_tpu.ops import tensor as T
    from trino_tpu.runtime.device_scheduler import SCHEDULER, program_launches
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.spi.connector import ColumnMetadata, SchemaTableName
    from trino_tpu.spi.page import Column, Page
    from trino_tpu.spi.types import BIGINT, vector_type

    runner = LocalQueryRunner.tpch(scale=0.001)
    mem = MemoryConnector()
    runner.register_catalog("memory", mem)
    name = SchemaTableName("default", "serve_emb")
    vtype = vector_type(dim)
    mem.create_table(name, [
        ColumnMetadata("id", BIGINT), ColumnMetadata("v", vtype),
    ])
    rng = np.random.RandomState(42)
    ids = np.arange(rows, dtype=np.int64)
    vecs = rng.standard_normal((rows, dim))
    mem.insert(name, Page(
        (Column.from_numpy(BIGINT, ids), Column.from_numpy(vtype, vecs)),
        jnp.ones((rows,), dtype=bool),
    ))

    def sql_for(i: int) -> str:
        qr = np.random.RandomState(9000 + i)
        q = ", ".join(f"{x:.6f}" for x in qr.standard_normal(dim))
        return (
            "SELECT id FROM memory.default.serve_emb "
            f"ORDER BY cosine_similarity(v, ARRAY[{q}]) DESC, id LIMIT {k}"
        )

    def fingerprint(rows_out) -> str:
        return hashlib.sha256(repr(rows_out).encode()).hexdigest()[:16]

    runner.session.set("tensor_plane", True)
    runner.session.set("vector_topk_fusion", True)
    max_level = max(levels)
    sqls = [sql_for(i) for i in range(max_level)]
    serial_fp = {}
    for i, s in enumerate(sqls):
        serial_fp[i] = fingerprint(runner.execute(s).rows)

    def run_level(level: int, batching: bool):
        if batching:
            runner.session.set("device_batching", True)
            runner.session.set("vector_query_batching", True)
            runner.session.set("batch_admit_window_ms", 25.0)
        else:
            for knob in ("device_batching", "vector_query_batching",
                         "batch_admit_window_ms"):
                runner.session.properties.pop(knob, None)
        SCHEDULER.reset_stats()
        fps = [None] * level
        errors = []
        barrier = threading.Barrier(level)

        def go(i):
            try:
                barrier.wait(timeout=120)
                fps[i] = fingerprint(runner.execute(sqls[i]).rows)
            except Exception as e:  # noqa: BLE001 — reported in the record
                errors.append(f"{type(e).__name__}: {e}")

        n0 = program_launches()
        t0 = time.perf_counter()
        threads = [
            threading.Thread(
                target=go, args=(i,), name=f"bench-client-{i}"
            )
            for i in range(level)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return {
            "device_program_launches": int(program_launches() - n0),
            "stacked_launches": int(SCHEDULER.vector_batched_launches),
            "batched_queries": (
                int(sum(1 for f in fps if f is not None))
                if batching else 0
            ),
            "wall_secs": round(wall, 4),
            "fingerprints_match_serial": all(
                fps[i] == serial_fp[i] for i in range(level)
            ),
            "errors": errors[:3],
        }

    by_level = {}
    for level in levels:
        off = run_level(level, batching=False)
        on = run_level(level, batching=True)
        by_level[str(level)] = {
            "off": off,
            "on": on,
            "launches_fewer_or_equal": (
                on["device_program_launches"]
                <= off["device_program_launches"]
            ),
        }
    for knob in ("device_batching", "vector_query_batching",
                 "batch_admit_window_ms"):
        runner.session.properties.pop(knob, None)

    # ---------------------------------------------------- ANN recall ladder
    tmp = tempfile.mkdtemp(prefix="ivf_bench_")
    fsm = FileSystemManager()
    fsm.register("local", lambda: LocalFileSystem(tmp))
    ivf = IvfVectorConnector(fsm, "local://ivf")
    t0 = time.perf_counter()
    ivf.build_index(
        SchemaTableName("default", "emb"),
        [ColumnMetadata("id", BIGINT), ColumnMetadata("v", vtype)],
        [(int(i), vecs[i].tolist()) for i in range(rows)],
        "v",
        n_clusters=n_clusters,
    )
    build_secs = time.perf_counter() - t0
    runner.register_catalog("vec", ivf)
    ann_sql = sqls[0].replace("memory.default.serve_emb", "vec.default.emb")
    exact_rows = runner.execute(ann_sql).rows
    ladder = []
    nprobe = 1
    while nprobe <= n_clusters:
        runner.session.set("ann_mode", f"approx(nprobe={nprobe})")
        p0 = T.ann_pruned_splits()
        t0 = time.perf_counter()
        got = runner.execute(ann_sql).rows
        wall = time.perf_counter() - t0
        ladder.append({
            "nprobe": nprobe,
            "recall_at_k": round(
                len({r[0] for r in got} & {r[0] for r in exact_rows})
                / len(exact_rows), 4,
            ),
            "pruned_splits": int(T.ann_pruned_splits() - p0),
            "wall_secs": round(wall, 4),
            "bit_identical_to_exact": got == exact_rows,
        })
        nprobe *= 2
    runner.session.properties.pop("ann_mode", None)
    runner.session.set("tensor_plane", False)
    runner.session.set("vector_topk_fusion", False)

    return {
        "rows": rows,
        "dim": dim,
        "k": k,
        "client_levels": list(levels),
        "n_clusters": n_clusters,
        "index_build_secs": round(build_secs, 3),
        "caveat": (
            "CPU backend: launch counts, result fingerprints, and the "
            "recall ladder are the measured claims; wall times carry no "
            "TPU speed claim (the stacked lanes are the MXU home shape "
            "measured under ROADMAP item 2's ladder)"
        ),
        "concurrency": by_level,
        "ann": {
            "ladder": ladder,
            "full_probe_bit_identical": ladder[-1]["bit_identical_to_exact"]
            if ladder and ladder[-1]["nprobe"] == n_clusters else None,
        },
    }


def measure_ha_ab(scale: float = 0.0005, clients: int = 100,
                  per_client: int = 1, ttl: float = 1.0):
    """Serving-fabric A/B (ISSUE 14 acceptance, BENCH_r16_ha_ab.json): a
    ``clients``-thread mixed FTE replay through a two-coordinator HA pair
    over real WorkerServers on one shared exchange substrate, with

    - a mid-run coordinator KILL: the ``coordinator_crash`` chaos site
      fires inside one in-flight query, the primary's lease renewals stop
      (the process is "dead"), the standby takes the lease at the next
      epoch and RESUMES every orphaned/fenced query from its dispatch
      journal — zero lost queries;
    - a worker scale-UP admitted into RUNNING queries mid-replay and a
      graceful scale-DOWN (drain, then retire) later;
    - a one-leader sampler polling both leases the whole run (exactly one
      leader at all times) and an explicit fencing assertion (the dead
      leader's late journal write is rejected).

    Every survivor's rows are fingerprinted against a chaos-free oracle of
    the same class — bit-identity is the correctness claim; latencies are
    CPU-labeled (single-core container: protocol/GIL contention dominates).
    """
    import hashlib as _hl
    import tempfile as _tf
    import threading as _th
    import time as _t

    import jax as _jax

    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.metadata import CatalogManager, Session
    from trino_tpu.parallel.runner import DistributedQueryRunner
    from trino_tpu.runtime.failure import ChaosInjector
    from trino_tpu.runtime.ha import (
        CoordinatorCrashError,
        DispatchJournal,
        FencedWriteError,
        LeaderLease,
        ScaleController,
        resume_fte_query,
    )
    from trino_tpu.server.worker import WorkerServer

    secret = "ha-bench-secret"
    schema = "sf" + f"{scale:g}".replace(".", "_")
    mix = {
        "q1": """
            SELECT l_returnflag, l_linestatus, sum(l_quantity), count(*)
            FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
            GROUP BY l_returnflag, l_linestatus
            ORDER BY l_returnflag, l_linestatus""",
        "q3": """
            SELECT o_orderkey, sum(l_extendedprice)
            FROM lineitem JOIN orders ON l_orderkey = o_orderkey
            WHERE o_orderdate < DATE '1995-03-15'
            GROUP BY o_orderkey ORDER BY 2 DESC, 1 LIMIT 10""",
        "q6": """
            SELECT sum(l_extendedprice * l_discount)
            FROM lineitem
            WHERE l_shipdate >= DATE '1994-01-01'
              AND l_shipdate < DATE '1995-01-01'
              AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24""",
        "q13": """
            SELECT c_custkey, count(o_orderkey)
            FROM customer LEFT JOIN orders ON c_custkey = o_custkey
            GROUP BY c_custkey ORDER BY 2 DESC, 1 LIMIT 10""",
    }
    names = sorted(mix)
    tmp = _tf.mkdtemp(prefix="ha_bench_")
    exdir = os.path.join(tmp, "exchange")
    hadir = os.path.join(tmp, "ha")

    def catalogs():
        c = CatalogManager()
        c.register("tpch", TpchConnector(scale=scale, split_target_rows=512))
        return c

    workers = [
        WorkerServer(catalogs(), secret=secret).start() for _ in range(3)
    ]
    urls = [f"http://{w.address}" for w in workers]

    def make_runner(ha: bool, lease=None):
        r = DistributedQueryRunner(
            Session(catalog="tpch", schema=schema), n_workers=2,
            worker_urls=list(urls[:2]), secret=secret,
        )
        r.catalogs.register(
            "tpch", TpchConnector(scale=scale, split_target_rows=512)
        )
        r.session.set("retry_policy", "TASK")
        r.session.set("fte_exchange_dir", exdir)
        if ha:
            r.session.set("ha_plane", True)
            r.session.set("elastic_workers", True)
            r.ha_lease = lease
        return r

    def fp(rows) -> str:
        return _hl.sha256(repr(rows).encode()).hexdigest()[:16]

    try:
        # chaos-free oracle per class (also warms every compile cache +
        # the workers' task paths)
        oracle_runner = make_runner(ha=False)
        oracle = {n: fp(oracle_runner.execute(mix[n]).rows) for n in names}

        lease_a = LeaderLease(hadir, "coordinator-a", ttl=ttl)
        lease_b = LeaderLease(hadir, "coordinator-b", ttl=ttl)
        assert lease_a.acquire()
        runner_a = make_runner(ha=True, lease=lease_a)
        runner_b = make_runner(ha=True, lease=lease_b)
        fleet = {"leader": runner_a}
        stop = _th.Event()
        a_dead = _th.Event()
        failover = {"done": False, "fenced_write_rejected": False,
                    "resumes": 0, "reruns": 0}
        failover_lock = _th.Lock()
        both_leaders = [0]
        leader_gaps = [0]

        def sampler():
            while not stop.is_set():
                a, b = lease_a.is_leader(), lease_b.is_leader()
                if a and b:
                    both_leaders[0] += 1
                if not (a or b):
                    leader_gaps[0] += 1  # expiry->takeover window (allowed)
                _t.sleep(0.005)

        def renewer():
            # the primary's renewal loop — "dies" with the coordinator
            while not stop.is_set() and not a_dead.is_set():
                lease_a.renew()
                _t.sleep(ttl / 3)

        def take_over():
            """Standby takeover + fencing assertion; idempotent."""
            with failover_lock:
                if failover["done"]:
                    return
                a_dead.set()
                deadline = _t.monotonic() + 30
                while not lease_b.acquire():
                    if _t.monotonic() > deadline:
                        raise RuntimeError("standby never took the lease")
                    _t.sleep(0.05)
                # fencing: the dead leader's late write must be rejected
                stale = DispatchJournal(
                    os.path.join(exdir, "fence_probe", "journal.jsonl"),
                    lease=lease_a, epoch=1,
                )
                try:
                    stale.append({"kind": "winner", "fid": 0, "p": 0,
                                  "attempt": 0})
                except FencedWriteError:
                    failover["fenced_write_rejected"] = True
                fleet["leader"] = runner_b
                failover["done"] = True

        def run_one(sql):
            """One client query through the fleet, failing over on a
            coordinator death (crash chaos or fenced old leader)."""
            try:
                return fleet["leader"].execute(sql)
            except (CoordinatorCrashError, FencedWriteError) as e:
                take_over()
                path = getattr(e, "journal_path", None)
                if path and os.path.isfile(path):
                    try:
                        r = resume_fte_query(runner_b, path)
                        with failover_lock:
                            failover["resumes"] += 1
                        return r
                    except Exception:  # noqa: BLE001 — rerun fallback below
                        pass
                with failover_lock:
                    failover["reruns"] += 1
                return runner_b.execute(sql)

        # elastic workers: scale-up admits urls[2] into RUNNING queries and
        # future submissions; scale-down drains urls[0] gracefully
        retired = []

        def _retire(url):
            retired.append(url)
            for r in (runner_a, runner_b):
                if url in r.worker_urls:
                    r.worker_urls.remove(url)

        ctl = ScaleController(
            spawn=lambda: urls[2], retire=_retire,
            min_workers=1, max_workers=3,
        )
        ctl.workers = list(urls[:2])

        def scale_up():
            url = ctl.scale_up()
            for r in (runner_a, runner_b):
                if url and url not in r.worker_urls:
                    r.worker_urls.append(url)
            return url

        latencies = []
        by_class = {n: [] for n in names}
        outcomes = {"finished": 0, "lost": 0}
        fps = {n: set() for n in names}
        lock = _th.Lock()
        done_count = [0]
        total = clients * per_client

        def client(cid):
            for j in range(per_client):
                cls = names[(cid + j) % len(names)]
                t0 = _t.perf_counter()
                try:
                    res = run_one(mix[cls])
                    dt = _t.perf_counter() - t0
                    with lock:
                        latencies.append(dt)
                        by_class[cls].append(dt)
                        outcomes["finished"] += 1
                        fps[cls].add(fp(res.rows))
                except Exception:  # noqa: BLE001 — a lost query is the metric
                    with lock:
                        outcomes["lost"] += 1
                finally:
                    with lock:
                        done_count[0] += 1

        def controller(chaos):
            # kill the coordinator after ~15% of the replay, scale up right
            # after failover, drain a worker at ~60%
            while done_count[0] < max(1, total // 7) and not stop.is_set():
                _t.sleep(0.02)
            chaos.arm("coordinator_crash", times=1, match="_post")
            while not failover["done"] and not stop.is_set():
                _t.sleep(0.05)
            up = scale_up()
            while done_count[0] < (6 * total) // 10 and not stop.is_set():
                _t.sleep(0.02)
            ctl.drain(urls[0], wait_secs=30.0)
            return up

        sampler_t = _th.Thread(
            target=sampler, daemon=True, name="bench-ha-sampler"
        )
        renewer_t = _th.Thread(
            target=renewer, daemon=True, name="bench-ha-renewer"
        )
        sampler_t.start()
        renewer_t.start()
        t0 = _t.perf_counter()
        with ChaosInjector() as chaos:
            ctl_t = _th.Thread(
                target=controller, args=(chaos,), daemon=True,
                name="bench-chaos-controller",
            )
            ctl_t.start()
            threads = [
                _th.Thread(
                    target=client, args=(c,), name=f"bench-chaos-client-{c}"
                )
                for c in range(clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            ctl_t.join(timeout=60)
        wall = _t.perf_counter() - t0
        stop.set()
        sampler_t.join(timeout=2)
        renewer_t.join(timeout=2)

        percentile = _nearest_rank_percentile
        lat = sorted(latencies)
        return {
            "scale": scale,
            "clients": clients,
            "per_client": per_client,
            "queries": total,
            "backend": _jax.default_backend(),
            "wall_secs": round(wall, 3),
            "qps": round(len(lat) / wall, 2) if wall else 0.0,
            "p50_ms": round(percentile(lat, 0.50) * 1000, 2),
            "p99_ms": round(percentile(lat, 0.99) * 1000, 2),
            "per_class": {
                n: {
                    "queries": len(ls),
                    "p50_ms": round(percentile(sorted(ls), 0.50) * 1000, 2),
                    "p99_ms": round(percentile(sorted(ls), 0.99) * 1000, 2),
                }
                for n, ls in by_class.items() if ls
            },
            **outcomes,
            "zero_lost_queries": outcomes["lost"] == 0
            and outcomes["finished"] == total,
            "survivors_bit_identical": all(
                fps[n] == {oracle[n]} for n in names if fps[n]
            ),
            "result_fingerprints": {n: sorted(fps[n]) for n in names},
            "oracle_fingerprints": oracle,
            "coordinator_kill": {
                "failover_completed": failover["done"],
                "fenced_write_rejected": failover["fenced_write_rejected"],
                "dispatch_replays": failover["resumes"],
                "rerun_fallbacks": failover["reruns"],
                "takeover_epoch": lease_b.epoch,
            },
            "one_leader_always": both_leaders[0] == 0,
            "leaderless_samples_during_failover": leader_gaps[0],
            "elastic": {
                "scaled_up_worker": urls[2] in (
                    runner_b.worker_urls + runner_a.worker_urls
                ),
                "drained_workers": retired,
                "drain_decisions": [
                    d for d in ctl.decisions if d.get("action") != "hold"
                ],
            },
        }
    finally:
        for w in workers:
            try:
                w.stop()
            except Exception:  # noqa: BLE001 — bench teardown
                pass


def measure_stats_overhead(scale: float = 0.1, runs: int = 7):
    """Statistics-feedback-plane A/B (ISSUE 8 acceptance): Q6 in-core with
    actuals collection ON vs OFF. The plane's hot-path cost is one dict
    store plus one tiny async row-count reduction per operator per page
    (host reads deferred past the result drain), so the medians must be
    indistinguishable."""
    import statistics

    from trino_tpu.runtime import LocalQueryRunner

    def timed(feedback: bool):
        runner = LocalQueryRunner.tpch(scale=scale)
        runner.session.set("statistics_feedback", feedback)
        runner.execute(Q6)  # warm compile caches
        samples = []
        for _ in range(runs):
            t0 = time.perf_counter()
            res = runner.execute(Q6)
            samples.append(time.perf_counter() - t0)
        return statistics.median(samples), samples, res

    off_med, off_samples, off_res = timed(False)
    on_med, on_samples, on_res = timed(True)
    nodes = (on_res.query_stats or {}).get("planNodes", {})
    return {
        "scale": scale,
        "runs": runs,
        "plane_off_median_secs": round(off_med, 6),
        "plane_on_median_secs": round(on_med, 6),
        "overhead_ratio": round(on_med / off_med, 4) if off_med else None,
        "plane_off_samples": [round(s, 6) for s in off_samples],
        "plane_on_samples": [round(s, 6) for s in on_samples],
        "plan_nodes_observed": len(nodes),
        # the REAL comparison — a mismatch must be reported, not abort the
        # bench child before it can emit the record
        "bit_identical": off_res.rows == on_res.rows,
    }


def measure_sanity_ab(scale: float = 0.01, iters: int = 100):
    """Plan-sanity-plane A/B (ISSUE 10 acceptance): the OPTIMIZE path
    (parse + plan + optimize, incl. the always-on final checks) timed with
    validate_plan OFF vs ON. Off must be indistinguishable from the
    pre-plane cost — the gate is one flag check per rule; the per-rule
    intermediate walks only exist when the knob is on. Also isolates the
    always-on final structural walk (validate_final) so its absolute cost
    is on record."""
    import statistics

    from trino_tpu.planner.sanity import validate_final
    from trino_tpu.runtime import LocalQueryRunner

    runner = LocalQueryRunner.tpch(scale=scale)
    out = {"scale": scale, "iters": iters, "queries": {}}

    for name, sql in (("q1", Q1), ("q3", Q3)):
        def timed(flag: bool):
            runner.session.set("validate_plan", flag)
            runner.plan_sql(sql)  # warm parser/metadata caches
            samples = []
            for _ in range(iters):
                t0 = time.perf_counter()
                runner.plan_sql(sql)
                samples.append(time.perf_counter() - t0)
            return statistics.median(samples)

        off_med = timed(False)
        on_med = timed(True)
        plan = runner.plan_sql(sql)
        t0 = time.perf_counter()
        for _ in range(iters):
            validate_final(plan, runner.metadata, runner.session,
                           stage="bench", with_estimates=False)
        final_secs = (time.perf_counter() - t0) / iters
        out["queries"][name] = {
            "validate_off_median_secs": round(off_med, 6),
            "validate_on_median_secs": round(on_med, 6),
            "on_over_off_ratio": round(on_med / off_med, 4) if off_med else None,
            "final_check_secs": round(final_secs, 7),
            "final_check_pct_of_off": round(100 * final_secs / off_med, 2)
            if off_med else None,
        }
    runner.session.properties.pop("validate_plan", None)
    return out


def measure_cache(scale: float = 0.01, runs: int = 9):
    """Warm-path cache plane A/B (ISSUE 9 acceptance): cold vs warm vs
    shared-prefix on the CPU backend.

    - cold: caches off, post-compile-warm best-of-3 (the round-trip every
      arrival used to pay)
    - warm: result+plan tiers on; p50 of ``runs`` repeated round-trips after
      the store pass — the acceptance bar is < 100 ms for Q1 and Q6
    - shared: two CONCURRENT queries sharing a scan+filter+agg prefix with
      the fragment tier on; the prefix must execute exactly once (asserted
      via the fragment tier's stats: 1 entry, >= 1 hit, and exactly one
      committed cache_store)

    Every cached result is oracle-verified bit-identical to its cold run.
    """
    import statistics
    import threading

    from trino_tpu.runtime import LocalQueryRunner
    from trino_tpu.runtime.cachestore import CACHES

    def p50(samples):
        return statistics.median(samples)

    out = {"scale": scale, "runs": runs, "queries": {}}
    for name, sql in (("q1", Q1), ("q6", Q6)):
        runner = LocalQueryRunner.tpch(scale=scale)
        CACHES.clear()
        # the cold phase must be COLD even on a deployment where
        # $TRINO_TPU_RESULT_CACHE force-enables the tier process-wide
        runner.session.set("result_cache", False)
        runner.session.set("fragment_cache", False)
        runner.session.set("plan_cache_size", 0)
        cold_res = runner.execute(sql)  # compile warm-up
        cold = []
        for _ in range(3):
            t0 = time.perf_counter()
            cold_res = runner.execute(sql)
            cold.append(time.perf_counter() - t0)
        runner.session.set("result_cache", True)
        runner.session.set("plan_cache_size", 64)
        t0 = time.perf_counter()
        store_res = runner.execute(sql)  # miss: executes + stores
        store_secs = time.perf_counter() - t0
        warm = []
        warm_res = None
        for _ in range(runs):
            t0 = time.perf_counter()
            warm_res = runner.execute(sql)
            warm.append(time.perf_counter() - t0)
        warm_p50 = p50(warm)
        out["queries"][name] = {
            "cold_best_secs": round(min(cold), 6),
            "store_run_secs": round(store_secs, 6),
            "warm_p50_secs": round(warm_p50, 6),
            "warm_samples": [round(s, 6) for s in warm],
            "speedup": round(min(cold) / warm_p50, 1) if warm_p50 else None,
            "warm_under_100ms": warm_p50 < 0.1,
            "cache_hit_tier": (warm_res.query_stats or {}).get("cacheHitTier"),
            # the oracle gate: a cached result must be bit-identical to the
            # cold path — report a mismatch, never silently bench it
            "bit_identical": warm_res.rows == cold_res.rows
            and store_res.rows == cold_res.rows,
        }

    # shared-prefix tier: two different statements over one agg prefix,
    # launched concurrently — single-flight means one executes, one blocks
    runner = LocalQueryRunner.tpch(scale=scale)
    qa = ("SELECT revenue FROM (SELECT sum(l_extendedprice * l_discount)"
          " AS revenue FROM lineitem WHERE l_quantity < 24)")
    qb = ("SELECT revenue + 1 FROM (SELECT sum(l_extendedprice *"
          " l_discount) AS revenue FROM lineitem WHERE l_quantity < 24)")
    runner.session.set("result_cache", False)
    runner.session.set("plan_cache_size", 0)
    runner.session.set("fragment_cache", False)
    cold_a = runner.execute(qa)
    cold_b = runner.execute(qb)
    runner.session.set("fragment_cache", True)
    CACHES.clear()
    results = {}

    def go(tag, sql):
        t0 = time.perf_counter()
        res = runner.execute(sql)
        results[tag] = (res, time.perf_counter() - t0)

    threads = [
        threading.Thread(target=go, args=("a", qa), name="bench-race-a"),
        threading.Thread(target=go, args=("b", qb), name="bench-race-b"),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    frag = {r[0]: r for r in CACHES.stats_rows()}["fragment"]
    out["shared_prefix"] = {
        "concurrent_secs": {
            "a": round(results["a"][1], 6), "b": round(results["b"][1], 6),
        },
        "fragment_entries": frag[1],
        "fragment_hits": frag[3],
        "fragment_misses": frag[4],
        # exactly-once: one committed materialization, the peer reused it
        "prefix_executed_once": frag[1] == 1 and frag[3] >= 1,
        "bit_identical": results["a"][0].rows == cold_a.rows
        and results["b"][0].rows == cold_b.rows,
    }
    CACHES.clear()
    return out


def measure_wallclock(runner, sql, runs=3):
    """End-to-end wall-clock (plan + execute + fetch) for operator-path
    queries; first run warms jit caches, then best-of-runs."""
    runner.execute(sql)  # warm compile caches
    best = float("inf")
    rows = 0
    for _ in range(runs):
        t0 = time.perf_counter()
        res = runner.execute(sql)
        best = min(best, time.perf_counter() - t0)
        rows = len(res.rows)
    return {"secs": round(best, 6), "result_rows": rows}


# --------------------------------------------------------------------------- #
# per-query child processes
# --------------------------------------------------------------------------- #


def _record_result(key, value):
    path = os.environ.get("BENCH_RESULTS")
    if not path:
        print(json.dumps({key: value}))
        return
    with open(path, "a") as f:
        f.write(json.dumps({"key": key, "value": value}) + "\n")
        f.flush()
        os.fsync(f.fileno())


def _make_runner(scale: float):
    import jax

    import trino_tpu  # noqa: F401  (enables x64)

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache_tpu")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    # tuned-capacity persistence (runtime/capstore): children and successive
    # rounds share fixpoint capacity vectors, so adaptive queries skip the
    # grow/shrink loop and their single compile hits the XLA cache above
    os.environ.setdefault(
        "TRINO_TPU_CAP_STORE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".tuned_caps.json"),
    )
    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=scale)


def child_main(task: str):
    scale = float(os.environ.get("BENCH_SCALE", "1"))
    runs = int(os.environ.get("BENCH_RUNS", "10"))

    if task == "meta":
        import jax

        import trino_tpu  # noqa: F401

        t0 = time.time()
        runner = _make_runner(scale)
        from trino_tpu.connectors.tpch import generator as g

        conn = runner.catalogs.get("tpch")
        nsplits = conn.split_count("lineitem", scale)
        total_rows = sum(
            g.lineitem_split_rows(scale, s, nsplits) for s in range(nsplits)
        )
        gen_secs = time.time() - t0
        np_result, np_secs, np_rows = numpy_baseline(scale)
        assert np_rows == total_rows, (np_rows, total_rows)
        _record_result("_meta", {
            "device": jax.devices()[0].device_kind,
            "backend": jax.default_backend(),
            "rows": total_rows,
            "datagen_secs": round(gen_secs, 2),
            "numpy_q6_secs": round(np_secs, 6),
            "baseline_rows_per_sec": round(np_rows / np_secs, 1),
            "numpy_q6_result": float(np_result),
        })
        return

    runner = _make_runner(scale)
    from trino_tpu.connectors.tpch import generator as g

    conn = runner.catalogs.get("tpch")
    nsplits = conn.split_count("lineitem", scale)
    total_rows = sum(g.lineitem_split_rows(scale, s, nsplits) for s in range(nsplits))

    if task == "q6":
        m = measure_traced_loop(runner, Q6, 0, ks=(8, 72), runs=max(3, runs // 3))
        m["rows_per_sec"] = round(total_rows / m["secs"], 1)
        # correctness cross-check against the host baseline (scaled decimal)
        import jax

        from trino_tpu.runtime.traced import compile_query

        plan = runner.plan_sql(Q6)
        fn, pages, _ = compile_query(plan, runner.metadata, runner.session)
        engine_result = jax.jit(fn)(*pages).to_pylist()[0][0]
        m["revenue"] = float(engine_result)  # meta child records the numpy value
        _record_result("q6", m)
        return
    if task == "q1":
        m = measure_traced_loop(runner, Q1, 2, ks=(2, 10), runs=3)
        m["rows_per_sec"] = round(total_rows / m["secs"], 1)
        _record_result("q1", m)
        return
    if task == "q6_sf10":
        m = measure_streaming_q6(10.0)
        _record_result("q6_sf10", m)
        return
    if task == "ladder":
        _record_result("ladder", run_ladder())
        return
    if task == "stats_ab":
        m = measure_stats_overhead(scale=min(scale, 0.1))
        _record_result("stats_ab", m)
        return
    if task == "sanity_ab":
        m = measure_sanity_ab(
            scale=float(os.environ.get("BENCH_SANITY_SCALE", "0.01"))
        )
        _record_result("sanity_ab", m)
        return
    if task == "exchange_ab":
        m = measure_exchange(scale=float(os.environ.get("BENCH_EXCHANGE_SCALE", "1")))
        _record_result("exchange_ab", m)
        return
    if task == "cache_ab":
        m = measure_cache(
            scale=float(os.environ.get("BENCH_CACHE_SCALE", "0.01"))
        )
        _record_result("cache_ab", m)
        return
    if task == "hostpath_ab":
        _record_result("hostpath_ab", run_hostpath_ab())
        return
    if task == "fleet_ab":
        _record_result("fleet_ab", run_fleet_ab())
        return
    if task == "concurrency":
        m = measure_concurrency(
            scale=float(os.environ.get("BENCH_CONCURRENCY_SCALE", "0.01"))
        )
        _record_result("concurrency", m)
        return
    if task == "batching_ab":
        m = measure_batching_ab(
            scale=float(os.environ.get("BENCH_CONCURRENCY_SCALE", "0.01"))
        )
        _record_result("batching_ab", m)
        return
    if task == "megakernel_ab":
        m = measure_megakernel_ab(
            scale=float(os.environ.get("BENCH_MEGAKERNEL_SCALE", "0.01"))
        )
        _record_result("megakernel_ab", m)
        return
    if task == "vector_ab":
        m = measure_vector_ab(
            rows=int(os.environ.get("BENCH_VECTOR_ROWS", "150000")),
            dim=int(os.environ.get("BENCH_VECTOR_DIM", "64")),
        )
        _record_result("vector_ab", m)
        return
    if task == "vector_serving_ab":
        m = measure_vector_serving_ab(
            rows=int(os.environ.get("BENCH_SERVING_ROWS", "50000")),
            dim=int(os.environ.get("BENCH_SERVING_DIM", "32")),
        )
        _record_result("vector_serving_ab", m)
        return
    if task == "ha_ab":
        m = measure_ha_ab(
            scale=float(os.environ.get("BENCH_HA_SCALE", "0.0005")),
            clients=int(os.environ.get("BENCH_HA_CLIENTS", "100")),
        )
        _record_result("ha_ab", m)
        return
    if task.startswith("ooc_"):
        # out-of-core tier (runtime/ooc.py): joins + aggregation streamed
        # through the fragmenter's stage cut with a disk-spillable host
        # bucket store — the SF10/SF100 ladder the round-4 verdict asked for
        _, qname, sfs = task.split("_", 2)
        sf = float(sfs.lstrip("sf").replace("_", "."))
        sql = {"q1": Q1, "q3": Q3, "q6": Q6, "q14": Q14, "q18": Q18}[qname]
        m = measure_ooc(sql, sf)
        _record_result(task, m)
        return
    if task in JOIN_QUERIES:
        sql = JOIN_QUERIES[task]
        # adaptive whole-query program FIRST (round 4): CBO-seeded capacities
        # tuned to measured actuals, 1-3 bounded compiles through the tunnel;
        # its number streams immediately. Falls back to the round-3 traced
        # formulations on failure.
        traced = None
        try:
            traced = measure_adaptive(runner, sql)
            _record_result(task, traced)
        except Exception as e:  # noqa: BLE001
            _record_result(
                task, {"adaptive_error": f"{type(e).__name__}: {str(e)[:200]}"}
            )
        if traced is None:
            try:
                if task in ("q3", "q18"):
                    traced = measure_traced_join_single(runner, sql)
                else:
                    traced = measure_traced_join_loop(runner, sql)
                _record_result(task, traced)
            except Exception as e:  # noqa: BLE001
                _record_result(
                    task, {"traced_error": f"{type(e).__name__}: {str(e)[:200]}"}
                )
        if task == "q18" and traced is not None:
            # the operator-at-a-time path needs >40min of tunnel compiles on
            # first contact (BASELINE.md round 3); don't burn the child budget
            traced = dict(traced)
            traced["wallclock_skipped"] = "operator-path compile cost; see BASELINE.md"
            _record_result(task, traced)
            return
        try:
            m = measure_wallclock(runner, sql)
        except Exception as e:  # noqa: BLE001 — the traced number survives
            if traced is not None:
                traced = dict(traced)
                traced["wallclock_error"] = f"{type(e).__name__}: {str(e)[:160]}"
                _record_result(task, traced)
            return
        if traced is None:
            _record_result(task, m)
            return
        # report whichever execution strategy is faster as the query's time
        # (both recorded): the engine would pick the better plan
        final = dict(traced)
        final["wallclock_secs"] = m["secs"]
        if m["secs"] < final["secs"]:
            final["traced_secs"] = final["secs"]
            final["secs"] = m["secs"]
            final["method"] = "operator_wallclock"
        _record_result(task, final)
        return
    raise SystemExit(f"unknown bench task: {task}")


# --------------------------------------------------------------------------- #
# parent orchestrator
# --------------------------------------------------------------------------- #


BENCH_SCHEMA_VERSION = 2  # v2: self-describing records (schema_version + git SHA)


def _git_sha() -> str:
    """Current commit (best-effort): BENCH_*.json files must say what code
    produced them."""
    import subprocess

    try:
        return (
            subprocess.run(
                ["git", "rev-parse", "--short=12", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, timeout=10, check=True,
            )
            .stdout.decode()
            .strip()
        )
    except Exception:  # noqa: BLE001 — not a reason to lose a bench round
        return "unknown"


# --------------------------------------------------------------------------- #
# the regression ladder (ROADMAP item 1's measurement half)
# --------------------------------------------------------------------------- #

# v3 = the ladder schema: hardware-labeled (platform/device/git_sha), median-
# of-N with MAD dispersion, per-query result fingerprints — the shape
# tools/bench_regress.py compares and tools/bench_schema.py enforces strictly
LADDER_SCHEMA_VERSION = 3

# the r06-r18 A/B suite distilled to one repeatable task: each query is the
# primary workload of one prior bench round (q6: r06 scan/agg; q1: r06 wide
# agg; q3/q14: r08 joins; q18 is excluded — its cold-tunnel compile cost
# [BASELINE.md round 3] would dominate a median-of-N ladder run)
LADDER_QUERIES = ("q6", "q1", "q3", "q14")


def _ladder_sql(name: str) -> str:
    return {"q6": Q6, "q1": Q1, "q3": Q3, "q14": Q14, "q18": Q18}[name]


def _mad(samples):
    """Median absolute deviation — the ladder's dispersion measure (robust
    to the one-slow-run outliers wall-clock benches always have)."""
    import statistics

    med = statistics.median(samples)
    return statistics.median([abs(s - med) for s in samples])


def run_ladder(scale=None, runs=None, queries=None, slowdown_secs=0.0):
    """Run the ladder suite in-process and return the v3 record.

    ``slowdown_secs`` is a documented test hook: it inflates every sample
    by a constant, letting tests assert tools/bench_regress.py flags a
    synthetically slowed run without depending on real machine noise.
    """
    import hashlib as _hl
    import statistics

    import jax

    scale = float(os.environ.get("BENCH_SCALE", "0.01")) if scale is None else scale
    runs = int(os.environ.get("BENCH_LADDER_RUNS", "5")) if runs is None else runs
    names = list(queries) if queries else list(LADDER_QUERIES)
    runner = _make_runner(scale)
    results = {}
    for name in names:
        sql = _ladder_sql(name)
        runner.execute(sql)  # warm compile caches: the ladder measures steady state
        samples = []
        fp = ""
        for _ in range(max(runs, 1)):
            t0 = time.perf_counter()
            res = runner.execute(sql)
            samples.append(round(time.perf_counter() - t0 + slowdown_secs, 6))
            fp = _hl.sha256(repr(res.rows).encode()).hexdigest()[:16]
        results[name] = {
            "median_secs": round(statistics.median(samples), 6),
            "mad_secs": round(_mad(samples), 6),
            "samples": samples,
            "fingerprint": fp,
        }
    platform = jax.default_backend()
    return {
        "bench": "ladder",
        "schema_version": LADDER_SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "platform": platform,
        "device": jax.devices()[0].device_kind,
        # the honest hardware label ROADMAP item 1 demands: CPU numbers are
        # functional evidence, not performance claims
        "hardware_verified": platform not in ("cpu", "interpreter"),
        "scale": scale,
        "runs": runs,
        "results": results,
    }


# --------------------------------------------------------------------------- #
# host-path observability A/B (ISSUE 18 / r19)
# --------------------------------------------------------------------------- #


def measure_hostpath_ab(scale: float = 0.01, clients=(1, 2, 4, 8, 16),
                        per_client: int = 6):
    """Host-path A/B (BENCH_r19_hostpath_ab.json): the r13/r16 saturation
    replay with the host-path observability plane OFF vs ON (continuous
    sampling profiler + GIL-contention probe, runtime/hostprof.py). The
    claims the record carries:

    - ``bit_identical_with_profiler``: every finished query class produced
      ONE result fingerprint within each mode and ACROSS the two modes —
      the profiler observes, it never changes bytes;
    - ``q6_warm_overhead``: median warm-Q6 latency with the sampler on vs
      off (the <5% on-path acceptance gate);
    - ``attribution``: a profiled max-concurrency pass splitting wall time
      between device work (the stats collector's ``device_busy_secs``),
      compile, and the protocol-host remainder — plus the probe's sleep-
      jitter percentiles and the heaviest collapsed host stacks, the
      instrument-backed version of the r13 "single-core host/GIL
      contention" diagnosis.

    Per (mode, level) the v3 ``results`` entries carry the raw per-query
    latency samples with median/MAD and the mode's combined result
    fingerprint, so tools/bench_regress.py can compare rounds.
    """
    import hashlib as _hl
    import statistics
    import threading as _th
    import time as _t

    from trino_tpu.runtime.hostprof import PROBE, PROFILER, _interval_secs
    from trino_tpu.runtime.local import LocalQueryRunner
    from trino_tpu.runtime.query_manager import QueryManager, QueryState

    off = measure_concurrency(
        scale=scale, clients=clients, per_client=per_client
    )
    PROFILER.clear()
    PROBE.clear()
    PROFILER.enable()
    PROBE.start()
    try:
        on = measure_concurrency(
            scale=scale, clients=clients, per_client=per_client
        )
    finally:
        PROFILER.disable()
        PROBE.stop()
        PROFILER.join()
    probe_replay = PROBE.summary()
    replay_ticks = PROFILER.tick_count
    replay_dropped = PROFILER.dropped_samples

    identical = off["internally_consistent"] and on["internally_consistent"]
    for cls, fps in off["result_fingerprints"].items():
        if on["result_fingerprints"].get(cls) != fps:
            identical = False

    # warm-Q6 overhead: the on-path must cost < 5% on a steady-state replay
    runner = LocalQueryRunner.tpch(scale=scale)
    runner.execute(Q6)  # warm the compile caches; the gate is steady state

    def q6_replay(n=11):
        samples, fp = [], ""
        for _ in range(n):
            t0 = _t.perf_counter()
            res = runner.execute(Q6)
            samples.append(round(_t.perf_counter() - t0, 6))
            fp = _hl.sha256(repr(res.rows).encode()).hexdigest()[:16]
        return samples, fp

    q6_off, q6_fp_off = q6_replay()
    PROFILER.enable()
    try:
        q6_on, q6_fp_on = q6_replay()
    finally:
        PROFILER.disable()
        PROFILER.join()
    med_off = statistics.median(q6_off)
    med_on = statistics.median(q6_on)
    overhead_pct = (
        round((med_on / med_off - 1.0) * 100.0, 2) if med_off else 0.0
    )

    # profiled attribution pass at max concurrency: split p99 wall time
    # between device work and the protocol host path
    level = max(clients)
    names = sorted(CONCURRENCY_MIX)
    PROFILER.clear()
    PROBE.clear()
    PROFILER.enable()
    PROBE.start()
    mgr = QueryManager(runner.execute, max_workers=max(4, level))
    lock = _th.Lock()
    done: list = []

    def client(cid):
        for j in range(per_client):
            cls = names[(cid + j) % len(names)]
            t0 = _t.perf_counter()
            q = mgr.submit(CONCURRENCY_MIX[cls])
            q.wait_done(600)
            with lock:
                done.append((_t.perf_counter() - t0, q))

    threads = [
        _th.Thread(
            target=client, args=(c,), name=f"bench-hostpath-client-{c}"
        )
        for c in range(level)
    ]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        PROFILER.disable()
        PROBE.stop()
        PROFILER.join()
    probe16 = PROBE.summary()
    top_stacks = [
        {"thread": t_, "stack": s, "samples": n, "share": sh}
        for t_, s, n, sh in PROFILER.profile_rows()[:12]
    ]
    lat = sorted(dt for dt, _ in done)
    wall = sum(lat)
    device = compile_ = 0.0
    for _dt, q in done:
        if q.state is QueryState.FINISHED:
            times = (q.query_stats or {}).get("times", {})
            device += float(times.get("device_busy_secs", 0.0))
            compile_ += float(times.get("compile_secs", 0.0))
    host = max(wall - device - compile_, 0.0)
    attribution = {
        "clients": level,
        "queries": len(lat),
        "p99_ms": round(
            _nearest_rank_percentile(lat, 0.99) * 1000, 2
        ) if lat else 0.0,
        "wall_secs_total": round(wall, 4),
        "device_busy_secs_total": round(device, 6),
        "compile_secs_total": round(compile_, 6),
        "protocol_host_secs_total": round(host, 4),
        "device_share": round(device / wall, 4) if wall else 0.0,
        "protocol_host_share": round(host / wall, 4) if wall else 0.0,
        "switch_latency": probe16,
        "top_host_stacks": top_stacks,
    }

    def mode_fingerprint(run):
        blob = json.dumps(run["result_fingerprints"], sort_keys=True)
        return _hl.sha256(blob.encode()).hexdigest()[:16]

    results = {}
    for mode, run in (("off", off), ("on", on)):
        fp = mode_fingerprint(run)
        for lv in run["levels"]:
            samples = lv["latency_samples"]
            results[f"{mode}_c{lv['clients']}"] = {
                "median_secs": round(statistics.median(samples), 6),
                "mad_secs": round(_mad(samples), 6),
                "samples": samples,
                "fingerprint": fp,
            }
    for mode, samples, fp in (
        ("q6_warm_off", q6_off, q6_fp_off),
        ("q6_warm_on", q6_on, q6_fp_on),
    ):
        results[mode] = {
            "median_secs": round(statistics.median(samples), 6),
            "mad_secs": round(_mad(samples), 6),
            "samples": samples,
            "fingerprint": fp,
        }

    return {
        "clients": list(clients),
        "per_client": per_client,
        "mix": names,
        "profiler": {
            "interval_ms": round(_interval_secs() * 1000, 3),
            "replay_ticks": replay_ticks,
            "replay_dropped_samples": replay_dropped,
            "replay_switch_latency": probe_replay,
        },
        "bit_identical_with_profiler": identical,
        "result_fingerprints_off": off["result_fingerprints"],
        "result_fingerprints_on": on["result_fingerprints"],
        "q6_warm_overhead": {
            "off_median_secs": round(med_off, 6),
            "on_median_secs": round(med_on, 6),
            "overhead_pct": overhead_pct,
        },
        "p99_ms_by_clients_off": {
            lv["clients"]: lv["p99_ms"] for lv in off["levels"]
        },
        "p99_ms_by_clients_on": {
            lv["clients"]: lv["p99_ms"] for lv in on["levels"]
        },
        "saturation_qps_off": off["saturation_qps"],
        "saturation_qps_on": on["saturation_qps"],
        "attribution": attribution,
        "results": results,
    }


def run_hostpath_ab(scale=None):
    """Run the hostpath A/B in-process and return the v3 record
    (``python bench.py hostpath_ab`` prints it; the checked-in
    BENCH_r19_hostpath_ab.json passes tools/bench_schema.py unwaived)."""
    import jax

    scale = (
        float(os.environ.get("BENCH_HOSTPATH_SCALE", "0.01"))
        if scale is None else scale
    )
    m = measure_hostpath_ab(scale=scale)
    platform = jax.default_backend()
    return {
        "bench": "hostpath_ab",
        "schema_version": LADDER_SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "platform": platform,
        "device": jax.devices()[0].device_kind,
        # CPU numbers are functional evidence, not performance claims
        "hardware_verified": platform not in ("cpu", "interpreter"),
        "scale": scale,
        **m,
    }


def _fleet_spawn(n, front_port, scale, tmp, env_extra, session_flags,
                 heartbeat_secs="0.5", tag="", extra_args=()):
    """Spawn ``n`` REAL coordinator processes (the trino_tpu.runtime.fleet
    CLI) sharing one SO_REUSEPORT front port; returns (procs, node_urls).
    Startup is ready-file based: each process writes its unique per-node
    URL once its listeners are bound."""
    import subprocess as _sp
    import time as _t

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu", JAX_PLATFORM_NAME="cpu",
        TRINO_TPU_FLEET_HEARTBEAT_SECS=heartbeat_secs,
        **env_extra,
    )
    procs, readies = [], []
    for i in range(n):
        ready = os.path.join(tmp, f"ready_{tag}{i}.txt")
        cmd = [sys.executable, "-m", "trino_tpu.runtime.fleet",
               "--front-port", str(front_port), "--node-id", f"n{i + 1}",
               "--ready-file", ready, "--scale", str(scale)]
        cmd += list(extra_args)
        for kv in session_flags:
            cmd += ["--session", kv]
        log = open(os.path.join(tmp, f"coord_{tag}{i}.log"), "wb")
        procs.append(_sp.Popen(cmd, env=env, stdout=log, stderr=log))
        readies.append(ready)
    urls = []
    deadline = _t.monotonic() + 300
    for p, ready in zip(procs, readies):
        while not os.path.exists(ready):
            if p.poll() is not None:
                raise RuntimeError(
                    f"fleet coordinator exited {p.returncode} during startup"
                )
            if _t.monotonic() > deadline:
                raise RuntimeError("fleet coordinator never became ready")
            _t.sleep(0.1)
        with open(ready) as f:
            urls.append(f.read().strip())
    return procs, urls


# the serving replay's session-identity pool: 100 concurrent clients
# acting as 4 identities re-running the same statement mix — the
# dashboard-shaped workload the shared warm tier serves. A bounded pool
# keeps the per-process plan-tier working set warmable, so the timed
# window compares PROTOCOL serving across fleet sizes instead of charging
# the larger fleets more one-time planning work.
_FLEET_USER_POOL = 4

# fleet_ab load generator: one Python process running ~25 client threads
# is NOT a neutral observer on a single-core box — at ~100 qps the
# generator's own GIL becomes the ceiling and hides server-side scaling.
# The replay therefore forks W generator processes which synchronize on a
# go-file, append one byte per finished query to a progress file (the
# mid-run killer watches those), and write per-query records at exit.
_FLEET_CLIENT_WORKER = """
import hashlib, json, os, sys, threading, time

cfg = json.load(open(sys.argv[1]))
sys.path.insert(0, cfg["repo"])
from trino_tpu.client.client import ClientError, StatementClient

mix, names = cfg["mix"], cfg["names"]
records, lock = [], threading.Lock()
prog = open(cfg["progress"], "a", buffering=1)


def fp(rows):
    return hashlib.sha256(repr(rows).encode()).hexdigest()[:16]


def run_one(user, sql):
    t0 = time.perf_counter()
    deadline = t0 + cfg["retry_deadline"]
    retries = 0
    while True:
        try:
            cl = StatementClient(cfg["front"], user=user, timeout=120.0)
            res = cl.execute(sql)
            return res, time.perf_counter() - t0, retries
        except (ClientError, OSError):
            if time.perf_counter() > deadline:
                raise
            retries += 1
            time.sleep(0.05)


def client(cid):
    pool = cfg.get("user_pool") or 0
    user = "user%02d" % (cid % pool if pool else cid)
    for j in range(cfg["per_client"]):
        cls = names[(cid + j) % len(names)]
        rec = {"cls": cls}
        try:
            res, dt, r = run_one(user, mix[cls])
            rec.update(lat=dt, fp=fp(res.rows), retries=r, lost=False)
        except Exception:
            rec.update(lost=True)
        with lock:
            records.append(rec)
            prog.write("x")


threads = [
    threading.Thread(target=client, args=(c,)) for c in cfg["client_ids"]
]
open(cfg["out"] + ".ready", "w").write("1")
while not os.path.exists(cfg["go"]):
    time.sleep(0.005)
for t in threads:
    t.start()
for t in threads:
    t.join()
with open(cfg["out"] + ".tmp", "w") as f:
    json.dump(records, f)
os.replace(cfg["out"] + ".tmp", cfg["out"])
"""


def _fleet_drive_clients(front, leg_tmp, client_ids, per_client, mix, names,
                         kill_proc=None, kill_after=None, workers=4,
                         retry_deadline=120.0, user_pool=0):
    """Drive the replay from ``workers`` forked generator processes;
    returns (records, wall_secs, killed). The wall clock opens when the
    go-file releases the already-spawned generators — process startup
    never pollutes the window."""
    import subprocess as _sp
    import threading as _th
    import time as _t

    repo = os.path.dirname(os.path.abspath(__file__))
    go = os.path.join(leg_tmp, "go")
    total = len(client_ids) * per_client
    groups = [client_ids[w::workers] for w in range(workers)]
    groups = [g for g in groups if g]
    procs, outs, progs = [], [], []
    for w, grp in enumerate(groups):
        cfgp = os.path.join(leg_tmp, f"client_{w}.json")
        outp = os.path.join(leg_tmp, f"client_{w}.out.json")
        progp = os.path.join(leg_tmp, f"client_{w}.progress")
        with open(cfgp, "w") as f:
            json.dump({
                "repo": repo, "front": front, "mix": mix, "names": names,
                "client_ids": grp, "per_client": per_client, "go": go,
                "out": outp, "progress": progp,
                "retry_deadline": retry_deadline, "user_pool": user_pool,
            }, f)
        procs.append(_sp.Popen(
            [sys.executable, "-c", _FLEET_CLIENT_WORKER, cfgp],
            cwd=leg_tmp,
        ))
        outs.append(outp)
        progs.append(progp)
    deadline = _t.monotonic() + 120
    for p, outp in zip(procs, outs):
        while not os.path.exists(outp + ".ready"):
            if p.poll() is not None:
                raise RuntimeError("fleet load generator died during setup")
            if _t.monotonic() > deadline:
                raise RuntimeError("fleet load generator never became ready")
            _t.sleep(0.01)

    killed = {"fired": False}
    if kill_proc is not None:
        def killer():
            while True:
                done = 0
                for pr in progs:
                    try:
                        done += os.path.getsize(pr)
                    except OSError:
                        pass
                if done >= (kill_after or max(1, total // 3)):
                    kill_proc.kill()
                    killed["fired"] = True
                    return
                _t.sleep(0.02)

        _th.Thread(target=killer, daemon=True,
                   name="bench-fleet-killer").start()

    t0 = _t.perf_counter()
    with open(go + ".tmp", "w") as f:
        f.write("1")
    os.replace(go + ".tmp", go)
    for p in procs:
        p.wait()
    wall = _t.perf_counter() - t0
    records = []
    for outp in outs:
        with open(outp) as f:
            records.extend(json.load(f))
    return records, wall, killed["fired"]


def measure_fleet_ab(scale: float = 0.0005, clients: int = 100,
                     per_client: int = 4, sizes=(1, 2, 4),
                     attr_clients: int = 16, attr_per_client: int = 6,
                     attr_scale: float = 0.01):
    """Active-active coordinator fleet A/B (ISSUE 19 acceptance,
    BENCH_r20_fleet_ab.json): the r16 100-client mixed replay against a
    REAL multi-process protocol front — N forked coordinators sharing one
    SO_REUSEPORT listen port, partitioned admission by session hash, and
    the shared warm tier letting ANY process serve a published result.

    Four claims ride the record:

    - ``qps_scaling_vs_single``: warm-tier serving throughput at 1/2/4
      coordinators. The container is SINGLE-core, so the win is not CPU
      parallelism — it is the r19 diagnosis cashed in: one process
      convoying ~100 protocol threads through one GIL (sampled GIL-probe
      p99 38ms vs a 5ms sleep) becomes four processes convoying ~25 each.
    - ``zero_lost_queries``: a dedicated max-size leg SIGKILLs one
      coordinator mid-replay; every client retries through the front port
      until the heartbeat lapses and the hash range reassigns — all
      queries finish.
    - ``bit_identical_to_single_coordinator_oracle``: every finished query
      class produced ONE fingerprint within each leg and it equals the
      single-coordinator leg's — across redirects, proxies, shared-tier
      hits, and the kill.
    - ``attribution``: the r19 hostpath methodology (16 clients x 6,
      UNCACHED so queries really execute; protocol-host = wall - device -
      compile from each owner's /v1/query queryStats) repeated at 1 and at
      max fleet size — the fleet's protocol-host share must land strictly
      below the r19 single-process 90.7%.
    """
    import hashlib as _hl
    import socket as _sock
    import statistics
    import tempfile as _tf
    import threading as _th
    import time as _t
    import urllib.request as _ur

    from trino_tpu.client.client import ClientError, StatementClient
    from trino_tpu.runtime.fleet import HashRing, partition_key

    mix = CONCURRENCY_MIX
    names = sorted(mix)
    tmp = _tf.mkdtemp(prefix="fleet_bench_")
    percentile = _nearest_rank_percentile

    def fp(rows) -> str:
        return _hl.sha256(repr(rows).encode()).hexdigest()[:16]

    def run_one(base, user, sql, retry_deadline=120.0):
        cl = StatementClient(base, user=user, timeout=120.0)
        t0 = _t.perf_counter()
        deadline = t0 + retry_deadline
        retries = 0
        while True:
            try:
                res = cl.execute(sql)
                return res, _t.perf_counter() - t0, retries
            except (ClientError, OSError):
                # the kill window: dead connections, 503s from proxies,
                # redirects chasing a not-yet-lapsed owner — retry until
                # the fleet reassigns the range and serves it
                if _t.perf_counter() > deadline:
                    raise
                retries += 1
                _t.sleep(0.05)

    def leg(n_coords, *, cached, kill=False, leg_clients=None,
            leg_per_client=None, attribution=False, leg_scale=None,
            plain=False, tag=""):
        leg_clients = clients if leg_clients is None else leg_clients
        leg_per_client = (
            per_client if leg_per_client is None else leg_per_client
        )
        leg_scale = scale if leg_scale is None else leg_scale
        leg_tmp = _tf.mkdtemp(prefix=f"leg_{tag}", dir=tmp)
        # plain = the single-coordinator BASELINE deployment: no fleet
        # membership, no front listener — exactly what r16/r19 measured,
        # and exactly what a deployment without the fleet knobs runs today
        env_extra = {}
        if not plain:
            env_extra["TRINO_TPU_FLEET_DIR"] = os.path.join(
                leg_tmp, "members"
            )
            os.makedirs(env_extra["TRINO_TPU_FLEET_DIR"], exist_ok=True)
        # the baseline leg is the SHIPPED r19 single-coordinator
        # deployment (stdlib accept backlog, two-round-trip protocol);
        # fleet legs run this PR's front plane (deep backlog via the
        # fleet CLI default + first-response long-poll) — the A/B
        # compares deployments, exactly like hostpath_ab's off/on
        session_flags = (
            [] if plain else ["protocol_first_response_wait=0.3"]
        )
        if cached:
            session_flags += ["result_cache=true", "shared_cache_tier=true"]
            env_extra["TRINO_TPU_SHARED_CACHE_DIR"] = os.path.join(
                leg_tmp, "warm"
            )
        sock = None
        if plain:
            procs, urls = _fleet_spawn(
                n_coords, 0, leg_scale, leg_tmp, env_extra, session_flags,
                tag=tag, extra_args=("--http-backlog", "0"),
            )
            front = urls[0]
        else:
            # reserve the front port: bound (not listening) with
            # SO_REUSEPORT, so the children can bind it and the kernel
            # balances accepted connections across the LISTENING
            # processes only
            sock = _sock.socket(_sock.AF_INET, _sock.SOCK_STREAM)
            sock.setsockopt(_sock.SOL_SOCKET, _sock.SO_REUSEPORT, 1)
            sock.bind(("127.0.0.1", 0))
            port = sock.getsockname()[1]
            front = f"http://127.0.0.1:{port}"
            procs, urls = _fleet_spawn(
                n_coords, port, leg_scale, leg_tmp, env_extra, session_flags,
                tag=tag,
            )
        lat: list = []
        by_class: dict = {n: [] for n in names}
        fps: dict = {n: set() for n in names}
        outcomes = {"finished": 0, "lost": 0, "retries": 0}
        info_uris: list = []
        lock = _th.Lock()
        total = leg_clients * leg_per_client
        killed = {"fired": False}
        try:
            # warm phase: one execution per class; with the shared warm
            # tier on, every OTHER process serves the published entry
            # without ever compiling
            for cls in names:
                run_one(front, "user00", mix[cls], retry_deadline=600.0)
            if cached:
                # the serving replay models the serving-plane workload the
                # warm tier exists for: a bounded pool of session
                # identities re-running the same statements. Warm every
                # (process, user, class) via each process's DIRECT url so
                # the timed window measures steady-state protocol serving
                # — per-process plan-tier misses would otherwise charge
                # the larger fleets more one-time work than the baseline
                for url in urls:
                    for u in range(_FLEET_USER_POOL):
                        for cls in names:
                            run_one(url, f"user{u:02d}", mix[cls],
                                    retry_deadline=600.0)
            if not cached:
                # attribution legs replay uncached, so warm every
                # (process, class) pair via each node's DIRECT url with a
                # user it owns — the timed pass measures steady-state
                # protocol + execute, not XLA compiles
                ring_ids = [f"n{i + 1}" for i in range(n_coords)]
                ring = HashRing(ring_ids)
                url_by_node = dict(zip(ring_ids, urls))
                owned_user: dict = {}
                for i in range(256):
                    u = f"user{i:02d}"
                    owned_user.setdefault(ring.owner(partition_key(u, "")), u)
                    if len(owned_user) == n_coords:
                        break
                for nid in ring_ids:
                    for cls in names:
                        run_one(url_by_node[nid], owned_user[nid], mix[cls],
                                retry_deadline=600.0)

            attr = {"device": 0.0, "compile": 0.0, "stats_missing": 0}
            if attribution:
                # the attribution replay is light (16 clients at ~1 qps)
                # and needs per-query infoUris — in-process threads are
                # fine and simpler here
                def client(cid):
                    user = f"user{cid:02d}"
                    for j in range(leg_per_client):
                        cls = names[(cid + j) % len(names)]
                        try:
                            res, dt, r = run_one(front, user, mix[cls])
                            with lock:
                                lat.append(dt)
                                by_class[cls].append(dt)
                                fps[cls].add(fp(res.rows))
                                outcomes["finished"] += 1
                                outcomes["retries"] += r
                                if res.info_uri:
                                    info_uris.append(res.info_uri)
                        except Exception:  # noqa: BLE001 — lost IS the metric
                            with lock:
                                outcomes["lost"] += 1

                threads = [
                    _th.Thread(target=client, args=(c,),
                               name=f"bench-fleet-client-{c}")
                    for c in range(leg_clients)
                ]
                t0 = _t.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = _t.perf_counter() - t0
            else:
                # the serving replay: forked load generators (see
                # _FLEET_CLIENT_WORKER); the killer SIGKILLs one owner once
                # ~1/3 of the replay has finished — a crash, not a drain,
                # its heartbeat must LAPSE
                records, wall, kfired = _fleet_drive_clients(
                    front, leg_tmp, list(range(leg_clients)),
                    leg_per_client, mix, names,
                    kill_proc=procs[-1] if kill else None,
                    kill_after=max(1, total // 3),
                    user_pool=_FLEET_USER_POOL,
                )
                killed["fired"] = kfired
                for rec in records:
                    if rec.get("lost"):
                        outcomes["lost"] += 1
                        continue
                    lat.append(rec["lat"])
                    by_class[rec["cls"]].append(rec["lat"])
                    fps[rec["cls"]].add(rec["fp"])
                    outcomes["finished"] += 1
                    outcomes["retries"] += rec.get("retries", 0)

            if attribution:
                # per-query owner-side attribution AFTER the timed window
                # (the info fetches must not load the front while timing)
                for uri in info_uris:
                    try:
                        req = _ur.Request(
                            uri, headers={"X-Trino-User": "bench"}
                        )
                        with _ur.urlopen(req, timeout=30) as resp:
                            qs = json.loads(resp.read()).get(
                                "queryStats", {}
                            )
                        attr["device"] += float(
                            qs.get("deviceBusyTime") or 0.0
                        )
                        attr["compile"] += float(
                            qs.get("analysisTime") or 0.0
                        )
                    except Exception:  # noqa: BLE001 — counted, not fatal
                        attr["stats_missing"] += 1
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=15)
                except Exception:  # noqa: BLE001 — bench teardown
                    p.kill()
            if sock is not None:
                sock.close()

        lats = sorted(lat)
        out = {
            "coordinators": n_coords,
            "plain_single_coordinator": plain,
            "clients": leg_clients,
            "per_client": leg_per_client,
            "queries": total,
            "cached_serving": cached,
            "wall_secs": round(wall, 3),
            "qps": round(len(lats) / wall, 2) if wall and lats else 0.0,
            "p50_ms": round(percentile(lats, 0.50) * 1000, 2) if lats else 0.0,
            "p99_ms": round(percentile(lats, 0.99) * 1000, 2) if lats else 0.0,
            "latency_samples": [round(x, 6) for x in lats],
            "finished": outcomes["finished"],
            "lost": outcomes["lost"],
            "client_retries": outcomes["retries"],
            "owner_killed_mid_run": kill and killed["fired"],
            "result_fingerprints": {n: sorted(fps[n]) for n in names},
            "internally_consistent": all(
                len(s) == 1 for s in fps.values() if s
            ),
        }
        if attribution:
            wall_total = sum(lats)
            host = max(wall_total - attr["device"] - attr["compile"], 0.0)
            out["attribution"] = {
                "queries_with_stats": len(info_uris) - attr["stats_missing"],
                "stats_missing": attr["stats_missing"],
                "wall_secs_total": round(wall_total, 4),
                "device_busy_secs_total": round(attr["device"], 6),
                "compile_secs_total": round(attr["compile"], 6),
                "protocol_host_secs_total": round(host, 4),
                "device_share": round(
                    attr["device"] / wall_total, 4
                ) if wall_total else 0.0,
                "protocol_host_share": round(
                    host / wall_total, 4
                ) if wall_total else 0.0,
            }
        return out

    # the size-1 serving leg and the single-process attribution leg are
    # PLAIN coordinators (no fleet plane at all): the baseline the ISSUE
    # names is the r16/r19 single-coordinator deployment, not a one-member
    # fleet
    legs = {
        n: leg(n, cached=True, plain=(n == 1), tag=f"c{n}_") for n in sizes
    }
    kill_leg = leg(max(sizes), cached=True, kill=True, tag="kill_")
    # r19's attribution methodology verbatim — 16 clients, scale 0.01, so
    # the protocol-host share lands on the same axis as the 90.7% finding
    attr_single = leg(
        1, cached=False, leg_clients=attr_clients,
        leg_per_client=attr_per_client, attribution=True, plain=True,
        leg_scale=attr_scale, tag="attr1_",
    )
    attr_fleet = leg(
        max(sizes), cached=False, leg_clients=attr_clients,
        leg_per_client=attr_per_client, attribution=True,
        leg_scale=attr_scale, tag="attrN_",
    )

    base = legs[min(sizes)]
    scaling = {
        str(n): round(legs[n]["qps"] / base["qps"], 3) if base["qps"] else 0.0
        for n in sizes
    }
    oracle = {
        n: v[0] for n, v in base["result_fingerprints"].items() if v
    }
    # the attribution legs run at r19's scale, so their oracle is the
    # single-coordinator attribution leg, not the serving-replay baseline
    attr_oracle = {
        n: v[0] for n, v in attr_single["result_fingerprints"].items() if v
    }
    checks = (
        [(lg, oracle) for lg in list(legs.values()) + [kill_leg]]
        + [(attr_single, attr_oracle), (attr_fleet, attr_oracle)]
    )
    identical = all(lg["internally_consistent"] for lg, _ in checks) and all(
        lg["result_fingerprints"].get(n, [None])[:1] in ([orc[n]], [])
        for lg, orc in checks for n in orc
    )

    results = {}
    for n in sizes:
        lg = legs[n]
        results[f"serve_c{n}"] = {
            "median_secs": round(
                statistics.median(lg["latency_samples"]), 6
            ) if lg["latency_samples"] else 0.0,
            "mad_secs": round(_mad(lg["latency_samples"]), 6),
            "samples": lg["latency_samples"],
            "fingerprint": fp(sorted(oracle.items())),
        }
    for key, lg in (("owner_kill", kill_leg),
                    ("attr_single", attr_single),
                    ("attr_fleet", attr_fleet)):
        results[key] = {
            "median_secs": round(
                statistics.median(lg["latency_samples"]), 6
            ) if lg["latency_samples"] else 0.0,
            "mad_secs": round(_mad(lg["latency_samples"]), 6),
            "samples": lg["latency_samples"],
            "fingerprint": fp(sorted(
                (n, v) for n, v in lg["result_fingerprints"].items()
            )),
        }

    share_fleet = attr_fleet["attribution"]["protocol_host_share"]
    return {
        "scale": scale,
        "mix": names,
        "workload": (
            "serving legs: warm-tier replay (result cache + shared warm "
            "tier + cache-aware admission) — the protocol front IS the "
            "bottleneck; attribution legs: the same mix uncached"
        ),
        "legs": {f"c{n}": legs[n] for n in sizes},
        "owner_kill": kill_leg,
        "attribution_single": attr_single,
        "attribution_fleet": attr_fleet,
        "qps_by_coordinators": {str(n): legs[n]["qps"] for n in sizes},
        "qps_scaling_vs_single": scaling,
        "zero_lost_queries": kill_leg["lost"] == 0
        and kill_leg["finished"] == kill_leg["queries"],
        "bit_identical_to_single_coordinator_oracle": identical,
        "oracle_fingerprints": oracle,
        "attr_oracle_fingerprints": attr_oracle,
        "attr_scale": attr_scale,
        "r19_protocol_host_share": 0.907,
        "protocol_host_share_single": (
            attr_single["attribution"]["protocol_host_share"]
        ),
        "protocol_host_share_fleet": share_fleet,
        "protocol_host_share_below_r19": share_fleet < 0.907,
        "results": results,
    }


def run_fleet_ab(scale=None):
    """Run the fleet A/B and return the v3 record (``python bench.py
    fleet_ab`` prints it; the checked-in BENCH_r20_fleet_ab.json passes
    tools/bench_schema.py unwaived)."""
    import jax

    scale = (
        float(os.environ.get("BENCH_FLEET_SCALE", "0.0005"))
        if scale is None else scale
    )
    m = measure_fleet_ab(scale=scale)
    platform = jax.default_backend()
    return {
        "bench": "fleet_ab",
        "schema_version": LADDER_SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "platform": platform,
        "device": jax.devices()[0].device_kind,
        # CPU numbers are functional evidence, not performance claims
        "hardware_verified": platform not in ("cpu", "interpreter"),
        "scale": scale,
        **m,
    }


def _emit_from_entries(results_path, note):
    """Assemble and print the ONE JSON line from the streamed results file."""
    entries = {}
    try:
        with open(results_path) as f:
            for line in f:
                if line.strip():
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn line from a killed child
                    entries[rec["key"]] = rec["value"]  # later records win
    except OSError:
        pass
    meta = entries.pop("_meta", {})
    queries = {k: v for k, v in entries.items() if not k.startswith("_")}
    for name in ("q6", "q1", "q3", "q14", "q18"):
        queries.setdefault(name, {"error": "lost (child timed out or died)"})
    q6 = queries.get("q6", {})
    rps = q6.get("rows_per_sec", 0.0) if isinstance(q6, dict) else 0.0
    baseline_rps = meta.get("baseline_rows_per_sec")
    scale = float(os.environ.get("BENCH_SCALE", "1"))
    record = {
        "metric": f"tpch_q6_sf{scale:g}_rows_per_sec",
        "value": rps,
        "unit": "rows/s",
        "vs_baseline": round(rps / baseline_rps, 3) if (baseline_rps and rps) else 0.0,
        "schema_version": BENCH_SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "detail": {**meta, "queries": queries},
    }
    if note:
        record["detail"]["note"] = note
    print(json.dumps(record))


def main():
    import subprocess
    import tempfile

    task = os.environ.get("BENCH_CHILD_TASK")
    if task:
        child_main(task)
        return

    if len(sys.argv) > 1 and sys.argv[1] == "ladder":
        # `python bench.py ladder`: the r06-r18 regression suite as ONE
        # in-process task emitting the hardware-labeled v3 JSON on stdout
        # (feed two of these to tools/bench_regress.py)
        print(json.dumps(run_ladder(), indent=2))
        return

    if len(sys.argv) > 1 and sys.argv[1] == "hostpath_ab":
        # `python bench.py hostpath_ab`: the r13/r16 saturation replay with
        # the host-path observability plane off vs on, plus the profiled
        # p99@16c protocol-host/device attribution
        # (BENCH_r19_hostpath_ab.json)
        print(json.dumps(run_hostpath_ab(), indent=2))
        return

    if len(sys.argv) > 1 and sys.argv[1] == "fleet_ab":
        # `python bench.py fleet_ab`: the r16 100-client replay against a
        # REAL multi-process active-active coordinator fleet at 1/2/4
        # processes sharing one SO_REUSEPORT front port, plus a mid-run
        # owner kill and the r19-methodology protocol-host attribution
        # (BENCH_r20_fleet_ab.json)
        print(json.dumps(run_fleet_ab(), indent=2))
        return

    # join children get 2x this; q18's warm path needs ~61s compile + 4
    # dispatches at ~43s (BASELINE.md round 3), so the default must clear 300s
    per_query_timeout = int(os.environ.get("BENCH_Q_TIMEOUT", "160"))
    with tempfile.NamedTemporaryFile("r", suffix=".jsonl", delete=False) as f:
        results_path = f.name

    state = {"note": None, "proc": None, "done": False}

    def emit_and_exit(signum=None, frame=None):
        """The driver kills us with `timeout` (SIGTERM first). Print whatever
        the children streamed so far and exit 0."""
        if state["done"]:
            return
        state["done"] = True
        if state["proc"] is not None and state["proc"].poll() is None:
            try:
                state["proc"].kill()
            except OSError:
                pass
        if signum is not None:
            state["note"] = state["note"] or f"parent got signal {signum}"
        _emit_from_entries(results_path, state["note"])
        sys.stdout.flush()
        try:
            os.unlink(results_path)
        except OSError:
            pass
        os._exit(0)

    signal.signal(signal.SIGTERM, emit_and_exit)
    signal.signal(signal.SIGINT, emit_and_exit)

    env_base = dict(os.environ, BENCH_RESULTS=results_path)
    if not device_healthcheck():
        sys.stderr.write("bench: device unhealthy, falling back to CPU backend\n")
        env_base["BENCH_FORCE_CPU"] = "1"

    # meta (datagen + numpy baseline) is host-only and fast; join children get
    # extra headroom for the per-operator warm run
    sf10_tmo = int(os.environ.get("BENCH_SF10_TIMEOUT", "900"))
    tasks = [("meta", 120), ("q6", per_query_timeout), ("q1", per_query_timeout),
             ("q3", per_query_timeout * 2), ("q14", per_query_timeout * 2),
             # q18's adaptive programs can be compile-bound on a cold tunnel
             # cache (BASELINE.md round 3 measured 1817s cold) — give it room
             ("q18", per_query_timeout * 6),
             # out-of-core ladder (runtime/ooc.py): joins above SF1 on one
             # chip — the round-5 capability proof; wall time is CPU
             # datagen-dominant, device work is per-bucket unit programs
             ("ooc_q6_sf10", sf10_tmo), ("ooc_q1_sf10", sf10_tmo),
             ("ooc_q3_sf10", sf10_tmo), ("ooc_q14_sf10", sf10_tmo),
             # exchange data plane A/B (host repartition+serde vs the device
             # epilogue + sliced v2 frames; BENCH_r07_exchange_ab.json)
             ("exchange_ab", per_query_timeout * 2),
             # sustained-concurrency replay under memory arbitration
             # (BENCH_r09_concurrency.json)
             ("concurrency", per_query_timeout * 2),
             # device-batching A/B: the same replay off vs on
             # (BENCH_r13_batching_ab.json)
             ("batching_ab", per_query_timeout * 4),
             # megakernel A/B: fused vs serial on the join-heavy shapes
             # (BENCH_r14_megakernel_ab.json)
             ("megakernel_ab", per_query_timeout * 2),
             # tensor-plane A/B: fused vector top-k + model scoring
             # (BENCH_r15_vector_ab.json)
             ("vector_ab", per_query_timeout * 2),
             # vector-serving A/B: query-matrix batching at 1/4/16/64
             # concurrent clients + the ANN recall ladder
             # (BENCH_r18_vector_serving_ab.json)
             ("vector_serving_ab", per_query_timeout * 4),
             # statistics-feedback-plane overhead A/B (plane on vs off;
             # BENCH_r10_stats_ab.json)
             ("stats_ab", per_query_timeout),
             # warm-path cache plane cold/warm/shared A/B
             # (BENCH_r11_cache_ab.json)
             ("cache_ab", per_query_timeout),
             # host-path observability plane off/on saturation A/B +
             # profiled attribution (BENCH_r19_hostpath_ab.json)
             ("hostpath_ab", per_query_timeout * 4),
             # active-active coordinator fleet scaling replay + owner
             # kill + fleet attribution (BENCH_r20_fleet_ab.json)
             ("fleet_ab", per_query_timeout * 4)]
    if os.environ.get("BENCH_SF100"):
        tasks += [("ooc_q6_sf100", sf10_tmo * 2), ("ooc_q1_sf100", sf10_tmo * 2),
                  ("ooc_q3_sf100", sf10_tmo * 3), ("ooc_q14_sf100", sf10_tmo * 3)]
    notes = []
    for name, tmo in tasks:
        env = dict(env_base, BENCH_CHILD_TASK=name)
        try:
            state["proc"] = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)], env=env
            )
            rc = state["proc"].wait(timeout=tmo)
            if rc != 0:
                notes.append(f"{name}: child exited {rc}")
        except subprocess.TimeoutExpired:
            state["proc"].kill()
            state["proc"].wait()
            notes.append(f"{name}: timed out after {tmo}s")
    state["note"] = "; ".join(notes) if notes else None
    state["done"] = True
    _emit_from_entries(results_path, state["note"])
    try:
        os.unlink(results_path)
    except OSError:
        pass


if __name__ == "__main__":
    main()
