"""Failure classification, retry/backoff policy, and the chaos harness.

Reference blueprint: execution/FailureInjector.java:35 (InjectedFailureType:51)
— fault injection is built into the engine and driven by tests (SURVEY.md §4
BaseFailureRecoveryTest) — io.trino.spi.ErrorType (USER_ERROR /
INTERNAL_ERROR / EXTERNAL error categories steering retry decisions in
EventDrivenFaultTolerantQueryScheduler: user errors fail the query
immediately, everything else re-attempts with backoff), and
RetryPolicy.QUERY (SqlQueryExecution.java:536: re-run the whole query on
retryable failure; task-level FTE lives in runtime/fte_scheduler.py).
"""

from __future__ import annotations

import random
import re
import threading
from contextlib import contextmanager
from enum import Enum
from typing import Callable, Dict, List, Optional


class ErrorCategory(Enum):
    """ref: io.trino.spi.ErrorType — the axis every retry decision turns on.

    USER: the query itself is wrong (semantic/compile/analysis failures);
    re-running it can never succeed, so retrying burns attempts for nothing.
    INTERNAL: an engine fault (bug, injected crash, corrupt state); a retry
    on fresh state may succeed. EXTERNAL: the environment failed (worker
    died, transport loss, deadline); retry on a DIFFERENT node.
    """

    USER = "USER"
    INTERNAL = "INTERNAL"
    EXTERNAL = "EXTERNAL"


class InjectedFailure(RuntimeError):
    """Carries an explicit category so chaos tests can model every error
    class (a USER-injected failure must fail fast, never retry). The
    category rides IN the message text too: worker-reported failures cross
    the wire as ``"TypeName: message"`` strings, and without the marker a
    remote USER injection would classify INTERNAL on the coordinator and
    burn retries the chaos contract says it must not."""

    def __init__(self, message: str = "", category: Optional[ErrorCategory] = None):
        cat = category or ErrorCategory.INTERNAL
        if category is not None and "[category=" not in message:
            message = f"{message} [category={cat.value}]"
        super().__init__(message)
        self.error_category = cat


class RetryableQueryError(RuntimeError):
    """A failure the QUERY retry policy may recover from by re-running the
    whole query (e.g. a worker task failed or a worker died mid-query)."""

    error_category = ErrorCategory.EXTERNAL


class TaskDeadlineExceeded(RuntimeError):
    """A task attempt ran past its completion deadline (hung worker, stalled
    RPC). EXTERNAL: the retry must land on a different node."""

    error_category = ErrorCategory.EXTERNAL


# semantic/analysis error types across the engine: re-running the same query
# can never succeed (matched by CLASS NAME so classification needs no import
# of every module, and so worker-reported failures — which arrive as
# "TypeName: message" text — classify identically on the coordinator).
# Admission rejections and administrative memory kills sit here too (ref:
# ErrorType of QUERY_QUEUE_FULL / CLUSTER_OUT_OF_MEMORY / ADMINISTRATIVELY_
# KILLED): the cluster DECIDED to shed this query — FTE retrying it would
# re-submit the very load the arbitration plane just rejected
_USER_ERROR_TYPES = frozenset({
    "CompileError", "SemanticError", "ParseError", "LexError",
    "FunctionResolutionError", "TableFunctionAnalysisError",
    "AccessDeniedError", "AuthenticationError", "DmlError", "MatchError",
    "StreamingUnsupported", "TransactionError",
    "QueryQueueFullError", "QueryKilledError", "AdministrativelyKilled",
})

# transient resource pressure (ref: ErrorType.INSUFFICIENT_RESOURCES): the
# QUERY is fine — a retry on a different or less-loaded worker can succeed,
# so these must NOT short-circuit the retry budget the way USER errors do
# (queue-full/killed are NOT here: those are deliberate shedding decisions)
_RESOURCE_ERROR_TYPES = frozenset({
    "ExceededMemoryLimitError",
})

# explicit category marker surviving "TypeName: message" serialization
_CATEGORY_MARKER_RE = re.compile(r"\[category=(USER|INTERNAL|EXTERNAL)\]")

# substrings that mark a worker-reported failure as transport-flavored
# (the producing worker died / hung rather than the task being wrong)
TRANSPORT_ERROR_MARKERS = (
    "URLError", "ConnectionRefused", "ConnectionReset", "unreachable",
    "TimeoutError", "RemoteDisconnected", "BadStatusLine", "IncompleteRead",
    "timed out", "TaskDeadlineExceeded",
)


def classify_error(exc: BaseException) -> ErrorCategory:
    """Map an exception to the category steering the retry decision.

    Precedence: an explicit ``error_category`` attribute wins (injected
    failures, deadline errors); then the type name against the USER set
    (whole MRO, so subclasses classify like their base); TaskFailedError
    text is parsed — workers serialize failures as "TypeName: message" —
    so a worker-side CompileError fails the query as fast as a local one;
    bare OSErrors are transport loss (EXTERNAL); everything else is an
    engine fault (INTERNAL, retryable)."""
    cat = getattr(exc, "error_category", None)
    if isinstance(cat, ErrorCategory):
        return cat
    names = {c.__name__ for c in type(exc).__mro__}
    if names & _RESOURCE_ERROR_TYPES:
        return ErrorCategory.INTERNAL
    if names & _USER_ERROR_TYPES:
        return ErrorCategory.USER
    if "TaskFailedError" in names:
        text = getattr(exc, "error_text", "") or str(exc)
        m = _CATEGORY_MARKER_RE.search(text)
        if m is not None:
            # an explicit category rode the wire (InjectedFailure et al.)
            return ErrorCategory[m.group(1)]
        head = text.split(":", 1)[0].strip()
        if head in _RESOURCE_ERROR_TYPES:
            return ErrorCategory.INTERNAL
        if head in _USER_ERROR_TYPES:
            return ErrorCategory.USER
        if any(m in text for m in TRANSPORT_ERROR_MARKERS):
            return ErrorCategory.EXTERNAL
        return ErrorCategory.INTERNAL
    if "HTTPError" in names:
        # the server ANSWERED (bad signature / undecodable plan / 5xx):
        # not transport loss, don't blacklist the node for it
        return ErrorCategory.INTERNAL
    if isinstance(exc, OSError):
        return ErrorCategory.EXTERNAL
    return ErrorCategory.INTERNAL


def retry_backoff(
    failure_count: int,
    initial: float = 0.05,
    cap: float = 2.0,
    rng: Callable[[], float] = random.random,
) -> float:
    """Capped exponential backoff with jitter (ref: the scheduler's
    taskRetryDelay: initial * 2^(n-1), capped, x0.5-1.5 jitter so a burst
    of failures doesn't re-dispatch in lockstep)."""
    base = min(cap, initial * (2.0 ** max(0, failure_count - 1)))
    return base * (0.5 + rng())


class FailureInjector:
    """Injects failures into operator evaluation, keyed by plan-node type.

    Usage (tests): injector.fail_once("AggregationNode"); attach to a
    PlanExecutor subclass or the retrying runner below.
    """

    _tls = threading.local()

    def __init__(self):
        self._remaining: Dict[str, int] = {}
        # category is PER node_type: arming USER for one site must not leak
        # onto later injections at other sites (which default to INTERNAL)
        self._categories: Dict[str, ErrorCategory] = {}
        self._lock = threading.Lock()
        self.injected = 0
        self._prev: Optional["FailureInjector"] = None

    def fail_once(self, node_type: str, times: int = 1,
                  category: Optional[ErrorCategory] = None) -> None:
        with self._lock:
            self._remaining[node_type] = self._remaining.get(node_type, 0) + times
            if category is not None:
                self._categories[node_type] = category

    def maybe_fail(self, node_type: str) -> None:
        with self._lock:
            n = self._remaining.get(node_type, 0)
            if n > 0:
                self._remaining[node_type] = n - 1
                self.injected += 1
                raise InjectedFailure(
                    f"injected failure at {node_type}",
                    category=self._categories.get(node_type),
                )

    def __enter__(self):
        # thread-local + save/restore: concurrent queries on other threads are
        # unaffected, and nested contexts restore the outer injector
        self._prev = getattr(FailureInjector._tls, "current", None)
        FailureInjector._tls.current = self
        return self

    def __exit__(self, *exc):
        FailureInjector._tls.current = self._prev
        return False

    @staticmethod
    def current() -> Optional["FailureInjector"]:
        return getattr(FailureInjector._tls, "current", None)

    @staticmethod
    @contextmanager
    def activated(inj: Optional["FailureInjector"]):
        """Install ``inj`` on THIS thread (the FTE scheduler dispatches task
        attempts onto pool threads; the submitting thread's injector must
        ride along or concurrent dispatch would silently disable every
        BaseFailureRecoveryTest-style test)."""
        prev = getattr(FailureInjector._tls, "current", None)
        FailureInjector._tls.current = inj
        try:
            yield inj
        finally:
            FailureInjector._tls.current = prev


class ChaosInjector:
    """Site-keyed chaos harness (the FailureInjector grown to the full
    engine surface — ref: InjectedFailureType:51 + BaseFailureRecoveryTest).

    PROCESS-GLOBAL by design: injection sites live in worker task threads,
    HTTP handler threads, and exchange sinks — none of which inherit a
    thread-local. Sites are free-form strings; the canonical ones are

    - transport_refuse / transport_hang / transport_slow  (worker RPC layer)
    - exchange_corrupt_frame / exchange_torn_commit       (durable exchange)
    - task_crash_mid_execute / task_crash_after_commit    (task layer)
    - task_stall                                          (speculation bait)

    ``arm(site, times=N, match="substr", ...)`` arms N firings, optionally
    gated on the call site's context text containing ``match``; params like
    ``delay`` (seconds) and ``category`` (USER/INTERNAL/EXTERNAL) ride to
    the site. ``fire`` decrements and returns the armed params, or None.
    Use as a context manager to install/uninstall globally.
    """

    _global: Optional["ChaosInjector"] = None
    _global_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, List[dict]] = {}
        self.fired: Dict[str, int] = {}
        self._prev: Optional["ChaosInjector"] = None

    def arm(self, site: str, times: int = 1, **params) -> None:
        with self._lock:
            self._armed.setdefault(site, []).append(
                {"times": int(times), "params": dict(params)}
            )

    def fire(self, site: str, text: str = "") -> Optional[dict]:
        with self._lock:
            for entry in self._armed.get(site, ()):
                if entry["times"] <= 0:
                    continue
                match = entry["params"].get("match", "")
                if match and match not in (text or ""):
                    continue
                entry["times"] -= 1
                self.fired[site] = self.fired.get(site, 0) + 1
                return dict(entry["params"])
        return None

    def __enter__(self):
        with ChaosInjector._global_lock:
            self._prev = ChaosInjector._global
            ChaosInjector._global = self
        return self

    def __exit__(self, *exc):
        with ChaosInjector._global_lock:
            ChaosInjector._global = self._prev
        return False


def chaos_fire(site: str, text: str = "") -> Optional[dict]:
    """Hot-path hook: one attribute read when no harness is installed."""
    c = ChaosInjector._global
    return c.fire(site, text) if c is not None else None


def chaos_category(act: dict) -> Optional[ErrorCategory]:
    """Armed ``category`` param ("USER"/"INTERNAL"/"EXTERNAL") -> enum."""
    name = act.get("category")
    return ErrorCategory[name] if name else None


def execute_with_retry(execute: Callable[[str], object], sql: str,
                       retry_policy: str = "NONE", max_retries: int = 1):
    """RetryPolicy.QUERY: re-run the whole query on retryable failure
    (ref: SqlQueryExecution.java:536-560 scheduler selection by retry
    policy). USER-category failures never retry — the query text cannot
    become correct by re-running it."""
    attempts = 0
    while True:
        try:
            return execute(sql)
        except (InjectedFailure, RetryableQueryError) as e:
            if classify_error(e) is ErrorCategory.USER:
                raise
            attempts += 1
            if retry_policy != "QUERY" or attempts > max_retries:
                raise
