"""Event listeners: structured query lifecycle events.

Reference blueprint: spi/eventlistener (QueryCreatedEvent /
QueryCompletedEvent / SplitCompletedEvent et al.) dispatched by
EventListenerManager (SURVEY.md §5.5) — consumers are audit logs, metrics
pipelines, lineage systems. The full lifecycle is dispatched by
QueryManager: ``query_created`` at submit, ``query_state_change`` per
transition, ``split_completed`` from the executor's split boundaries,
``query_completed`` on the terminal transition. Listeners implement any
subset of those methods (each receives the event dict); a plain callable is
a legacy completion-only listener and receives the QueryExecution itself.

Shipped listeners: a size-rotating JSONL :class:`FileEventListener` (the
trino file/http event-listener analogue), an in-memory
:class:`CollectingEventListener` (TestingEventListener), and
:class:`QueryHistoryStore` — a JSONL-persisted completed-query store that
survives coordinator restarts and backs ``system.runtime.query_history``.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from contextlib import contextmanager
from typing import Callable, Iterable, List, Optional

from .query_manager import QueryExecution

_EVENT_TYPE = {
    "query_created": "QueryCreated",
    "query_state_change": "QueryStateChange",
    "query_completed": "QueryCompleted",
    "split_completed": "SplitCompleted",
}

LIFECYCLE_EVENTS = tuple(_EVENT_TYPE)


def lifecycle_event(q: QueryExecution, kind: str) -> dict:
    """ref: spi/eventlistener/Query*Event.java field set (subset); one shape
    for every lifecycle stage so consumers key on ``eventType``."""
    return {
        "eventType": _EVENT_TYPE.get(kind, kind),
        "queryId": q.query_id,
        "state": q.state.value,
        "user": q.user,
        "source": q.source,
        "resourceGroup": q.resource_group,
        "query": q.sql,
        "createTime": q.stats.create_time,
        "endTime": q.stats.end_time,
        "elapsedSeconds": round(q.stats.elapsed, 6),
        "cpuSeconds": round(q.stats.cpu_time, 6),
        "outputRows": q.stats.rows,
        "error": q.error,
        "errorType": q.error_type,
    }


def query_completed_event(q: QueryExecution) -> dict:
    """Back-compat builder (pre-lifecycle name)."""
    return lifecycle_event(
        q, "query_completed" if q.state.is_done else "query_state_change"
    )


class EventListener:
    """Base listener (ref: spi/eventlistener/EventListener.java). Override
    any subset; every method takes the event dict."""

    def query_created(self, event: dict) -> None:  # noqa: B027 — optional hook
        pass

    def query_state_change(self, event: dict) -> None:  # noqa: B027
        pass

    def split_completed(self, event: dict) -> None:  # noqa: B027
        pass

    def query_completed(self, event: dict) -> None:  # noqa: B027
        pass


class FileEventListener(EventListener):
    """Append query events to a JSONL file, rotating by size (thread-safe;
    the trino-file-event-listener analogue). Default records completion
    events only; pass ``events=LIFECYCLE_EVENTS`` for the full lifecycle."""

    def __init__(self, path: str, events: Iterable[str] = ("query_completed",),
                 max_bytes: int = 16 * 1024 * 1024):
        self.path = path
        self.events = frozenset(events)
        self.max_bytes = max_bytes
        # dedicated I/O-serialization lock: rotation + append are its ONLY
        # job and no shared state hides behind it, so event dispatchers never
        # block on disk while holding anything another thread reads
        # (lint rule blocking-call-under-lock; the cachestore persistence
        # path uses the same split)
        self._io_lock = threading.Lock()

    def _write(self, kind: str, record: dict) -> None:
        if kind not in self.events:
            return
        line = json.dumps(record)
        with self._io_lock:
            try:
                if os.path.getsize(self.path) + len(line) > self.max_bytes:
                    os.replace(self.path, self.path + ".1")
            except OSError:
                pass  # no file yet
            with open(self.path, "a") as f:
                f.write(line + "\n")

    def query_created(self, event: dict) -> None:
        self._write("query_created", event)

    def query_state_change(self, event: dict) -> None:
        self._write("query_state_change", event)

    def split_completed(self, event: dict) -> None:
        self._write("split_completed", event)

    def query_completed(self, event: dict) -> None:
        self._write("query_completed", event)

    def __call__(self, q: QueryExecution) -> None:
        # legacy direct-invocation path (completion-only)
        self._write("query_completed", query_completed_event(q))


class CollectingEventListener(EventListener):
    """In-memory listener collecting every lifecycle event it is dispatched
    (TestingEventListener analogue)."""

    def __init__(self):
        self.events: List[dict] = []
        self._lock = threading.Lock()

    def _collect(self, event: dict) -> None:
        with self._lock:
            self.events.append(event)

    query_created = _collect
    query_state_change = _collect
    split_completed = _collect
    query_completed = _collect

    def of_type(self, event_type: str) -> List[dict]:
        with self._lock:
            return [e for e in self.events if e.get("eventType") == event_type]

    def __call__(self, q: QueryExecution) -> None:
        self._collect(query_completed_event(q))


class QueryHistoryStore(EventListener):
    """Persistent completed-query store: JSONL on disk, bounded in memory.

    Backs ``system.runtime.query_history`` across coordinator restarts —
    construction replays the tail of the existing file (the reference keeps
    this in the dispatcher's QueryTracker + external sinks; a TPU-resident
    engine wants it queryable in-engine). Compaction: when the on-disk line
    count exceeds ``2 * max_records``, the file is rewritten with only the
    retained tail (atomic via temp file + replace).
    """

    def __init__(self, path: str, max_records: int = 1000):
        self.path = path
        self.max_records = max_records
        # _lock guards the in-memory ring + counters (lock-brief: records()
        # readers must never wait behind a compaction rewrite); _io_lock is
        # the dedicated append/compaction serializer — file I/O happens only
        # under it and it guards no other state (lint blocking-call-under-lock)
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        self._records: deque = deque(maxlen=max_records)
        self._disk_lines = 0
        torn = 0
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    self._disk_lines += 1
                    try:
                        self._records.append(json.loads(line))
                    except ValueError:
                        torn += 1  # torn tail line from a crash (kill
                        continue  # mid-append): skipped, counted, never fatal
        except OSError:
            pass
        if torn:
            from .ha import note_torn_record, repair_jsonl_tail

            note_torn_record(torn)
            # terminate the torn line so the next append starts a fresh
            # record instead of concatenating onto the fragment
            repair_jsonl_tail(path)

    def query_completed(self, event: dict) -> None:
        line = json.dumps(event)
        with self._io_lock:
            # disk BEFORE memory: a record visible through records() is
            # already durable (restart replay must never lose it)
            with open(self.path, "a") as f:
                f.write(line + "\n")
            with self._lock:
                self._records.append(event)
                self._disk_lines += 1
                compact = self._disk_lines > 2 * self.max_records
                snapshot = list(self._records) if compact else None
            if compact:
                # rewrite from the snapshot taken above; concurrent appends
                # queue on _io_lock so the file never interleaves
                tmp = self.path + ".tmp"
                with open(tmp, "w") as f:
                    for rec in snapshot:
                        f.write(json.dumps(rec) + "\n")
                os.replace(tmp, self.path)
                with self._lock:
                    self._disk_lines = len(snapshot)

    def __call__(self, q: QueryExecution) -> None:
        self.query_completed(query_completed_event(q))

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._records)


# --------------------------------------------------------------------------- #
# split-event sink (executor -> QueryManager, no explicit plumbing)
# --------------------------------------------------------------------------- #

_split_tls = threading.local()


@contextmanager
def split_events(fire: Callable[[dict], None]):
    """Install ``fire`` as this thread's split-completed sink for the scope
    (the QueryManager wraps executor_fn with it only when some listener
    implements ``split_completed`` — the default path costs one thread-local
    read per split)."""
    prev = getattr(_split_tls, "fire", None)
    _split_tls.fire = fire
    try:
        yield
    finally:
        _split_tls.fire = prev


def split_event_sink() -> Optional[Callable[[dict], None]]:
    return getattr(_split_tls, "fire", None)
