"""Columnar Page/Column substrate — the device-resident analogue of Trino Pages.

Reference blueprint: core/trino-spi/src/main/java/io/trino/spi/Page.java:31 and the
Block hierarchy under spi/block/ (SURVEY.md §2.1). A Trino Page is an ordered list
of Blocks plus a positionCount; a Block is one of 12 physical layouts with validity
("null") masks and dictionary/RLE wrappers.

TPU-first redesign (not a port):

- A :class:`Column` is a fixed-capacity device array (``data``) + a boolean validity
  mask (``valid``). Null handling is mask-based everywhere — there is no sentinel.
- A :class:`Page` is a tuple of equal-capacity Columns plus an ``active`` row mask.
  Because XLA requires static shapes, *filtering never compacts*: a Filter operator
  just ANDs into ``active`` (SURVEY.md §7 "pad-and-mask everywhere; the kernels must
  be oblivious to logical length"). Compaction happens only at exchange boundaries
  and at host materialization.
- VARCHAR columns carry a host-side **sorted dictionary** (strings never touch the
  device); the device sees int32 codes. Sorted means code order == string order, so
  range predicates run on codes. This plays the role of Trino's DictionaryBlock
  (spi/block/DictionaryBlock.java) but as a global, per-column property.
- Pages are JAX pytrees: they flow through jit/shard_map directly, and a Page's
  ``layout()`` (types + capacity) is the compilation cache key, exactly as Trino's
  PageFunctionCompiler caches per (expression, block layout).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .types import Type, DecimalType, VarcharType


class Dictionary:
    """Host-side sorted string dictionary shared by a VARCHAR column.

    Identity-hashed so it can ride in jit static aux data without content hashing;
    connectors create one Dictionary per column at ingest and reuse it, so the jit
    cache stays warm across splits.
    """

    __slots__ = ("values", "_lookup", "_fp", "_value_keys", "_host_bytes")

    def __init__(self, values: np.ndarray):
        # values must be sorted and unique for code-order == string-order.
        self.values = np.asarray(values, dtype=object)
        self._lookup: Optional[dict] = None
        self._fp: Optional[int] = None
        self._value_keys: Optional[np.ndarray] = None
        # memoized host size (runtime.memory.page_bytes): dictionaries are
        # immutable and shared across pages, so sizing sweeps once
        self._host_bytes: Optional[int] = None

    @staticmethod
    def from_strings(strings: Iterable[str]) -> "Dictionary":
        uniq = sorted(set(strings))
        return Dictionary(np.asarray(uniq, dtype=object))

    _empty: Optional["Dictionary"] = None

    @classmethod
    def empty(cls) -> "Dictionary":
        """THE dictionary for zero-row string columns (empty table-scan
        partitions, empty exchange inputs): one "" sentinel value so every
        dictionary-driven compile path (LIKE LUTs, comparison code lookup)
        stays well-formed — a zero-value dictionary breaks the LUT gather.
        All rows of such pages are inactive, so the sentinel never surfaces.
        A process-wide singleton: identity-hashed jit static aux stays warm
        across empty partitions."""
        if cls._empty is None:
            cls._empty = Dictionary(np.asarray([""], dtype=object))
        return cls._empty

    def __len__(self) -> int:
        return len(self.values)

    def code_of(self, s: str) -> int:
        """Exact-match code, or -1 if absent."""
        if self._lookup is None:
            self._lookup = {v: i for i, v in enumerate(self.values)}
        return self._lookup.get(s, -1)

    def searchsorted(self, s: str, side: str = "left") -> int:
        lo, hi = 0, len(self.values)
        while lo < hi:
            mid = (lo + hi) // 2
            v = self.values[mid]
            if v < s or (side == "right" and v == s):
                lo = mid + 1
            else:
                hi = mid
        return lo

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(len(codes), dtype=object)
        in_range = (codes >= 0) & (codes < len(self.values))
        out[in_range] = self.values[codes[in_range]]
        out[~in_range] = None
        return out

    def fingerprint(self) -> int:
        """Content fingerprint (cached): equal vocabularies compare equal even
        across deserialized copies — identity (__eq__/__hash__) stays object-
        based so jit static-aux caching is untouched."""
        if self._fp is None:
            import hashlib

            h = hashlib.blake2b(digest_size=8)
            for v in self.values:
                h.update(str(v).encode())
                h.update(b"\x00")
            self._fp = int.from_bytes(h.digest(), "little", signed=True)
        return self._fp

    def value_keys(self) -> np.ndarray:
        """code -> content-stable int64 key (cached LUT). Lets repartition
        hashing of dictionary columns be consistent across producers whose
        dictionaries differ (codes are only comparable within one dictionary)."""
        if self._value_keys is None:
            import hashlib

            lut = np.empty(len(self.values), dtype=np.int64)
            for i, s in enumerate(self.values):
                d = hashlib.blake2b(str(s).encode(), digest_size=8).digest()
                lut[i] = int.from_bytes(d, "little", signed=True)
            self._value_keys = lut
        return self._value_keys

    def __hash__(self):
        return id(self)

    def __eq__(self, other):
        return self is other

    def __repr__(self):  # pragma: no cover
        return f"Dictionary(n={len(self.values)})"


@jax.tree_util.register_pytree_node_class
@dataclass
class Column:
    """One column: device data + validity mask + SQL type (+ host dictionary).

    Nested layouts (ref spi/block/ArrayBlock.java, MapBlock.java, RowBlock.java —
    offset-based there; pad-and-mask here, see types.ArrayType):

    - ARRAY:  ``data[cap, W]`` + ``elem_valid[cap, W]`` + ``lengths[cap]``
      (positions 0..len-1 exist; elem_valid marks non-null among them)
    - MAP:    ``children == (keys, values)`` — two array-layout Columns with a
      shared length; parent ``data`` is a dummy int8 lane
    - ROW:    ``children`` holds one scalar-layout Column per field
    """

    type: Type
    data: jnp.ndarray
    valid: jnp.ndarray
    dictionary: Optional[Dictionary] = None
    lengths: Optional[jnp.ndarray] = None  # [cap] int32 (array/map)
    elem_valid: Optional[jnp.ndarray] = None  # [cap, W] (array)
    children: tuple = ()  # nested Columns (map: keys/values; row: fields)

    def tree_flatten(self):
        return (
            (self.data, self.valid, self.lengths, self.elem_valid, self.children),
            (self.type, self.dictionary),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        t, d = aux
        data, valid, lengths, elem_valid, kids = children
        return cls(
            type=t, data=data, valid=valid, dictionary=d,
            lengths=lengths, elem_valid=elem_valid, children=tuple(kids),
        )

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @staticmethod
    def from_numpy(
        type_: Type,
        values: np.ndarray,
        valid: Optional[np.ndarray] = None,
        capacity: Optional[int] = None,
        dictionary: Optional[Dictionary] = None,
    ) -> "Column":
        values = np.asarray(values)
        n = len(values)
        cap = capacity if capacity is not None else n
        dtype = type_.storage_dtype
        # multi-lane storage (long decimals: (n, 2) int64 limbs) pads on axis 0
        data = np.zeros((cap,) + tuple(values.shape[1:]), dtype=dtype)
        data[:n] = values.astype(dtype, copy=False)
        v = np.zeros(cap, dtype=np.bool_)
        v[:n] = True if valid is None else np.asarray(valid, dtype=np.bool_)
        return Column(type_, jnp.asarray(data), jnp.asarray(v), dictionary)

    @staticmethod
    def from_strings(
        strings: Sequence[Optional[str]],
        type_: Type = None,
        capacity: Optional[int] = None,
        dictionary: Optional[Dictionary] = None,
    ) -> "Column":
        type_ = type_ or VarcharType()
        present = [s for s in strings if s is not None]
        d = dictionary or Dictionary.from_strings(present)
        codes = np.array([d.code_of(s) if s is not None else 0 for s in strings], dtype=np.int32)
        if dictionary is not None and np.any(codes < 0):
            missing = sorted({s for s in present if d.code_of(s) < 0})
            raise ValueError(f"strings absent from supplied dictionary: {missing[:5]}")
        valid = np.array([s is not None for s in strings], dtype=np.bool_)
        return Column.from_numpy(type_, codes, valid, capacity, dictionary=d)

    @staticmethod
    def from_nested(
        type_: Type,
        values: Sequence,
        capacity: Optional[int] = None,
        width: Optional[int] = None,
    ) -> "Column":
        """Build a nested (array/map/row) column from python values (host path,
        used by connectors/tests; the hot paths construct device layouts
        directly)."""
        from .types import ArrayType, MapType, RowType

        n = len(values)
        cap = capacity if capacity is not None else n
        valid = np.array([v is not None for v in values], dtype=np.bool_)
        valid = np.concatenate([valid, np.zeros(cap - n, dtype=np.bool_)])
        if isinstance(type_, ArrayType):
            lists = [list(v) if v is not None else [] for v in values]
            w = width if width is not None else max([len(x) for x in lists] + [1])
            lengths = np.zeros(cap, dtype=np.int32)
            lengths[:n] = [min(len(x), w) for x in lists]
            ev = np.zeros((cap, w), dtype=np.bool_)
            flat = [x[j] if j < len(x) else None for x in lists for j in range(w)]
            for i, x in enumerate(lists):
                for j, e in enumerate(x[:w]):
                    ev[i, j] = e is not None
            if isinstance(type_.element, (ArrayType, MapType, RowType)):
                # nested element: keep a flattened [cap*w] child column and a
                # dummy parent lane (decode reshapes the child back)
                flat += [None] * ((cap - n) * w)
                child = Column.from_nested(type_.element, flat, capacity=cap * w)
                return Column(
                    type_, jnp.zeros((cap, w), dtype=jnp.int8), jnp.asarray(valid),
                    lengths=jnp.asarray(lengths), elem_valid=jnp.asarray(ev),
                    children=(child,),
                )
            ecol = _scalar_from_pylist(type_.element, flat)
            data = np.asarray(ecol.data).reshape(n, w)
            if cap > n:
                data = np.concatenate([data, np.zeros((cap - n, w), dtype=data.dtype)])
            return Column(
                type_, jnp.asarray(data), jnp.asarray(valid), ecol.dictionary,
                lengths=jnp.asarray(lengths), elem_valid=jnp.asarray(ev),
            )
        if isinstance(type_, MapType):
            keys = [list(v.keys()) if v is not None else None for v in values]
            vals = [list(v.values()) if v is not None else None for v in values]
            w = width if width is not None else max(
                [len(k) for k in keys if k is not None] + [1]
            )
            kcol = Column.from_nested(ArrayType(element=type_.key), keys, cap, w)
            vcol = Column.from_nested(ArrayType(element=type_.value), vals, cap, w)
            return Column(
                type_, jnp.zeros(cap, dtype=jnp.int8), jnp.asarray(valid),
                lengths=kcol.lengths, children=(kcol, vcol),
            )
        if isinstance(type_, RowType):
            kids = []
            for i, (_, ft) in enumerate(type_.fields):
                fvals = [v[i] if v is not None else None for v in values]
                kids.append(
                    Column.from_nested(ft, fvals, cap)
                    if isinstance(ft, (ArrayType, MapType, RowType))
                    else _scalar_from_pylist(ft, fvals, cap)
                )
            return Column(
                type_, jnp.zeros(cap, dtype=jnp.int8), jnp.asarray(valid),
                children=tuple(kids),
            )
        return _scalar_from_pylist(type_, list(values), cap)

    def to_numpy(self, active: Optional[np.ndarray] = None) -> np.ndarray:
        """Materialize to host as an object-free array; nulls -> masked separately."""
        data = np.asarray(self.data)
        if active is not None:
            data = data[active]
        return data

    def decode(self, active: Optional[np.ndarray] = None) -> np.ndarray:
        """Host materialization into python-visible values (objects), nulls as None.

        Note: decimals decode via float division, exact only up to 2**53 of scaled
        magnitude — fine for result display/tests; a lossless Decimal path can be
        added at the client-protocol layer when needed.
        """
        from .types import ArrayType, MapType, RowType

        data = np.asarray(self.data)
        valid = np.asarray(self.valid)
        if active is not None:
            data, valid = data[active], valid[active]
        if isinstance(self.type, ArrayType):
            ev = np.asarray(self.elem_valid)
            lengths = np.asarray(self.lengths)
            if self.children:
                # nested element: children[0] is the flattened [cap*w] column
                cap, w = ev.shape
                elems = self.children[0].decode(None).reshape(cap, w)
                if active is not None:
                    ev, lengths = ev[active], lengths[active]
                    elems = elems[active]
                out = np.empty(len(lengths), dtype=object)
                for i in range(len(lengths)):
                    out[i] = list(elems[i, : lengths[i]]) if valid[i] else None
                return out
            if active is not None:
                ev, lengths = ev[active], lengths[active]
            n, w = data.shape
            flat = Column(self.type.element, data.reshape(-1), ev.reshape(-1),
                          self.dictionary).decode(None)
            elems = flat.reshape(n, w)
            out = np.empty(n, dtype=object)
            for i in range(n):
                out[i] = list(elems[i, : lengths[i]]) if valid[i] else None
            return out
        if isinstance(self.type, MapType):
            keys = self.children[0].decode(active)
            vals = self.children[1].decode(active)
            out = np.empty(len(keys), dtype=object)
            for i in range(len(keys)):
                out[i] = (
                    dict(zip(keys[i], vals[i]))
                    if valid[i] and keys[i] is not None
                    else None
                )
            return out
        if isinstance(self.type, RowType):
            fields = [c.decode(active) for c in self.children]
            out = np.empty(len(valid), dtype=object)
            for i in range(len(valid)):
                out[i] = tuple(f[i] for f in fields) if valid[i] else None
            return out
        if self.dictionary is not None:
            out = self.dictionary.decode(data.astype(np.int64))
            out[~valid] = None
            return out
        if self.type.name in ("tdigest", "qdigest"):
            # summary repr (the digest is queried via value_at_quantile;
            # Trino renders an opaque varbinary here)
            out = np.empty(len(data), dtype=object)
            kc = data.shape[1] // 2
            for i, ok in enumerate(valid.tolist()):
                out[i] = (
                    f"{self.type.name}[n={int(data[i, kc:].sum())}]"
                    if ok
                    else None
                )
            return out
        if isinstance(self.type, DecimalType) and self.type.precision > 18:
            # Int128 limbs -> exact python ints; Decimal output (floats would
            # silently destroy the precision that is the type's whole point)
            import decimal as _d

            from ..ops.int128 import np_to_ints

            ints = np_to_ints(data)
            signed = [(x + 2**127) % 2**128 - 2**127 for x in ints]
            out = np.empty(len(data), dtype=object)
            sc = self.type.scale
            for i, (x, ok) in enumerate(zip(signed, valid.tolist())):
                # tuple construction is context-exact (Decimal arithmetic
                # would round to the ambient 28-digit context precision)
                sign = 1 if x < 0 else 0
                digits = tuple(int(ch) for ch in str(abs(x)))
                out[i] = _d.Decimal((sign, digits, -sc)) if ok else None
            return out
        if isinstance(self.type, DecimalType) and self.type.scale > 0:
            out = np.empty(len(data), dtype=object)
            scale = 10 ** self.type.scale
            for i, (x, ok) in enumerate(zip(data.tolist(), valid.tolist())):
                out[i] = (x / scale) if ok else None
            return out
        if self.type.name == "date":
            import datetime

            epoch = datetime.date(1970, 1, 1)
            out = np.empty(len(data), dtype=object)
            for i, (x, ok) in enumerate(zip(data.tolist(), valid.tolist())):
                out[i] = (epoch + datetime.timedelta(days=x)) if ok else None
            return out
        if self.type.name == "timestamp":
            import datetime

            out = np.empty(len(data), dtype=object)
            for i, (x, ok) in enumerate(zip(data.tolist(), valid.tolist())):
                out[i] = (
                    datetime.datetime(1970, 1, 1) + datetime.timedelta(microseconds=x)
                ) if ok else None
            return out
        if self.type.name == "time":
            import datetime

            out = np.empty(len(data), dtype=object)
            for i, (x, ok) in enumerate(zip(data.tolist(), valid.tolist())):
                if not ok:
                    out[i] = None
                    continue
                s, us = divmod(int(x), 1_000_000)
                h, rem = divmod(s, 3600)
                m, sec = divmod(rem, 60)
                out[i] = datetime.time(h % 24, m, sec, us)
            return out
        if self.type.name == "time with time zone":
            import datetime

            from .types import twtz_unpack

            out = np.empty(len(data), dtype=object)
            for i, (x, ok) in enumerate(zip(data.tolist(), valid.tolist())):
                if not ok:
                    out[i] = None
                    continue
                local, off = twtz_unpack(int(x))
                sec, us = divmod(local, 1_000_000)
                h, rem = divmod(int(sec), 3600)
                m, sc = divmod(rem, 60)
                tz = datetime.timezone(datetime.timedelta(minutes=off))
                out[i] = datetime.time(h % 24, m, sc, int(us), tzinfo=tz)
            return out
        if self.type.name == "timestamp with time zone":
            import datetime

            out = np.empty(len(data), dtype=object)
            for i, (x, ok) in enumerate(zip(data.tolist(), valid.tolist())):
                if not ok:
                    out[i] = None
                    continue
                millis = int(x) >> 12
                off = (int(x) & 0xFFF) - 841
                tz = datetime.timezone(datetime.timedelta(minutes=off))
                out[i] = datetime.datetime.fromtimestamp(
                    millis / 1000, tz=datetime.timezone.utc
                ).astimezone(tz)
            return out
        out = np.empty(len(data), dtype=object)
        lst = data.tolist()
        for i, ok in enumerate(valid.tolist()):
            out[i] = lst[i] if ok else None
        return out


@jax.tree_util.register_pytree_node_class
@dataclass
class Page:
    """A batch of rows: equal-capacity columns + an ``active`` row mask.

    ``active[i]`` means row i logically exists (it is both within the split's row
    count and has survived every filter so far). ref: spi/Page.java:31
    ``getPositionCount`` maps to ``num_rows()`` (a traced reduction, not static).
    """

    columns: tuple
    active: jnp.ndarray

    def tree_flatten(self):
        return (self.columns, self.active), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        cols, active = children
        return cls(columns=tuple(cols), active=active)

    @property
    def capacity(self) -> int:
        return int(self.active.shape[0])

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def num_rows(self) -> jnp.ndarray:
        return jnp.sum(self.active.astype(jnp.int32))

    def column(self, i: int) -> Column:
        return self.columns[i]

    def layout(self) -> tuple:
        """Static compilation cache key (types + dictionaries + shapes —
        nested columns' element width W is part of the physical layout)."""
        return (
            tuple(_column_layout(c) for c in self.columns),
            self.capacity,
        )

    def with_columns(self, columns: Sequence[Column]) -> "Page":
        return Page(tuple(columns), self.active)

    def append_column(self, col: Column) -> "Page":
        # ref: spi/Page.java:160 appendColumn
        return Page(self.columns + (col,), self.active)

    def mask(self, keep: jnp.ndarray) -> "Page":
        """Filter: AND into the active mask (no compaction — static shapes)."""
        return Page(self.columns, self.active & keep)

    @staticmethod
    def from_arrays(
        types: Sequence[Type],
        arrays: Sequence[np.ndarray],
        valids: Optional[Sequence[Optional[np.ndarray]]] = None,
        dictionaries: Optional[Sequence[Optional[Dictionary]]] = None,
        capacity: Optional[int] = None,
    ) -> "Page":
        n = len(arrays[0]) if arrays else 0
        if len(types) != len(arrays):
            raise ValueError(f"{len(types)} types but {len(arrays)} arrays")
        if any(len(a) != n for a in arrays):
            raise ValueError(f"unequal column lengths: {[len(a) for a in arrays]}")
        cap = capacity if capacity is not None else n
        valids = valids or [None] * len(arrays)
        dictionaries = dictionaries or [None] * len(arrays)
        cols = tuple(
            Column.from_numpy(t, a, v, cap, d)
            for t, a, v, d in zip(types, arrays, valids, dictionaries)
        )
        active = np.zeros(cap, dtype=np.bool_)
        active[:n] = True
        return Page(cols, jnp.asarray(active))

    def to_pylist(self) -> list:
        """Host materialization: list of row tuples in storage order (active only)."""
        active = np.asarray(self.active)
        cols = [c.decode(active) for c in self.columns]
        return [tuple(col[i] for col in cols) for i in range(int(active.sum()))]


def _column_layout(c: Column) -> tuple:
    kids = tuple(_column_layout(k) for k in c.children)
    return (c.type, c.dictionary, tuple(c.data.shape), kids)


def _scalar_from_pylist(
    type_: Type, values: Sequence, capacity: Optional[int] = None
) -> Column:
    """Python scalars -> a scalar-layout Column (strings dictionary-encode,
    decimals scale, dates/timestamps convert to epoch units)."""
    import datetime

    from .types import DecimalType as _Dec

    n = len(values)
    cap = capacity if capacity is not None else n
    if type_.name in ("varchar", "char"):
        return Column.from_strings(list(values) + [None] * (cap - n), type_)
    valid = np.array([v is not None for v in values] + [False] * (cap - n), np.bool_)
    if isinstance(type_, _Dec) and type_.precision > 18:
        import decimal as _d

        from ..ops.int128 import np_from_ints

        with _d.localcontext() as ctx:
            # default context rounds at 28 significant digits — exactly the
            # values this type exists for; widen before scaling
            ctx.prec = 60
            scaled = [
                int(_d.Decimal(str(v)).scaleb(type_.scale).to_integral_value())
                if v is not None
                else 0
                for v in values
            ] + [0] * (cap - n)
        return Column(type_, jnp.asarray(np_from_ints(scaled)), jnp.asarray(valid))
    conv = np.zeros(cap, dtype=type_.storage_dtype)
    for i, v in enumerate(values):
        if v is None:
            continue
        if isinstance(type_, _Dec):
            conv[i] = round(float(v) * 10**type_.scale)
        elif type_.name == "date":
            d = v if isinstance(v, datetime.date) else datetime.date.fromisoformat(v)
            conv[i] = (d - datetime.date(1970, 1, 1)).days
        elif type_.name == "timestamp":
            ts = (
                v
                if isinstance(v, datetime.datetime)
                else datetime.datetime.fromisoformat(v)
            )
            conv[i] = round((ts - datetime.datetime(1970, 1, 1)).total_seconds() * 1e6)
        else:
            conv[i] = v
    return Column(type_, jnp.asarray(conv), jnp.asarray(valid))


def compact_indices(active: np.ndarray) -> np.ndarray:
    """Host helper: indices of active rows (used at materialization boundaries)."""
    return np.nonzero(np.asarray(active))[0]
