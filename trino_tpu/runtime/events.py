"""Event listeners: structured query lifecycle events.

Reference blueprint: spi/eventlistener (QueryCompletedEvent et al.) dispatched by
EventListenerManager.queryCompleted (SURVEY.md §5.5) — consumers are audit logs,
metrics pipelines, lineage systems. Round 1 ships the JSONL file listener (the
trino-http-event-listener/file analogue); attach via QueryManager.add_listener.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

from .query_manager import QueryExecution


def query_completed_event(q: QueryExecution) -> dict:
    """ref: spi/eventlistener/QueryCompletedEvent.java field set (subset)."""
    return {
        "eventType": "QueryCompleted" if q.state.is_done else "QueryStateChange",
        "queryId": q.query_id,
        "state": q.state.value,
        "query": q.sql,
        "createTime": q.stats.create_time,
        "endTime": q.stats.end_time,
        "elapsedSeconds": round(q.stats.elapsed, 6),
        "cpuSeconds": round(q.stats.cpu_time, 6),
        "outputRows": q.stats.rows,
        "error": q.error,
        "errorType": q.error_type,
    }


class FileEventListener:
    """Append query events to a JSONL file (thread-safe)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def __call__(self, q: QueryExecution) -> None:
        record = query_completed_event(q)
        line = json.dumps(record)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


class CollectingEventListener:
    """In-memory listener (TestingEventListener analogue)."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def __call__(self, q: QueryExecution) -> None:
        with self._lock:
            self.events.append(query_completed_event(q))
