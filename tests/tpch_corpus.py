"""The full 22-query TPC-H corpus as canonical SQL text.

Query texts follow the reference's benchmark SQL
(testing/trino-benchmark-queries/src/main/resources/sql/trino/tpch/) with the
standard substitution parameters — the same forms the per-query oracle tests
(test_tpch.py / test_tpch_full.py) execute. Collected here so whole-corpus
sweeps (plan-sanity validation, benches) can iterate all 22 without
re-scraping test bodies.
"""

TPCH_QUERIES = {
    "q01": """
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
               avg(l_quantity) AS avg_qty,
               avg(l_extendedprice) AS avg_price,
               avg(l_discount) AS avg_disc,
               count(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
    """,
    "q02": """
        SELECT s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address,
               s_phone, s_comment
        FROM part, supplier, partsupp, nation, region
        WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
          AND p_size = 25 AND p_type LIKE '%BRASS'
          AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
          AND r_name = 'EUROPE'
          AND ps_supplycost = (
              SELECT min(ps_supplycost)
              FROM partsupp, supplier, nation, region
              WHERE p_partkey = ps_partkey AND s_suppkey = ps_suppkey
                AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
                AND r_name = 'EUROPE')
        ORDER BY s_acctbal DESC, n_name, s_name, p_partkey LIMIT 100
    """,
    "q03": """
        SELECT l_orderkey,
               sum(l_extendedprice * (1 - l_discount)) AS revenue,
               o_orderdate, o_shippriority
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING'
          AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND o_orderdate < DATE '1995-03-15'
          AND l_shipdate > DATE '1995-03-15'
        GROUP BY l_orderkey, o_orderdate, o_shippriority
        ORDER BY revenue DESC, o_orderdate, l_orderkey
        LIMIT 10
    """,
    "q04": """
        SELECT o_orderpriority, count(*) AS order_count
        FROM orders
        WHERE o_orderdate >= DATE '1993-07-01' AND o_orderdate < DATE '1993-10-01'
          AND EXISTS (SELECT * FROM lineitem
                      WHERE l_orderkey = o_orderkey AND l_commitdate < l_receiptdate)
        GROUP BY o_orderpriority ORDER BY o_orderpriority
    """,
    "q05": """
        SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM customer, orders, lineitem, supplier, nation, region
        WHERE c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND l_suppkey = s_suppkey
          AND c_nationkey = s_nationkey
          AND s_nationkey = n_nationkey
          AND n_regionkey = r_regionkey
          AND r_name = 'ASIA'
          AND o_orderdate >= DATE '1994-01-01'
          AND o_orderdate < DATE '1995-01-01'
        GROUP BY n_name
        ORDER BY revenue DESC
    """,
    "q06": """
        SELECT sum(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1994-01-01'
          AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
          AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
          AND l_quantity < 24
    """,
    "q07": """
        SELECT supp_nation, cust_nation, l_year, sum(volume) AS revenue FROM (
          SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
                 EXTRACT(YEAR FROM l_shipdate) AS l_year,
                 l_extendedprice * (1 - l_discount) AS volume
          FROM supplier, lineitem, orders, customer, nation n1, nation n2
          WHERE s_suppkey = l_suppkey AND o_orderkey = l_orderkey AND c_custkey = o_custkey
            AND s_nationkey = n1.n_nationkey AND c_nationkey = n2.n_nationkey
            AND ((n1.n_name = 'FRANCE' AND n2.n_name = 'GERMANY')
              OR (n1.n_name = 'GERMANY' AND n2.n_name = 'FRANCE'))
            AND l_shipdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31') AS shipping
        GROUP BY supp_nation, cust_nation, l_year
        ORDER BY supp_nation, cust_nation, l_year
    """,
    "q08": """
        SELECT o_year,
               sum(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0 END)
                 / sum(volume) AS mkt_share
        FROM (SELECT extract(YEAR FROM o_orderdate) AS o_year,
                     l_extendedprice * (1 - l_discount) AS volume,
                     n2.n_name AS nation
              FROM part, supplier, lineitem, orders, customer,
                   nation n1, nation n2, region
              WHERE p_partkey = l_partkey AND s_suppkey = l_suppkey
                AND l_orderkey = o_orderkey AND o_custkey = c_custkey
                AND c_nationkey = n1.n_nationkey
                AND n1.n_regionkey = r_regionkey AND r_name = 'AMERICA'
                AND s_nationkey = n2.n_nationkey
                AND o_orderdate BETWEEN DATE '1995-01-01' AND DATE '1996-12-31'
                AND p_type = 'ECONOMY ANODIZED STEEL') AS all_nations
        GROUP BY o_year ORDER BY o_year
    """,
    "q09": """
        SELECT nation, o_year, sum(amount) AS sum_profit FROM (
          SELECT n_name AS nation, EXTRACT(YEAR FROM o_orderdate) AS o_year,
                 l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity AS amount
          FROM part, supplier, lineitem, partsupp, orders, nation
          WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey AND ps_partkey = l_partkey
            AND p_partkey = l_partkey AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
            AND p_name LIKE '%green%') AS profit
        GROUP BY nation, o_year
        ORDER BY nation, o_year DESC
    """,
    "q10": """
        SELECT c_custkey, c_name, sum(l_extendedprice * (1 - l_discount)) AS revenue, c_acctbal
        FROM customer, orders, lineitem, nation
        WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
          AND o_orderdate >= DATE '1993-10-01' AND o_orderdate < DATE '1994-01-01'
          AND l_returnflag = 'R' AND c_nationkey = n_nationkey
        GROUP BY c_custkey, c_name, c_acctbal
        ORDER BY revenue DESC, c_custkey
        LIMIT 20
    """,
    "q11": """
        SELECT ps_partkey, sum(ps_supplycost * ps_availqty) AS value
        FROM partsupp, supplier, nation
        WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey AND n_name = 'GERMANY'
        GROUP BY ps_partkey
        HAVING sum(ps_supplycost * ps_availqty) > (
          SELECT sum(ps_supplycost * ps_availqty) * 0.0001
          FROM partsupp, supplier, nation
          WHERE ps_suppkey = s_suppkey AND s_nationkey = n_nationkey AND n_name = 'GERMANY')
        ORDER BY value DESC, ps_partkey
    """,
    "q12": """
        SELECT l_shipmode,
               sum(CASE WHEN o_orderpriority = '1-URGENT' OR o_orderpriority = '2-HIGH'
                        THEN 1 ELSE 0 END) AS high_line_count,
               sum(CASE WHEN o_orderpriority <> '1-URGENT' AND o_orderpriority <> '2-HIGH'
                        THEN 1 ELSE 0 END) AS low_line_count
        FROM orders, lineitem
        WHERE o_orderkey = l_orderkey
          AND l_shipmode IN ('MAIL', 'SHIP')
          AND l_commitdate < l_receiptdate
          AND l_shipdate < l_commitdate
          AND l_receiptdate >= DATE '1994-01-01'
          AND l_receiptdate < DATE '1995-01-01'
        GROUP BY l_shipmode
        ORDER BY l_shipmode
    """,
    "q13": """
        SELECT c_count, count(*) AS custdist
        FROM (
          SELECT c_custkey, count(o_orderkey) AS c_count
          FROM customer LEFT JOIN orders ON c_custkey = o_custkey
            AND o_comment NOT LIKE '%special%requests%'
          GROUP BY c_custkey
        ) AS c_orders
        GROUP BY c_count
        ORDER BY custdist DESC, c_count DESC
    """,
    "q14": """
        SELECT 100.00 * sum(CASE WHEN p_type LIKE 'PROMO%'
                                 THEN l_extendedprice * (1 - l_discount)
                                 ELSE 0 END)
               / sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
        FROM lineitem, part
        WHERE l_partkey = p_partkey
          AND l_shipdate >= DATE '1995-09-01'
          AND l_shipdate < DATE '1995-10-01'
    """,
    "q15": """
        WITH revenue0 AS (
          SELECT l_suppkey AS supplier_no, sum(l_extendedprice * (1 - l_discount)) AS total_revenue
          FROM lineitem
          WHERE l_shipdate >= DATE '1996-01-01' AND l_shipdate < DATE '1996-04-01'
          GROUP BY l_suppkey)
        SELECT s_suppkey, s_name, total_revenue
        FROM supplier, revenue0
        WHERE s_suppkey = supplier_no AND total_revenue = (SELECT max(total_revenue) FROM revenue0)
        ORDER BY s_suppkey
    """,
    "q16": """
        SELECT p_brand, p_type, p_size, count(DISTINCT ps_suppkey) AS supplier_cnt
        FROM partsupp, part
        WHERE p_partkey = ps_partkey AND p_brand <> 'Brand#45'
          AND p_type NOT LIKE 'MEDIUM POLISHED%'
          AND p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
        GROUP BY p_brand, p_type, p_size
        ORDER BY supplier_cnt DESC, p_brand, p_type, p_size
    """,
    "q17": """
        SELECT sum(l_extendedprice) / 7.0 AS avg_yearly
        FROM lineitem, part
        WHERE p_partkey = l_partkey AND p_brand = 'Brand#23'
          AND l_quantity < (SELECT 0.2 * avg(l_quantity) FROM lineitem l2
                            WHERE l2.l_partkey = p_partkey)
    """,
    "q18": """
        SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity)
        FROM customer, orders, lineitem
        WHERE o_orderkey IN (
            SELECT l_orderkey FROM lineitem
            GROUP BY l_orderkey HAVING sum(l_quantity) > 150
          )
          AND c_custkey = o_custkey
          AND o_orderkey = l_orderkey
        GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        ORDER BY o_totalprice DESC, o_orderdate, o_orderkey
        LIMIT 100
    """,
    "q19": """
        SELECT sum(l_extendedprice * (1 - l_discount)) AS revenue
        FROM lineitem, part
        WHERE p_partkey = l_partkey
          AND ((p_brand = 'Brand#12'
                AND p_container IN ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
                AND l_quantity >= 1 AND l_quantity <= 1 + 10
                AND p_size BETWEEN 1 AND 5
                AND l_shipmode IN ('AIR', 'AIR REG')
                AND l_shipinstruct = 'DELIVER IN PERSON')
            OR (p_brand = 'Brand#23'
                AND p_container IN ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
                AND l_quantity >= 10 AND l_quantity <= 10 + 10
                AND p_size BETWEEN 1 AND 10
                AND l_shipmode IN ('AIR', 'AIR REG')
                AND l_shipinstruct = 'DELIVER IN PERSON')
            OR (p_brand = 'Brand#34'
                AND p_container IN ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
                AND l_quantity >= 20 AND l_quantity <= 20 + 10
                AND p_size BETWEEN 1 AND 15
                AND l_shipmode IN ('AIR', 'AIR REG')
                AND l_shipinstruct = 'DELIVER IN PERSON'))
    """,
    "q20": """
        SELECT s_name, s_address FROM supplier, nation
        WHERE s_suppkey IN (
            SELECT ps_suppkey FROM partsupp
            WHERE ps_partkey IN (SELECT p_partkey FROM part
                                 WHERE p_name LIKE 'forest%')
              AND ps_availqty > (
                  SELECT 0.5 * sum(l_quantity) FROM lineitem
                  WHERE l_partkey = ps_partkey AND l_suppkey = ps_suppkey
                    AND l_shipdate >= DATE '1994-01-01'
                    AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR))
          AND s_nationkey = n_nationkey AND n_name = 'CANADA'
        ORDER BY s_name
    """,
    "q21": """
        SELECT s_name, count(*) AS numwait
        FROM supplier, lineitem l1, orders, nation
        WHERE s_suppkey = l1.l_suppkey AND o_orderkey = l1.l_orderkey
          AND o_orderstatus = 'F' AND l1.l_receiptdate > l1.l_commitdate
          AND EXISTS (SELECT * FROM lineitem l2
                      WHERE l2.l_orderkey = l1.l_orderkey
                        AND l2.l_suppkey <> l1.l_suppkey)
          AND NOT EXISTS (SELECT * FROM lineitem l3
                          WHERE l3.l_orderkey = l1.l_orderkey
                            AND l3.l_suppkey <> l1.l_suppkey
                            AND l3.l_receiptdate > l3.l_commitdate)
          AND s_nationkey = n_nationkey AND n_name = 'SAUDI ARABIA'
        GROUP BY s_name ORDER BY numwait DESC, s_name LIMIT 100
    """,
    "q22": """
        SELECT cntrycode, count(*) AS numcust, sum(acctbal) AS totacctbal
        FROM (SELECT substr(c_phone, 1, 2) AS cntrycode, c_acctbal AS acctbal
              FROM customer
              WHERE substr(c_phone, 1, 2) IN ('13', '31', '23', '29', '30', '18', '17')
                AND c_acctbal > (SELECT avg(c_acctbal) FROM customer
                                 WHERE c_acctbal > 0.00
                                   AND substr(c_phone, 1, 2) IN
                                       ('13', '31', '23', '29', '30', '18', '17'))
                AND NOT EXISTS (SELECT * FROM orders
                                WHERE o_custkey = c_custkey)) AS custsale
        GROUP BY cntrycode ORDER BY cntrycode
    """,
}
