"""Client protocol tests: coordinator REST server + StatementClient + CLI.

Coverage model: the reference's client-protocol tests (protocol semantics:
nextUri paging until drained, error propagation, query info endpoints).
"""

import json
import urllib.request

import pytest

from trino_tpu.client import ClientError, StatementClient
from trino_tpu.server import CoordinatorServer


@pytest.fixture(scope="module")
def server(tpch_tiny):
    srv = CoordinatorServer(tpch_tiny).start()
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def client(server):
    return StatementClient(f"http://{server.address}")


class TestProtocol:
    def test_simple_query(self, client):
        res = client.execute("SELECT count(*) FROM nation")
        assert res.columns == ["count"]
        assert res.rows == [[25]]
        assert res.stats["state"] == "FINISHED"

    def test_multi_row_paging(self, client):
        res = client.execute("SELECT n_nationkey FROM nation ORDER BY n_nationkey")
        assert [r[0] for r in res.rows] == list(range(25))

    def test_error_propagates(self, client):
        with pytest.raises(ClientError) as e:
            client.execute("SELECT bogus_column FROM nation")
        assert "bogus_column" in str(e.value)

    def test_parse_error(self, client):
        with pytest.raises(ClientError):
            client.execute("SELEKT 1")

    def test_query_info(self, client):
        res = client.execute("SELECT 1")
        info = client.query_info(res.query_id)
        assert info["state"] == "FINISHED"
        assert info["query"] == "SELECT 1"

    def test_server_info(self, client):
        info = client.server_info()
        assert info["coordinator"] is True

    def test_date_json_encoding(self, client):
        res = client.execute("SELECT min(o_orderdate) FROM orders")
        assert isinstance(res.rows[0][0], str)  # ISO date string on the wire
        assert res.rows[0][0].startswith("199")

    def test_column_type_signatures(self, server):
        """Column metadata carries real Trino type signatures the reference
        client can decode (ref: ClientTypeSignature / StatementClientV1)."""
        body = (
            b"SELECT n_nationkey, n_name, CAST(1.5 AS decimal(12,2)) d, "
            b"DATE '2020-01-01' dt, TRUE b FROM nation LIMIT 1"
        )
        req = urllib.request.Request(
            f"http://{server.address}/v1/statement", data=body, method="POST"
        )
        with urllib.request.urlopen(req) as resp:
            payload = json.loads(resp.read())
        while "columns" not in payload:
            with urllib.request.urlopen(payload["nextUri"]) as resp:
                payload = json.loads(resp.read())
        cols = {c["name"]: c for c in payload["columns"]}
        assert cols["n_nationkey"]["type"] == "bigint"
        assert cols["n_nationkey"]["typeSignature"]["rawType"] == "bigint"
        assert cols["n_name"]["type"] == "varchar(25)"
        assert cols["n_name"]["typeSignature"]["rawType"] == "varchar"
        assert cols["n_name"]["typeSignature"]["arguments"][0]["value"] == 25
        assert cols["d"]["type"] == "decimal(12,2)"
        assert cols["d"]["typeSignature"]["arguments"] == [
            {"kind": "LONG", "value": 12},
            {"kind": "LONG", "value": 2},
        ]
        assert cols["dt"]["type"] == "date"
        assert cols["b"]["type"] == "boolean"
        # decimal rides the wire as an exact-scale string (client decode rule)
        row = payload["data"][0]
        decimal_idx = list(cols).index("d")
        assert row[decimal_idx] == "1.50"

    def test_status_endpoint(self, server):
        with urllib.request.urlopen(f"http://{server.address}/v1/status") as resp:
            payload = json.loads(resp.read())
        assert payload["nodeCount"] == 1
        assert payload["totalQueries"] >= 1


class TestCli:
    def test_format_table(self):
        from trino_tpu.cli import format_table

        out = format_table(["a", "bb"], [(1, "x"), (None, "yy")])
        lines = out.split("\n")
        assert lines[0].startswith("a")
        assert "NULL" in out

    def test_embedded_execute(self, capsys):
        from trino_tpu.cli import main

        rc = main(["--scale", "0.0005", "--schema", "sf0.0005",
                   "-e", "SELECT count(*) FROM region"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "5" in out


class TestNodeEndpoints:
    def test_announce_and_list(self, server):
        import urllib.request

        req = urllib.request.Request(
            f"http://{server.address}/v1/announcement/worker-1",
            data=json.dumps({"uri": "http://w1:9999"}).encode(),
            method="PUT",
        )
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 202
        with urllib.request.urlopen(f"http://{server.address}/v1/node") as resp:
            nodes = json.loads(resp.read())
        assert any(n["nodeId"] == "worker-1" and n["state"] == "ACTIVE" for n in nodes)


class TestWebUi:
    def test_status_page(self, server, client):
        client.execute("SELECT 1")
        with urllib.request.urlopen(f"http://{server.address}/") as resp:
            html = resp.read().decode()
        assert "trino-tpu coordinator" in html
        assert "SELECT 1" in html


class TestClientSessionState:
    """Prepared statements and transactions are CLIENT session state carried
    by protocol headers (X-Trino-Prepared-Statement / X-Trino-Transaction-Id)
    — they must survive landing on different server pool threads, and two
    clients must not see each other's state."""

    def test_prepare_execute_roundtrip(self, server):
        c = StatementClient(f"http://{server.address}")
        c.execute("PREPARE stmt1 FROM SELECT n_name FROM nation WHERE n_nationkey = ?")
        # client accumulated the prepared statement from the response header
        assert "stmt1" in c._prepared
        res = c.execute("EXECUTE stmt1 USING 3")
        assert res.rows == [["CANADA"]]
        c.execute("DEALLOCATE PREPARE stmt1")
        assert "stmt1" not in c._prepared
        with pytest.raises(ClientError):
            c.execute("EXECUTE stmt1 USING 3")

    def test_prepared_statements_isolated_between_clients(self, server):
        a = StatementClient(f"http://{server.address}")
        b = StatementClient(f"http://{server.address}")
        a.execute("PREPARE mine FROM SELECT 1")
        with pytest.raises(ClientError):
            b.execute("EXECUTE mine USING ")
        # b never learned a's statement
        assert "mine" not in b._prepared

    def test_transaction_across_requests(self, server):
        from trino_tpu.connectors.memory import MemoryConnector

        server.runner.register_catalog("txmem", MemoryConnector())
        c = StatementClient(f"http://{server.address}")
        c.execute("CREATE TABLE txmem.default.t AS SELECT 1 AS x")
        c.execute("START TRANSACTION")
        assert c._txn_id  # returned via X-Trino-Started-Transaction-Id
        c.execute("INSERT INTO txmem.default.t SELECT 2")
        c.execute("ROLLBACK")
        assert c._txn_id is None
        res = c.execute("SELECT count(*) FROM txmem.default.t")
        assert res.rows == [[1]]
        c.execute("START TRANSACTION")
        c.execute("INSERT INTO txmem.default.t SELECT 3")
        c.execute("COMMIT")
        res = c.execute("SELECT count(*) FROM txmem.default.t")
        assert res.rows == [[2]]


class TestUiStats:
    def test_cluster_stats_endpoint(self, server, client):
        import json
        import urllib.request

        client.execute("SELECT 1")
        with urllib.request.urlopen(
            f"http://{server.address}/ui/api/stats", timeout=10
        ) as resp:
            stats = json.loads(resp.read())
        assert stats["totalQueries"] >= 1
        assert stats["finishedQueries"] >= 1
        assert "queriesByState" in stats
