"""Connector SPI — pluggable data sources.

Reference blueprint: core/trino-spi/src/main/java/io/trino/spi/connector/ (173 files;
SURVEY.md §2.1): Connector.java:29 -> ConnectorMetadata.java:70 / ConnectorSplitManager
/ ConnectorPageSourceProvider -> ConnectorPageSource.java:23 (getNextSourcePage:58).

TPU-first adjustments:
- A page source yields *large fixed-capacity* Pages (one per split by default) so each
  split is one XLA program invocation, not a stream of 4KB pages.
- ``ConnectorMetadata.apply_filter`` accepts a TupleDomain for predicate pushdown
  (ref: ConnectorMetadata.applyFilter) — connectors may prune splits with it.
- Columns are requested by index list so connectors can skip decoding unused columns
  (projection pushdown, ref: ConnectorMetadata.applyProjection).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .page import Page
from .types import Type


@dataclass(frozen=True)
class ColumnMetadata:
    name: str
    type: Type


@dataclass(frozen=True)
class SchemaTableName:
    schema: str
    table: str

    def __str__(self):
        return f"{self.schema}.{self.table}"


@dataclass(frozen=True)
class TableHandle:
    """Engine-side handle (ref: io/trino/metadata/TableHandle.java): names a table
    within a catalog plus connector-private state (e.g. pushed-down predicate)."""

    catalog: str
    schema_table: SchemaTableName
    connector_handle: Any = None

    def __str__(self):
        return f"{self.catalog}.{self.schema_table}"


@dataclass(frozen=True)
class TablePartitioning:
    """Physical split partitioning a connector declares: split i holds
    exactly the rows whose bucket(columns) == i (ref:
    spi/connector/ConnectorNodePartitioningProvider.java:22). ``rule``
    names the bucketing function — only identical rules co-locate."""

    columns: Tuple[str, ...]
    bucket_count: int
    rule: str = "hash"  # the shared host_partition_targets hash


@dataclass(frozen=True)
class TableMetadata:
    name: SchemaTableName
    columns: Tuple[ColumnMetadata, ...]
    # physical sort order of the rows each split yields, ascending (ref:
    # connector-declared local properties / SortOrder metadata — lets the
    # engine stream grouped aggregation without sorting)
    sorted_by: Tuple[str, ...] = ()

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)


@dataclass(frozen=True)
class Split:
    """A schedulable unit of table data (ref: spi/connector/ConnectorSplit.java).

    ``row_range`` is the convention used by generator-backed connectors (tpch);
    other connectors may stash anything in ``info``.
    """

    table: TableHandle
    split_id: int
    total_splits: int
    info: Any = None


@dataclass(frozen=True)
class ColumnStatistics:
    """Per-column estimates (ref: spi/statistics/ColumnStatistics.java).

    ``low``/``high`` are in order-key space: numerics as-is, dates as epoch
    days, dictionary strings as codes."""

    ndv: Optional[float] = None
    low: Optional[float] = None
    high: Optional[float] = None
    null_fraction: float = 0.0


@dataclass(frozen=True)
class TableStatistics:
    row_count: Optional[float] = None
    # per-column ndv estimates keyed by column name (legacy; prefer columns)
    distinct_counts: Dict[str, float] = field(default_factory=dict)
    # full per-column stats keyed by column name
    columns: Dict[str, ColumnStatistics] = field(default_factory=dict)

    def column(self, name: str) -> ColumnStatistics:
        if name in self.columns:
            return self.columns[name]
        if name in self.distinct_counts:
            return ColumnStatistics(ndv=self.distinct_counts[name])
        return ColumnStatistics()


class ConnectorMetadata:
    """ref: spi/connector/ConnectorMetadata.java:70."""

    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        raise NotImplementedError

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        raise NotImplementedError

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        return TableStatistics()

    def apply_filter(self, handle: TableHandle, domain: "TupleDomain") -> Optional[TableHandle]:
        """Return a new handle with the domain absorbed, or None if not supported.
        ref: ConnectorMetadata.applyFilter (pushdown hooks, SURVEY.md §2.1)."""
        return None

    def apply_version(self, handle: TableHandle, version: int) -> Optional[TableHandle]:
        """Resolve FOR VERSION AS OF into a snapshot-pinned handle, or None
        when the connector has no time travel (ref: ConnectorMetadata
        getTableHandle(version) — iceberg snapshot reads)."""
        return None

    def table_partitioning(self, handle: TableHandle) -> Optional["TablePartitioning"]:
        """Declared physical partitioning of the table's splits, or None.
        When two join sides are partitioned on their join keys with the SAME
        bucket count and rule, the planner skips the repartition exchange —
        split i IS bucket i on both sides, so co-located scheduling aligns
        them (ref: spi/connector/ConnectorNodePartitioningProvider.java:22,
        TpchNodePartitioningProvider, BucketNodeMap)."""
        return None


class ConnectorSplitManager:
    """ref: spi/connector/ConnectorSplitManager.java."""

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        raise NotImplementedError


class ConnectorPageSourceProvider:
    """ref: spi/connector/ConnectorPageSourceProvider.java -> ConnectorPageSource."""

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        raise NotImplementedError


class Connector:
    """ref: spi/connector/Connector.java:29."""

    name: str = "connector"

    def metadata(self) -> ConnectorMetadata:
        raise NotImplementedError

    def split_manager(self) -> ConnectorSplitManager:
        raise NotImplementedError

    def page_source_provider(self) -> ConnectorPageSourceProvider:
        raise NotImplementedError
