"""Table-function SPI (spi/table_function.py).

ref: spi/function/table/ConnectorTableFunction.java:23 (analyze ->
returned type), Argument model (Scalar/Table/Descriptor),
operator/table/ExcludeColumnsFunction.java. TPU redesign: table functions
are planner rewrites — generators lower to leaf device programs,
pass-throughs to projections; no row-processor operator exists.
"""

import pytest

from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.spi.table_function import (
    ConnectorTableFunction,
    builtin_table_functions,
)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


class TestSequence:
    def test_count_and_values(self, runner):
        assert runner.execute(
            "SELECT count(*), min(sequential_number), max(sequential_number) "
            "FROM TABLE(sequence(1, 100))"
        ).rows == [(100, 1, 100)]

    def test_negative_step(self, runner):
        assert runner.execute("SELECT * FROM TABLE(sequence(5, 1, -2))").rows == [
            (5,), (3,), (1,),
        ]

    def test_named_arguments(self, runner):
        assert runner.execute(
            "SELECT count(*) FROM TABLE(sequence(start => 1, stop => 10))"
        ).rows == [(10,)]

    def test_zero_step_rejected(self, runner):
        with pytest.raises(Exception) as ei:
            runner.execute("SELECT * FROM TABLE(sequence(1, 10, 0))")
        assert "step" in str(ei.value)


class TestExcludeColumns:
    def test_drops_descriptor_columns(self, runner):
        rows = runner.execute(
            "SELECT * FROM TABLE(exclude_columns(input => TABLE(region), "
            "columns => DESCRIPTOR(r_comment))) ORDER BY r_regionkey LIMIT 2"
        ).rows
        assert rows == [(0, "AFRICA"), (1, "AMERICA")]

    def test_subquery_table_argument(self, runner):
        rows = runner.execute(
            "SELECT * FROM TABLE(exclude_columns("
            "input => TABLE(SELECT r_regionkey k, r_name FROM region), "
            "columns => DESCRIPTOR(r_name))) ORDER BY k LIMIT 2"
        ).rows
        assert rows == [(0,), (1,)]

    def test_joins_compose_above(self, runner):
        rows = runner.execute(
            "SELECT n_name FROM TABLE(exclude_columns(input => TABLE(nation), "
            "columns => DESCRIPTOR(n_comment))) n "
            "JOIN region r ON n.n_regionkey = r.r_regionkey "
            "WHERE r.r_name = 'ASIA' ORDER BY n_name LIMIT 2"
        ).rows
        assert rows == [("CHINA",), ("INDIA",)]

    def test_unknown_column_rejected(self, runner):
        with pytest.raises(Exception) as ei:
            runner.execute(
                "SELECT * FROM TABLE(exclude_columns(input => TABLE(region), "
                "columns => DESCRIPTOR(nope)))"
            )
        assert "nope" in str(ei.value)

    def test_all_columns_rejected(self, runner):
        with pytest.raises(Exception) as ei:
            runner.execute(
                "SELECT * FROM TABLE(exclude_columns("
                "input => TABLE(SELECT r_name FROM region), "
                "columns => DESCRIPTOR(r_name)))"
            )
        assert "every column" in str(ei.value)


class TestRegistry:
    def test_builtins_registered(self):
        reg = builtin_table_functions()
        assert reg.names() == [
            "exclude_columns", "gbdt_score", "linear_score", "sequence",
        ]

    def test_custom_function_shape(self):
        class Nop(ConnectorTableFunction):
            name = "nop"
            arguments = (("input", "table"),)

            def analyze(self, args, context):
                return args["input"].plan

        reg = builtin_table_functions()
        reg.register(Nop())
        assert reg.get("nop") is not None

    def test_unknown_function_rejected(self, runner):
        with pytest.raises(Exception) as ei:
            runner.execute("SELECT * FROM TABLE(no_such_fn(1))")
        assert "unknown table function" in str(ei.value)
