"""Query tracing: OpenTelemetry-style spans without the OTel dependency.

Reference blueprint: the reference threads an io.opentelemetry Tracer through
the whole engine (Trino's TracingMetadata / planning spans: "planner",
"analyzer", "optimizer", per-stage execution spans) and exports via OTLP.
This module keeps the same span model (trace id, span id, parent, name,
start/end nanos, attributes) with an in-memory per-query exporter the
coordinator serves as JSON — an OTLP forwarder can be attached as a sink.
"""

from __future__ import annotations

import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    name: str
    start_ns: int
    end_ns: Optional[int] = None
    attributes: Dict[str, object] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_id,
            "name": self.name,
            "startTimeUnixNano": self.start_ns,
            "endTimeUnixNano": self.end_ns,
            "attributes": self.attributes,
            "durationMs": (
                (self.end_ns - self.start_ns) / 1e6 if self.end_ns else None
            ),
        }


@dataclass
class TraceContext:
    """An immutable capture of the current span, for carrying trace
    parentage across thread boundaries (Context.makeCurrent() analogue)."""

    span: Optional[Span]


class Tracer:
    """Per-process tracer; spans are grouped by trace (one trace per query).
    ``sink`` (if set) receives each finished span — attach an OTLP forwarder
    there."""

    def __init__(self, max_traces: int = 200):
        self._lock = threading.Lock()
        self._traces: Dict[str, List[Span]] = {}
        self._order: List[str] = []
        self._max_traces = max_traces
        self._tls = threading.local()
        self.sink: Optional[Callable[[Span], None]] = None

    def _current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    @contextmanager
    def span(self, name: str, trace_id: Optional[str] = None, **attributes):
        parent = self._current()
        if parent is not None:
            trace_id = parent.trace_id
        elif trace_id is None:
            trace_id = uuid.uuid4().hex
        s = Span(
            trace_id=trace_id,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent else None,
            name=name,
            start_ns=time.time_ns(),
            attributes=dict(attributes),
        )
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        stack.append(s)
        with self._lock:
            if trace_id not in self._traces:
                self._traces[trace_id] = []
                self._order.append(trace_id)
                while len(self._order) > self._max_traces:
                    self._traces.pop(self._order.pop(0), None)
            self._traces[trace_id].append(s)
        try:
            yield s
        except Exception as e:
            s.attributes["error"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            s.end_ns = time.time_ns()
            stack.pop()
            if self.sink is not None:
                try:
                    self.sink(s)
                except Exception:
                    pass

    # -------------------------------------------------- context propagation

    def capture(self) -> "TraceContext":
        """Snapshot the calling thread's current span for cross-thread
        propagation. Spans opened on a pooled thread (runtime/spiller
        io_pool, worker task threads) get a FRESH thread-local stack and
        would otherwise orphan from the query trace — capture() on the
        submitting thread + attach() on the worker re-parents them."""
        return TraceContext(self._current())

    @contextmanager
    def attach(self, ctx: Optional["TraceContext"]):
        """Make ``ctx``'s span the current parent on THIS thread for the
        duration. Only the stack entry is thread-local — the span object is
        shared, and attach never finishes it (the owning thread's span()
        exit does); children opened under attach read parent ids only, so
        concurrent attaches of one context are safe."""
        span = ctx.span if ctx is not None else None
        if span is None:
            yield None
            return
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        stack.append(span)
        try:
            yield span
        finally:
            stack.pop()

    def capture_ids(self) -> Optional[Dict[str, str]]:
        """Wire form of capture(): the current span's ids as a small dict
        (ship it in a task descriptor / header), or None outside any span."""
        s = self._current()
        if s is None:
            return None
        return {"trace_id": s.trace_id, "span_id": s.span_id}

    @contextmanager
    def attach_remote(self, ids: Optional[Dict[str, str]]):
        """Adopt a REMOTE parent (ids that crossed a process or wire
        boundary, from capture_ids()) as this thread's current parent.
        Spans opened underneath join that trace with the remote span as
        parent; the phantom parent itself is never recorded here."""
        if not ids or not ids.get("trace_id"):
            yield None
            return
        phantom = Span(
            trace_id=str(ids["trace_id"]),
            span_id=str(ids.get("span_id") or ""),
            parent_id=None,
            name="<remote>",
            start_ns=0,
        )
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        stack.append(phantom)
        try:
            yield phantom
        finally:
            stack.pop()

    def wrap(self, fn: Callable) -> Callable:
        """capture() now, attach() around each later call — the convenience
        form for pool submission: ``pool.submit(TRACER.wrap(job), ...)``."""
        ctx = self.capture()

        def wrapped(*args, **kwargs):
            with self.attach(ctx):
                return fn(*args, **kwargs)

        return wrapped

    def trace(self, trace_id: str) -> List[dict]:
        with self._lock:
            return [s.to_dict() for s in self._traces.get(trace_id, [])]

    def traces(self) -> List[str]:
        with self._lock:
            return list(self._order)


TRACER = Tracer()
