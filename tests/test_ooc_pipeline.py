"""Pipelined out-of-core execution (runtime/ooc.py) + round-5 advisor fixes.

The bucket loop is a pipeline: prefetch threads read/decompress the next
buckets' partitions (LZ4 spill files, spi/host_pages) and start their
host->device transfers while the current bucket's program runs, under a
bounded in-flight byte budget; bucket inputs pad to canonical shape classes
so the loop compiles once per class, not per bucket. Every pipelined result
must be BIT-identical to the serial path (same programs, same order — float
summation order does not change), with and without forced disk spill.
"""

import os
import threading
import time

import numpy as np
import pytest

from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.ooc import OutOfCoreRunner, _shape_class

SCALE = 0.01

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

Q14 = """
SELECT 100.00 * sum(CASE WHEN p_type LIKE 'PROMO%'
                         THEN l_extendedprice * (1 - l_discount) ELSE 0 END)
       / sum(l_extendedprice * (1 - l_discount)) AS promo_revenue
FROM lineitem, part
WHERE l_partkey = p_partkey AND l_shipdate >= DATE '1995-09-01'
  AND l_shipdate < DATE '1995-10-01'
"""

Q18 = """
SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice, sum(l_quantity)
FROM customer, orders, lineitem
WHERE o_orderkey IN (
    SELECT l_orderkey FROM lineitem GROUP BY l_orderkey HAVING sum(l_quantity) > 300)
  AND c_custkey = o_custkey AND o_orderkey = l_orderkey
GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate LIMIT 100
"""


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


def _rows(page):
    act = np.asarray(page.active)
    return [tuple(r) for r, a in zip(page.to_pylist(), act) if a]


def _run(runner, sql, **kw):
    kw.setdefault("n_buckets", 8)
    kw.setdefault("split_batch", 2)
    plan = runner.plan_sql(sql)
    o = OutOfCoreRunner(plan, runner.metadata, runner.session, **kw)
    names, page = o.execute()
    return _rows(page), o.stats


class TestPipelinedParity:
    """Pipelined == serial, bit for bit, spilled or not."""

    @pytest.mark.parametrize("sql", [Q3, Q14, Q18], ids=["q3", "q14", "q18"])
    def test_bit_identical_to_serial(self, runner, sql):
        from trino_tpu.runtime import capstore

        capstore.clear_memory()  # both runs cold: identical tuning path
        serial, _ = _run(runner, sql, prefetch_depth=0)
        capstore.clear_memory()
        piped, stats = _run(runner, sql, prefetch_depth=2)
        assert piped == serial  # exact: same programs in the same order
        assert stats["prefetch_misses"] == 0

    @pytest.mark.parametrize("sql", [Q3, Q18], ids=["q3", "q18"])
    def test_bit_identical_under_forced_spill(self, runner, sql, tmp_path):
        from trino_tpu.runtime import capstore

        capstore.clear_memory()
        serial, _ = _run(runner, sql, prefetch_depth=0)
        capstore.clear_memory()
        piped, stats = _run(
            runner, sql, prefetch_depth=2, mem_budget_bytes=1,
            spool_dir=str(tmp_path),
        )
        assert piped == serial
        assert stats["spilled_bytes"] > 0  # the LZ4 disk tier actually ran
        assert not list(tmp_path.iterdir())  # spool cleaned up

    def test_matches_in_core(self, runner):
        ref = [tuple(r) for r in runner.execute(Q3).rows]
        got, _ = _run(runner, Q3)
        assert len(got) == len(ref)
        for rg, rr in zip(got, ref):
            for a, b in zip(rg, rr):
                if isinstance(a, float):
                    assert abs(a - b) < max(1e-6, 1e-9 * abs(b))
                else:
                    assert a == b


class TestPrefetchBudget:
    def test_tiny_budget_caps_inflight(self, runner):
        serial, _ = _run(runner, Q3, prefetch_depth=0)
        got, stats = _run(runner, Q3, prefetch_depth=4, prefetch_budget_bytes=1)
        assert got == serial
        # a 1-byte budget admits at most ONE bucket past the cap (pipeline
        # progress guarantee) and never queues a second
        assert stats["prefetch_max_depth"] <= 1

    def test_default_budget_reaches_depth(self, runner):
        _, stats = _run(runner, Q3, prefetch_depth=2)
        assert stats["prefetch_max_depth"] <= 2
        assert stats["prefetch_hits"] > 0


class TestCompileReuse:
    def test_compiles_do_not_scale_with_buckets(self, runner):
        _, s4 = _run(runner, Q3, n_buckets=4)
        _, s16 = _run(runner, Q3, n_buckets=16)
        assert s16["compiles"] <= s4["compiles"] + 1, (s4, s16)

    def test_shape_classes_are_few(self, runner):
        _, stats = _run(runner, Q3, n_buckets=16)
        # 16 buckets x multiple hash edges collapse into a handful of
        # canonical classes (4x spacing), not one shape per bucket
        assert stats["shape_classes"] <= 6

    def test_shape_class_spacing(self):
        assert _shape_class(1) == 1024
        assert _shape_class(1024) == 1024
        assert _shape_class(1025) == 4096
        assert _shape_class(5000) == 16384

    def test_caps_persist_across_runners(self, runner):
        from trino_tpu.runtime import capstore

        capstore.clear_memory()
        ref = [tuple(r) for r in runner.execute(Q18).rows]
        got1, first = _run(runner, Q18, n_buckets=4)
        assert first["caps_from_store"] == 0
        got2, second = _run(runner, Q18, n_buckets=4)
        # the second runner seeds every tuned fragment's per-stage capacity
        # vector from the in-process capstore instead of re-tuning
        assert second["caps_from_store"] > 0
        for got in (got1, got2):
            assert len(got) == len(ref)
            for rg, rr in zip(got, ref):
                for a, b in zip(rg, rr):
                    if isinstance(a, float):
                        assert abs(a - b) < max(1e-6, 1e-9 * abs(b))
                    else:
                        assert a == b


class TestConcurrentDictionaryCache:
    """Scan prefetch calls create_page_source from pool threads; a cold
    dictionary cache key hit concurrently must still yield ONE identity-
    hashed Dictionary object, or every program keyed on the loser retraces."""

    def test_tpch_dictionary_single_object_under_race(self):
        from concurrent.futures import ThreadPoolExecutor

        from trino_tpu.connectors.tpch import TpchConnector

        for _ in range(20):
            conn = TpchConnector(scale=0.01)
            with ThreadPoolExecutor(max_workers=4) as pool:
                dicts = list(
                    pool.map(
                        lambda _: conn.dictionary("lineitem", "l_returnflag", 0.01),
                        range(4),
                    )
                )
            assert all(d is dicts[0] for d in dicts)


class TestSpillFileRoundtrip:
    def test_arrays_roundtrip(self, tmp_path):
        from trino_tpu.runtime.spiller import io_pool
        from trino_tpu.spi.host_pages import read_arrays_lz4, write_arrays_lz4

        arrays = [
            np.arange(10000, dtype=np.int64),
            np.random.default_rng(0).random((100, 7)),
            np.ones(3, dtype=np.bool_),
            np.zeros(0, dtype=np.float32),
        ]
        path = str(tmp_path / "chunk.lz4")
        write_arrays_lz4(path, arrays, pool=io_pool())
        back = read_arrays_lz4(path, pool=io_pool())
        assert len(back) == len(arrays)
        for a, b in zip(arrays, back):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b)

    def test_compresses_compressible_data(self, tmp_path):
        from trino_tpu.spi import host_pages
        from trino_tpu import native

        if not native.native_available():
            pytest.skip("native LZ4 unavailable")
        a = np.zeros(100000, dtype=np.int64)
        path = str(tmp_path / "z.lz4")
        host_pages.write_arrays_lz4(path, [a])
        assert os.path.getsize(path) < a.nbytes // 10
        assert np.array_equal(host_pages.read_arrays_lz4(path)[0], a)


class TestFairExecutorHeap:
    """Advisor round-5: per-query FIFO + lazy heap replaces the O(n log n)
    full re-sort per task start."""

    def _drain(self, ex, order, n, deadline=5.0):
        t_end = time.monotonic() + deadline
        while len(order) < n and time.monotonic() < t_end:
            time.sleep(0.005)
        assert len(order) == n, order

    def test_least_served_first_fifo_within_query(self):
        from trino_tpu.server.worker import FairTaskExecutor

        ex = FairTaskExecutor(n_threads=1)
        try:
            gate = threading.Event()
            started = threading.Event()
            order = []

            def blocker():
                started.set()
                gate.wait(5)

            ex.submit("q0", "q0_f0_p0", blocker)
            assert started.wait(5)
            with ex._cond:
                ex._usage["qa"] = 5.0
                ex._usage["qb"] = 0.0
            for name, tid in (("a1", "qa_f0_p0"), ("a2", "qa_f1_p0")):
                ex.submit("qa", tid, lambda n=name: order.append(n))
            ex.submit("qb", "qb_f0_p0", lambda: order.append("b1"))
            gate.set()
            self._drain(ex, order, 3)
            assert order == ["b1", "a1", "a2"]
        finally:
            ex.stop()

    def test_stale_heap_entry_rekeys(self):
        from trino_tpu.server.worker import FairTaskExecutor

        ex = FairTaskExecutor(n_threads=1)
        try:
            gate = threading.Event()
            started = threading.Event()
            order = []

            def blocker():
                started.set()
                gate.wait(5)

            ex.submit("q0", "q0_f0_p0", blocker)
            assert started.wait(5)
            with ex._cond:
                ex._usage["qa"] = 0.0
                ex._usage["qb"] = 0.05

            def slow_a():
                order.append("a1")
                time.sleep(0.2)

            ex.submit("qa", "qa_f0_p0", slow_a)
            ex.submit("qa", "qa_f1_p0", lambda: order.append("a2"))
            ex.submit("qb", "qb_f0_p0", lambda: order.append("b1"))
            gate.set()
            self._drain(ex, order, 3)
            # qa runs first (least served) but its 0.2s of usage makes its
            # STALE heap entry lose to qb on re-key — the lazy decrease-key
            # path — before qa's second task runs
            assert order == ["a1", "b1", "a2"]
        finally:
            ex.stop()

    def test_throughput_many_queries(self):
        from trino_tpu.server.worker import FairTaskExecutor

        ex = FairTaskExecutor(n_threads=4)
        try:
            done = []
            lock = threading.Lock()
            for i in range(400):
                q = f"q{i % 20}"

                def fn(i=i):
                    with lock:
                        done.append(i)

                ex.submit(q, f"{q}_f{i}_p0", fn)
            t_end = time.monotonic() + 10
            while len(done) < 400 and time.monotonic() < t_end:
                time.sleep(0.01)
            assert len(done) == 400
        finally:
            ex.stop()


class TestCommitToctou:
    """Advisor round-5: the sweep can land between commit()'s tombstone check
    and its rename; the re-check after the rename must undo the commit."""

    def test_sweep_inside_commit_window(self, tmp_path, monkeypatch):
        from trino_tpu.runtime import exchange_spi

        mgr = exchange_spi.ExchangeManager(base_dir=str(tmp_path))
        ex = mgr.create_exchange("q1", 0)
        sink = ex.part_sink(0, 0)
        sink.add_part(0, b"blob", rows=1)

        real_replace = os.replace

        def racy_replace(src, dst):
            real_replace(src, dst)
            # the sweep's rmtree ran while our rename was in flight and
            # missed the just-renamed dir; only the tombstone remains
            with open(tmp_path / ".removed-q1", "w"):
                pass

        monkeypatch.setattr(exchange_spi.os, "replace", racy_replace)
        with pytest.raises(exchange_spi.QueryExchangeRemoved):
            sink.commit()
        # the resurrected attempt dir was removed, not leaked forever
        assert not os.path.exists(sink._final)

    def test_plain_sink_sweep_inside_commit_window(self, tmp_path, monkeypatch):
        from trino_tpu.runtime import exchange_spi

        mgr = exchange_spi.ExchangeManager(base_dir=str(tmp_path))
        ex = mgr.create_exchange("q3", 0)
        sink = ex.sink(0, 0)
        sink.add(b"blob")

        real_replace = os.replace

        def racy_replace(src, dst):
            real_replace(src, dst)
            with open(tmp_path / ".removed-q3", "w"):
                pass

        monkeypatch.setattr(exchange_spi.os, "replace", racy_replace)
        with pytest.raises(exchange_spi.QueryExchangeRemoved):
            sink.commit()
        assert not os.path.exists(sink._final)

    def test_plain_sink_rejects_commit_after_sweep(self, tmp_path):
        from trino_tpu.runtime import exchange_spi

        mgr = exchange_spi.ExchangeManager(base_dir=str(tmp_path))
        ex = mgr.create_exchange("q4", 0)
        sink = ex.sink(0, 0)
        sink.add(b"blob")
        mgr.remove_query("q4")  # sweep completes before the commit
        with pytest.raises(exchange_spi.QueryExchangeRemoved):
            sink.commit()
        assert not os.path.exists(sink._final)

    def test_normal_commit_still_works(self, tmp_path):
        from trino_tpu.runtime import exchange_spi

        mgr = exchange_spi.ExchangeManager(base_dir=str(tmp_path))
        ex = mgr.create_exchange("q2", 0)
        sink = ex.part_sink(0, 0)
        sink.add_part(0, b"blob", rows=3)
        sink.commit()
        assert ex.committed_parts_attempt(0) == 0
        assert ex.attempt_meta(0)["rows"] == 3


class TestMixedDistinctAlignment:
    """Advisor round-5: the distinct/plain merge must verify ALL group-key
    columns (data + valid masks), not just group_keys[0]."""

    @pytest.fixture()
    def mem_runner(self):
        import jax.numpy as jnp

        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.metadata import Session
        from trino_tpu.spi.connector import ColumnMetadata, SchemaTableName
        from trino_tpu.spi.page import Column, Page
        from trino_tpu.spi.types import BIGINT

        r = LocalQueryRunner(Session(catalog="mem", schema="default"))
        mc = MemoryConnector()
        r.register_catalog("mem", mc)
        t = SchemaTableName("default", "t")
        mc.create_table(
            t,
            [
                ColumnMetadata("k1", BIGINT),
                ColumnMetadata("k2", BIGINT),
                ColumnMetadata("x", BIGINT),
                ColumnMetadata("y", BIGINT),
            ],
        )
        k1 = np.array([1, 1, 1, 2, 2, 0], dtype=np.int64)
        k1v = np.array([1, 1, 1, 1, 1, 0], dtype=bool)  # last row: k1 NULL
        k2 = np.array([7, 7, 8, 7, 7, 7], dtype=np.int64)
        x = np.array([10, 10, 11, 12, 13, 14], dtype=np.int64)
        y = np.array([1, 2, 3, 4, 5, 6], dtype=np.int64)
        n = len(k1)
        cols = (
            Column.from_numpy(BIGINT, k1, k1v, capacity=n),
            Column.from_numpy(BIGINT, k2, np.ones(n, bool), capacity=n),
            Column.from_numpy(BIGINT, x, np.ones(n, bool), capacity=n),
            Column.from_numpy(BIGINT, y, np.ones(n, bool), capacity=n),
        )
        mc.insert(t, Page(cols, jnp.asarray(np.ones(n, bool))))
        return r

    def test_mixed_distinct_plain_with_null_keys(self, mem_runner):
        got = {
            tuple(r)
            for r in mem_runner.execute(
                "SELECT k1, k2, count(DISTINCT x), sum(y) FROM t GROUP BY k1, k2"
            ).rows
        }
        # (1,7): x={10}, y=1+2 ; (1,8): x={11}, y=3 ; (2,7): x={12,13}, y=9 ;
        # (NULL,7): x={14}, y=6
        assert got == {
            (1, 7, 1, 3),
            (1, 8, 1, 3),
            (2, 7, 2, 9),
            (None, 7, 1, 6),
        }


class TestSequenceStepZero:
    """Advisor round-5: literal step 0 raises the engine's CompileError, not
    a raw range() ValueError."""

    def test_step_zero_is_compile_error(self, runner):
        from trino_tpu.ops.compiler import CompileError

        with pytest.raises(CompileError, match="step must not be zero"):
            runner.execute("SELECT sequence(1, 5, 0) FROM nation LIMIT 1")

    def test_nonzero_step_still_works(self, runner):
        rows = runner.execute("SELECT sequence(1, 7, 3) FROM nation LIMIT 1").rows
        assert rows[0][0] == [1, 4, 7]


class TestScanBucketSymbolsFailClosed:
    """Advisor round-5: a ProjectNode with no Reference mapping for a bucket
    column must yield None (fail closed), not the identity fallback."""

    @pytest.fixture()
    def bucketed(self):
        import jax.numpy as jnp

        from trino_tpu.connectors.memory import MemoryConnector
        from trino_tpu.metadata import Session
        from trino_tpu.spi.connector import ColumnMetadata, SchemaTableName
        from trino_tpu.spi.page import Column, Page
        from trino_tpu.spi.types import BIGINT

        r = LocalQueryRunner(Session(catalog="mem", schema="default"))
        mc = MemoryConnector()
        r.register_catalog("mem", mc)
        t = SchemaTableName("default", "facts")
        mc.create_table(
            t, [ColumnMetadata("k", BIGINT), ColumnMetadata("v", BIGINT)],
            bucketed_by=["k"], bucket_count=4,
        )
        k = np.arange(20, dtype=np.int64)
        cols = (
            Column.from_numpy(BIGINT, k, np.ones(20, bool), capacity=20),
            Column.from_numpy(BIGINT, k * 10, np.ones(20, bool), capacity=20),
        )
        mc.insert(t, Page(cols, jnp.asarray(np.ones(20, bool))))
        return r

    def test_plain_scan_maps_bucket_columns(self, bucketed):
        from trino_tpu.planner.fragmenter import _scan_bucket_symbols
        from trino_tpu.planner.plan import TableScanNode, visit_plan

        scans = []
        visit_plan(
            bucketed.plan_sql("SELECT k, v FROM facts").root,
            lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
        )
        assert _scan_bucket_symbols(scans[0], bucketed.metadata) is not None

    def test_computed_projection_fails_closed(self, bucketed):
        from trino_tpu.planner.fragmenter import _scan_bucket_symbols
        from trino_tpu.planner.plan import ProjectNode, TableScanNode, visit_plan
        from trino_tpu.spi.types import BIGINT
        from trino_tpu.sql.ir import Call, Constant, Reference

        scans = []
        visit_plan(
            bucketed.plan_sql("SELECT k, v FROM facts").root,
            lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
        )
        scan = scans[0]
        k_sym = next(s for s, c in scan.assignments if c == "k")
        # an in-place recompute `k := k + 1` reuses the symbol name with NO
        # Reference assignment: the partitioning does NOT survive, and the
        # old falsy-rename fallback claimed it did
        proj = ProjectNode(
            source=scan,
            assignments=(
                (
                    k_sym,
                    Call(
                        "$add",
                        (Reference(k_sym, BIGINT), Constant(BIGINT, 1)),
                        BIGINT,
                    ),
                ),
            ),
        )
        assert _scan_bucket_symbols(proj, bucketed.metadata) is None

    def test_all_computed_outer_projection_kills_chain(self, bucketed):
        from trino_tpu.planner.fragmenter import _scan_bucket_symbols
        from trino_tpu.planner.plan import ProjectNode, TableScanNode, visit_plan
        from trino_tpu.spi.types import BIGINT
        from trino_tpu.sql.ir import Call, Constant, Reference

        scans = []
        visit_plan(
            bucketed.plan_sql("SELECT k, v FROM facts").root,
            lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
        )
        scan = scans[0]
        k_sym = next(s for s, c in scan.assignments if c == "k")
        # inner projection passes k through as y; the OUTER projection is
        # all-computed ({} Reference mapping) — the chain must die there,
        # not reset to the inner identity mapping
        inner = ProjectNode(
            source=scan, assignments=(("y_sym", Reference(k_sym, BIGINT)),)
        )
        outer = ProjectNode(
            source=inner,
            assignments=(
                (
                    "z_sym",
                    Call(
                        "$add",
                        (Reference("y_sym", BIGINT), Constant(BIGINT, 1)),
                        BIGINT,
                    ),
                ),
            ),
        )
        assert _scan_bucket_symbols(outer, bucketed.metadata) is None
