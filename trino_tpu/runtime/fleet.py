"""Active-active coordinator fleet: partitioned admission, follower
reads, and a multi-process protocol front.

Round 19 measured the ceiling this plane removes: at 16 clients, p99 is
8.5% device / 90.7% protocol-host with the GIL-contention probe showing
38ms p99 against a 5ms sleep (BENCH_r19_hostpath_ab.json) — the chip is
idle while ONE Python process's protocol loop serializes every client.
"Accelerating Presto with GPUs" (PAPERS.md) names the pattern: once the
device path is fast, the host/protocol path must scale OUT. The round-16
serving fabric (runtime/ha.py) already made a query outlive its
coordinator; this module makes the standby fleet *serve*:

- :class:`FleetMember` — membership on the ``fs.py`` object-store
  substrate (``members/<node_id>.json`` heartbeat objects, atomic puts,
  TTL liveness). Heartbeats carry the same bounded metric snapshot worker
  announcements do (``clusterobs.announcement_metrics``), and every member
  folds its peers' snapshots into its :class:`~.clusterobs.ClusterMetrics`
  — so ``system.metrics.cluster_counters`` shows per-coordinator
  ``trino_tpu_protocol_queue_depth`` / admission counters (node column)
  from ANY member, and fleet hot-spotting is visible without a scrape tier.
- :class:`HashRing` — consistent-hash ownership over the LIVE member set:
  each member projects ``RING_POINTS`` virtual points; a statement's
  partition key is owned by the first point clockwise. A dead member's
  arcs fall to its clockwise successors — the failover reassignment
  contract is that every key NOT owned by the dead node keeps its owner
  (no fleet-wide reshuffle), and in-flight queries of the dead owner are
  recovered by the journal replay path that already exists
  (``ha.resume_fte_query`` over ``orphaned_journals``).
- Partitioned admission: a non-owner coordinator receiving POST
  /v1/statement either 307-redirects the client to the owner's unique
  address or proxies the statement there (``$TRINO_TPU_FLEET_ROUTE``),
  under ``proto_route`` / ``proto_proxy`` phase spans so routing cost is
  attributed, not hidden.
- Follower reads: ``system.*``-only statements, warm result-cache hits
  (the round-16 ``peek_cached_result`` PURE probe against the shared
  tier), and ``GET /v1/query/{id}`` status polls (served from the
  ``status/<query_id>.json`` board the owner publishes on lifecycle
  transitions) are answered by ANY member without touching the owner.
- Multi-process protocol front: N forked coordinator processes share one
  client-facing listen port via ``SO_REUSEPORT`` (each also binds a
  unique per-node port that membership advertises for redirect/proxy
  targets), so concurrent client protocol loops stop convoying on one
  GIL. Each front process is a FULL coordinator in the lease/journal
  protocol. ``python -m trino_tpu.runtime.fleet`` serves one such process
  (bench.py fleet_ab and deployments fork N of them).

Everything is gated off by default: with ``$TRINO_TPU_FLEET_DIR`` unset
no membership object, no heartbeat thread, and no routing branch exists —
the single-coordinator path is byte-identical (poisoning-tested).
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import knobs
from ..fs import LocalFileSystem, Location
from .observability import RECORDER

# one shared HELP string per counter: the metric HELP lint requires every
# call site of a name to agree
ROUTED_HELP = "statements 307-redirected to their owning coordinator"
PROXIED_HELP = "statements proxied to their owning coordinator"
FOLLOWER_READS_HELP = (
    "read-only requests served by a non-owner fleet coordinator"
)
HEARTBEATS_HELP = "fleet membership heartbeats published"
REASSIGNS_HELP = (
    "fleet members whose hash range was reassigned after their heartbeat "
    "lapsed"
)

# virtual points per member on the ownership ring: enough that N<=8 real
# members split a realistic key population within a few percent of even;
# rings are memoized per live-member set, so the build cost is paid once
# per membership change, never per routing decision
RING_POINTS = 512


def _counter(name: str, help_: str):
    from .metrics import REGISTRY

    return REGISTRY.counter(name, help=help_)


def _hash64(text: str) -> int:
    return int.from_bytes(
        hashlib.sha256(text.encode()).digest()[:8], "big"
    )


class HashRing:
    """Consistent-hash ownership over an id set (see module docstring)."""

    def __init__(self, members, points: int = RING_POINTS):
        ring = sorted(
            (_hash64(f"{m}#{i}"), m)
            for m in set(members)
            for i in range(points)
        )
        self._points = [p for p, _ in ring]
        self._owners = [m for _, m in ring]

    def owner(self, key: str) -> Optional[str]:
        if not self._points:
            return None
        idx = bisect.bisect_right(self._points, _hash64(key))
        return self._owners[idx % len(self._owners)]


def partition_key(user: str, source: str = "", group: str = "") -> str:
    """The ownership hash key for one statement: the session identity
    (``user@source``) by default; ``$TRINO_TPU_FLEET_PARTITION_BY=group``
    overrides to the resolved resource-group path so every session of a
    group lands on one coordinator (its admission queue stays a single
    total order, exactly as in a one-coordinator deployment)."""
    mode = knobs.env_str("TRINO_TPU_FLEET_PARTITION_BY", "session")
    if mode == "group" and group:
        return f"group:{group}"
    return f"session:{user}@{source}"


class FleetMember:
    """One coordinator's view of the fleet (substrate + ring + board)."""

    def __init__(self, fleet_dir: str, node_id: str, url: str,
                 heartbeat_secs: Optional[float] = None,
                 cluster_metrics=None):
        self.fs = LocalFileSystem(fleet_dir)
        self.fleet_dir = fleet_dir
        self.node_id = node_id
        self.url = url  # the member's UNIQUE address (redirect/proxy target)
        self.heartbeat_secs = (
            heartbeat_secs
            if heartbeat_secs is not None
            else knobs.env_float("TRINO_TPU_FLEET_HEARTBEAT_SECS", 1.0)
        )
        # a member is live while its last heartbeat's deadline is ahead of
        # the reader's clock; 3 beats of grace mirrors the worker
        # heartbeat-loss ladder (one missed beat must not reshuffle the ring)
        self.ttl_secs = 3.0 * max(self.heartbeat_secs, 0.05)
        self.cluster_metrics = cluster_metrics
        # wired by the server: live queue depth for the heartbeat record
        self.queue_depth_fn: Optional[Callable[[], int]] = None
        self._known_live: set = set()
        # routing hot path: the live set is re-read from the substrate at
        # most every quarter-heartbeat (membership changes no faster), and
        # rings are memoized per member set
        self.live_cache_secs = self.heartbeat_secs / 4.0
        self._live_cache: Optional[Dict[str, dict]] = None
        self._live_cache_at = 0.0
        self._ring_cache: Dict[tuple, HashRing] = {}
        self._cache_lock = threading.Lock()
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ substrate

    def _member_loc(self, node_id: str) -> Location:
        return Location("local", f"members/{node_id}.json")

    def _status_loc(self, query_id: str) -> Location:
        return Location("local", f"status/{query_id}.json")

    # ----------------------------------------------------------- membership

    def publish_heartbeat(self) -> None:
        """Atomic put of this member's liveness record, with the bounded
        metric snapshot riding along (the announcement contract: heartbeats
        must never bloat past the liveness budget, overflow is counted)."""
        from .clusterobs import announcement_metrics

        series, _dropped = announcement_metrics()
        record = {
            "node_id": self.node_id,
            "url": self.url,
            "pid": os.getpid(),
            "deadline": time.time() + self.ttl_secs,
            "queue_depth": (
                int(self.queue_depth_fn()) if self.queue_depth_fn else 0
            ),
            "metrics": series,
        }
        self.fs.write(
            self._member_loc(self.node_id),
            json.dumps(record).encode(),
        )
        _counter("trino_tpu_fleet_heartbeats_total", HEARTBEATS_HELP).inc()

    def live_members(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Every member whose heartbeat deadline is ahead of ``now``.
        Unreadable/partial objects are skipped (atomic puts make them
        impossible locally; a real object store can list-before-put).
        Results are cached for a quarter-heartbeat (pass ``now`` to
        bypass — tests and the reassignment check do)."""
        use_cache = now is None
        if use_cache:
            with self._cache_lock:
                if (
                    self._live_cache is not None
                    and time.time() - self._live_cache_at
                    < self.live_cache_secs
                ):
                    return dict(self._live_cache)
        now = time.time() if now is None else now
        live: Dict[str, dict] = {}
        try:
            entries = list(self.fs.list_files(Location("local", "members")))
        except OSError:
            entries = []
        for entry in entries:
            try:
                rec = json.loads(self.fs.read(entry.location))
            except (OSError, ValueError):
                continue
            if not isinstance(rec, dict):
                continue
            if float(rec.get("deadline", 0)) > now:
                live[str(rec.get("node_id", ""))] = rec
        if use_cache:
            with self._cache_lock:
                self._live_cache = dict(live)
                self._live_cache_at = time.time()
        return live

    def ring(self, live: Optional[Dict[str, dict]] = None) -> HashRing:
        live = self.live_members() if live is None else live
        ids = set(live) | {self.node_id}  # self serves even pre-first-beat
        key = tuple(sorted(ids))
        with self._cache_lock:
            ring = self._ring_cache.get(key)
            if ring is None:
                if len(self._ring_cache) > 64:
                    self._ring_cache.clear()  # bounded across churn
                ring = HashRing(ids)
                self._ring_cache[key] = ring
        return ring

    def owner_of(self, key: str) -> dict:
        """The live member record owning ``key`` (self when the ring picks
        this node or the owner's record is unreadable). Also the
        reassignment observation point: a member that left the live set
        since the last look is counted and marked in the flight recorder —
        the smoke and the bench read failover off this signal."""
        live = self.live_members()
        departed = self._known_live - set(live) - {self.node_id}
        self._known_live = set(live)
        for dead in sorted(departed):
            _counter(
                "trino_tpu_fleet_reassigns_total", REASSIGNS_HELP
            ).inc()
            with RECORDER.span(
                "fleet_reassign", "fleet", dead=dead,
                survivors=len(live),
            ):
                pass
        owner_id = self.ring(live).owner(key)
        if owner_id == self.node_id or owner_id not in live:
            return {"node_id": self.node_id, "url": self.url}
        return live[owner_id]

    def ingest_peer_metrics(self) -> None:
        """Fold every live peer's heartbeat metric snapshot into the local
        ClusterMetrics — the federation satellite: any member's
        ``system.metrics.cluster_counters`` shows every coordinator's
        queue depth / admission counters under its node label."""
        if self.cluster_metrics is None:
            return
        for node_id, rec in self.live_members().items():
            if node_id == self.node_id:
                continue
            series = rec.get("metrics")
            if isinstance(series, list) and series:
                self.cluster_metrics.ingest(node_id, series)

    # --------------------------------------------------------- status board

    def publish_status(self, query_id: str, payload: dict) -> None:
        """Owner-side: atomic put of one query's status for follower
        ``GET /v1/query/{id}`` polls (lifecycle-event shaped + owner id)."""
        body = dict(payload)
        body["fleet_owner"] = self.node_id
        self.fs.write(
            self._status_loc(query_id), json.dumps(body).encode()
        )

    def read_status(self, query_id: str) -> Optional[dict]:
        try:
            rec = json.loads(self.fs.read(self._status_loc(query_id)))
        except (OSError, ValueError):
            return None
        return rec if isinstance(rec, dict) else None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "FleetMember":
        self.publish_heartbeat()  # visible before the first loop tick
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"fleet-heartbeat-{self.node_id}",
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.heartbeat_secs):
            try:
                self.publish_heartbeat()
                self.ingest_peer_metrics()
            except Exception:  # noqa: BLE001 — liveness must never die
                pass

    def stop(self, deregister: bool = True) -> None:
        """Graceful stop deletes the membership object so the ring
        reassigns immediately; ``deregister=False`` models a crash — the
        record stays until its TTL lapses, exactly like a dead process."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if deregister:
            try:
                self.fs.delete(self._member_loc(self.node_id))
            except OSError:
                pass


class FleetStatusListener:
    """EventListener publishing owner-side lifecycle records onto the
    status board (created and completed overwrite the same object — last
    write wins, reads are atomic). Intermediate state changes are NOT
    published: each publish is a synchronous board write on the serving
    path, a warm hit runs PLANNING→RUNNING→FINISHED in microseconds, and
    the follower-read contract is bounded-stale anyway — the created
    record plus the terminal record (query_completed fires on EVERY
    terminal transition, cancel included) bound a query's lifetime."""

    def __init__(self, member: FleetMember):
        self._member = member

    def _publish(self, event: dict) -> None:
        qid = event.get("queryId")
        if qid:
            try:
                self._member.publish_status(qid, event)
            except OSError:
                pass

    def query_created(self, event: dict) -> None:
        self._publish(event)

    def query_completed(self, event: dict) -> None:
        self._publish(event)


def member_from_env(url: str, node_id: Optional[str] = None,
                    cluster_metrics=None) -> Optional[FleetMember]:
    """The deployment gate: a FleetMember iff ``$TRINO_TPU_FLEET_DIR`` is
    set (the plane's single opt-in). Everything else has safe defaults."""
    fleet_dir = knobs.env_path("TRINO_TPU_FLEET_DIR")
    if not fleet_dir:
        return None
    node_id = node_id or f"coordinator-{os.getpid()}-{url.rsplit(':', 1)[-1]}"
    return FleetMember(
        fleet_dir, node_id, url, cluster_metrics=cluster_metrics
    )


def is_system_read(sql: str) -> bool:
    """Conservative follower-read classifier: a SELECT whose every
    FROM/JOIN target is in the ``system`` catalog (three-part names only —
    anything the cheap scan cannot prove system-only routes to the owner).
    No parse: this runs inside proto_route on every fleet statement."""
    import re

    text = sql.strip()
    if not re.match(r"(?is)^select\b", text):
        return False
    # capture the whole comma list after FROM (implicit cross joins): every
    # relation in "FROM a, b" must prove system-only, not just the first
    targets = []
    for clause in re.findall(
        r"(?is)\b(?:from|join)\s+([a-z_][\w.\"]*(?:\s*,\s*[a-z_][\w.\"]*)*)",
        text,
    ):
        targets.extend(t.strip() for t in clause.split(","))
    if not targets:
        return False
    return all(t.lower().startswith("system.") for t in targets)


# --------------------------------------------------------------------- front


def main(argv: Optional[List[str]] = None) -> int:
    """Serve ONE coordinator process of a multi-process fleet front:
    binds the shared client-facing port with SO_REUSEPORT (kernel
    load-balances accepts across the forked siblings) plus a unique
    per-node port that membership advertises as the redirect/proxy
    target. bench.py fleet_ab forks N of these."""
    import argparse
    import signal
    import sys

    parser = argparse.ArgumentParser(prog="trino_tpu.runtime.fleet")
    parser.add_argument("--front-port", type=int, required=True,
                        help="shared SO_REUSEPORT client-facing port")
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--scale", type=float, default=0.0005)
    parser.add_argument("--ready-file", default="",
                        help="written with this node's unique url once up")
    parser.add_argument("--session", action="append", default=[],
                        metavar="K=V", help="session property overrides")
    parser.add_argument("--http-backlog", type=int, default=128,
                        help="listen(2) accept-backlog per front process "
                        "(the front plane's storm sizing; the default "
                        "deployment keeps the stdlib listen(5))")
    args = parser.parse_args(argv)

    # accept-queue sizing is part of the front plane: a concurrent-session
    # storm must queue in the kernel, not drop SYNs into ~1s retransmits
    if args.http_backlog > 0:
        os.environ.setdefault(
            "TRINO_TPU_HTTP_BACKLOG", str(args.http_backlog)
        )

    from ..runtime.local import LocalQueryRunner
    from ..server.coordinator import CoordinatorServer

    runner = LocalQueryRunner.tpch(scale=args.scale)
    for kv in args.session:
        k, _, v = kv.partition("=")
        parsed: object = v
        if v.lower() in ("true", "false"):
            parsed = v.lower() == "true"
        else:
            try:
                parsed = int(v)
            except ValueError:
                try:
                    parsed = float(v)
                except ValueError:
                    pass
        runner.session.set(k, parsed)
    server = CoordinatorServer(
        runner, node_id=args.node_id, front_port=args.front_port
    ).start()
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"http://{server.address}")
        os.replace(tmp, args.ready_file)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":  # pragma: no cover — exercised via subprocess
    import sys

    sys.exit(main())
