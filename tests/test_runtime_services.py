"""Cross-cutting runtime services: memory limits, admission control, event
listeners, dynamic filtering (SURVEY.md §5 auxiliary subsystems)."""

import threading
import time

import pytest

from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.events import CollectingEventListener, FileEventListener
from trino_tpu.runtime.memory import (
    AggregatedMemoryContext,
    ExceededMemoryLimitError,
)
from trino_tpu.runtime.query_manager import QueryManager, QueryState

SCALE = 0.0005


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


class TestMemoryAccounting:
    def test_context_tree(self):
        root = AggregatedMemoryContext(limit_bytes=1000)
        a = root.new_local("op_a")
        b = root.new_local("op_b")
        a.set_bytes(400)
        b.set_bytes(500)
        assert root.reserved_bytes == 900
        a.set_bytes(100)
        assert root.reserved_bytes == 600
        assert root.peak_bytes == 900
        with pytest.raises(ExceededMemoryLimitError):
            b.set_bytes(950)

    def test_query_limit_enforced(self, runner):
        runner.session.set("query_max_memory_bytes", 2000)
        try:
            with pytest.raises(ExceededMemoryLimitError):
                runner.execute("SELECT l_orderkey, l_quantity FROM lineitem")
        finally:
            runner.session.properties.pop("query_max_memory_bytes", None)

    def test_unlimited_by_default(self, runner):
        assert runner.execute("SELECT count(*) FROM lineitem").rows


class TestAdmissionControl:
    def test_concurrency_cap_queues(self):
        running = []
        lock = threading.Lock()
        release = threading.Event()

        class SlowResult:
            column_names = ["x"]
            rows = [(1,)]

        def slow_exec(sql):
            with lock:
                running.append(1)
                peak = len(running)
            release.wait(timeout=5)
            with lock:
                running.pop()
            return SlowResult()

        mgr = QueryManager(slow_exec, max_workers=4, max_concurrent=2)
        queries = [mgr.submit(f"q{i}") for i in range(4)]
        time.sleep(0.3)
        with lock:
            assert len(running) <= 2  # only two admitted
        release.set()
        for q in queries:
            assert q.wait_done(timeout=10)
            assert q.state == QueryState.FINISHED

    def test_cancel_queued(self):
        def run(sql):
            time.sleep(0.2)

            class R:
                column_names = ["x"]
                rows = []

            return R()

        mgr = QueryManager(run, max_concurrent=1)
        first = mgr.submit("a")
        second = mgr.submit("b")
        mgr.cancel(second.query_id)
        assert second.state == QueryState.CANCELED
        assert first.wait_done(timeout=10)


class TestEventListeners:
    def test_collecting_listener(self, runner):
        mgr = QueryManager(runner.execute)
        listener = CollectingEventListener()
        mgr.add_listener(listener)
        q = mgr.submit("SELECT 1")
        q.wait_done(timeout=30)
        deadline = time.time() + 5
        while not listener.events and time.time() < deadline:
            time.sleep(0.02)
        assert listener.events
        ev = listener.events[-1]
        assert ev["eventType"] == "QueryCompleted"
        assert ev["state"] == "FINISHED"
        assert ev["query"] == "SELECT 1"

    def test_file_listener(self, runner, tmp_path):
        import json

        path = str(tmp_path / "queries.jsonl")
        mgr = QueryManager(runner.execute)
        mgr.add_listener(FileEventListener(path))
        q = mgr.submit("SELECT bad syntax here from")
        q.wait_done(timeout=30)
        # listeners fire after the final state transition — poll briefly
        import os

        deadline = time.time() + 5
        while not os.path.exists(path) and time.time() < deadline:
            time.sleep(0.02)
        with open(path) as f:
            ev = json.loads(f.readline())
        assert ev["state"] == "FAILED"
        assert ev["errorType"]


class TestDynamicFiltering:
    def test_parity_on_off(self, runner):
        sql = (
            "SELECT count(*), sum(l_quantity) FROM lineitem "
            "JOIN orders ON l_orderkey = o_orderkey WHERE o_orderkey BETWEEN 100 AND 140"
        )
        on = runner.execute(sql).rows
        runner.session.set("enable_dynamic_filtering", False)
        try:
            off = runner.execute(sql).rows
        finally:
            runner.session.properties.pop("enable_dynamic_filtering", None)
        assert on == off

    def test_left_join_not_filtered(self, runner):
        # outer joins must keep unmatched probe rows: DF must not apply
        sql = (
            "SELECT count(*) FROM customer LEFT JOIN orders "
            "ON c_custkey = o_custkey AND o_totalprice > 100000"
        )
        assert runner.execute(sql).rows[0][0] >= 75  # every customer kept
