"""Device batching plane: ragged multi-query packing of compatible fragments.

BENCH_r09_concurrency.json is the motivating cliff: the mixed Q1/Q3/Q6/Q13
replay saturates at ~6.5 qps with 2 clients and DEGRADES toward 4 qps at 16
— the chip runs one fragment program at a time, so admission control merely
reorders a serial queue. The LLM-serving literature supplies the fix
("Ragged Paged Attention", arXiv:2604.15464: continuous batching of ragged,
shape-heterogeneous requests into one kernel; "Query Processing on Tensor
Computation Runtimes", arXiv:2203.01877: amortizing program dispatch across
work items is where tensor-runtime SQL wins live traffic). This module is
the scheduler that sits between the executors and the chip:

- **Work items, not launches.** Batchable fragment subtrees
  (scan→filter→project→(partial-)agg, the same shape the fragment cache
  recognizes) SUBMIT to the scheduler instead of dispatching their operator
  programs directly. The *batch key* is the compiled-program cache key we
  already have: the plancodec structural fingerprint of the subtree plus
  the capstore canonical capacity class (+ layout signature) of its input —
  items sharing a key would compile the SAME XLA program, so they can share
  one launch.

- **Ragged lanes.** A group of compatible items stacks its input pages
  along a new leading batch dim (all lanes sit at one canonical capacity
  class; per-lane row counts ride the active masks — the ragged part) and
  executes the subtree ONCE as a ``jax.jit(jax.vmap(lane_fn))`` program
  whose per-lane outputs are demuxed back to their owning queries. Lanes
  whose input page is the *same device array* (the shared-scan case below)
  deduplicate: the computation runs once and fans out — bit-identical by
  construction. A group that degenerates to one unique lane executes the
  plain serial per-operator programs, so the single-query path stays
  byte-identical with batching on.

- **Priority admission between launches.** Launches serialize through an
  admission gate ordered by (resource-group scheduling weight, queue age):
  a big OOC query's unit launches (runtime/ooc.py routes them through the
  same gate) no longer head-of-line-block a hundred Q6-class point queries
  — between any two launches the highest-priority oldest waiter goes next.

- **Shared-scan elimination.** The fragment cache's single-flight dedup
  generalizes from *identical prefixes* to *overlapping scans*: concurrent
  queries whose leaf scans cover the same table + conjuncts (the statstore
  canonical leaf key) subsume into ONE scan whose immutable device pages
  fan out to every waiter. Keys carry the connector version token
  (cache_table_version), so a post-DML arrival can never share a pre-DML
  page; unversioned or cache-bypass catalogs never share.

Failure isolation: a mid-batch failure (chaos kill, OOM) never poisons
peers — the batched launch falls back to per-lane serial execution, so only
the genuinely failing lane's query fails; a shared-scan winner that dies
publishes the error and waiters fall back to scanning themselves.

Everything is gated behind the ``device_batching`` session knob (default
off): with it off no binding is attached and the execution path is
byte-identical to the pre-plane engine (one ``is None`` attribute read).

Observability: paired ``batch_admit``/``batch_launch``/``batch_demux``
flight spans (lane count, packed rows, launch key on the E-args),
``trino_tpu_batched_fragments_total`` / ``trino_tpu_batch_lane_occupancy``
/ ``trino_tpu_device_programs_total`` /
``trino_tpu_shared_scan_{hits,misses}_total`` metrics, and
``tools/obs_smoke.py run_batching_smoke`` in tier-1.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

# how long a shared-scan entry may serve after its flight completed: long
# enough for back-to-back dashboard arrivals to subsume, short enough that
# lingering device pages cannot pile up (entries are also LRU-bounded)
SHARED_SCAN_TTL_SECS = 10.0
SHARED_SCAN_MAX_ENTRIES = 32
# how long a completed subtree subsumption may keep serving — the
# CONTINUOUS-BATCHING WINDOW, deliberately short: under load, same-class
# queries arrive within it and amortize into one computation (throughput
# scales with concurrency); at low load it expires between arrivals and
# every query recomputes (this is a batching window, not a result cache —
# the warm-path cache plane owns longer-lived reuse). Bit-identity holds
# at ANY length: the key pins the input pages' identities, so a lingered
# result can never be staler than the scans a recomputation would read.
SUBSUME_LINGER_SECS = 0.25
SUBSUME_MAX_ENTRIES = 64
# how long a lane waits on its batch leader (or a scan waiter on the scan
# winner) before giving up and executing itself — a hung peer must never
# wedge a query (the fragment cache's single-flight contract)
LANE_WAIT_SECS = 120.0


# --------------------------------------------------------------- observability


def _counter(name: str, labels=None):
    from .metrics import REGISTRY

    helps = {
        "trino_tpu_device_programs_total":
            "device program launches at the operator/fragment boundary "
            "(a packed ragged batch counts once; serial operators count "
            "one per program)",
        "trino_tpu_batched_fragments_total":
            "fragment work items served by multi-lane ragged batch launches",
        "trino_tpu_subsumed_fragments_total":
            "fragment subtrees served by a concurrent identical execution "
            "(whole-subtree single-flight subsumption)",
        "trino_tpu_shared_scan_hits_total":
            "leaf scans served from a concurrent overlapping scan "
            "(shared-scan elimination)",
        "trino_tpu_shared_scan_misses_total":
            "leaf scans that executed (shared-scan flight winners + "
            "unshareable scans)",
    }
    return REGISTRY.counter(name, labels or {}, help=helps[name])


def _occupancy_histogram():
    from .metrics import REGISTRY

    # lanes per launch: 1, 2, 4, 8, ... (powers of two match the padded
    # batch shapes the launcher actually compiles)
    return REGISTRY.histogram(
        "trino_tpu_batch_lane_occupancy",
        buckets=[1, 2, 4, 8, 16, 32],
        help="work-item lanes packed per device batch launch",
    )


_programs_counter = None


def on_program_launch(n: int = 1) -> None:
    """One device program launch at the operator/fragment boundary — the
    counter the batching A/B bench reads (fewer launches is the win).
    Ticked per operator program on the serial path (executor._eval_node)
    and ONCE per packed ragged launch here; the counter object is memoized
    — the hot-path cost is one lock-guarded float add."""
    global _programs_counter
    c = _programs_counter
    if c is None:
        c = _programs_counter = _counter("trino_tpu_device_programs_total")
    c.inc(n)


def program_launches() -> float:
    return _counter("trino_tpu_device_programs_total").value


# ------------------------------------------------------------------- priority


_priority_tls = threading.local()


class priority_scope:
    """Thread-local resource-group priority for everything this thread
    submits to the scheduler (QueryManager installs it with the admitted
    ticket's group scheduling weight)."""

    def __init__(self, weight: float):
        self.weight = float(weight)

    def __enter__(self):
        self._prev = getattr(_priority_tls, "weight", None)
        _priority_tls.weight = self.weight
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            del _priority_tls.weight
        else:
            _priority_tls.weight = self._prev
        return False


def current_priority() -> float:
    return float(getattr(_priority_tls, "weight", 1.0))


class _LaunchGate:
    """Priority admission between launches: one launch holds the gate at a
    time, and on release the waiter with the highest (weight, age) key is
    admitted — the scheduler's "admit new items between program launches"
    contract. FIFO within a weight (arrival time breaks ties)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._busy = False
        self._waiting: List[Tuple[float, float, int]] = []  # heap
        self._seq = 0

    def acquire(self, priority: float) -> None:
        with self._cond:
            self._seq += 1
            token = (-float(priority), time.monotonic(), self._seq)
            heapq.heappush(self._waiting, token)
            try:
                while self._busy or self._waiting[0] != token:
                    self._cond.wait(timeout=1.0)
            except BaseException:
                # an interrupted waiter must not leave its token at the
                # heap head — that would wedge the process-global gate
                self._waiting.remove(token)
                heapq.heapify(self._waiting)
                self._cond.notify_all()
                raise
            heapq.heappop(self._waiting)
            self._busy = True

    def release(self) -> None:
        with self._cond:
            self._busy = False
            self._cond.notify_all()

    def __enter__(self):
        self.acquire(current_priority())
        return self

    def __exit__(self, *exc):
        self.release()
        return False


# ------------------------------------------------------------ batchable chain


def _split_chain(root):
    """AggregationNode root -> (bottom-up [input.., root] chain above the
    input node, input node). The chain is the pure part the scheduler can
    trace once and vmap; the input node (scan/exchange/...) is evaluated by
    the owning executor (shared-scan elimination hooks the scan there)."""
    from ..planner.plan import FilterNode, ProjectNode

    chain = [root]
    cur = root.source
    while isinstance(cur, (FilterNode, ProjectNode)):
        chain.append(cur)
        cur = cur.source
    chain.reverse()
    return chain, cur


def _chain_statically_batchable(root, session) -> bool:
    """Cheap pre-input checks: aggregate shapes a host-sync-free lane
    function can express (the direct-indexed / global paths of
    aggregate_relation). The domain check (dictionary sizes) needs the
    input relation and happens in :meth:`BatchBinding.execute`."""
    from .executor import _DIRECT_AGG_FUNCS, _LANE_AGGS, _RESORT_AGGS

    for _, a in root.aggregations:
        if a.distinct or a.ordering:
            return False
        if a.function not in _DIRECT_AGG_FUNCS:
            return False
        if a.function in _LANE_AGGS or a.function in _RESORT_AGGS:
            return False
    # the spill path host-syncs sizes and hash-partitions — serial only
    try:
        if int(session.get("spill_operator_threshold_bytes") or 0):
            return False
    except KeyError:
        pass
    # Pallas kernels are not exercised under vmap — keep them serial. The
    # mode vocabulary resolves through the central knob registry (the same
    # policy executor._pallas_mode applies), so the two launch sites cannot
    # drift. The megakernel plane (pallas_fusion) composes freely: batchable
    # chains are scan-rooted and join-free, so a fused join/agg fragment
    # never appears inside a ragged lane body — fusion and batching serve
    # disjoint fragment shapes of the same query.
    from .. import knobs

    try:
        if knobs.resolve_pallas_aggregation(
            session.get("pallas_aggregation")
        ) != "off":
            return False
    except KeyError:
        pass
    return True


def _layout_sig(page) -> Tuple:
    """Input layout half of the batch key: everything the traced program
    shape depends on beyond the plan structure — dtypes, capacity, nested
    lane widths, dictionary CONTENT identity (fingerprints: two lanes with
    content-equal dictionaries run one program over either's codes)."""
    def col_sig(c) -> Tuple:
        return (
            str(c.data.dtype), tuple(c.data.shape[1:]),
            None if c.dictionary is None else c.dictionary.fingerprint(),
            None if c.lengths is None else str(c.lengths.dtype),
            None if c.elem_valid is None else tuple(c.elem_valid.shape[1:]),
            tuple(col_sig(k) for k in c.children),
        )

    return (page.capacity, tuple(col_sig(c) for c in page.columns))


def _apply_chain_node(rel, node, types):
    """One pure chain step — the EXACT per-operator programs the serial
    executor dispatches (_exec_FilterNode/_exec_ProjectNode/the
    host-sync-free aggregation paths), reused so a lane computes the same
    bytes batched or not. Traceable: no host syncs anywhere."""
    import jax.numpy as jnp

    from ..ops.compiler import compile_expression
    from ..planner.plan import AggregationNode, FilterNode, ProjectNode
    from ..sql.ir import Reference
    from .executor import (
        Page,
        Relation,
        _direct_agg_domains,
        _jit_aggregate,
        _jit_direct_aggregate,
        _jit_filter,
        _jit_project,
        _needed_agg_symbols,
    )

    if isinstance(node, FilterNode):
        fn, _ = compile_expression(node.predicate, rel.layout(), rel.capacity)
        page = _jit_filter(fn, rel.env(), rel.page)
        return Relation(page, rel.symbols, rel.sorted_by)
    if isinstance(node, ProjectNode):
        layout = rel.layout()
        compiled = []
        symbols = []
        alias_of = {}
        for sym, expr in node.assignments:
            fn, out_dict = compile_expression(expr, layout, rel.capacity)
            type_ = types.get(sym) or expr.type
            compiled.append((fn, type_, out_dict))
            symbols.append(sym)
            if isinstance(expr, Reference):
                alias_of[expr.symbol] = sym
        page = _jit_project(tuple(compiled), rel.env(), rel.page)
        sorted_by = []
        for s in rel.sorted_by:
            out = alias_of.get(s)
            if out is None:
                break
            sorted_by.append(out)
        return Relation(page, tuple(symbols), tuple(sorted_by))
    if isinstance(node, AggregationNode):
        out_symbols = node.group_keys + tuple(s for s, _ in node.aggregations)
        domains = _direct_agg_domains(rel, node)
        if domains is not None:
            page = _jit_direct_aggregate(
                node.group_keys, node.aggregations, domains, rel.symbols,
                rel.page, "off",
            )
            return Relation(page, out_symbols)
        # global aggregation (no group keys): the serial path's
        # _maybe_compact is skipped here — compaction only drops masked
        # rows, whose where()-zeroed contributions are exact identities
        # for every reduction in _DIRECT_AGG_FUNCS, so the output bytes
        # match the serial program's
        needed = _needed_agg_symbols(node)
        cols = tuple(rel.column_for(s) for s in needed)
        page = _jit_aggregate(
            node.group_keys, node.aggregations, needed, 1, 0,
            Page(cols, rel.page.active), None, jnp.int32(1),
        )
        return Relation(page, out_symbols)
    raise AssertionError(f"unbatchable chain node {type(node).__name__}")


def _domains_resolvable(rel, root) -> bool:
    """The input-dependent half of batchability: grouped aggregations must
    take the direct-indexed path (small static key domains) — the sort
    path host-syncs its group count and cannot trace."""
    from .executor import _direct_agg_domains

    if not root.group_keys:
        return True
    return _direct_agg_domains(rel, root) is not None


# ----------------------------------------------------------------- work items


@dataclass
class _Lane:
    """One submitted work item: a fragment subtree execution waiting to be
    packed. ``rel`` is the evaluated input relation; the leader fills
    ``result``/``error`` (or flips ``fallback`` so the owner self-serves)."""

    key: Tuple
    rel: Any
    chain: List
    types: Dict
    # resource-group weight at submit time: the GROUP launches at its
    # highest lane's priority (queue age is the gate's own acquire time)
    priority: float
    event: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[BaseException] = None
    fallback: bool = False


class _Group:
    """Lanes admitted under one batch key; the first submitter is the
    leader and closes admission after the window."""

    def __init__(self, key: Tuple):
        self.key = key
        self.lanes: List[_Lane] = []
        self.closed = False


class _SubsumeFlight:
    """Single-flight ticket for one whole-subtree execution: concurrent
    queries whose subtree shares the structural fingerprint AND the same
    shared-scan input pages (object identity — versioned, so equal pages
    imply equal data) are ONE computation; the winner publishes its output
    Relation and the losers' queries consume it bit-identically.

    A completed flight LINGERS for ``SUBSUME_LINGER_SECS`` (the continuous-
    batching window): same-class arrivals that drift past the winner's
    in-flight window still subsume instead of recomputing. This is exactly
    as fresh as the shared-scan linger it is anchored to — the key holds
    the input pages' identities, and a DML bumps the version under the
    scan key, so a lingered result can never be staler than the pages a
    recomputation would read."""

    def __init__(self):
        self.event = threading.Event()
        self.rel: Any = None
        self.error: Optional[BaseException] = None
        self.done = False
        self.completed_at = 0.0
        # the input pages whose id()s ride the flight key: pinned HERE so
        # a freed page's recycled address can never match a lingering key
        self.pins: Tuple = ()


@dataclass
class _ScanEntry:
    """Shared-scan single-flight ticket + short-lived published result."""

    event: threading.Event
    created: float
    # published by the winner: (page, (sym, col) assignments, sorted_by
    # COLUMN names); errors publish ``error`` instead
    page: Any = None
    assignments: Tuple = ()
    sorted_cols: Tuple = ()
    error: Optional[BaseException] = None
    done: bool = False
    # weakref to the executing PlanExecutor: a winner re-reading its OWN
    # entry (the subsume pre-pass resolves leaves, then the executor's
    # real eval fetches again) is one logical fetch, not a cross-query
    # share — suppressed by EXECUTOR identity, never by thread id (pool
    # threads are reused across queries)
    winner_ref: Any = None


class DeviceScheduler:
    """Process-wide scheduler (one chip, one instance — ``SCHEDULER``).

    Thread model: there is no daemon thread. The first submitter of a batch
    key becomes the group LEADER: it holds admission open for
    ``batch_admit_window_ms``, then stacks whatever lanes joined, takes the
    launch gate, runs ONE program, and demuxes. Joiners block on their lane
    event and fall back to self-execution if the leader dies or times out.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Dict[Tuple, _Group] = {}
        self._fn_cache: Dict[Tuple, Any] = {}
        self._scans: "OrderedDict[Tuple, _ScanEntry]" = OrderedDict()
        self._subsume: "OrderedDict[Tuple, _SubsumeFlight]" = OrderedDict()
        self._plans: "OrderedDict[Tuple, _SubsumeFlight]" = OrderedDict()
        # per-plan-node memo for the submit pre-pass (fingerprints, plan
        # profiles, leaf keys): plan flights hand concurrent queries the
        # SAME plan objects, so the wave-of-16 herd computes these once.
        # Entries hold the node itself — id() stays valid while cached.
        self._node_memo: "OrderedDict[Tuple, Tuple[Any, Any]]" = OrderedDict()
        self.gate = _LaunchGate()
        # observability for tests (metrics are the production surface)
        self.batched_launches = 0
        self.single_launches = 0
        self.scan_executions = 0
        self.scan_shares = 0
        self.subsumed = 0
        self.plans_shared = 0
        self.vector_batched_launches = 0
        self.vector_broadcast_routes = 0

    # ------------------------------------------------------------- batching

    def execute(self, binding: "BatchBinding", executor, root):
        """The executor-facing entry (PlanExecutor.eval): run the subtree
        under ``root`` through the batching plane, or return None to fall
        through to plain per-node execution.

        Two dedup tiers compose here:

        1. *Whole-subtree subsumption* — concurrent queries whose subtree
           fingerprint AND shared-scan input pages match are one
           computation (single-flight, winner fans out). This covers
           join-bearing subtrees the ragged launcher cannot trace.
        2. *Lane packing* — for traceable scan→filter→project→agg chains,
           distinct-input items sharing a program pack into one ragged
           vmapped launch.
        """
        from ..planner.plan import AggregationNode, VectorTopNNode
        from .observability import RECORDER

        # the ragged chain machinery traces aggregation-rooted subtrees;
        # sort/TopN/VectorTopN roots (and agg roots it cannot trace) still
        # get the subsumption tier — the serial winner computes anything
        batchable = isinstance(root, AggregationNode) and \
            _chain_statically_batchable(root, binding.session)
        # vector serving tier: VectorTopN items differing only in their
        # constant query vector coalesce into one stacked launch (identical
        # statements dedup via subsumption FIRST — the tiers compose)
        vector = (
            isinstance(root, VectorTopNNode)
            and executor.allow_host_sync
            and binding.vector_batching()
        )
        sub = self._subsume_enter(binding, executor, root)
        if sub is None and not batchable and not vector:
            return None
        skey = flight = None
        if sub is not None:
            skey, flight, winner = sub
            if not winner:
                ok = flight.event.wait(LANE_WAIT_SECS)
                if ok and flight.error is None and flight.rel is not None:
                    self.subsumed += 1
                    _counter("trino_tpu_subsumed_fragments_total").inc()
                    RECORDER.instant(
                        "fragment_subsumed", "batch", key=skey[0][:16]
                    )
                    return flight.rel
                # dead/failed winner: compute ourselves, holding no flight
                skey = flight = None
        try:
            rel = self._execute_item(binding, executor, root, batchable,
                                     vector)
        except BaseException as e:
            if flight is not None:
                flight.error = e
                self._subsume_exit(skey, flight)
                flight = None
            raise
        if flight is not None:
            flight.rel = rel
            self._subsume_exit(skey, flight)
        return rel

    def _subsume_enter(self, binding: "BatchBinding", executor, root):
        """-> (skey, flight, is_winner) or None when this subtree cannot
        subsume: a leaf that is not a versioned-shareable table scan, a
        nondeterministic expression (two executions may legitimately
        differ), or no fingerprint. The pre-pass resolves every leaf scan
        through shared-scan elimination — page IDENTITY is the data half
        of the key (versioned keys make equal pages imply equal data)."""
        from ..planner.plan import TableScanNode
        from .cachestore import profile_plan, session_props_key

        leaves: List = []

        def walk(n):
            if not n.sources:
                leaves.append(n)
                return
            for s in n.sources:
                walk(s)

        walk(root)
        if not leaves or not all(
            isinstance(l, TableScanNode) for l in leaves
        ):
            return None
        if any(self._scan_key(binding, l) is None for l in leaves):
            return None
        profile = self._memo("profile", root, profile_plan)
        if not profile.fingerprint or not profile.cache_safe:
            return None
        inner = executor._exec_TableScanNode
        pages = [
            self.shared_scan(binding, executor, leaf, inner).page
            for leaf in leaves
        ]
        skey = (
            profile.fingerprint, tuple(id(p) for p in pages),
            session_props_key(binding.session), binding.registry,
        )
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            flight = self._subsume.get(skey)
            if flight is not None and flight.done and (
                flight.error is not None
                or now - flight.completed_at > SUBSUME_LINGER_SECS
            ):
                del self._subsume[skey]
                flight = None
            if flight is None:
                flight = self._subsume[skey] = _SubsumeFlight()
                flight.pins = tuple(pages)
                self._subsume.move_to_end(skey)
                while len(self._subsume) > SUBSUME_MAX_ENTRIES:
                    old_key, old = next(iter(self._subsume.items()))
                    if not old.done:  # never evict an in-flight winner
                        break
                    del self._subsume[old_key]
                return skey, flight, True
            self._subsume.move_to_end(skey)
            return skey, flight, False

    def _sweep_locked(self, now: float) -> None:
        """Reclaim EVERY expired done entry (device pages / pinned result
        Relations must not sit in HBM waiting for a same-key re-access
        that may never come). Called under _lock from the entry points;
        both maps are small by construction, so the walk is cheap."""
        for k in [
            k for k, e in self._scans.items()
            if e.done and (
                e.error is not None
                or now - e.created > SHARED_SCAN_TTL_SECS
            )
        ]:
            del self._scans[k]
        for k in [
            k for k, f in self._subsume.items()
            if f.done and (
                f.error is not None
                or now - f.completed_at > SUBSUME_LINGER_SECS
            )
        ]:
            del self._subsume[k]
        for k in [
            k for k, f in self._plans.items()
            if f.done and (
                f.error is not None
                or now - f.completed_at > SUBSUME_LINGER_SECS
            )
        ]:
            del self._plans[k]

    def _memo(self, tag: str, node, fn):
        """Bounded per-node-identity memo (the entry pins the node, so a
        recycled id can never serve a stale value)."""
        key = (tag, id(node))
        with self._lock:
            hit = self._node_memo.get(key)
            if hit is not None and hit[0] is node:
                self._node_memo.move_to_end(key)
                return hit[1]
        val = fn(node)
        with self._lock:
            self._node_memo[key] = (node, val)
            self._node_memo.move_to_end(key)
            while len(self._node_memo) > 512:
                self._node_memo.popitem(last=False)
        return val

    def _subsume_exit(self, skey, flight: _SubsumeFlight) -> None:
        with self._lock:
            flight.done = True
            flight.completed_at = time.monotonic()
            if flight.error is not None and self._subsume.get(skey) is flight:
                # failed flights never linger (the next arrival recomputes)
                del self._subsume[skey]
        flight.event.set()

    # ------------------------------------------------------------ plan flights

    def plan_flight(self, key: Tuple, compute):
        """Single-flight planning for identical concurrent statements: the
        wave-of-16 thundering herd parses/plans/optimizes ONCE; everyone
        else rides the winner's frozen plan (plans are immutable — the plan
        cache already serves one object to concurrent executions). Same
        continuous-batching linger as subtree subsumption; the CALLER gates
        on the plan tier's correctness rules (nondeterministic text,
        history_based_stats, open transactions)."""
        now = time.monotonic()
        with self._lock:
            flight = self._plans.get(key)
            if flight is not None and flight.done and (
                flight.error is not None
                or now - flight.completed_at > SUBSUME_LINGER_SECS
            ):
                del self._plans[key]
                flight = None
            if flight is None:
                flight = self._plans[key] = _SubsumeFlight()
                self._plans.move_to_end(key)
                while len(self._plans) > SUBSUME_MAX_ENTRIES:
                    ok, old = next(iter(self._plans.items()))
                    if not old.done:
                        break
                    del self._plans[ok]
                winner = True
            else:
                self._plans.move_to_end(key)
                winner = False
        if not winner:
            if flight.event.wait(LANE_WAIT_SECS) and flight.error is None \
                    and flight.rel is not None:
                self.plans_shared += 1
                return flight.rel
            return compute()  # dead/failed winner: plan it ourselves
        try:
            plan = compute()
        except BaseException as e:
            with self._lock:
                flight.error = e
                flight.done = True
                flight.completed_at = time.monotonic()
                if self._plans.get(key) is flight:
                    del self._plans[key]
            flight.event.set()
            raise
        with self._lock:
            flight.rel = plan
            flight.done = True
            flight.completed_at = time.monotonic()
        flight.event.set()
        return plan

    def _execute_item(self, binding: "BatchBinding", executor, root,
                      batchable: bool, vector: bool = False):
        """One work item past subsumption: the lane/group machinery for
        traceable chains, the vector serving tier for VectorTopN roots,
        plain serial execution otherwise."""
        from .observability import RECORDER

        if vector:
            return self._execute_vector_item(binding, executor, root)
        if not batchable:
            rel = executor._eval_node(root)
            # _eval_node booked the root (and children) already — tell the
            # eval() hook not to book it a second time
            executor._batch_root_booked = root
            return rel
        chain, input_node = _split_chain(root)
        # the input subtree evaluates through the OWNING executor — scans
        # get shared-scan elimination, remote sources read their staged
        # pages, and per-node stats/actuals below the chain stay exact
        rel = executor.eval(input_node)
        if not _domains_resolvable(rel, root):
            # grouped agg without small static domains: finish serially on
            # the exact serial path (aggregate_relation, host syncs and
            # all) — bit-identical by construction
            return self._run_serial_chain(executor, rel, chain, count=True)
        from .plancodec import fingerprint

        fp = self._memo("fp", root, fingerprint)
        if not fp:
            return self._run_serial_chain(executor, rel, chain, count=True)
        # NOTE: the partition scope is deliberately NOT in the batch key —
        # lanes carry their own input data, so partition p and p' of one
        # fragment (same program, different splits) are exactly the ragged
        # case that should pack. The scope DOES key shared scans below.
        key = (fp, binding.registry, _layout_sig(rel.page))
        lane = _Lane(
            key=key, rel=rel, chain=chain, types=dict(executor.types),
            priority=binding.priority(),
        )
        max_lanes = binding.max_lanes()
        with self._lock:
            g = self._pending.get(key)
            if g is not None and not g.closed and len(g.lanes) < max_lanes:
                g.lanes.append(lane)
                leader = False
            else:
                g = _Group(key)
                g.lanes.append(lane)
                self._pending[key] = g
                leader = True
        if leader:
            try:
                with RECORDER.span(
                    "batch_admit", "batch", key=key[0][:16]
                ) as sp:
                    # hold admission open so concurrent compatible items
                    # pack (pointless when the knob caps groups at one)
                    window = binding.admit_window_secs()
                    if window > 0 and max_lanes > 1:
                        time.sleep(window)
                    with self._lock:
                        g.closed = True
                        if self._pending.get(key) is g:
                            del self._pending[key]
                    sp["lanes"] = len(g.lanes)
                self._run_group(g)
            except BaseException:
                # an interrupted leader must not strand its group: close
                # it, wake every unserved lane onto the serial fallback
                with self._lock:
                    g.closed = True
                    if self._pending.get(key) is g:
                        del self._pending[key]
                for l in g.lanes:
                    if l.result is None and l.error is None:
                        l.fallback = True
                    l.event.set()
                raise
        else:
            lane.event.wait(LANE_WAIT_SECS)
        if lane.error is not None:
            raise lane.error
        if lane.result is None or lane.fallback:
            # leader died/hung or the batched launch failed: only lanes
            # that ALSO fail on their own serial run may fail
            return self._run_serial_chain(
                executor, lane.rel, lane.chain, count=True
            )
        return lane.result

    def _run_serial_chain(self, executor, rel, chain, count: bool):
        """The serial tail of a submitted item: the same per-operator
        programs _eval_node would dispatch, minus per-node bookkeeping
        (the caller books the root — the fragment-cache-hit convention)."""
        return self._serial_chain(
            rel, chain, executor.types, executor._pallas_mode(), count
        )

    @staticmethod
    def _serial_chain(rel, chain, types, pallas_mode: str, count: bool):
        from ..planner.plan import AggregationNode
        from .executor import aggregate_relation

        for node in chain:
            if isinstance(node, AggregationNode):
                rel = aggregate_relation(rel, node, types, pallas_mode)
            else:
                rel = _apply_chain_node(rel, node, types)
            if count:
                on_program_launch()
        return rel

    # ------------------------------------------------------- vector serving

    def _execute_vector_item(self, binding: "BatchBinding", executor, root):
        """Vector serving tier: one VectorTopN work item. Eligible items
        (a constant-query similarity score, or broadcast embedding-JOIN
        provenance) group under the MASKED plan fingerprint — the plan with
        the lead score's query constant blanked to NULL — plus the input
        layout signature and session key, linger for the admit window like
        the agg tier, and launch as ONE statically-unrolled device program
        (executor._jit_vector_topn_lanes) whose per-lane closures keep each
        lane's OWN query constant. Lanes are NEVER deduplicated by input
        page identity here — identical pages with different query constants
        are exactly the case being batched (identical whole statements
        already collapsed in the subsumption tier above). Ineligible shapes
        run the plain fused serial program."""
        from ..ops import tensor as T
        from .cachestore import session_props_key
        from .executor import _maybe_compact
        from .observability import RECORDER
        from .plancodec import fingerprint

        rel = executor.eval(root.source)
        if executor.allow_host_sync:
            rel = _maybe_compact(rel)
        bsyms = getattr(rel.page, "_vector_broadcast", None) or frozenset()
        fp = None
        plan = T.vector_batch_masked_node(root, bsyms)
        if plan is not None:
            masked, kind = plan
            if kind == "bcast":
                self.vector_broadcast_routes += 1
                RECORDER.instant("vector_broadcast_route", "batch")
            fp = fingerprint(masked) or None
        if fp is None:
            # not a stackable lane: the one fused serial program (the root's
            # launch books here; eval() still accounts the root normally)
            on_program_launch()
            return executor.run_vector_topn(root, rel)
        key = (
            "vec", fp, binding.registry,
            session_props_key(binding.session), _layout_sig(rel.page),
        )
        lane = _Lane(
            key=key, rel=rel, chain=[root], types=dict(executor.types),
            priority=binding.priority(),
        )
        max_lanes = binding.max_lanes()
        with self._lock:
            g = self._pending.get(key)
            if g is not None and not g.closed and len(g.lanes) < max_lanes:
                g.lanes.append(lane)
                leader = False
            else:
                g = _Group(key)
                g.lanes.append(lane)
                self._pending[key] = g
                leader = True
        if leader:
            try:
                with RECORDER.span(
                    "batch_admit", "batch", key=fp[:16]
                ) as sp:
                    window = binding.admit_window_secs()
                    if window > 0 and max_lanes > 1:
                        time.sleep(window)
                    with self._lock:
                        g.closed = True
                        if self._pending.get(key) is g:
                            del self._pending[key]
                    sp["lanes"] = len(g.lanes)
                self._run_vector_group(g)
            except BaseException:
                with self._lock:
                    g.closed = True
                    if self._pending.get(key) is g:
                        del self._pending[key]
                for l in g.lanes:
                    if l.result is None and l.error is None:
                        l.fallback = True
                    l.event.set()
                raise
        else:
            lane.event.wait(LANE_WAIT_SECS)
        if lane.error is not None:
            raise lane.error
        if lane.result is None or lane.fallback:
            # leader died/hung or the batched launch failed: per-lane fused
            # serial fallback — only a lane that ALSO fails on its own run
            # may fail, and it computes the same bytes it would have batched
            on_program_launch()
            return executor.run_vector_topn(root, lane.rel)
        return lane.result

    def _run_vector_group(self, group: _Group) -> None:
        """Leader-side vector launch: compile every lane's OWN assignments
        (each compiled closure closes over that lane's query constant — the
        trace-time-constant environment the serial program folds), run the
        statically-unrolled batched program ONCE under the launch gate, and
        demux per-lane result pages. Never raises — a failure flips the
        whole group onto the per-lane fused-serial fallback."""
        from ..ops import tensor as T
        from ..ops.compiler import compile_expression
        from .executor import Relation, _jit_vector_topn_lanes

        lanes = group.lanes
        try:
            priority = max(l.priority for l in lanes)
            _occupancy_histogram().observe(len(lanes))
            specs, envs, pages = [], [], []
            dim = 0
            for lane in lanes:
                node = lane.chain[0]
                layout = lane.rel.layout()
                compiled = []
                for sym, expr in node.assignments:
                    fn, out_dict = compile_expression(
                        expr, layout, lane.rel.capacity
                    )
                    type_ = lane.types.get(sym) or expr.type
                    compiled.append((fn, type_, out_dict))
                specs.append((
                    tuple(compiled),
                    tuple(s for s, _ in node.assignments),
                    node.orderings, node.count,
                ))
                envs.append(lane.rel.env())
                pages.append(lane.rel.page)
                info = T.assignments_vector_info(node.assignments)
                if info:
                    dim = max(dim, info[1])
            packed_rows = sum(l.rel.capacity for l in lanes)
            with T.vector_batch_launch_span(
                len(lanes), packed_rows, dim, lanes[0].chain[0].count
            ):
                self.gate.acquire(priority)
                try:
                    out = _jit_vector_topn_lanes(
                        tuple(specs), tuple(envs), tuple(pages)
                    )
                finally:
                    self.gate.release()
                self.vector_batched_launches += 1
                on_program_launch()
            T.on_vector_kernel()
            T.on_vector_batched(len(lanes))
            if len(lanes) > 1:
                _counter("trino_tpu_batched_fragments_total").inc(len(lanes))
            for lane, page in zip(lanes, out):
                node = lane.chain[0]
                lane.result = Relation(
                    page, tuple(s for s, _ in node.assignments)
                )
        except BaseException:
            for lane in lanes:
                lane.fallback = True
        finally:
            for lane in lanes:
                lane.event.set()

    def _run_group(self, group: _Group) -> None:
        """Leader-side: dedup lanes by input page identity, launch once,
        demux, wake every lane. Never raises — failures either land on the
        whole group's fallback flag (lanes self-serve serially) or on a
        single lane's error."""
        from .observability import RECORDER

        lanes = group.lanes
        try:
            unique: "OrderedDict[int, List[_Lane]]" = OrderedDict()
            for lane in lanes:
                unique.setdefault(id(lane.rel.page), []).append(lane)
            reps = [ls[0] for ls in unique.values()]
            # the group launches at its HIGHEST lane's priority: a
            # high-weight joiner must not queue at its low-weight
            # leader's rank
            priority = max(l.priority for l in lanes)
            _occupancy_histogram().observe(len(lanes))
            if len(lanes) > 1:
                _counter("trino_tpu_batched_fragments_total").inc(len(lanes))
            if len(reps) == 1:
                # one unique input (shared scans collapse identical
                # queries here): run the exact serial programs once and
                # fan the immutable result out to every lane
                rep = reps[0]
                with RECORDER.span("batch_launch", "batch") as sp:
                    self.gate.acquire(priority)
                    try:
                        result = self._launch_single(rep)
                    finally:
                        self.gate.release()
                    sp["lanes"] = len(lanes)
                    sp["unique_lanes"] = 1
                    sp["packed_rows"] = rep.rel.capacity
                    sp["key"] = group.key[0][:16]
                with RECORDER.span("batch_demux", "batch", lanes=len(lanes)):
                    for lane in lanes:
                        lane.result = result
                return
            self._launch_ragged(group, reps, unique, priority)
        except BaseException:
            for lane in lanes:
                lane.fallback = True
        finally:
            for lane in lanes:
                lane.event.set()

    def _launch_single(self, lane: _Lane):
        # batchable chains pre-check pallas to the "off" resolution, so the
        # shared serial walk is exactly the owning executor's computation
        self.single_launches += 1
        return self._serial_chain(
            lane.rel, lane.chain, lane.types, "off", count=True
        )

    def _launch_ragged(self, group, reps: List[_Lane], unique,
                       priority: float = 1.0) -> None:
        """>= 2 distinct inputs sharing a program: stack along a new lane
        dim (ragged row counts ride the active masks), ONE vmapped launch,
        slice per-lane outputs back out."""
        import jax
        import jax.numpy as jnp

        from .executor import Relation
        from .observability import RECORDER

        template = reps[0]
        pages = [self._normalize_page(l.rel.page, template.rel.page)
                 for l in reps]
        n = len(pages)
        # pad the lane dim to a power of two so the compiled batch shapes
        # stay a small set (padding lanes repeat lane 0 with a dead mask
        # and are never demuxed)
        padded = 1
        while padded < n:
            padded *= 2
        if padded > n:
            dead = jax.tree_util.tree_map(
                lambda a: jnp.zeros_like(a), pages[0]
            )
            pages = pages + [dead] * (padded - n)
        fn_key = (group.key, padded)
        with self._lock:
            fn = self._fn_cache.get(fn_key)
        if fn is None:
            chain, types = template.chain, template.types
            symbols = template.rel.symbols
            sorted_by = template.rel.sorted_by

            def lane_fn(page):
                rel = Relation(page, symbols, sorted_by)
                for node in chain:
                    rel = _apply_chain_node(rel, node, types)
                return rel.page

            from . import kernelcost

            fn = kernelcost.jit(
                jax.vmap(lane_fn), label="ragged_batch_lanes"
            )
            with self._lock:
                self._fn_cache[fn_key] = fn
                # runaway guard: distinct (key, width) programs are few by
                # construction; a blown cache means keys are unstable
                while len(self._fn_cache) > 256:
                    self._fn_cache.pop(next(iter(self._fn_cache)))
        packed_rows = sum(l.rel.capacity for l in reps)
        with RECORDER.span("batch_launch", "batch") as sp:
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *pages
            )
            self.gate.acquire(priority)
            try:
                out = fn(stacked)
            finally:
                self.gate.release()
            self.batched_launches += 1
            on_program_launch()
            sp["lanes"] = len(group.lanes)
            sp["unique_lanes"] = n
            sp["packed_rows"] = packed_rows
            sp["key"] = group.key[0][:16]
        out_symbols = self._chain_output_symbols(template)
        with RECORDER.span("batch_demux", "batch", lanes=len(group.lanes)):
            for i, lanes in enumerate(unique.values()):
                lane_page = jax.tree_util.tree_map(lambda a, i=i: a[i], out)
                rel = Relation(lane_page, out_symbols)
                for lane in lanes:
                    lane.result = rel

    @staticmethod
    def _chain_output_symbols(lane: _Lane) -> Tuple[str, ...]:
        root = lane.chain[-1]
        return tuple(root.group_keys) + tuple(
            s for s, _ in root.aggregations
        )

    @staticmethod
    def _normalize_page(page, template):
        """Re-attach the template lane's dictionary objects (equal content
        by key construction) so the stacked pytree has ONE aux treedef."""
        from ..spi.page import Column, Page

        def norm(c, t):
            return Column(
                c.type, c.data, c.valid, t.dictionary, c.lengths,
                c.elem_valid,
                tuple(norm(k, tk) for k, tk in zip(c.children, t.children)),
            )

        if page is template:
            return page
        return Page(
            tuple(norm(c, t) for c, t in zip(page.columns, template.columns)),
            page.active,
        )

    # ---------------------------------------------------------- shared scans

    def shared_scan(self, binding: "BatchBinding", executor, node, inner):
        """Single-flight overlapping-scan dedup: the first query to need a
        (table, conjuncts, columns, version, partition-scope) scan executes
        it; concurrent (and briefly subsequent) queries reuse the immutable
        device pages. Unkeyable or unversioned scans execute normally."""
        key = self._scan_key(binding, node)
        if key is None:
            self.scan_executions += 1
            _counter("trino_tpu_shared_scan_misses_total").inc()
            on_program_launch()
            return inner(node)
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            entry = self._scans.get(key)
            if entry is None:
                import weakref

                entry = _ScanEntry(
                    event=threading.Event(), created=now,
                    winner_ref=(
                        weakref.ref(executor) if executor is not None
                        else None
                    ),
                )
                self._scans[key] = entry
                self._scans.move_to_end(key)
                while len(self._scans) > SHARED_SCAN_MAX_ENTRIES:
                    self._scans.popitem(last=False)
                winner = True
            else:
                self._scans.move_to_end(key)
                winner = False
        if winner:
            try:
                rel = inner(node)
                entry.page = rel.page
                entry.assignments = tuple(node.assignments)
                # sorted_by published as COLUMN names: symbol spaces differ
                # across the queries that share this scan
                sym_to_col = dict(node.assignments)
                entry.sorted_cols = tuple(
                    sym_to_col[s] for s in rel.sorted_by
                )
            except BaseException as e:
                entry.error = e
                raise
            finally:
                entry.done = True
                entry.event.set()
            self.scan_executions += 1
            _counter("trino_tpu_shared_scan_misses_total").inc()
            on_program_launch()
            return rel
        if not entry.event.wait(LANE_WAIT_SECS) or entry.error is not None:
            # hung or failed winner: self-serve (and let the entry expire)
            self.scan_executions += 1
            _counter("trino_tpu_shared_scan_misses_total").inc()
            on_program_launch()
            return inner(node)
        return self._rebind_scan(executor, node, entry)

    def _rebind_scan(self, executor, node, entry: _ScanEntry):
        """A shared page re-expressed in THIS query's symbol space."""
        from .executor import Relation
        from .observability import RECORDER

        winner = entry.winner_ref() if entry.winner_ref is not None else None
        if winner is None or winner is not executor:
            # a genuine cross-query share — the winner re-reading the entry
            # it just produced (subsume pre-pass, then the real eval) is
            # just avoiding a redundant scan, not eliminating anyone else's
            self.scan_shares += 1
            _counter("trino_tpu_shared_scan_hits_total").inc()
            RECORDER.instant(
                "shared_scan_hit", "batch",
                table=str(node.table.schema_table),
            )
        col_to_sym = {c: s for s, c in node.assignments}
        symbols = tuple(s for s, _ in node.assignments)
        sorted_by = []
        for col in entry.sorted_cols:
            sym = col_to_sym.get(col)
            if sym is None:
                break
            sorted_by.append(sym)
        return Relation(entry.page, symbols, tuple(sorted_by))

    def _scan_key(self, binding: "BatchBinding", node) -> Optional[Tuple]:
        from .cachestore import BYPASS, table_version
        from .statstore import leaf_key_for

        leaf = self._memo("leaf", node, leaf_key_for)
        if leaf is None:
            return None
        h = node.table
        # a time-travel pin (FOR VERSION) reads a snapshot the leaf key
        # knows nothing about — it MUST key separately from a current-
        # version scan of the same table/conjuncts (the result cache's
        # profile_plan extracts the same pin)
        pinned = None
        ch = h.connector_handle
        if isinstance(ch, dict) and "snapshot_id" in ch:
            pinned = str(ch["snapshot_id"])
        version = table_version(
            binding.metadata, h.catalog, h.schema_table.schema,
            h.schema_table.table, pinned,
        )
        if version is None or version == BYPASS:
            # unversioned: equal keys would not imply equal data across a
            # linger window; bypass rather than risk a stale share
            return None
        return (
            binding.registry, binding.scope, leaf, version,
            tuple(c for _, c in node.assignments),
        )

    # --------------------------------------------------------------- testing

    def reset_stats(self) -> None:
        with self._lock:
            self.batched_launches = 0
            self.single_launches = 0
            self.scan_executions = 0
            self.scan_shares = 0
            self.subsumed = 0
            self.plans_shared = 0
            self.vector_batched_launches = 0
            self.vector_broadcast_routes = 0
            self._scans.clear()
            # drop only COMPLETED lingering flights: an in-flight winner's
            # ticket must survive a concurrent stats reset
            for k in [k for k, f in self._subsume.items() if f.done]:
                del self._subsume[k]
            for k in [k for k, f in self._plans.items() if f.done]:
                del self._plans[k]


@dataclass
class BatchBinding:
    """What a PlanExecutor needs to route work through the scheduler:
    resolution context plus the partition scope (partition p of n scans
    different splits than p' of n' — lanes and shared scans must never
    alias across partitions), mirroring cachestore.FragmentBinding."""

    scheduler: DeviceScheduler
    metadata: Any
    session: Any
    scope: str = ""
    # CatalogManager.cache_nonce of the owning runner: same-named catalogs
    # in two runners may hold different data
    registry: str = ""

    def execute(self, executor, node):
        return self.scheduler.execute(self, executor, node)

    def shared_scan(self, executor, node, inner):
        return self.scheduler.shared_scan(self, executor, node, inner)

    def priority(self) -> float:
        return current_priority()

    def vector_batching(self) -> bool:
        try:
            return bool(self.session.get("vector_query_batching"))
        except KeyError:
            return False

    def max_lanes(self) -> int:
        try:
            return max(1, int(self.session.get("batch_max_lanes") or 1))
        except KeyError:
            return 8

    def admit_window_secs(self) -> float:
        try:
            return max(
                0.0, float(self.session.get("batch_admit_window_ms") or 0)
            ) / 1000.0
        except KeyError:
            return 0.002


def register_metrics() -> None:
    """Eagerly register every batching metric family with its HELP text:
    exposition (and the smoke's HELP lint) must see the families before
    the first event of each kind happens to occur — a burst that dedups
    purely by subsumption would otherwise never register the lane-packing
    counters."""
    for name in (
        "trino_tpu_device_programs_total",
        "trino_tpu_batched_fragments_total",
        "trino_tpu_subsumed_fragments_total",
        "trino_tpu_shared_scan_hits_total",
        "trino_tpu_shared_scan_misses_total",
    ):
        _counter(name)
    _occupancy_histogram()
    from ..ops.tensor import register_vector_serving_metrics

    register_vector_serving_metrics()


def attach(executor, metadata, session, catalogs=None, scope: str = "") -> None:
    """Install a BatchBinding on ``executor`` when the ``device_batching``
    knob is on (the one call every entry point makes; off = no attribute,
    byte-identical path)."""
    try:
        enabled = bool(session.get("device_batching"))
    except KeyError:
        enabled = False
    if not enabled:
        return
    register_metrics()
    executor.device_batching = BatchBinding(
        SCHEDULER, metadata, session, scope=scope,
        registry=getattr(catalogs, "cache_nonce", "") if catalogs else "",
    )


def launch_slot(enabled: bool = True):
    """Admission-gate slot for NON-batchable launches that should still
    yield between programs (the OOC unit loop): a context manager holding
    the gate at this thread's priority. ``enabled=False`` is a no-op so
    call sites stay one-liners."""
    import contextlib

    if not enabled:
        return contextlib.nullcontext()
    return SCHEDULER.gate


SCHEDULER = DeviceScheduler()
