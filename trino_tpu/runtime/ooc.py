"""Out-of-core execution for ARBITRARY fragment trees — joins included.

Round-4 verdict: `runtime/streaming.py` streams exactly one plan shape
(scan -> filter/project -> one aggregation), so no join had ever executed
above SF1. The reference streams *any* operator pipeline over
larger-than-memory data (operator/Driver.java:372 page pull;
operator/join/spilling/HashBuilderOperator.java:68 partitioned spill state
machine; SpillableHashAggregationBuilder). This module is the TPU-first
generalization: the distributed fragmenter's stage cut IS the out-of-core
execution plan, run on ONE chip with a disk-spillable host bucket store as
the exchange:

- `add_exchanges` + `create_fragments` (planner/fragmenter.py) already cut
  the plan at repartition boundaries and split aggregations into
  partial/final — exactly the decomposition grace hash join / partitioned
  aggregation needs. Nothing is re-derived here.
- A producer fragment never materializes its output: each execution unit's
  output page is fetched, hash-bucketed on host (the SAME value-stable rule
  the DCN exchange uses, parallel/runner.host_partition_targets), and
  appended to a `BucketStore` that overflows to disk beyond a byte budget
  (parallel LZ4 spill files, spi/host_pages.write_arrays_lz4).
- SOURCE fragments iterate scan splits in BATCHES of K splits per device
  dispatch; batch N+1 is decoded/assembled on the shared host-I/O pool
  (runtime/spiller.io_pool) while batch N's program runs, so datagen/decode
  no longer serializes with dispatch.
- FIXED_HASH fragments run bucket-at-a-time: every input edge of bucket b
  is co-partitioned by construction, so join build+probe and final
  aggregation see complete key groups. Device memory is bounded by
  (1 + prefetch_depth) buckets' padded inputs, not the table —
  double buffering trades one extra staged bucket of HBM for the overlap;
  prefetch_depth=0 restores the strict single-bucket bound. The loop is
  PIPELINED: a
  `_BucketPrefetcher` reads/decompresses the next buckets' partitions and
  starts their host->device transfers (double buffering via
  `jax.device_put`) under a bounded in-flight byte budget while the current
  bucket's program runs — the device never waits on host I/O unless the
  budget forces it ("Query Processing on Tensor Computation Runtimes",
  arxiv 2203.01877 overlap discipline).
- SINGLE fragments (query tails: final TopN/sort/output) gather the tiny
  upstream results and run once.

Static-shape discipline + compile reuse: bucket inputs are padded to a
SMALL set of canonical shape classes (4x-spaced capacities, `_shape_class`)
instead of per-bucket power-of-two sizes, so the whole bucket loop pays one
XLA compile per class instead of one per distinct bucket size. Inside each
unit program the PER-STAGE capacities narrow adaptively (the
runtime/adaptive machinery applied per fragment): the first unit runs at
full capacity recording per-stage actual row counts, every later unit runs
the TUNED program — join outputs and aggregations sized by measured
cardinality instead of the padded input capacity (a Q3-class scan unit's
partial aggregation over the join output is ~10x cheaper compacted). The
tuned vector is persisted per fragment fingerprint (runtime/capstore), so
repeat runs skip the tuning compile entirely (the Q18 `tune_secs: 655`
pathology).

Unsupported (falls back to in-core or partitioned-spill paths):
REPARTITION_RANGE (out-of-core distributed sort), cross joins (two scans in
one fragment), nested-lane columns crossing an exchange.
"""

from __future__ import annotations

import os
import tempfile
import time
from collections import deque
from dataclasses import replace as _dc_replace
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metadata import Metadata, Session
from ..planner.fragmenter import (
    Partitioning,
    PlanFragment,
    RemoteSourceNode,
    SubPlan,
    add_exchanges,
    create_fragments,
)
import jax

from ..planner.plan import (
    ExchangeType,
    LogicalPlan,
    OutputNode,
    PlanNode,
    TableScanNode,
    visit_plan,
)
from ..spi.host_pages import read_arrays_lz4, write_arrays_lz4
from ..spi.page import Page
from ..parallel.runner import (
    _FragmentExecutor,
    _page_from_host_chunks,
    _page_to_host,
    empty_page_for,
    host_partition_targets,
    run_fragment_partition,
    scan_sources,
)
from . import capstore
from . import kernelcost
from . import observability as obs
from .adaptive import _AdaptiveTracedExecutor, candidate_nodes
from .executor import ExecutionError, Relation, _concat_pages, _round_capacity
from .observability import RECORDER
from .spiller import io_pool
from .traced import is_traceable
from .tracing import TRACER

HostChunk = List[Tuple]  # [(type, data, valid, dictionary), ...] per column


class OutOfCoreUnsupported(ExecutionError):
    pass


def _chunk_bytes(cols: HostChunk) -> int:
    return sum(d.nbytes + v.nbytes for _, d, v, _ in cols)


def _shape_class(n: int, base: int = 1024) -> int:
    """Canonical capacity class: 4x-spaced (1024, 4096, 16384, ...) instead
    of per-bucket powers of two. Varying bucket sizes collapse into a
    handful of classes, so the bucket loop compiles once per CLASS — at the
    cost of <=4x padding on the smallest buckets of a class. Delegates to
    capstore.capacity_class: the OOC bucket loop and the device-batching
    plane's batch keys must agree on class edges (see the boundary
    contract there)."""
    from .capstore import capacity_class

    return capacity_class(n, base)


class _DiskChunk:
    """One spilled chunk: data/valid arrays in an LZ4 spill file
    (spi/host_pages.write_arrays_lz4 — per-array frames compress/decompress
    in parallel on the shared I/O pool), types + dictionaries (tiny,
    code-table objects) retained in memory."""

    __slots__ = ("path", "types", "dicts", "nbytes", "rows")

    def __init__(self, path: str, cols: HostChunk, pool=None):
        self.path = path
        self.types = [c[0] for c in cols]
        self.dicts = [c[3] for c in cols]
        self.nbytes = _chunk_bytes(cols)
        self.rows = len(cols[0][1]) if cols else 0
        write_arrays_lz4(
            path, [c[1] for c in cols] + [c[2] for c in cols], pool=pool
        )

    def load(self, pool=None) -> HostChunk:
        arrs = read_arrays_lz4(self.path, pool=pool)
        k = len(self.types)
        return [
            (tp, arrs[i], arrs[k + i], dc)
            for i, (tp, dc) in enumerate(zip(self.types, self.dicts))
        ]


class BucketStore:
    """P-bucket columnar chunk store for one exchange edge: memory-first,
    newest chunks spill to disk once the in-memory byte budget is exceeded
    (the reference's FileSystemExchangeSink role, played by local disk;
    plugin/trino-exchange-filesystem/.../FileSystemExchangeSink.java)."""

    def __init__(self, n_buckets: int, budget_bytes: int, spool_dir: str, tag: str):
        self.n_buckets = n_buckets
        self.budget_bytes = budget_bytes
        self.spool_dir = spool_dir
        self.tag = tag
        self.chunks: List[List[object]] = [[] for _ in range(n_buckets)]
        self.mem_bytes = 0
        self.spilled_bytes = 0
        self._bucket_bytes = [0] * n_buckets
        self._seq = 0

    def append(self, bucket: int, cols: HostChunk, pool=None) -> None:
        if not cols or len(cols[0][1]) == 0:
            return
        size = _chunk_bytes(cols)
        self._bucket_bytes[bucket] += size
        if self.mem_bytes + size > self.budget_bytes:
            path = os.path.join(self.spool_dir, f"{self.tag}-{bucket}-{self._seq}.lz4")
            self._seq += 1
            with RECORDER.span("spill_write", "spill", tag=self.tag,
                               bucket=bucket, bytes=size):
                self.chunks[bucket].append(_DiskChunk(path, cols, pool=pool))
            obs.on_spill_write(size, event=False)
            self.spilled_bytes += size
        else:
            self.chunks[bucket].append(cols)
            self.mem_bytes += size

    def rows_of(self, bucket: int) -> int:
        total = 0
        for c in self.chunks[bucket]:
            total += c.rows if isinstance(c, _DiskChunk) else len(c[0][1])
        return total

    def bucket_nbytes(self, bucket: int) -> int:
        """Uncompressed bytes appended to ``bucket`` (the prefetcher's
        in-flight budget accounting)."""
        return self._bucket_bytes[bucket]

    def read(self, bucket: int, pool=None) -> List[HostChunk]:
        out: List[HostChunk] = []
        for c in self.chunks[bucket]:
            if isinstance(c, _DiskChunk):
                with RECORDER.span("spill_read", "spill", tag=self.tag,
                                   bucket=bucket, bytes=c.nbytes):
                    out.append(c.load(pool=pool))
                obs.on_spill_read(c.nbytes, event=False)
            else:
                out.append(c)
        return out

    def read_all(self, pool=None) -> List[HostChunk]:
        out: List[HostChunk] = []
        for b in range(self.n_buckets):
            out.extend(self.read(b, pool=pool))
        return out

    def drop(self) -> None:
        for lst in self.chunks:
            for c in lst:
                if isinstance(c, _DiskChunk):
                    try:
                        os.unlink(c.path)
                    except OSError:
                        pass
        self.chunks = [[] for _ in range(self.n_buckets)]
        self.mem_bytes = 0


def _split_chunk_by_targets(
    cols: HostChunk, targets: np.ndarray, n: int
) -> List[Optional[HostChunk]]:
    """One stable argsort + slicing instead of n boolean scans."""
    order = np.argsort(targets, kind="stable")
    sorted_t = targets[order]
    bounds = np.searchsorted(sorted_t, np.arange(n + 1))
    gathered = [(tp, d[order], v[order], dc) for tp, d, v, dc in cols]
    out: List[Optional[HostChunk]] = []
    for b in range(n):
        lo, hi = bounds[b], bounds[b + 1]
        if lo == hi:
            out.append(None)
            continue
        out.append([(tp, d[lo:hi], v[lo:hi], dc) for tp, d, v, dc in gathered])
    return out


_empty_page = empty_page_for


class _OOCFragmentExecutor(_FragmentExecutor):
    """Fragment executor whose table scans read a pre-assembled split-batch
    page instead of loading the whole table."""

    def __init__(self, plan, metadata, session, staged, scan_pages: Dict[int, Page]):
        super().__init__(plan, metadata, session, staged, partition=0, n_workers=1)
        self._scan_pages = scan_pages

    def _exec_TableScanNode(self, node: TableScanNode) -> Relation:
        page = self._scan_pages.get(id(node))
        if page is None:
            return super()._exec_TableScanNode(node)
        symbols = tuple(s for s, _ in node.assignments)
        return Relation(page, symbols)


class _AdaptiveUnitExecutor(_AdaptiveTracedExecutor):
    """Traced executor for ONE fragment execution unit: scans AND remote
    sources fed as page arguments, per-stage capacities narrowed to hints
    with (overflow, actual) recording — runtime/adaptive applied inside the
    out-of-core unit program. The whole unit is one XLA program — one
    device dispatch per split batch / bucket, which is what makes the
    out-of-core tier viable through a remote-TPU tunnel (per-operator
    dispatch pays a tunnel round-trip per op; round 3 measured 15.8 s
    wallclock Q3 that way)."""

    def __init__(
        self, plan, metadata, session, scan_pages, remote_pages, capacities, records
    ):
        super().__init__(plan, metadata, session, scan_pages, capacities, records)
        self._remote_pages = remote_pages

    def _exec_RemoteSourceNode(self, node: RemoteSourceNode) -> Relation:
        return Relation(self._remote_pages[node.fragment_id], node.symbols)


class _BucketPrefetcher:
    """Pipelines the bucket loop: while bucket b's program runs on device,
    the next buckets' partitions are read from the store (disk chunks LZ4-
    decompressed inline on the pool thread), assembled into canonically-
    shaped pages, and `jax.device_put` so the host->device copy is in
    flight before the main loop asks for them (double buffering at
    ``depth=2``). In-flight host bytes stay under ``budget_bytes``; at most
    one bucket is admitted past the budget so the pipeline always makes
    progress. Consumption strictly follows submission order, so a miss only
    happens when prefetch is disabled or the budget starved the queue —
    the main loop then assembles inline (counted in ``misses``)."""

    def __init__(
        self,
        runner: "OutOfCoreRunner",
        hash_edges: List[RemoteSourceNode],
        buckets: List[int],
        caps: Dict[Tuple[int, int], int],
        depth: int,
        budget_bytes: int,
    ):
        self.runner = runner
        self.hash_edges = hash_edges
        self.buckets = buckets
        self.caps = caps
        self.depth = max(0, depth)
        self.budget = max(1, budget_bytes)
        self._next = 0
        self._futures: Dict[int, Tuple[object, int]] = {}
        self._inflight = 0
        self.hits = 0
        self.misses = 0
        self.max_inflight_bytes = 0
        self.max_depth = 0
        self.host_wait_secs = 0.0
        # cross-thread trace context: prefetch jobs run on the shared io_pool
        # whose threads have fresh Tracer stacks — capture the submitting
        # thread's span NOW so pool-side spans parent into the query trace
        # instead of orphaning (and the runner's collector stays active)
        self._trace_ctx = TRACER.capture()
        self._pump()

    def _job(self, b: int) -> Dict[int, Page]:
        with TRACER.attach(self._trace_ctx), obs.collecting(
            self.runner.collector
        ), TRACER.span("ooc.prefetch", bucket=b):
            with RECORDER.span("prefetch_build", "prefetch", bucket=b):
                return self._build(b)

    def _estimate(self, b: int) -> int:
        return sum(
            self.runner.stores[rs.fragment_id].bucket_nbytes(b)
            for rs in self.hash_edges
        )

    def _build(self, b: int, pool=None) -> Dict[int, Page]:
        return {
            rs.fragment_id: self.runner._input_page(
                rs, b, capacity=self.caps.get((rs.fragment_id, b)), pool=pool
            )
            for rs in self.hash_edges
        }

    def _pump(self) -> None:
        while self._next < len(self.buckets) and len(self._futures) < self.depth:
            b = self.buckets[self._next]
            est = self._estimate(b)
            if self._futures and self._inflight + est > self.budget:
                break  # budget-capped; retried after the next get()
            self._inflight += est
            self.max_inflight_bytes = max(self.max_inflight_bytes, self._inflight)
            RECORDER.instant("prefetch_issue", "prefetch", bucket=b, est_bytes=est)
            self._futures[b] = (io_pool().submit(self._job, b), est)
            self.max_depth = max(self.max_depth, len(self._futures))
            self._next += 1

    def get(self, b: int) -> Dict[int, Page]:
        ent = self._futures.pop(b, None)
        if ent is None:
            self.misses += 1
            if self._next < len(self.buckets) and self.buckets[self._next] == b:
                self._next += 1  # keep submission aligned with consumption
            pages = self._build(b, pool=io_pool())
            RECORDER.instant("prefetch_miss", "prefetch", bucket=b)
        else:
            fut, est = ent
            t0 = time.perf_counter()
            with RECORDER.span("prefetch_wait", "prefetch", bucket=b):
                pages = fut.result()
            self.host_wait_secs += time.perf_counter() - t0
            self._inflight -= est
            self.hits += 1
            RECORDER.instant("prefetch_complete", "prefetch", bucket=b)
        self._pump()
        return pages


class OutOfCoreRunner:
    """Drives one query's fragment tree out-of-core on a single chip."""

    def __init__(
        self,
        plan: LogicalPlan,
        metadata: Metadata,
        session: Session,
        n_buckets: int = 64,
        split_batch: int = 8,
        mem_budget_bytes: int = 2 << 30,
        spool_dir: Optional[str] = None,
        prefetch_depth: int = 2,
        prefetch_budget_bytes: int = 256 << 20,
    ):
        self.metadata = metadata
        self.session = session
        self.n_buckets = n_buckets
        self.split_batch = max(1, split_batch)
        self.mem_budget = mem_budget_bytes
        # pipeline knobs: how many buckets/split batches may be staged ahead
        # of the device (2 = classic double buffering) and how many host
        # bytes those staged inputs may pin
        self.prefetch_depth = max(0, prefetch_depth)
        self.prefetch_budget = max(1, prefetch_budget_bytes)
        # distributed sort would need REPARTITION_RANGE (global quantiles over
        # a stream); query tails sort SINGLE instead
        session_ooc = _dc_replace(
            session, properties={**session.properties, "distributed_sort": False}
        )
        distributed = add_exchanges(plan, metadata, session_ooc)
        self.subplan: SubPlan = create_fragments(distributed)
        self.types = self.subplan.types
        self._consumer_edge: Dict[int, RemoteSourceNode] = {}
        for frag in self.subplan.fragments:
            visit_plan(
                frag.root,
                lambda n: self._consumer_edge.__setitem__(n.fragment_id, n)
                if isinstance(n, RemoteSourceNode)
                else None,
            )
        self._validate()  # before mkdtemp: a rejected plan must not leak a dir
        self._own_spool = spool_dir is None
        self.spool_dir = spool_dir or tempfile.mkdtemp(prefix="trino-tpu-ooc-")
        self.stores: Dict[int, BucketStore] = {}
        # observability plane: the runner's stats collector (joins an
        # enclosing query collector when one is active — e.g. a server-side
        # query whose plan routed out-of-core). bench.py and the trace
        # tooling read the plane via collector.snapshot().
        self.collector = obs.current_collector() or obs.QueryStatsCollector()
        self.stats: Dict[str, object] = {
            "fragments": len(self.subplan.fragments),
            # pipeline overlap evidence (bench reads these): seconds the
            # main loop spent inside device dispatch+sync vs blocked on
            # prefetch results, plus prefetch hit/miss and shape-class counts
            "device_busy_secs": 0.0,
            "compile_secs": 0.0,
            "fallback_secs": 0.0,
            "host_wait_secs": 0.0,
            "emit_secs": 0.0,
            "prefetch_hits": 0,
            "prefetch_misses": 0,
            "prefetch_max_inflight_bytes": 0,
            "prefetch_max_depth": 0,
            "caps_from_store": 0,
        }
        # per-(fragment, capacity-vector) jitted unit programs + the record
        # order their actuals vector reports in
        self._unit_fns: Dict[Tuple[int, tuple], object] = {}
        self._unit_keys: Dict[Tuple[int, tuple], List[int]] = {}
        # per-fragment tuned per-stage capacities (node id -> capacity) at
        # the tuning unit's input capacity (_caps_ref), plus the per-input-
        # class rescaled vectors actually handed to programs
        self._unit_caps: Dict[int, Dict[int, int]] = {}
        self._caps_ref: Dict[int, int] = {}
        self._class_caps: Dict[Tuple[int, int], Dict[int, int]] = {}
        self._caps_tuned: Dict[int, bool] = {}
        self._candidates: Dict[int, list] = {}
        self._frag_fp: Dict[int, str] = {}
        self._traceable: Dict[int, bool] = {}
        self._shape_classes: set = set()

    # ------------------------------------------------------------ validation

    def _validate(self) -> None:
        for frag in self.subplan.fragments:
            scans: List[TableScanNode] = []
            visit_plan(
                frag.root,
                lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
            )
            if len(scans) > 1:
                raise OutOfCoreUnsupported(
                    "fragment with multiple scans (cross join?) cannot stream"
                )
            edge = self._consumer_edge.get(frag.fragment_id)
            if edge is not None and edge.exchange_type == ExchangeType.REPARTITION_RANGE:
                raise OutOfCoreUnsupported(
                    "REPARTITION_RANGE (distributed sort) not supported out-of-core"
                )

    # ------------------------------------------------------------- plumbing

    def _edge_buckets(self, fid: int) -> int:
        edge = self._consumer_edge.get(fid)
        if edge is not None and edge.exchange_type == ExchangeType.REPARTITION:
            return self.n_buckets
        return 1

    def _emit(self, frag: PlanFragment, page: Page) -> None:
        """Bucket one execution unit's output into the fragment's store."""
        t0 = time.perf_counter()
        try:
            with RECORDER.span("emit", "bucket", fragment=frag.fragment_id):
                store = self.stores[frag.fragment_id]
                cols = _page_to_host(page)
                if not cols:
                    return
                edge = self._consumer_edge.get(frag.fragment_id)
                if (
                    edge is None
                    or edge.exchange_type != ExchangeType.REPARTITION
                    or store.n_buckets == 1
                ):
                    store.append(0, cols, pool=io_pool())
                    return
                out_symbols = list(frag.root.output_symbols)
                key_idx = [out_symbols.index(k) for k in edge.partition_keys]
                targets = host_partition_targets(cols, key_idx, store.n_buckets)
                for b, chunk in enumerate(
                    _split_chunk_by_targets(cols, targets, store.n_buckets)
                ):
                    if chunk is not None:
                        store.append(b, chunk, pool=io_pool())
        finally:
            dt = time.perf_counter() - t0
            self.stats["emit_secs"] += dt
            self.collector.add_time("emit_secs", dt, fragment=frag.fragment_id)

    def _input_page(
        self,
        rs: RemoteSourceNode,
        bucket: Optional[int],
        capacity: Optional[int] = None,
        pool=None,
    ) -> Page:
        """Assemble one remote source's input page for one execution unit.
        ``capacity`` overrides the power-of-two default with a canonical
        shape class (bucket loop); ``pool`` parallelizes LZ4 decompression
        of spilled chunks — pass None when already ON a pool thread."""
        store = self.stores[rs.fragment_id]
        if rs.exchange_type == ExchangeType.REPARTITION and bucket is not None:
            chunks = store.read(bucket, pool=pool)
        else:  # GATHER / BROADCAST: complete producer output
            chunks = store.read_all(pool=pool)
        if not chunks:
            return _empty_page(rs.symbols, self.types)
        rows = sum(len(c[0][1]) for c in chunks)
        # static-shape discipline: canonical class when given (bucket loop
        # shares compiled programs across ALL buckets of a class), else
        # power-of-two padding
        cap = capacity if capacity is not None and capacity >= rows else (
            _round_capacity(max(rows, 1))
        )
        nbytes = sum(_chunk_bytes(c) for c in chunks)
        self.collector.add_count("h2d_bytes", nbytes)
        self.collector.add_count("input_rows", rows)
        RECORDER.instant(
            "h2d_transfer", "transfer", fragment=rs.fragment_id,
            bucket=-1 if bucket is None else bucket, bytes=nbytes, rows=rows,
        )
        # device_put starts the host->device copy NOW — from a prefetch
        # thread this is the double-buffered transfer overlapping compute
        return jax.device_put(_page_from_host_chunks(chunks, capacity=cap))

    def _remotes_of(self, frag: PlanFragment) -> List[RemoteSourceNode]:
        from ..planner.fragmenter import remote_sources

        return remote_sources(frag.root)

    def _fragment_traceable(self, frag: PlanFragment) -> bool:
        flag = self._traceable.get(frag.fragment_id)
        if flag is None:
            flag = is_traceable(
                LogicalPlan(frag.root, self.types),
                allow_joins=True,
                extra_types=(RemoteSourceNode,),
            )
            self._traceable[frag.fragment_id] = flag
        return flag

    def _unit_fn(self, frag: PlanFragment, caps: Dict[int, int]):
        """One jitted program per (fragment, per-stage capacity vector);
        jax's own cache handles the handful of canonical input shape
        classes. Returns (fn, keys) where ``keys`` lists the node ids in
        the order the actuals vector reports them."""
        fid = frag.fragment_id
        sig = tuple(sorted(caps.items()))
        key = (fid, sig)
        fn = self._unit_fns.get(key)
        if fn is not None:
            return fn, self._unit_keys[key]
        plan = LogicalPlan(frag.root, self.types)
        remote_fids = [rs.fragment_id for rs in self._remotes_of(frag)]
        root = frag.root
        keys_holder: List[int] = []

        def run(scan_page: Optional[Page], remote_pages: Tuple[Page, ...]):
            import jax.numpy as jnp

            scans = {} if scan_page is None else {0: scan_page}
            records: List[Tuple[int, object, object]] = []
            executor = _AdaptiveUnitExecutor(
                plan, self.metadata, self.session, scans,
                dict(zip(remote_fids, remote_pages)), dict(caps), records,
            )
            if isinstance(root, OutputNode):
                rel = executor.eval(root.source)
                symbols = root.symbols
            else:
                rel = executor.eval(root)
                symbols = root.output_symbols
            page = Page(
                tuple(rel.column_for(s) for s in symbols), rel.page.active
            )
            keys_holder.clear()
            keys_holder.extend(k for k, _, _ in records)
            overflow = jnp.int64(0)
            for _, o, _ in records:
                overflow = overflow + o.astype(jnp.int64)
            for o in executor.overflows:
                overflow = overflow + o.astype(jnp.int64)
            actuals = (
                jnp.stack([a for _, _, a in records])
                if records
                else jnp.zeros((0,), dtype=jnp.int64)
            )
            return page, overflow, actuals

        fn = kernelcost.jit(run, label="ooc_unit")
        self._unit_fns[key] = fn
        self._unit_keys[key] = keys_holder
        return fn, keys_holder

    # ------------------------------------------ per-stage capacity reuse

    def _caps_key(self, frag: PlanFragment) -> str:
        fp = self._frag_fp.get(frag.fragment_id)
        if fp is None:
            fp = capstore.plan_fingerprint(LogicalPlan(frag.root, self.types))
            self._frag_fp[frag.fragment_id] = fp
        return (fp + ":ooc-caps") if fp else ""

    def _frag_candidates(self, frag: PlanFragment) -> list:
        fid = frag.fragment_id
        nodes = self._candidates.get(fid)
        if nodes is None:
            nodes = candidate_nodes(LogicalPlan(frag.root, self.types))
            self._candidates[fid] = nodes
        return nodes

    def _seed_caps(self, frag: PlanFragment) -> Dict[int, int]:
        """The fragment's REF-scale per-stage capacity vector: tuned on the
        FIRST unit and reused by every later unit, seeded from the capstore
        fingerprint when a previous run of the same fragment shape already
        tuned it — one tuning compile per plan shape, ever, instead of a
        tune per bucket. The stored vector carries the tuning unit's input
        capacity as its last element so a later process can rescale."""
        fid = frag.fragment_id
        caps = self._unit_caps.get(fid)
        if caps is not None:
            return caps
        caps = {}
        key = self._caps_key(frag)
        if key:
            vec = capstore.load(key)
            nodes = self._frag_candidates(frag)
            if vec is not None and len(vec) == len(nodes) + 1 and vec[-1]:
                for node, cap in zip(nodes, vec):
                    if cap is not None:
                        caps[id(node)] = int(cap)
                self._caps_ref[fid] = int(vec[-1])
                self._caps_tuned[fid] = True
                self.stats["caps_from_store"] += 1
                self.collector.add_count("caps_from_store")
        self._unit_caps[fid] = caps
        return caps

    def _store_caps(self, frag: PlanFragment) -> None:
        key = self._caps_key(frag)
        fid = frag.fragment_id
        if not key or not self._caps_ref.get(fid):
            return
        caps = self._unit_caps.get(fid, {})
        capstore.save(
            key,
            [caps.get(id(n)) for n in self._frag_candidates(frag)]
            + [self._caps_ref[fid]],
        )

    def _caps_for(self, frag: PlanFragment, in_cap: int) -> Dict[int, int]:
        """Per-stage capacities for a unit whose input capacity class is
        ``in_cap``: the ref-scale tuned vector, linearly rescaled when this
        unit's input class differs from the tuning unit's (a scan fragment
        tunes on a cheap single-split unit, then full split batches run at
        8x the input — stage cardinalities scale roughly with input rows,
        and the overflow retry catches the cases where they don't)."""
        fid = frag.fragment_id
        cached = self._class_caps.get((fid, in_cap))
        if cached is not None:
            return cached
        base = self._seed_caps(frag)
        ref = self._caps_ref.get(fid)
        if not base or not ref or not in_cap or in_cap == ref:
            caps = dict(base)
        else:
            r = in_cap / ref
            caps = {
                k: max(1024, _round_capacity(int(v * r) + 16))
                for k, v in base.items()
            }
        self._class_caps[(fid, in_cap)] = caps
        return caps

    def _tune_caps(
        self, frag: PlanFragment, in_cap: int, keys: List[int], actuals
    ) -> None:
        """Record the first successful unit's measured per-stage counts as
        the fragment's ref-scale capacity vector (x1.5 headroom +
        power-of-two rounding absorbs unit-to-unit variation; an
        overflowing later unit grows its class and recompiles once)."""
        fid = frag.fragment_id
        caps = {
            k: _round_capacity(int(act * 1.5) + 16)
            for k, act in zip(keys, np.asarray(actuals))
        }
        self._unit_caps[fid] = caps
        self._caps_ref[fid] = in_cap
        self._caps_tuned[fid] = True
        self._class_caps[(fid, in_cap)] = dict(caps)
        self._store_caps(frag)

    def _run_unit(
        self,
        frag: PlanFragment,
        staged: Dict[int, List[Page]],
        scan_pages: Dict[int, Page],
    ) -> Page:
        fid = frag.fragment_id
        if self._fragment_traceable(frag):
            scan_page = next(iter(scan_pages.values())) if scan_pages else None
            remote_fids = [rs.fragment_id for rs in self._remotes_of(frag)]
            remote_pages = tuple(staged[f][0] for f in remote_fids)
            in_cap = scan_page.capacity if scan_page is not None else max(
                (p.capacity for p in remote_pages), default=0
            )
            caps = self._caps_for(frag, in_cap)
            for attempt in range(10):
                fn, keys = self._unit_fn(frag, caps)
                try:
                    n_compiled = fn._cache_size()
                except Exception:
                    n_compiled = None
                t0 = time.perf_counter()
                # device batching plane: an OOC unit is ONE program launch —
                # it books the launch counter, and when batching is on it
                # yields the admission gate between units so higher-priority
                # point-query batches are no longer head-of-line-blocked by
                # a long bucket loop
                from .device_scheduler import launch_slot, on_program_launch

                try:
                    gated = bool(self.session.get("device_batching"))
                except KeyError:
                    gated = False
                with launch_slot(gated), RECORDER.span(
                    "unit", "bucket", fragment=fid, attempt=attempt
                ), obs.compile_window() as cw:
                    on_program_launch()
                    page, overflow, actuals = fn(scan_page, remote_pages)
                    ovf = int(np.asarray(overflow))  # blocks until device done
                elapsed = time.perf_counter() - t0
                # attribute trace+compile time separately so the bench's
                # device_busy_frac reflects actual overlap, not cold compiles
                try:
                    compiled = n_compiled is not None and fn._cache_size() > n_compiled
                except Exception:
                    compiled = False
                key = "compile_secs" if compiled else "device_busy_secs"
                self.stats[key] += elapsed
                # the jax.monitoring listener already credited cw.seconds of
                # backend-compile time to the QUERY total — book only the
                # remainder there (or compile time would count twice), but
                # give the fragment its full share so fragments still sum
                # to the query-level numbers
                self.collector.add_time(
                    key, max(elapsed - cw.seconds, 0.0), fragment=fid
                )
                if cw.seconds:
                    self.collector.add_fragment_time(
                        fid, "compile_secs", cw.seconds
                    )
                if ovf:
                    self.collector.add_count("overflow_retries")
                if ovf == 0:
                    if not self._caps_tuned.get(fid):
                        self._tune_caps(frag, in_cap, keys, actuals)
                    return page
                # a stage overflowed its capacity (the untuned first unit
                # at full capacity never does; a rescaled later unit can):
                # grow every point to at least its observed count and retry
                grown = dict(caps)
                for k, act in zip(keys, np.asarray(actuals)):
                    base = _round_capacity(int(act * (1.5 + attempt)) + 16)
                    grown[k] = max(base, caps.get(k, 0))
                caps = grown
                self._class_caps[(fid, in_cap)] = caps
                # back-propagate to the ref-scale vector + capstore: an
                # undersized persisted vector must not make every other
                # class — and every future process — re-pay this overflow
                # dispatch and recompile
                ref = self._caps_ref.get(fid)
                if self._caps_tuned.get(fid) and ref:
                    r = (in_cap / ref) if in_cap else 1.0
                    base_vec = self._unit_caps.setdefault(fid, {})
                    for k, cap in grown.items():
                        back = _round_capacity(int(cap / r) if r else cap)
                        if back > base_vec.get(k, 0):
                            base_vec[k] = back
                    self._store_caps(frag)
                    # other classes' cached vectors rescaled from the old
                    # undersized base: drop them so they re-derive from the
                    # grown vector instead of re-paying this overflow
                    for ck in [
                        ck
                        for ck in self._class_caps
                        if ck[0] == fid and ck[1] != in_cap
                    ]:
                        del self._class_caps[ck]
            raise ExecutionError("OOC unit capacity tuning did not converge")
        plan = LogicalPlan(frag.root, self.types)
        ex = _OOCFragmentExecutor(plan, self.metadata, self.session, staged, scan_pages)
        t0 = time.perf_counter()
        with RECORDER.span("unit_fallback", "bucket", fragment=fid):
            page = run_fragment_partition(ex, frag.root)
        # host-synced op-at-a-time execution, NOT device-saturating work —
        # booked separately so device_busy_frac stays honest
        dt = time.perf_counter() - t0
        self.stats["fallback_secs"] += dt
        self.collector.add_time("fallback_secs", dt, fragment=fid)
        return page

    # ------------------------------------------------------------- stages

    def _execute_source(self, frag: PlanFragment) -> None:
        scan: List[TableScanNode] = []
        visit_plan(
            frag.root,
            lambda n: scan.append(n) if isinstance(n, TableScanNode) else None,
        )
        node = scan[0]
        splits, col_indexes, provider = scan_sources(self.metadata, node)

        # non-repartition inputs (broadcast builds, gathered subquery results)
        staged = {
            rs.fragment_id: [self._input_page(rs, None, pool=io_pool())]
            for rs in self._remotes_of(frag)
        }
        # the FIRST unit is always a single split: it doubles as the
        # per-stage capacity tuning unit (_tune_caps), so keep it cheap —
        # every later batch runs the tuned (rescaled) program.
        # Unconditional (not gated on tuning state) so unit boundaries —
        # and therefore float combination order — are identical between
        # cold and capstore-warm runs.
        if len(splits) > 1:
            batches = [splits[:1]] + [
                splits[i : i + self.split_batch]
                for i in range(1, len(splits), self.split_batch)
            ]
        else:
            batches = [
                splits[i : i + self.split_batch]
                for i in range(0, max(len(splits), 1), self.split_batch)
            ]

        trace_ctx = TRACER.capture()

        def assemble(batch) -> Page:
            # pool-side: re-attach the query's trace context + collector
            # (spiller.io_pool threads have fresh thread-local stacks)
            with TRACER.attach(trace_ctx), obs.collecting(self.collector):
                with RECORDER.span(
                    "scan_batch", "scan", fragment=frag.fragment_id,
                    splits=len(batch),
                ):
                    if batch:
                        pages = [
                            provider.create_page_source(sp, col_indexes)
                            for sp in batch
                        ]
                        page = pages[0] if len(pages) == 1 else _concat_pages(pages)
                    else:  # empty table still needs one unit (partial global aggs)
                        page = _empty_page(
                            tuple(s for s, _ in node.assignments), self.types
                        )
                    # start the host->device copy from the worker thread (double
                    # buffering: batch N+1 transfers while batch N computes)
                    return jax.device_put(page)

        units = 0
        if self.prefetch_depth < 1:
            for batch in batches:  # serial fallback (prefetch disabled)
                out = self._run_unit(frag, staged, {id(node): assemble(batch)})
                self._emit(frag, out)
                units += 1
        else:
            from .memory import page_bytes

            pending: deque = deque()
            idx = 0
            est_bytes: Optional[int] = None  # measured from consumed batches
            while idx < len(batches) or pending:
                # the byte budget caps staged batches too: once a batch's
                # real size is known, admit only as many as fit (always >=1
                # so the pipeline keeps moving)
                if est_bytes:
                    limit = max(
                        1, min(self.prefetch_depth, self.prefetch_budget // est_bytes)
                    )
                else:
                    limit = self.prefetch_depth
                while idx < len(batches) and len(pending) < limit:
                    pending.append(io_pool().submit(assemble, batches[idx]))
                    idx += 1
                t0 = time.perf_counter()
                page = pending.popleft().result()
                dt = time.perf_counter() - t0
                self.stats["host_wait_secs"] += dt
                self.collector.add_time(
                    "host_wait_secs", dt, fragment=frag.fragment_id
                )
                est_bytes = max(est_bytes or 0, page_bytes(page))
                out = self._run_unit(frag, staged, {id(node): page})
                self._emit(frag, out)
                units += 1
        self.stats[f"f{frag.fragment_id}_units"] = units

    def _bucket_caps(
        self, hash_edges: List[RemoteSourceNode], buckets: List[int]
    ) -> Dict[Tuple[int, int], int]:
        """Canonical shape class per (edge, bucket): 4x-spaced classes mean
        a 32-bucket loop typically sees 1-2 distinct input shapes per edge —
        one compile per class, not per bucket."""
        caps: Dict[Tuple[int, int], int] = {}
        for rs in hash_edges:
            store = self.stores[rs.fragment_id]
            for b in buckets:
                cls = _shape_class(max(store.rows_of(b), 1))
                caps[(rs.fragment_id, b)] = cls
                self._shape_classes.add((rs.fragment_id, cls))
        return caps

    def _execute_buckets(self, frag: PlanFragment) -> None:
        remotes = self._remotes_of(frag)
        hash_edges = [
            rs for rs in remotes if rs.exchange_type == ExchangeType.REPARTITION
        ]
        if not hash_edges:
            # no co-partitioned inputs (all broadcast/gather): one unit
            self._emit(frag, self._execute_single(frag))
            self.stats[f"f{frag.fragment_id}_units"] = 1
            return
        shared = {
            rs.fragment_id: [self._input_page(rs, None, pool=io_pool())]
            for rs in remotes
            if rs.exchange_type != ExchangeType.REPARTITION
        }
        # empty buckets emit nothing for every operator
        buckets = [
            b
            for b in range(self.n_buckets)
            if any(self.stores[rs.fragment_id].rows_of(b) for rs in hash_edges)
        ]
        caps = self._bucket_caps(hash_edges, buckets)
        prefetcher = _BucketPrefetcher(
            self, hash_edges, buckets, caps,
            self.prefetch_depth, self.prefetch_budget,
        )
        units = 0
        for b in buckets:
            staged = dict(shared)
            for fid, page in prefetcher.get(b).items():
                staged[fid] = [page]
            out = self._run_unit(frag, staged, {})
            self._emit(frag, out)
            units += 1
        self.stats[f"f{frag.fragment_id}_units"] = units
        self.stats["host_wait_secs"] += prefetcher.host_wait_secs
        self.stats["prefetch_hits"] += prefetcher.hits
        self.stats["prefetch_misses"] += prefetcher.misses
        self.collector.add_time(
            "host_wait_secs", prefetcher.host_wait_secs,
            fragment=frag.fragment_id,
        )
        self.collector.add_count("prefetch_hits", prefetcher.hits)
        self.collector.add_count("prefetch_misses", prefetcher.misses)
        self.stats["prefetch_max_inflight_bytes"] = max(
            self.stats["prefetch_max_inflight_bytes"],
            prefetcher.max_inflight_bytes,
        )
        self.stats["prefetch_max_depth"] = max(
            self.stats["prefetch_max_depth"], prefetcher.max_depth
        )

    def _execute_single(self, frag: PlanFragment) -> Page:
        staged = {
            rs.fragment_id: [self._input_page(rs, None, pool=io_pool())]
            for rs in self._remotes_of(frag)
        }
        return self._run_unit(frag, staged, {})

    # ------------------------------------------------------------- driver

    def execute(self) -> Tuple[List[str], Page]:
        with obs.collecting(self.collector), RECORDER.span(
            "ooc_query", "query", fragments=len(self.subplan.fragments)
        ):
            return self._execute()

    def _execute(self) -> Tuple[List[str], Page]:
        try:
            final_page: Optional[Page] = None
            root_id = self.subplan.root_fragment.fragment_id
            for frag in self.subplan.fragments:
                has_scan: List[TableScanNode] = []
                visit_plan(
                    frag.root,
                    lambda n: has_scan.append(n)
                    if isinstance(n, TableScanNode)
                    else None,
                )
                if frag.fragment_id == root_id:
                    final_page = self._execute_single(frag)
                    break
                self.stores[frag.fragment_id] = BucketStore(
                    self._edge_buckets(frag.fragment_id),
                    self.mem_budget,
                    self.spool_dir,
                    f"f{frag.fragment_id}",
                )
                if has_scan:
                    self._execute_source(frag)
                elif frag.partitioning in (
                    Partitioning.FIXED_HASH,
                    Partitioning.FIXED_ARBITRARY,
                ):
                    self._execute_buckets(frag)
                else:
                    self._emit(frag, self._execute_single(frag))
                # every fragment has exactly ONE consumer (each REMOTE
                # exchange cuts its own fragment), so its producers' stores
                # are dead as soon as it finishes: free host memory + spool
                # eagerly — peak usage is bounded by adjacent stages, not the
                # whole fragment tree
                for fid in frag.input_fragments:
                    store = self.stores.get(fid)
                    if store is not None:
                        store.drop()  # spilled_bytes counter survives drop
            assert final_page is not None
            root = self.subplan.root_fragment.root
            assert isinstance(root, OutputNode)
            self.stats["spilled_bytes"] = sum(
                s.spilled_bytes for s in self.stores.values()
            )
            self.stats["shape_classes"] = len(self._shape_classes)
            compiles = 0
            for fn in self._unit_fns.values():
                try:
                    compiles += fn._cache_size()
                except Exception:
                    pass
            self.stats["compiles"] = compiles
            return list(root.column_names), final_page
        finally:
            for s in self.stores.values():
                s.drop()
            if self._own_spool:
                try:
                    os.rmdir(self.spool_dir)
                except OSError:
                    pass


def execute_out_of_core(
    plan: LogicalPlan,
    metadata: Metadata,
    session: Session,
    n_buckets: int = 64,
    split_batch: int = 8,
    mem_budget_bytes: int = 2 << 30,
    prefetch_depth: int = 2,
    prefetch_budget_bytes: int = 256 << 20,
) -> Tuple[List[str], Page]:
    runner = OutOfCoreRunner(
        plan,
        metadata,
        session,
        n_buckets=n_buckets,
        split_batch=split_batch,
        mem_budget_bytes=mem_budget_bytes,
        prefetch_depth=prefetch_depth,
        prefetch_budget_bytes=prefetch_budget_bytes,
    )
    return runner.execute()
