"""Row-level DML: DELETE / UPDATE / MERGE against writable connectors.

Reference blueprint: io.trino.execution.{DeleteTask-less} row-level-DML path —
SqlQueryExecution plans TableDelete/Merge nodes into MergeWriterOperator +
ConnectorMergeSink (core/trino-main/src/main/java/io/trino/operator/
MergeWriterOperator.java, MergeProcessor). The TPU redesign keeps whole pages
device-resident: a DELETE is one jitted mask program per stored page, an
UPDATE a where-select over recomputed columns, and a MERGE a vectorized
equi-key match (sorted-build probe) deciding update/delete/insert lanes —
no per-row writer loop anywhere.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..ops import kernels as K
from ..ops.compiler import CVal, compile_expression
from ..spi.page import Column, Dictionary, Page
from ..spi.types import common_super_type, is_string
from ..sql import tree as t
from ..sql.ir import CastExpr, IrExpr
from .executor import Relation, _cval_of, _column_of


class DmlError(ValueError):
    pass


def _resolve_writable(runner, qname, op: str):
    catalog, st = runner._resolve_name(qname)
    connector = runner.catalogs.get(catalog)
    if connector is None:
        raise DmlError(f"catalog not found: {catalog}")
    if not hasattr(connector, "replace_pages"):
        raise DmlError(f"catalog {catalog} does not support {op}")
    meta = connector.metadata().get_table_metadata(st)
    if meta is None:
        raise DmlError(f"table not found: {st}")
    return connector, st, meta


def _translator(runner, fields):
    from ..planner.logical_planner import (
        ExpressionTranslator,
        LogicalPlanner,
        Scope,
    )

    planner = LogicalPlanner(runner.metadata, runner.session)
    scope = Scope(list(fields), None)
    return ExpressionTranslator(planner, scope, allow_subqueries=False)


def _table_fields(meta, qualifier: Optional[str], prefix: str = ""):
    from ..planner.logical_planner import Field

    return [
        Field(c.name, c.type, prefix + c.name, qualifier=qualifier)
        for c in meta.columns
    ]


def _assignable(src, target) -> bool:
    """DML assignment compatibility: normal coercion rules, except any string
    fits any string column (the dictionary layout carries no length — declared
    varchar(n) lengths are not enforced, a documented deviation)."""
    if is_string(src) and is_string(target):
        return True
    return common_super_type(src, target) == target


def _coerce(translator, ir: IrExpr, target) -> IrExpr:
    if is_string(ir.type) and is_string(target):
        return ir  # physical layout identical (dictionary codes)
    return translator._cast_to(ir, target)


def _mutation_guard(connector):
    """The connector's read-compute-swap lock (nullcontext when absent)."""
    import contextlib

    guard = getattr(connector, "mutation_guard", None)
    return guard() if guard is not None else contextlib.nullcontext()


def _predicate_mask(ir: Optional[IrExpr], rel: Relation) -> jnp.ndarray:
    """Rows where the predicate is definitively TRUE (3VL: NULL = no fire)."""
    if ir is None:
        return rel.page.active
    fn, _ = compile_expression(ir, rel.layout(), rel.capacity)
    v = fn(rel.env())
    return rel.page.active & v.valid & v.data.astype(jnp.bool_)


def _select_column(fire, new_col: Column, old_col: Column) -> Column:
    """where(fire, new, old) with dictionary re-encoding when the string
    vocabularies differ (codes are only comparable within one dictionary)."""
    nd, od = new_col.data, old_col.data
    dictionary = old_col.dictionary or new_col.dictionary
    if (
        is_string(old_col.type)
        and new_col.dictionary is not None
        and old_col.dictionary is not None
        and new_col.dictionary.fingerprint() != old_col.dictionary.fingerprint()
    ):
        values = sorted(
            set(old_col.dictionary.values) | set(new_col.dictionary.values)
        )
        dictionary = Dictionary(np.asarray(values, dtype=object))
        code_of = {s: c for c, s in enumerate(values)}
        old_lut = np.array([code_of[s] for s in old_col.dictionary.values], np.int32)
        new_lut = np.array([code_of[s] for s in new_col.dictionary.values], np.int32)
        od = jnp.asarray(old_lut)[jnp.clip(od, 0, len(old_lut) - 1)]
        nd = jnp.asarray(new_lut)[jnp.clip(nd, 0, len(new_lut) - 1)]
    data = jnp.where(fire, nd.astype(od.dtype), od)
    valid = jnp.where(fire, new_col.valid, old_col.valid)
    return Column(old_col.type, data, valid, dictionary)


def execute_delete(runner, stmt: t.Delete) -> int:
    connector, st, meta = _resolve_writable(runner, stmt.table, "DELETE")
    translator = _translator(runner, _table_fields(meta, st.table))
    ir = translator.translate(stmt.where) if stmt.where is not None else None
    symbols = tuple(c.name for c in meta.columns)
    deleted = 0
    new_pages = []
    with _mutation_guard(connector):
        table = connector.table(st)
        for page in table.pages:
            rel = Relation(page, symbols)
            fire = _predicate_mask(ir, rel)
            deleted += int(jnp.sum(fire.astype(jnp.int32)))
            new_pages.append(Page(page.columns, page.active & ~fire))
        connector.replace_pages(st, new_pages)
    return deleted


def execute_update(runner, stmt: t.Update) -> int:
    connector, st, meta = _resolve_writable(runner, stmt.table, "UPDATE")
    translator = _translator(runner, _table_fields(meta, st.table))
    where_ir = translator.translate(stmt.where) if stmt.where is not None else None
    col_types = {c.name: c.type for c in meta.columns}
    assignment_irs: Dict[str, IrExpr] = {}
    for col, expr in stmt.assignments:
        if col not in col_types:
            raise DmlError(f"UPDATE: unknown column {col!r}")
        if col in assignment_irs:
            raise DmlError(f"UPDATE: multiple assignments to column {col!r}")
        ir = translator.translate(expr)
        target = col_types[col]
        if ir.type != target:
            if not _assignable(ir.type, target):
                raise DmlError(
                    f"UPDATE {col}: cannot assign {ir.type.display()} "
                    f"to {target.display()}"
                )
            ir = _coerce(translator, ir, target)
        assignment_irs[col] = ir

    symbols = tuple(c.name for c in meta.columns)
    updated = 0
    new_pages = []
    with _mutation_guard(connector):
        table = connector.table(st)
        for page in table.pages:
            rel = Relation(page, symbols)
            fire = _predicate_mask(where_ir, rel)
            updated += int(jnp.sum(fire.astype(jnp.int32)))
            cols = []
            for name, old in zip(symbols, page.columns):
                ir = assignment_irs.get(name)
                if ir is None:
                    cols.append(old)
                    continue
                fn, out_dict = compile_expression(ir, rel.layout(), rel.capacity)
                v = fn(rel.env())
                new_col = _column_of(old.type, v, out_dict)
                cols.append(_select_column(fire, new_col, old))
            new_pages.append(Page(tuple(cols), page.active))
        connector.replace_pages(st, new_pages)
    return updated


def _single_equality(on: t.Expression) -> Tuple[t.Expression, t.Expression]:
    if isinstance(on, t.Comparison) and on.op == t.ComparisonOp.EQUAL:
        return on.left, on.right
    raise DmlError(
        "MERGE requires a single equality ON condition "
        "(target.key = source.key) in this engine"
    )


def execute_merge(runner, stmt: t.Merge) -> int:
    """Vectorized equi-key MERGE: match target rows against the source with
    the sorted-build probe kernel, then apply matched update/delete lanes and
    append the not-matched insert page. Duplicate source matches for one
    target row raise, as the reference does (MergeProcessor's
    one-source-row-per-target check)."""
    connector, st, meta = _resolve_writable(runner, stmt.target, "MERGE")

    # source relation -> one materialized page via SELECT * FROM <source>
    from ..planner.logical_planner import LogicalPlanner
    from ..planner import optimize
    from .executor import PlanExecutor

    planner = LogicalPlanner(runner.metadata, runner.session)
    src_query = t.Query(
        body=t.QuerySpecification(
            select_items=(t.SelectItem(expression=t.Star()),), from_=stmt.source
        )
    )
    src_plan = planner.plan(t.QueryStatement(query=src_query))
    src_plan = optimize(src_plan, runner.metadata, runner.session)
    # the USING relation is a read: subject to SELECT access control like any
    # CTAS/INSERT source (checkCanSelectFromColumns in the reference's analyzer)
    runner._check_select_access(src_plan)
    executor = PlanExecutor(src_plan, runner.metadata, runner.session)
    src_names, src_page = executor.execute()

    target_alias = stmt.target_alias or st.table
    tfields = _table_fields(meta, target_alias)
    from ..planner.logical_planner import Field

    src = stmt.source
    if isinstance(src, t.AliasedRelation):
        src_qualifier = src.alias
    elif isinstance(src, t.Table):
        src_qualifier = src.name.parts[-1]  # unaliased table: its own name
    else:
        src_qualifier = "source"
    sfields = [
        Field(n, c.type, "$src_" + n, qualifier=src_qualifier)
        for n, c in zip(src_names, src_page.columns)
    ]
    translator = _translator(runner, tfields + sfields)

    lhs, rhs = _single_equality(stmt.on)
    lhs_ir = translator.translate(lhs)
    rhs_ir = translator.translate(rhs)
    tsyms = {f.symbol for f in tfields}
    if getattr(lhs_ir, "symbol", None) in tsyms:
        t_key_ir, s_key_ir = lhs_ir, rhs_ir
    else:
        t_key_ir, s_key_ir = rhs_ir, lhs_ir

    tsymbols = tuple(c.name for c in meta.columns)
    ssymbols = tuple("$src_" + n for n in src_names)
    src_rel = Relation(src_page, ssymbols)

    # source key (evaluated once)
    s_fn, _ = compile_expression(s_key_ir, src_rel.layout(), src_rel.capacity)
    s_key = s_fn(src_rel.env())

    # hoist per-case semantic analysis out of the page loop (only
    # compile_expression depends on the page layout)
    col_types = {c.name: c.type for c in meta.columns}
    matched_cases = []
    for case in stmt.cases:
        if not case.matched:
            continue
        cond_ir = (
            translator.translate(case.condition)
            if case.condition is not None
            else None
        )
        assigns = []
        seen_cols = set()
        for colname, expr in case.assignments:
            if colname not in col_types:
                raise DmlError(f"MERGE UPDATE: unknown column {colname!r}")
            if colname in seen_cols:
                raise DmlError(
                    f"MERGE UPDATE: multiple assignments to column {colname!r}"
                )
            seen_cols.add(colname)
            ir = translator.translate(expr)
            target_t = col_types[colname]
            if ir.type != target_t:
                if not _assignable(ir.type, target_t):
                    raise DmlError(f"MERGE UPDATE {colname}: type mismatch")
                ir = _coerce(translator, ir, target_t)
            assigns.append((colname, target_t, ir))
        matched_cases.append((case, cond_ir, assigns))

    with _mutation_guard(connector):
        total_affected = 0
        new_pages = []
        table = connector.table(st)
        matched_any_src = jnp.zeros(src_page.capacity, dtype=jnp.bool_)

        for page in table.pages:
            # joint env: target page columns + broadcast of nothing — matched
            # source VALUES are gathered per target row below
            rel = Relation(page, tsymbols)
            t_fn, _ = compile_expression(t_key_ir, rel.layout(), rel.capacity)
            t_key = t_fn(rel.env())

            tk = jnp.where(t_key.valid, K.order_key(t_key.data), jnp.int64(K.INT64_MAX))
            sk = jnp.where(s_key.valid, K.order_key(s_key.data), jnp.int64(K.INT64_MAX - 1))
            if is_string(t_key_ir.type):
                # dictionaries may differ: compare via content-stable value keys
                td = t_key.dictionary
                sd = s_key.dictionary
                if td is not None and sd is not None and td.fingerprint() != sd.fingerprint():
                    tk = jnp.where(
                        t_key.valid,
                        jnp.asarray(td.value_keys())[jnp.clip(t_key.data, 0, len(td) - 1)],
                        jnp.int64(K.INT64_MAX),
                    )
                    sk = jnp.where(
                        s_key.valid,
                        jnp.asarray(sd.value_keys())[jnp.clip(s_key.data, 0, len(sd) - 1)],
                        jnp.int64(K.INT64_MAX - 1),
                    )
            perm_b, lo, hi, count = K.join_match(
                sk, s_key.valid & src_page.active, tk, t_key.valid & page.active
            )
            # null/inactive sentinels can collide in key space: only rows with a
            # VALID target key participate in matching at all
            live = page.active & t_key.valid
            if int(jnp.max(jnp.where(live, count, 0))) > 1:
                raise DmlError("MERGE: more than one source row matches a target row")
            matched = live & (count > 0)
            # the matching source row per target row (first match)
            safe_lo = jnp.clip(lo, 0, src_page.capacity - 1)
            src_pos = perm_b[safe_lo]
            matched_any_src = matched_any_src | _scatter_matched(
                src_pos, matched, src_page.capacity
            )

            # environment with source columns gathered to target rows
            env = dict(rel.env())
            gathered_cols = {}
            for sname, scol in zip(ssymbols, src_page.columns):
                g = Column(
                    scol.type,
                    scol.data[src_pos],
                    scol.valid[src_pos] & matched,
                    scol.dictionary,
                )
                gathered_cols[sname] = g
                env[sname] = _cval_of(g)
            joint_layout = dict(rel.layout())
            for sname, g in gathered_cols.items():
                from ..ops.compiler import ColumnLayout

                joint_layout[sname] = ColumnLayout(g.type, g.dictionary)

            active = page.active
            cols = list(page.columns)
            remaining = matched
            for case, cond_ir, assigns in matched_cases:
                if cond_ir is None:
                    fire = remaining
                else:
                    cfn, _ = compile_expression(cond_ir, joint_layout, page.capacity)
                    cv = cfn(env)
                    fire = remaining & cv.valid & cv.data.astype(jnp.bool_)
                remaining = remaining & ~fire
                total_affected += int(jnp.sum(fire.astype(jnp.int32)))
                if case.operation == "delete":
                    active = active & ~fire
                else:  # update
                    for colname, target_t, ir in assigns:
                        fn, out_dict = compile_expression(ir, joint_layout, page.capacity)
                        v = fn(env)
                        idx = tsymbols.index(colname)
                        new_col = _column_of(target_t, v, out_dict)
                        cols[idx] = _select_column(fire, new_col, cols[idx])
            new_pages.append(Page(tuple(cols), active))

        # WHEN NOT MATCHED THEN INSERT — source rows no target row matched.
        # A NULL-key source row matches nothing and therefore INSERTS (SQL MERGE
        # semantics) — do not require key validity here.
        insert_cases = [c for c in stmt.cases if not c.matched]
        if insert_cases:
            from ..sql.ir import references as _ir_refs

            unmatched = src_page.active & ~matched_any_src
            remaining = unmatched
            src_layout = dict(src_rel.layout())
            src_env = {s: _cval_of(c) for s, c in zip(ssymbols, src_page.columns)}

            def _check_source_only(ir, what: str):
                bad = _ir_refs(ir) - set(src_layout)
                if bad:
                    raise DmlError(
                        f"MERGE {what} may reference only source columns; "
                        f"target column(s) {sorted(bad)} are not visible there"
                    )

            for case in insert_cases:
                if case.operation != "insert":
                    raise DmlError("WHEN NOT MATCHED supports only INSERT")
                cond_ir = (
                    translator.translate(case.condition)
                    if case.condition is not None
                    else None
                )
                if cond_ir is not None:
                    _check_source_only(cond_ir, "WHEN NOT MATCHED condition")
                if cond_ir is None:
                    fire = remaining
                else:
                    cfn, _ = compile_expression(cond_ir, src_layout, src_page.capacity)
                    cv = cfn(src_env)
                    fire = remaining & cv.valid & cv.data.astype(jnp.bool_)
                remaining = remaining & ~fire
                n_ins = int(jnp.sum(fire.astype(jnp.int32)))
                total_affected += n_ins
                if n_ins == 0:
                    continue
                ins_cols_order = case.insert_columns or tsymbols
                if set(ins_cols_order) != set(tsymbols):
                    raise DmlError(
                        "MERGE INSERT must provide every target column"
                    )
                if len(case.insert_values) != len(ins_cols_order):
                    raise DmlError("MERGE INSERT: column/value count mismatch")
                by_col = dict(zip(ins_cols_order, case.insert_values))
                out_cols = []
                for cname in tsymbols:
                    ir = translator.translate(by_col[cname])
                    _check_source_only(ir, "INSERT value")
                    target_t = col_types[cname]
                    if ir.type != target_t:
                        if not _assignable(ir.type, target_t):
                            raise DmlError(f"MERGE INSERT {cname}: type mismatch")
                        ir = _coerce(translator, ir, target_t)
                    fn, out_dict = compile_expression(ir, src_layout, src_page.capacity)
                    v = fn(src_env)
                    out_cols.append(_column_of(target_t, v, out_dict))
                new_pages.append(Page(tuple(out_cols), fire))
        connector.replace_pages(st, new_pages)
    return total_affected


def _scatter_matched(src_pos, matched, cap: int):
    ids = jnp.where(matched, src_pos, cap).astype(jnp.int32)
    return (
        jnp.zeros((cap + 1,), dtype=jnp.bool_).at[ids].set(True, mode="drop")[:cap]
    )
