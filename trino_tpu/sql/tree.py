"""SQL abstract syntax tree.

Reference blueprint: core/trino-parser/src/main/java/io/trino/sql/tree/ (hundreds of
node classes; SURVEY.md §2.2). We keep the same node taxonomy — Statement / Query /
QueryBody / Relation / Expression — as frozen dataclasses. The planner consumes this
AST via the analyzer; a *separate* IR expression language (trino_tpu.sql.ir, mirroring
io.trino.sql.ir) is what the optimizer and compiler see.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional, Sequence, Tuple


class Node:
    """Base AST node."""

    __slots__ = ()


# --------------------------------------------------------------------------- #
# Expressions (ref: sql/tree/Expression.java and subclasses)
# --------------------------------------------------------------------------- #


class Expression(Node):
    __slots__ = ()


@dataclass(frozen=True)
class Identifier(Expression):
    name: str  # already lower-cased unless delimited

    def __str__(self):
        return self.name


@dataclass(frozen=True)
class QualifiedName(Node):
    parts: Tuple[str, ...]

    def __str__(self):
        return ".".join(self.parts)

    @property
    def last(self) -> str:
        return self.parts[-1]


@dataclass(frozen=True)
class Dereference(Expression):
    """Qualified column reference, e.g. l.orderkey (ref: DereferenceExpression.java)."""

    base: Expression
    fieldname: str

    def __str__(self):
        return f"{self.base}.{self.fieldname}"


@dataclass(frozen=True)
class Array(Expression):
    """ARRAY[e1, ...] constructor (ref: sql/tree/ArrayConstructor.java)."""

    items: tuple = ()


@dataclass(frozen=True)
class Subscript(Expression):
    """base[index] — array element / map value access (ref: SubscriptExpression.java)."""

    base: Expression = None
    index: Expression = None


@dataclass(frozen=True)
class LongLiteral(Expression):
    value: int


@dataclass(frozen=True)
class DoubleLiteral(Expression):
    value: float


@dataclass(frozen=True)
class DecimalLiteral(Expression):
    text: str  # e.g. "0.05" — scale preserved


@dataclass(frozen=True)
class StringLiteral(Expression):
    value: str


@dataclass(frozen=True)
class BooleanLiteral(Expression):
    value: bool


@dataclass(frozen=True)
class NullLiteral(Expression):
    pass


@dataclass(frozen=True)
class DateLiteral(Expression):
    """DATE 'YYYY-MM-DD' (ref: GenericLiteral with type DATE)."""

    text: str


@dataclass(frozen=True)
class TimestampLiteral(Expression):
    text: str


@dataclass(frozen=True)
class TimeLiteral(Expression):
    """TIME 'HH:MM:SS.fff' (ref: GenericLiteral with type TIME)."""

    text: str


@dataclass(frozen=True)
class IntervalLiteral(Expression):
    """INTERVAL '3' MONTH (ref: sql/tree/IntervalLiteral.java)."""

    value: str
    unit: str  # year|month|day|hour|minute|second
    sign: int = 1


class ArithmeticOp(Enum):
    ADD = "+"
    SUBTRACT = "-"
    MULTIPLY = "*"
    DIVIDE = "/"
    MODULUS = "%"


@dataclass(frozen=True)
class ArithmeticBinary(Expression):
    op: ArithmeticOp
    left: Expression
    right: Expression


@dataclass(frozen=True)
class ArithmeticUnary(Expression):
    op: str  # '-' or '+'
    value: Expression


class ComparisonOp(Enum):
    EQUAL = "="
    NOT_EQUAL = "<>"
    LESS_THAN = "<"
    LESS_THAN_OR_EQUAL = "<="
    GREATER_THAN = ">"
    GREATER_THAN_OR_EQUAL = ">="
    IS_DISTINCT_FROM = "IS DISTINCT FROM"


@dataclass(frozen=True)
class Comparison(Expression):
    op: ComparisonOp
    left: Expression
    right: Expression


@dataclass(frozen=True)
class Logical(Expression):
    op: str  # 'AND' | 'OR'
    terms: Tuple[Expression, ...]


@dataclass(frozen=True)
class Not(Expression):
    value: Expression


@dataclass(frozen=True)
class IsNull(Expression):
    value: Expression


@dataclass(frozen=True)
class IsNotNull(Expression):
    value: Expression


@dataclass(frozen=True)
class Between(Expression):
    value: Expression
    min: Expression
    max: Expression
    negated: bool = False


@dataclass(frozen=True)
class InList(Expression):
    value: Expression
    items: Tuple[Expression, ...]
    negated: bool = False


@dataclass(frozen=True)
class InSubquery(Expression):
    value: Expression
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class Exists(Expression):
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Expression):
    query: "Query"


@dataclass(frozen=True)
class Like(Expression):
    value: Expression
    pattern: Expression
    escape: Optional[Expression] = None
    negated: bool = False


@dataclass(frozen=True)
class FunctionCall(Expression):
    name: QualifiedName
    args: Tuple[Expression, ...]
    distinct: bool = False
    is_star: bool = False  # count(*)
    filter: Optional[Expression] = None
    window: Optional["WindowSpec"] = None
    # aggregate ordering: array_agg(x ORDER BY y) / listagg(..) WITHIN GROUP
    # (ORDER BY y) (ref: sql/tree/FunctionCall.java orderBy field)
    order_by: Tuple["SortItem", ...] = ()
    # IGNORE NULLS | RESPECT NULLS (ref: FunctionCall.nullTreatment), for
    # lead/lag/first_value/last_value/nth_value
    null_treatment: Optional[str] = None


@dataclass(frozen=True)
class WindowFrame(Node):
    """ROWS/RANGE frame (ref: sql/tree/WindowFrame.java). Bound kinds:
    UNBOUNDED_PRECEDING | PRECEDING | CURRENT_ROW | FOLLOWING |
    UNBOUNDED_FOLLOWING; value set for PRECEDING/FOLLOWING."""

    type_: str  # "ROWS" | "RANGE"
    start_kind: str
    end_kind: str
    start_value: Optional[int] = None
    end_value: Optional[int] = None


@dataclass(frozen=True)
class WindowSpec(Node):
    """OVER (PARTITION BY ... ORDER BY ... [frame]) (ref: sql/tree/WindowSpecification.java)."""

    partition_by: Tuple[Expression, ...]
    order_by: Tuple["SortItem", ...]
    frame: Optional[WindowFrame] = None


@dataclass(frozen=True)
class Lambda(Expression):
    """x -> expr | (x, y) -> expr (ref: sql/tree/LambdaExpression.java);
    only valid as an argument of a higher-order function."""

    params: Tuple[str, ...] = ()
    body: Expression = None


@dataclass(frozen=True)
class WhenClause(Node):
    condition: Expression
    result: Expression


@dataclass(frozen=True)
class SearchedCase(Expression):
    when_clauses: Tuple[WhenClause, ...]
    default: Optional[Expression]


@dataclass(frozen=True)
class SimpleCase(Expression):
    operand: Expression
    when_clauses: Tuple[WhenClause, ...]
    default: Optional[Expression]


@dataclass(frozen=True)
class Cast(Expression):
    value: Expression
    type_name: str
    safe: bool = False  # TRY_CAST


@dataclass(frozen=True)
class Extract(Expression):
    field_name: str  # YEAR|MONTH|DAY|...
    value: Expression


@dataclass(frozen=True)
class CurrentDate(Expression):
    pass


@dataclass(frozen=True)
class Row(Expression):
    items: Tuple[Expression, ...]


@dataclass(frozen=True)
class Star(Expression):
    """Bare ``*`` or ``t.*`` in a select list."""

    qualifier: Optional[QualifiedName] = None


# --------------------------------------------------------------------------- #
# Relations (ref: sql/tree/Relation.java subclasses)
# --------------------------------------------------------------------------- #


class Relation(Node):
    __slots__ = ()


@dataclass(frozen=True)
class Table(Relation):
    name: QualifiedName
    # time travel (FOR VERSION AS OF n — iceberg-style snapshot reads)
    version: object = None


@dataclass(frozen=True)
class AliasedRelation(Relation):
    relation: Relation
    alias: str
    column_names: Tuple[str, ...] = ()


@dataclass(frozen=True)
class TableSubquery(Relation):
    query: "Query"


@dataclass(frozen=True)
class Unnest(Relation):
    expressions: Tuple[Expression, ...]
    with_ordinality: bool = False


@dataclass(frozen=True)
class TableFunctionRelation(Relation):
    """TABLE(fn(args)) in FROM (ref: sql/tree/TableFunctionInvocation.java).

    ``args`` holds positional Expressions; ``named_args`` holds
    (name, value) pairs where value is an Expression, a Relation (TABLE
    argument), or a Descriptor (DESCRIPTOR(col, ...)) — the polymorphic
    table-function argument model (spi/function/table/Argument.java)."""

    name: str = ""
    args: Tuple[Expression, ...] = ()
    named_args: Tuple[Tuple[str, object], ...] = ()


@dataclass(frozen=True)
class Descriptor(Node):
    """DESCRIPTOR(a, b, ...) argument (sql/tree/Descriptor.java)."""

    columns: Tuple[str, ...] = ()


# --------------------------------------------------------------------------- #
# MATCH_RECOGNIZE (ref: sql/tree/PatternRecognitionRelation.java + the
# rowPattern grammar rules in SqlBase.g4)
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class PatternVariable(Node):
    name: str


@dataclass(frozen=True)
class PatternConcatenation(Node):
    elements: Tuple[Node, ...]


@dataclass(frozen=True)
class PatternAlternation(Node):
    alternatives: Tuple[Node, ...]


@dataclass(frozen=True)
class PatternQuantified(Node):
    """element{min,max}; max None = unbounded; greedy False = reluctant (?)."""

    element: Node
    min: int
    max: Optional[int]
    greedy: bool = True


@dataclass(frozen=True)
class MeasureItem(Node):
    expression: Expression
    name: str
    semantics: Optional[str] = None  # RUNNING | FINAL | None (context default)


@dataclass(frozen=True)
class SkipTo(Node):
    """AFTER MATCH SKIP: PAST_LAST | TO_NEXT_ROW | TO_FIRST var | TO_LAST var."""

    mode: str = "PAST_LAST"
    target: Optional[str] = None


@dataclass(frozen=True)
class MatchRecognize(Relation):
    relation: Relation = None
    partition_by: Tuple[Expression, ...] = ()
    order_by: Tuple["SortItem", ...] = ()
    measures: Tuple[MeasureItem, ...] = ()
    rows_per_match: str = "ONE"  # ONE | ALL
    after_skip: SkipTo = SkipTo()
    pattern: Node = None
    subsets: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
    defines: Tuple[Tuple[str, Expression], ...] = ()


class JoinType(Enum):
    INNER = "INNER"
    LEFT = "LEFT"
    RIGHT = "RIGHT"
    FULL = "FULL"
    CROSS = "CROSS"
    IMPLICIT = "IMPLICIT"


@dataclass(frozen=True)
class JoinOn(Node):
    expression: Expression


@dataclass(frozen=True)
class JoinUsing(Node):
    columns: Tuple[str, ...]


@dataclass(frozen=True)
class NaturalJoin(Node):
    pass


@dataclass(frozen=True)
class Join(Relation):
    join_type: JoinType
    left: Relation
    right: Relation
    criteria: Optional[Node] = None  # JoinOn | JoinUsing | NaturalJoin | None (cross)


@dataclass(frozen=True)
class Lateral(Relation):
    query: "Query"


# --------------------------------------------------------------------------- #
# Query structure (ref: sql/tree/{Query,QuerySpecification,Select,...}.java)
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class SortItem(Node):
    key: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = type default (last for ASC)


@dataclass(frozen=True)
class SelectItem(Node):
    expression: Expression
    alias: Optional[str] = None


@dataclass(frozen=True)
class GroupingElement(Node):
    expressions: Tuple[Expression, ...]
    kind: str = "simple"  # simple | rollup | cube | grouping_sets
    # for GROUPING SETS: the alternative sets (expressions is their union)
    sets: Optional[Tuple[Tuple[Expression, ...], ...]] = None


class QueryBody(Node):
    __slots__ = ()


@dataclass(frozen=True)
class QuerySpecification(QueryBody):
    select_items: Tuple[SelectItem, ...]
    distinct: bool = False
    from_: Optional[Relation] = None
    where: Optional[Expression] = None
    group_by: Tuple[GroupingElement, ...] = ()
    having: Optional[Expression] = None
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


class SetOpType(Enum):
    UNION = "UNION"
    INTERSECT = "INTERSECT"
    EXCEPT = "EXCEPT"


@dataclass(frozen=True)
class SetOperation(QueryBody):
    op: SetOpType
    left: QueryBody
    right: QueryBody
    distinct: bool = True  # False == ALL


@dataclass(frozen=True)
class Values(QueryBody):
    rows: Tuple[Expression, ...]  # each a Row or single expression


@dataclass(frozen=True)
class TableRef(QueryBody):
    """``TABLE t`` shorthand."""

    name: QualifiedName


@dataclass(frozen=True)
class WithQuery(Node):
    name: str
    query: "Query"
    column_names: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Query(Node):
    body: QueryBody
    with_queries: Tuple[WithQuery, ...] = ()
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0


# --------------------------------------------------------------------------- #
# Statements (ref: sql/tree/Statement.java subclasses)
# --------------------------------------------------------------------------- #


class Statement(Node):
    __slots__ = ()


@dataclass(frozen=True)
class QueryStatement(Statement):
    query: Query


@dataclass(frozen=True)
class Explain(Statement):
    statement: Statement
    analyze: bool = False
    explain_type: str = "LOGICAL"  # LOGICAL | DISTRIBUTED | IO
    # EXPLAIN ANALYZE VERBOSE: per-operator device/host/compile columns
    verbose: bool = False


@dataclass(frozen=True)
class ShowTables(Statement):
    schema: Optional[QualifiedName] = None


@dataclass(frozen=True)
class ShowSchemas(Statement):
    catalog: Optional[str] = None


@dataclass(frozen=True)
class ShowColumns(Statement):
    table: QualifiedName = None


@dataclass(frozen=True)
class ShowCatalogs(Statement):
    pass


@dataclass(frozen=True)
class ShowSession(Statement):
    pass


@dataclass(frozen=True)
class SetSession(Statement):
    name: QualifiedName = None
    value: Expression = None


@dataclass(frozen=True)
class ResetSession(Statement):
    """ref: sql/tree/ResetSession.java + execution/ResetSessionTask."""

    name: QualifiedName = None


@dataclass(frozen=True)
class CreateTableAsSelect(Statement):
    name: QualifiedName = None
    query: Query = None
    if_not_exists: bool = False


@dataclass(frozen=True)
class CreateCatalog(Statement):
    """CREATE CATALOG name USING connector [WITH (k = v, ...)]
    (ref: sql/tree/CreateCatalog.java)."""

    name: str = ""
    connector: str = ""
    properties: Tuple[Tuple[str, object], ...] = ()
    if_not_exists: bool = False


@dataclass(frozen=True)
class DropCatalog(Statement):
    name: str = ""
    if_exists: bool = False


@dataclass(frozen=True)
class CreateTable(Statement):
    """CREATE TABLE name (col type, ...) (ref: sql/tree/CreateTable.java)."""

    name: QualifiedName = None
    columns: Tuple[Tuple[str, str], ...] = ()  # (name, type text)
    if_not_exists: bool = False


@dataclass(frozen=True)
class InsertInto(Statement):
    table: QualifiedName = None
    columns: Tuple[str, ...] = ()
    query: Query = None


@dataclass(frozen=True)
class DropTable(Statement):
    name: QualifiedName = None
    if_exists: bool = False


@dataclass(frozen=True)
class CreateView(Statement):
    """CREATE [OR REPLACE] VIEW name AS query (ref: sql/tree/CreateView.java).
    ``query_text`` keeps the original SQL of the body: views are stored as
    text and re-analyzed at use, like the reference (ViewDefinition)."""

    name: QualifiedName = None
    query: Query = None
    query_text: str = ""
    replace: bool = False


@dataclass(frozen=True)
class DropView(Statement):
    """DROP VIEW [IF EXISTS] name (ref: sql/tree/DropView.java)."""

    name: QualifiedName = None
    if_exists: bool = False


@dataclass(frozen=True)
class CreateFunction(Statement):
    """CREATE [OR REPLACE] FUNCTION name(p type, ...) RETURNS type RETURN expr
    (ref: sql/tree/CreateFunction.java + routine/FunctionSpecification — the
    expression-bodied subset of SQL routines; compiled by inlining at use)."""

    name: QualifiedName = None
    parameters: Tuple[Tuple[str, str], ...] = ()  # (name, type text)
    return_type: str = ""
    body: Expression = None
    body_text: str = ""
    replace: bool = False


@dataclass(frozen=True)
class DropFunction(Statement):
    """DROP FUNCTION [IF EXISTS] name (ref: sql/tree/DropFunction.java)."""

    name: QualifiedName = None
    if_exists: bool = False


@dataclass(frozen=True)
class Use(Statement):
    """USE [catalog.]schema (ref: sql/tree/Use.java)."""

    catalog: Optional[str] = None
    schema: str = ""


@dataclass(frozen=True)
class ShowFunctions(Statement):
    """SHOW FUNCTIONS (ref: sql/tree/ShowFunctions.java)."""


@dataclass(frozen=True)
class Grant(Statement):
    """GRANT privs ON [TABLE] t TO [USER] grantee (ref: sql/tree/Grant.java)."""

    privileges: Tuple[str, ...] = ()  # empty = ALL PRIVILEGES
    table: QualifiedName = None
    grantee: str = ""


@dataclass(frozen=True)
class Revoke(Statement):
    """REVOKE privs ON [TABLE] t FROM [USER] grantee (sql/tree/Revoke.java)."""

    privileges: Tuple[str, ...] = ()
    table: QualifiedName = None
    grantee: str = ""


@dataclass(frozen=True)
class ShowCreate(Statement):
    """SHOW CREATE TABLE|VIEW name (ref: sql/tree/ShowCreate.java)."""

    kind: str = "table"  # "table" | "view"
    name: QualifiedName = None


@dataclass(frozen=True)
class Call(Statement):
    """CALL catalog.schema.procedure(arg, ...) (ref: sql/tree/Call.java +
    execution/CallTask — procedures live in connectors; the builtin registry
    is the system catalog's, e.g. system.runtime.kill_query)."""

    name: QualifiedName = None
    arguments: Tuple[Expression, ...] = ()


@dataclass(frozen=True)
class Parameter(Expression):
    """Positional ``?`` parameter (ref: sql/tree/Parameter.java); bound by
    EXECUTE ... USING."""

    index: int = 0


@dataclass(frozen=True)
class Prepare(Statement):
    """PREPARE name FROM statement (ref: sql/tree/Prepare.java)."""

    name: str = ""
    statement: Statement = None
    # original source text of the body, for the X-Trino-Added-Prepare
    # response header (the client re-sends it on later requests)
    body_text: str = ""


@dataclass(frozen=True)
class ExecuteStmt(Statement):
    """EXECUTE name [USING expr, ...] (ref: sql/tree/Execute.java)."""

    name: str = ""
    parameters: Tuple[Expression, ...] = ()


@dataclass(frozen=True)
class Deallocate(Statement):
    """DEALLOCATE PREPARE name (ref: sql/tree/Deallocate.java)."""

    name: str = ""


@dataclass(frozen=True)
class DescribeInput(Statement):
    name: str = ""


@dataclass(frozen=True)
class DescribeOutput(Statement):
    name: str = ""


@dataclass(frozen=True)
class StartTransaction(Statement):
    """ref: sql/tree/StartTransaction.java (transaction/TransactionManager)."""

    read_only: bool = False
    isolation: str = "SERIALIZABLE"


@dataclass(frozen=True)
class Commit(Statement):
    pass


@dataclass(frozen=True)
class Rollback(Statement):
    pass


@dataclass(frozen=True)
class Delete(Statement):
    """DELETE FROM t [WHERE cond] (ref: sql/tree/Delete.java)."""

    table: QualifiedName = None
    where: Optional[Expression] = None


@dataclass(frozen=True)
class Update(Statement):
    """UPDATE t SET c = e, ... [WHERE cond] (ref: sql/tree/Update.java)."""

    table: QualifiedName = None
    assignments: Tuple[Tuple[str, Expression], ...] = ()
    where: Optional[Expression] = None


@dataclass(frozen=True)
class MergeCase(Node):
    """One WHEN [NOT] MATCHED [AND cond] THEN ... clause."""

    matched: bool = True
    condition: Optional[Expression] = None
    operation: str = "update"  # update | delete | insert
    # update: ((col, expr), ...); insert: columns + values
    assignments: Tuple[Tuple[str, Expression], ...] = ()
    insert_columns: Tuple[str, ...] = ()
    insert_values: Tuple[Expression, ...] = ()


@dataclass(frozen=True)
class Merge(Statement):
    """MERGE INTO target USING source ON cond WHEN ... (ref: sql/tree/Merge.java)."""

    target: QualifiedName = None
    target_alias: Optional[str] = None
    source: Relation = None
    on: Expression = None
    cases: Tuple[MergeCase, ...] = ()


# --------------------------------------------------------------------------- #
# prepared-statement parameter utilities (ref: execution/ParameterExtractor +
# sql/planner ParameterRewriter — generic frozen-dataclass tree rewrite)
# --------------------------------------------------------------------------- #


def count_parameters(node) -> int:
    """Number of distinct positional parameters in a statement tree."""
    import dataclasses

    seen = set()

    def walk(v):
        if isinstance(v, Parameter):
            seen.add(v.index)
        elif dataclasses.is_dataclass(v) and not isinstance(v, type):
            for f in dataclasses.fields(v):
                walk(getattr(v, f.name))
        elif isinstance(v, (tuple, list)):
            for x in v:
                walk(x)

    walk(node)
    return len(seen)


def substitute_parameters(node, values):
    """Replace every Parameter(i) with ``values[i]`` (an Expression),
    rebuilding only the spine that changed."""
    import dataclasses

    def sub(v):
        if isinstance(v, Parameter):
            if v.index >= len(values):
                raise ValueError(
                    f"parameter ?{v.index + 1} has no bound value "
                    f"({len(values)} provided)"
                )
            return values[v.index]
        if dataclasses.is_dataclass(v) and not isinstance(v, type):
            changes = {}
            for f in dataclasses.fields(v):
                old = getattr(v, f.name)
                new = sub(old)
                if new is not old:
                    changes[f.name] = new
            return dataclasses.replace(v, **changes) if changes else v
        if isinstance(v, tuple):
            new = tuple(sub(x) for x in v)
            return new if any(a is not b for a, b in zip(new, v)) else v
        return v

    return sub(node)
