#!/usr/bin/env python
"""TPC-DS full-corpus conformance harness: parse / plan / execute / VERIFY
all 99 canonical queries (103 files with a/b variants), each in its own
child process with a hard timeout.

ref: the reference's result-verified conformance bar (H2QueryRunner +
QueryAssertions, SURVEY.md §4); our second engine is the sqlite oracle
(tests/tpcds_oracle.py) over identical generated data. ROLLUP/GROUPING
queries are outside sqlite's dialect and report "oracle-unsupported"
(their GROUPING machinery is result-checked by the pandas families in
tests/test_tpcds.py).

Usage:
  python tools/tpcds_conformance.py              # run all, write report
  python tools/tpcds_conformance.py --child q03  # internal per-query child
  python tools/tpcds_conformance.py --timeout 600 --scale 0.01

Writes TPCDS_CONFORMANCE.json {query: {status, rows, secs, detail}} and
prints the summary table. Statuses: verified | executed (oracle
unsupported) | mismatch | parse/plan/execute-error | timeout.
"""

import argparse
import glob
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CANON = (
    "/root/reference/testing/trino-benchmark-queries/src/main/resources/sql/trino/tpcds"
)
ROLLUP = {"q05", "q14a", "q18", "q22", "q27", "q36", "q67", "q70", "q77", "q80", "q86"}


def load_sql(name: str) -> str:
    sql = open(os.path.join(CANON, f"{name}.sql")).read().strip().rstrip(";")
    sql = sql.replace('"${database}"."${schema}".', "")
    return sql.replace("${database}.${schema}.", "")


def child(name: str, scale: float) -> None:
    """Runs in a subprocess: prints ONE json line with the result."""
    os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
    sys.path.insert(0, REPO)  # script lives in tools/: repo root isn't on path
    sys.setrecursionlimit(20000)  # q08-class giant IN-lists recurse in the parser
    out = {"query": name}
    t_start = time.time()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        cache = os.path.join(REPO, "tests", ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        try:
            jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
        except Exception:
            jax.config.update("jax_compilation_cache_dir", "")

        sql = load_sql(name)
        from trino_tpu.sql import parse_statement

        parse_statement(sql)
        out["parse"] = True

        from trino_tpu.connectors import tpcds as ds
        from trino_tpu.metadata import Session
        from trino_tpu.runtime import LocalQueryRunner

        schema = "sf" + f"{scale:g}".replace(".", "_")
        runner = LocalQueryRunner(Session(catalog="tpcds", schema=schema))
        runner.register_catalog("tpcds", ds.TpcdsConnector(scale=scale))
        runner.plan_sql(sql)
        out["plan"] = True

        res = runner.execute(sql)
        out["execute"] = True
        out["rows"] = len(res.rows)

        if name in ROLLUP:
            out["status"] = "executed"
            out["detail"] = "oracle-unsupported (ROLLUP/GROUPING)"
        else:
            sys.path.insert(0, os.path.join(REPO, "tests"))
            from tpcds_oracle import oracle_rows, rows_match, tpcds_sqlite

            con = tpcds_sqlite(scale)
            expected = oracle_rows(con, sql)
            diff = rows_match([tuple(r) for r in res.rows], expected, ordered=True)
            if diff is None:
                out["status"] = "verified"
            else:
                # ORDER BY ties differ legitimately across engines; retry
                # as a multiset before calling it a mismatch
                diff_unordered = rows_match(
                    [tuple(r) for r in res.rows], expected, ordered=False
                )
                if diff_unordered is None:
                    out["status"] = "verified"
                    out["detail"] = "tie-order differs (multiset equal)"
                else:
                    out["status"] = "mismatch"
                    out["detail"] = diff_unordered
    except Exception as e:  # noqa: BLE001 — every failure becomes a record
        if out.get("execute"):
            stage = "oracle"  # the ENGINE executed; the sqlite side failed
        elif out.get("plan"):
            stage = "execute"
        elif out.get("parse"):
            stage = "plan"
        else:
            stage = "parse"
        out["status"] = f"{stage}-error"
        out["detail"] = f"{type(e).__name__}: {str(e)[:200]}"
    out["secs"] = round(time.time() - t_start, 1)
    print(json.dumps(out), flush=True)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", help="internal: run one query and exit")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--timeout", type=int, default=900)
    ap.add_argument("--only", help="comma-separated query names")
    ap.add_argument("--out", default=os.path.join(REPO, "TPCDS_CONFORMANCE.json"))
    args = ap.parse_args()

    if args.child:
        child(args.child, args.scale)
        return

    names = sorted(
        os.path.basename(f)[:-4] for f in glob.glob(os.path.join(CANON, "q*.sql"))
    )
    if args.only:
        names = [n for n in names if n in set(args.only.split(","))]

    results = {}
    # resume support: a previous partial run's records are kept
    if os.path.exists(args.out):
        try:
            results = json.load(open(args.out))
        except ValueError:
            results = {}
    for i, name in enumerate(names):
        if name in results and results[name].get("status") not in (None, "timeout"):
            continue
        cmd = [
            sys.executable, os.path.abspath(__file__),
            "--child", name, "--scale", str(args.scale),
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=args.timeout,
                cwd=REPO,
            )
            line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
            try:
                results[name] = json.loads(line)
            except ValueError:
                results[name] = {
                    "query": name,
                    "status": "execute-error",
                    "detail": (proc.stderr or "no output")[-300:],
                }
        except subprocess.TimeoutExpired:
            results[name] = {
                "query": name, "status": "timeout", "secs": args.timeout,
            }
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1, sort_keys=True)
        r = results[name]
        print(
            f"[{i+1}/{len(names)}] {name}: {r.get('status')}"
            f" ({r.get('secs', '?')}s) {r.get('detail', '')}",
            flush=True,
        )

    counts = {}
    for r in results.values():
        counts[r.get("status", "?")] = counts.get(r.get("status", "?"), 0) + 1
    total = len(results)
    print("\n== TPC-DS conformance summary ==")
    print(f"files: {total}")
    for k in sorted(counts):
        print(f"  {k}: {counts[k]}")
    verified = counts.get("verified", 0)
    executed = verified + counts.get("executed", 0)
    print(f"executed (incl. verified): {executed}; verified: {verified}")


if __name__ == "__main__":
    main()
