"""SQL routines (CREATE FUNCTION ... RETURN expr).

Coverage model: the reference's sql/routine tests (TestSqlRoutineCompiler /
LanguageFunctionManager) for the expression-bodied subset — definition,
inlining at call sites, overload by arity, nesting, validation at CREATE,
recursion rejection, and DROP."""

import pytest

from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture()
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


class TestSqlRoutines:
    def test_define_and_call(self, runner):
        runner.execute(
            "CREATE FUNCTION double_it(x bigint) RETURNS bigint RETURN x * 2"
        )
        assert runner.execute("SELECT double_it(21)").rows == [(42,)]
        # inlined into vectorized execution over a table
        assert runner.execute(
            "SELECT sum(double_it(n_nationkey)) FROM nation"
        ).rows == [(600,)]

    def test_multiple_parameters_and_coercion(self, runner):
        runner.execute(
            "CREATE FUNCTION taxed(p double, t double) RETURNS double "
            "RETURN p * (1.0 + t)"
        )
        ((v,),) = runner.execute("SELECT taxed(10.0, 0.1)").rows
        assert abs(v - 11.0) < 1e-9
        # integer argument coerces to the declared double parameter
        ((v,),) = runner.execute("SELECT taxed(10, 0.5)").rows
        assert abs(v - 15.0) < 1e-9

    def test_overload_by_arity(self, runner):
        runner.execute("CREATE FUNCTION f(x bigint) RETURNS bigint RETURN x + 1")
        runner.execute(
            "CREATE FUNCTION f(x bigint, y bigint) RETURNS bigint RETURN x + y"
        )
        assert runner.execute("SELECT f(1), f(1, 10)").rows == [(2, 11)]

    def test_nested_routines(self, runner):
        runner.execute("CREATE FUNCTION g(x bigint) RETURNS bigint RETURN x * 3")
        runner.execute("CREATE FUNCTION h(x bigint) RETURNS bigint RETURN g(x) + 1")
        assert runner.execute("SELECT h(5)").rows == [(16,)]

    def test_case_body_and_strings(self, runner):
        runner.execute(
            "CREATE FUNCTION size_class(q double) RETURNS varchar RETURN "
            "CASE WHEN q < 10 THEN 'small' WHEN q < 40 THEN 'medium' "
            "ELSE 'large' END"
        )
        rows = runner.execute(
            "SELECT size_class(l_quantity), count(*) FROM lineitem "
            "GROUP BY 1 ORDER BY 1"
        ).rows
        assert [r[0] for r in rows] == ["large", "medium", "small"]

    def test_create_or_replace(self, runner):
        runner.execute("CREATE FUNCTION v() RETURNS bigint RETURN 1")
        with pytest.raises(Exception, match="already exists"):
            runner.execute("CREATE FUNCTION v() RETURNS bigint RETURN 2")
        runner.execute("CREATE OR REPLACE FUNCTION v() RETURNS bigint RETURN 2")
        assert runner.execute("SELECT v()").rows == [(2,)]

    def test_invalid_body_rejected_at_create(self, runner):
        with pytest.raises(Exception):
            runner.execute(
                "CREATE FUNCTION bad(x bigint) RETURNS bigint RETURN nope(x)"
            )
        # the failed CREATE left no registration behind
        with pytest.raises(Exception):
            runner.execute("SELECT bad(1)")

    def test_recursion_rejected(self, runner):
        with pytest.raises(Exception, match="recursive"):
            runner.execute(
                "CREATE FUNCTION r(x bigint) RETURNS bigint RETURN r(x - 1)"
            )

    def test_drop_function(self, runner):
        runner.execute("CREATE FUNCTION gone() RETURNS bigint RETURN 9")
        runner.execute("DROP FUNCTION gone")
        with pytest.raises(Exception):
            runner.execute("SELECT gone()")
        runner.execute("DROP FUNCTION IF EXISTS gone")  # no error
        with pytest.raises(Exception, match="not found"):
            runner.execute("DROP FUNCTION gone")

    def test_routine_in_where_and_join(self, runner):
        runner.execute(
            "CREATE FUNCTION is_even(x bigint) RETURNS boolean RETURN x % 2 = 0"
        )
        rows = runner.execute(
            "SELECT count(*) FROM nation WHERE is_even(n_nationkey)"
        ).rows
        assert rows == [(13,)]
