"""Logical/physical plan nodes.

Reference blueprint: core/trino-main/src/main/java/io/trino/sql/planner/plan/
(~60 node types; SURVEY.md §2.3). Round 1 implements the nodes needed for the SELECT
core + distribution: TableScan, Filter, Project, Aggregation (with partial/final
steps), Join, SemiJoin, Sort, TopN, Limit, Distinct (as Aggregation), Values, Union,
Window, Exchange, Output.

Symbols: plan-wide unique lowercase names (Trino's Symbol); every node lists its
``output_symbols`` and the types live in a side ``TypeProvider`` dict owned by the
plan, exactly like Trino's SymbolAllocator.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

from ..spi.connector import TableHandle
from ..spi.predicate import TupleDomain
from ..spi.types import Type
from ..sql.ir import IrExpr, Reference


class PlanNode:
    __slots__ = ()

    @property
    def sources(self) -> Tuple["PlanNode", ...]:
        raise NotImplementedError

    @property
    def output_symbols(self) -> Tuple[str, ...]:
        raise NotImplementedError

    def with_sources(self, sources: Tuple["PlanNode", ...]) -> "PlanNode":
        raise NotImplementedError


@dataclass(frozen=True)
class TableScanNode(PlanNode):
    """ref: sql/planner/plan/TableScanNode.java. ``assignments`` maps output symbol
    -> connector column name; ``constraint`` is the pushed-down TupleDomain keyed by
    column name (applyFilter absorbed it)."""

    table: TableHandle
    assignments: Tuple[Tuple[str, str], ...]  # (symbol, column_name)
    constraint: TupleDomain = TupleDomain.all()
    # stop-early row target from PushLimitIntoTableScan (guaranteed=false:
    # the LimitNode above still enforces the exact count)
    limit: Optional[int] = None

    @property
    def sources(self):
        return ()

    @property
    def output_symbols(self):
        return tuple(s for s, _ in self.assignments)

    def with_sources(self, sources):
        assert not sources
        return self


@dataclass(frozen=True)
class FilterNode(PlanNode):
    source: PlanNode = None
    predicate: IrExpr = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.source.output_symbols

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class ProjectNode(PlanNode):
    source: PlanNode = None
    assignments: Tuple[Tuple[str, IrExpr], ...] = ()  # symbol -> expression

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return tuple(s for s, _ in self.assignments)

    def with_sources(self, sources):
        return replace(self, source=sources[0])

    def is_identity(self) -> bool:
        return all(
            isinstance(e, Reference) and e.symbol == s for s, e in self.assignments
        )


class AggregationStep(Enum):
    SINGLE = "SINGLE"
    PARTIAL = "PARTIAL"
    FINAL = "FINAL"


@dataclass(frozen=True)
class Aggregation:
    """One aggregate: symbol <- fn(args) [FILTER mask_symbol]. Args are symbols
    (pre-projected), matching Trino's AggregationNode.Aggregation."""

    function: str
    args: Tuple[str, ...]
    distinct: bool = False
    filter: Optional[str] = None  # boolean symbol
    output_type: Type = None
    # ORDER BY inside the aggregate (array_agg(x ORDER BY y), listagg WITHIN
    # GROUP); ref AggregationNode.Aggregation orderingScheme
    ordering: Tuple["Ordering", ...] = ()


@dataclass(frozen=True)
class AggregationNode(PlanNode):
    """ref: sql/planner/plan/AggregationNode.java; executed by the analogue of
    HashAggregationOperator (SURVEY.md §2.5)."""

    source: PlanNode = None
    group_keys: Tuple[str, ...] = ()
    aggregations: Tuple[Tuple[str, Aggregation], ...] = ()
    step: AggregationStep = AggregationStep.SINGLE

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.group_keys + tuple(s for s, _ in self.aggregations)

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class TableFunctionNode(PlanNode):
    """Leaf produced by TABLE(fn(...)) (ref: plan/TableFunctionNode.java,
    operator/table/TableFunctionOperator.java). ``sequence`` generates its
    rows as one jnp.arange page — a pure device computation, no host loop."""

    symbols: Tuple[str, ...] = ()
    function: str = ""
    # host-evaluated constant arguments (sequence: start, stop, step)
    args: Tuple[object, ...] = ()

    @property
    def sources(self):
        return ()

    @property
    def output_symbols(self):
        return self.symbols

    def with_sources(self, sources):
        return self


@dataclass(frozen=True)
class UnnestNode(PlanNode):
    """Expand array/map columns into rows (ref: sql/planner/plan/UnnestNode.java,
    operator/unnest/UnnestOperator.java). TPU lowering: output capacity is the
    static ``cap * W`` lane grid; rows beyond each array's length stay inactive
    (pad-and-mask on the flattened element axis)."""

    source: PlanNode = None
    replicate_symbols: Tuple[str, ...] = ()
    # (input array/map symbol, output symbols — 1 for arrays, 2 for maps)
    unnest_symbols: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
    ordinality_symbol: Optional[str] = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        out = list(self.replicate_symbols)
        for _, outs in self.unnest_symbols:
            out.extend(outs)
        if self.ordinality_symbol:
            out.append(self.ordinality_symbol)
        return tuple(out)

    def with_sources(self, sources):
        return replace(self, source=sources[0])


class JoinKind(Enum):
    INNER = "INNER"
    LEFT = "LEFT"
    RIGHT = "RIGHT"
    FULL = "FULL"
    CROSS = "CROSS"


class JoinDistribution(Enum):
    PARTITIONED = "PARTITIONED"
    BROADCAST = "BROADCAST"  # replicate build side
    AUTO = "AUTO"


@dataclass(frozen=True)
class JoinNode(PlanNode):
    """ref: sql/planner/plan/JoinNode.java. criteria: equi-join clauses
    (left_symbol = right_symbol); ``filter`` is a residual non-equi condition."""

    left: PlanNode = None
    right: PlanNode = None
    kind: JoinKind = JoinKind.INNER
    criteria: Tuple[Tuple[str, str], ...] = ()
    filter: Optional[IrExpr] = None
    distribution: JoinDistribution = JoinDistribution.AUTO

    @property
    def sources(self):
        return (self.left, self.right)

    @property
    def output_symbols(self):
        return self.left.output_symbols + self.right.output_symbols

    def with_sources(self, sources):
        return replace(self, left=sources[0], right=sources[1])


@dataclass(frozen=True)
class SemiJoinNode(PlanNode):
    """x IN (subquery) -> boolean output symbol (ref: plan/SemiJoinNode.java).

    ``null_aware``: SQL IN three-valued semantics — the match column is NULL
    (not FALSE) when the probe key is NULL, or when it is unmatched and the
    filtering side contains a NULL (SemiJoinNode's output is nullable in the
    reference for exactly this). EXISTS-derived semi joins are two-valued."""

    source: PlanNode = None
    filtering_source: PlanNode = None
    source_key: str = ""
    filtering_key: str = ""
    output: str = ""  # boolean symbol appended to source outputs
    null_aware: bool = False

    @property
    def sources(self):
        return (self.source, self.filtering_source)

    @property
    def output_symbols(self):
        return self.source.output_symbols + (self.output,)

    def with_sources(self, sources):
        return replace(self, source=sources[0], filtering_source=sources[1])


@dataclass(frozen=True)
class Ordering:
    symbol: str
    ascending: bool = True
    nulls_first: bool = False


@dataclass(frozen=True)
class SortNode(PlanNode):
    source: PlanNode = None
    orderings: Tuple[Ordering, ...] = ()

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.source.output_symbols

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class TopNNode(PlanNode):
    """ref: plan/TopNNode.java; partial/final like Trino for distributed TopN."""

    source: PlanNode = None
    count: int = 0
    orderings: Tuple[Ordering, ...] = ()
    partial: bool = False

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.source.output_symbols

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class VectorTopNNode(PlanNode):
    """Fused scores -> top-k device program (tensor workload plane, ref
    arXiv:2306.08367 §5: keep the similarity matmul and the selection in ONE
    kernel launch). Produced by optimizer.fuse_vector_topn from
    ``TopN(Project)`` when the leading ORDER BY key is a vector-similarity
    score computed by the projection; the executor runs the projection
    closures AND the top-k permutation inside one jit program — strictly
    fewer device programs than the serial Project + TopN pair, bit-identical
    to it (same compiled expression closures, same stable sort kernel).

    ``assignments`` is the absorbed projection (output symbols == its
    symbols); ``orderings`` reference assignment symbols, like TopN's
    orderings reference its source's."""

    source: PlanNode = None
    assignments: Tuple[Tuple[str, IrExpr], ...] = ()
    count: int = 0
    orderings: Tuple[Ordering, ...] = ()
    partial: bool = False

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return tuple(s for s, _ in self.assignments)

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class LimitNode(PlanNode):
    source: PlanNode = None
    count: int = 0
    offset: int = 0
    partial: bool = False

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.source.output_symbols

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class ValuesNode(PlanNode):
    symbols: Tuple[str, ...] = ()
    rows: Tuple[Tuple[Any, ...], ...] = ()  # literal host values, storage repr

    @property
    def sources(self):
        return ()

    @property
    def output_symbols(self):
        return self.symbols

    def with_sources(self, sources):
        return self


@dataclass(frozen=True)
class UnionNode(PlanNode):
    """ref: plan/UnionNode.java; symbol_mapping[i] maps this node's outputs to the
    i-th source's symbols."""

    inputs: Tuple[PlanNode, ...] = ()
    symbols: Tuple[str, ...] = ()
    symbol_mapping: Tuple[Tuple[str, ...], ...] = ()  # per-source input symbols

    @property
    def sources(self):
        return self.inputs

    @property
    def output_symbols(self):
        return self.symbols

    def with_sources(self, sources):
        return replace(self, inputs=tuple(sources))


@dataclass(frozen=True)
class WindowFrame:
    """Planner frame (ref: plan/WindowNode.Frame). Mirrors tree.WindowFrame."""

    type_: str = "RANGE"  # "ROWS" | "RANGE"
    start_kind: str = "UNBOUNDED_PRECEDING"
    end_kind: str = "CURRENT_ROW"
    # int for ROWS; int or float for RANGE value offsets (DAYs for dates)
    start_value: Optional[float] = None
    end_value: Optional[float] = None


@dataclass(frozen=True)
class WindowFunction:
    function: str
    args: Tuple[str, ...]
    output_type: Type = None
    # None = the SQL default: RANGE UNBOUNDED PRECEDING..CURRENT ROW when the
    # spec has an ORDER BY, else the whole partition
    frame: Optional[WindowFrame] = None
    # per-arg constant value when the argument is a literal, else None —
    # scalar parameters (ntile N, lead/lag offset+default, nth_value N) must
    # be constants and are read host-side from here
    const_args: Tuple[object, ...] = ()
    # IGNORE NULLS (lead/lag/first_value/last_value/nth_value)
    ignore_nulls: bool = False


@dataclass(frozen=True)
class WindowNode(PlanNode):
    """ref: plan/WindowNode.java (operator/window/, SURVEY.md §2.5)."""

    source: PlanNode = None
    partition_by: Tuple[str, ...] = ()
    order_by: Tuple[Ordering, ...] = ()
    functions: Tuple[Tuple[str, WindowFunction], ...] = ()

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.source.output_symbols + tuple(s for s, _ in self.functions)

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class PatternRecognitionNode(PlanNode):
    """MATCH_RECOGNIZE (ref: plan/PatternRecognitionNode.java; the matcher is
    runtime/match_recognize.py, the Matcher.java/Program.java analogue).

    measures: (symbol, ir_expr, type) triples; defines: (var, ir_bool_expr);
    pattern: the sql.tree row-pattern AST (frozen dataclasses, hashable);
    subsets: union variables. rows_per_match: ONE | ALL."""

    source: PlanNode = None
    partition_by: Tuple[str, ...] = ()
    order_by: Tuple[Ordering, ...] = ()
    measures: Tuple[Tuple[str, object, object], ...] = ()
    rows_per_match: str = "ONE"
    skip_mode: str = "PAST_LAST"
    skip_target: Optional[str] = None
    pattern: object = None
    subsets: Tuple[Tuple[str, Tuple[str, ...]], ...] = ()
    defines: Tuple[Tuple[str, object], ...] = ()

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        if self.rows_per_match == "ONE":
            return self.partition_by + tuple(s for s, _, _ in self.measures)
        return self.source.output_symbols + tuple(s for s, _, _ in self.measures)

    def with_sources(self, sources):
        return replace(self, source=sources[0])


class ExchangeType(Enum):
    GATHER = "GATHER"
    REPARTITION = "REPARTITION"
    # range shuffle by the leading sort key — the distributed-sort data plane
    # (docs admin/dist-sort.md; consumer-side order replaces MergeOperator)
    REPARTITION_RANGE = "REPARTITION_RANGE"
    BROADCAST = "BROADCAST"


class ExchangeScope(Enum):
    LOCAL = "LOCAL"
    REMOTE = "REMOTE"


@dataclass(frozen=True)
class ExchangeNode(PlanNode):
    """ref: plan/ExchangeNode.java — the parallelism boundary. REMOTE exchanges
    become stage boundaries at fragmentation (PlanFragmenter.java:126); on TPU the
    REPARTITION data path is the ICI all-to-all (SURVEY.md §3.3 TPU mapping)."""

    source: PlanNode = None
    exchange_type: ExchangeType = ExchangeType.GATHER
    scope: ExchangeScope = ExchangeScope.REMOTE
    partition_keys: Tuple[str, ...] = ()
    # REPARTITION_RANGE: the sort order driving range boundaries; on a GATHER:
    # a merge-exchange marker (producer shards are sorted; concatenation in
    # shard order IS the merged order — ref operator/MergeOperator.java)
    orderings: Tuple[Ordering, ...] = ()

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.source.output_symbols

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class EnforceSingleRowNode(PlanNode):
    source: PlanNode = None

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.source.output_symbols

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass(frozen=True)
class OutputNode(PlanNode):
    """Root node: names the result columns (ref: plan/OutputNode.java)."""

    source: PlanNode = None
    column_names: Tuple[str, ...] = ()
    symbols: Tuple[str, ...] = ()

    @property
    def sources(self):
        return (self.source,)

    @property
    def output_symbols(self):
        return self.symbols

    def with_sources(self, sources):
        return replace(self, source=sources[0])


@dataclass
class LogicalPlan:
    """A plan tree + symbol types (Trino: PlanNode + TypeProvider/SymbolAllocator)."""

    root: PlanNode
    types: Dict[str, Type]

    def type_of(self, symbol: str) -> Type:
        return self.types[symbol]


def visit_plan(node: PlanNode, fn) -> None:
    """Pre-order traversal."""
    fn(node)
    for s in node.sources:
        visit_plan(s, fn)


def rewrite_plan(node: PlanNode, fn) -> PlanNode:
    """Bottom-up rewrite: fn(node_with_rewritten_sources) -> node."""
    new_sources = tuple(rewrite_plan(s, fn) for s in node.sources)
    if new_sources != node.sources:
        node = node.with_sources(new_sources)
    return fn(node)


def format_plan(plan: LogicalPlan, annotate=None) -> str:
    """EXPLAIN text (ref: sql/planner/planprinter/PlanPrinter.java).
    ``annotate(node) -> str`` appends per-node stats (EXPLAIN ANALYZE)."""
    lines: List[str] = []

    def fmt(node: PlanNode, indent: int):
        pad = "  " * indent
        name = type(node).__name__.replace("Node", "")
        detail = ""
        if isinstance(node, TableScanNode):
            detail = f"[{node.table}]"
            if node.constraint.domains:
                detail += f" constraint={[c for c, _ in node.constraint.domains]}"
        elif isinstance(node, FilterNode):
            detail = f"[{node.predicate}]"
        elif isinstance(node, ProjectNode):
            detail = "[" + ", ".join(f"{s} := {e}" for s, e in node.assignments) + "]"
        elif isinstance(node, AggregationNode):
            aggs = ", ".join(f"{s} := {a.function}({', '.join(a.args)})" for s, a in node.aggregations)
            detail = f"[{node.step.value} keys={list(node.group_keys)} {aggs}]"
        elif isinstance(node, JoinNode):
            crit = " AND ".join(f"{l} = {r}" for l, r in node.criteria)
            detail = f"[{node.kind.value} {crit}]"
        elif isinstance(node, VectorTopNNode):
            aggs = ", ".join(f"{s} := {e}" for s, e in node.assignments)
            detail = (
                f"[fused {node.count} by {[o.symbol for o in node.orderings]}"
                f"{' partial' if node.partial else ''} {aggs}]"
            )
        elif isinstance(node, (TopNNode,)):
            detail = f"[{node.count} by {[o.symbol for o in node.orderings]}{' partial' if node.partial else ''}]"
        elif isinstance(node, LimitNode):
            detail = f"[{node.count}]"
        elif isinstance(node, SortNode):
            detail = f"[{[o.symbol for o in node.orderings]}]"
        elif isinstance(node, ExchangeNode):
            detail = f"[{node.scope.value} {node.exchange_type.value} keys={list(node.partition_keys)}]"
        elif isinstance(node, OutputNode):
            detail = f"[{', '.join(node.column_names)}]"
        elif isinstance(node, ValuesNode):
            detail = f"[{len(node.rows)} rows]"
        extra = annotate(node) if annotate is not None else ""
        lines.append(f"{pad}- {name}{detail}{extra}")
        for s in node.sources:
            fmt(s, indent + 1)

    fmt(plan.root, 0)
    return "\n".join(lines)
