"""TIME and TIMESTAMP WITH TIME ZONE types + the sequence table function.

Model: the reference's TestTimeType / TestTimestampWithTimeZoneType
(spi/type/, DateTimeEncoding.java packed millis<<12|zoneKey representation)
and operator/table sequence function coverage.
"""

import datetime

import pytest


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.0005)


def one(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


class TestTimeType:
    def test_literal(self, runner):
        assert one(runner, "SELECT TIME '10:30:05.123'") == (
            datetime.time(10, 30, 5, 123000),
        )

    def test_extract_fields(self, runner):
        assert one(
            runner,
            "SELECT hour(TIME '10:30:05'), minute(TIME '10:30:05'), "
            "second(TIME '10:30:05')",
        ) == (10, 30, 5)

    def test_comparison_and_minmax(self, runner):
        assert one(runner, "SELECT TIME '09:00:00' < TIME '10:00:00'") == (True,)
        assert one(
            runner,
            "SELECT min(t1), max(t1) FROM "
            "(VALUES (TIME '09:00:00'), (TIME '17:30:00')) v(t1)",
        ) == (datetime.time(9, 0), datetime.time(17, 30))

    def test_cast_timestamp_to_time(self, runner):
        assert one(
            runner, "SELECT CAST(TIMESTAMP '2020-06-01 12:34:56' AS time)"
        ) == (datetime.time(12, 34, 56),)

    def test_null(self, runner):
        assert one(runner, "SELECT CAST(NULL AS time)") == (None,)


class TestTimestampWithTimeZone:
    def test_literal_fixed_offset(self, runner):
        (v,) = one(runner, "SELECT TIMESTAMP '2020-06-01 12:00:00 +05:30'")
        assert v.utcoffset() == datetime.timedelta(minutes=330)
        assert v.hour == 12

    def test_named_zone(self, runner):
        (v,) = one(runner, "SELECT TIMESTAMP '2020-06-01 12:00:00 Asia/Kolkata'")
        assert v.utcoffset() == datetime.timedelta(minutes=330)

    def test_equality_is_by_instant(self, runner):
        assert one(
            runner,
            "SELECT TIMESTAMP '2020-06-01 12:00:00 +05:30' = "
            "TIMESTAMP '2020-06-01 06:30:00 UTC'",
        ) == (True,)
        assert one(
            runner,
            "SELECT TIMESTAMP '2020-06-01 12:00:00 Asia/Kolkata' < "
            "TIMESTAMP '2020-06-01 07:00:00 UTC'",
        ) == (True,)

    def test_extract_in_value_zone(self, runner):
        assert one(
            runner,
            "SELECT hour(TIMESTAMP '2020-06-01 12:00:00 +05:30'), "
            "day(TIMESTAMP '2020-06-01 01:00:00 +05:30')",
        ) == (12, 1)

    def test_cast_to_timestamp_keeps_wall_time(self, runner):
        assert one(
            runner,
            "SELECT CAST(TIMESTAMP '2020-06-01 12:00:00 +05:30' AS timestamp)",
        ) == (datetime.datetime(2020, 6, 1, 12, 0),)

    def test_cast_from_timestamp_attaches_utc(self, runner):
        (v,) = one(
            runner,
            "SELECT CAST(TIMESTAMP '2020-06-01 12:00:00' AS "
            "timestamp(3) with time zone)",
        )
        assert v.utcoffset() == datetime.timedelta(0)

    def test_column_filter(self, runner):
        (n,) = one(
            runner,
            "SELECT count(*) FROM (SELECT CAST(o_orderdate AS "
            "timestamp(3) with time zone) AS ttz FROM orders) t "
            "WHERE ttz >= TIMESTAMP '1998-01-01 00:00:00 UTC'",
        )
        assert n > 0

    def test_type_display(self, runner):
        from trino_tpu.spi.types import parse_type

        t = parse_type("timestamp(3) with time zone")
        assert t.display() == "timestamp(3) with time zone"
        assert parse_type("time(3)").display() == "time(3)"


class TestSequenceTableFunction:
    def test_basic(self, runner):
        got = runner.execute("SELECT * FROM TABLE(sequence(1, 5))").rows
        assert got == [(1,), (2,), (3,), (4,), (5,)]

    def test_step_and_negative(self, runner):
        got = runner.execute("SELECT * FROM TABLE(sequence(10, 1, -3))").rows
        assert got == [(10,), (7,), (4,), (1,)]

    def test_aggregate_over_sequence(self, runner):
        assert one(
            runner, "SELECT sum(sequential_number) FROM TABLE(sequence(1, 100))"
        ) == (5050,)

    def test_join_with_table(self, runner):
        got = runner.execute(
            "SELECT s.sequential_number, n.n_name FROM TABLE(sequence(0, 2)) s "
            "JOIN nation n ON s.sequential_number = n.n_nationkey ORDER BY 1"
        ).rows
        assert got == [(0, "ALGERIA"), (1, "ARGENTINA"), (2, "BRAZIL")]

    def test_zero_step_rejected(self, runner):
        with pytest.raises(Exception, match="step"):
            runner.execute("SELECT * FROM TABLE(sequence(1, 5, 0))")


class TestTimeWithTimeZone:
    """TIME(p) WITH TIME ZONE (ref: spi/type/TimeWithTimeZoneType.java):
    packed UTC-normalized micros + offset, instant-ordered like TTZ."""

    def test_literal_and_display(self, runner):
        import datetime

        rows = runner.execute("SELECT TIME '10:00:00+02:00'").rows
        t = rows[0][0]
        assert t.hour == 10 and t.utcoffset() == datetime.timedelta(hours=2)

    def test_instant_ordering_and_comparison(self, runner):
        rows = runner.execute(
            "SELECT t FROM (VALUES (TIME '10:00:00+02:00'), "
            "(TIME '09:30:00+00:00'), (TIME '03:00:00-08:00')) x(t) ORDER BY t"
        ).rows
        instants = [
            (r[0].hour * 60 + r[0].minute) - r[0].utcoffset().total_seconds() // 60
            for r in rows
        ]
        assert instants == sorted(instants)
        assert runner.execute(
            "SELECT TIME '10:00:00+02:00' < TIME '09:30:00+00:00'"
        ).rows == [(True,)]

    def test_casts_both_ways(self, runner):
        import datetime

        rows = runner.execute(
            "SELECT CAST(TIME '10:00:00+02:00' AS time), "
            "CAST(TIME '12:34:56' AS time with time zone)"
        ).rows
        plain, withtz = rows[0]
        assert plain == datetime.time(10, 0)
        assert withtz.tzinfo == datetime.timezone.utc
        assert (withtz.hour, withtz.minute, withtz.second) == (12, 34, 56)

    def test_equality_is_by_instant(self, runner):
        # comparisons normalize to the instant (reference comparison
        # operators); DISTINCT/GROUP BY hash the packed (instant, zone)
        # pair — same documented deviation as TIMESTAMP W/ TZ
        assert runner.execute(
            "SELECT TIME '10:00:00+02:00' = TIME '08:00:00+00:00'"
        ).rows == [(True,)]
