"""Exchange data plane: serde v2 sliced frames, the device repartition
epilogue's bit-identity with the host rule, buffered exchange sinks, and
output-buffer backpressure accounting (ref: PagePartitioner +
PagesSerdeFactory + PartitionedOutputBuffer test matrices)."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trino_tpu import native
from trino_tpu.ops import repartition as R
from trino_tpu.runtime.serde import (
    LazyPageFrame,
    deserialize_page,
    serialize_page,
    serialize_page_slices,
)
from trino_tpu.spi.host_pages import (
    host_partition_targets,
    page_to_host,
    pages_from_host_rows,
)
from trino_tpu.spi.page import Column, Dictionary, Page
from trino_tpu.spi.types import parse_type

needs_native = pytest.mark.skipif(
    not native.native_available(), reason="g++ toolchain unavailable"
)

SCALE = 0.0005


def _scalar_page(tname: str, n: int = 300, cap: int = 512, seed: int = 0) -> Page:
    rng = np.random.default_rng(seed)
    t = parse_type(tname)
    if tname == "boolean":
        data = rng.random(n) < 0.5
    elif tname in ("real", "double"):
        data = rng.standard_normal(n)
    else:
        data = rng.integers(-100, 100, n)
    col = Column.from_numpy(t, data, valid=rng.random(n) > 0.2, capacity=cap)
    key = Column.from_numpy(
        parse_type("bigint"), rng.integers(0, 40, n), capacity=cap
    )
    active = np.zeros(cap, dtype=np.bool_)
    active[:n] = True
    active[rng.integers(0, n, n // 10)] = False  # filtered holes
    return Page((key, col), jnp.asarray(active))


def _roundtrip_vs_host(page: Page, key_idx, n_parts: int):
    """Device epilogue + sliced v2 frames must decode to EXACTLY the rows the
    host rule selects, in the same order, with the same masks."""
    cols, offsets, counts = R.repartition_to_host(page, key_idx, n_parts)
    frames = serialize_page_slices(cols, offsets, counts)
    hc = page_to_host(page)
    target = host_partition_targets(hc, list(key_idx), n_parts)
    for k in range(n_parts):
        expected = pages_from_host_rows(hc, target == k)
        got = deserialize_page(frames[k])
        assert got.to_pylist() == expected.to_pylist(), f"partition {k}"


class TestSerdeV2Roundtrip:
    @pytest.mark.parametrize(
        "tname",
        ["boolean", "tinyint", "smallint", "integer", "bigint", "real",
         "double", "date", "decimal(12,2)"],
    )
    def test_scalar_dtypes(self, tname):
        _roundtrip_vs_host(_scalar_page(tname), [0], 4)

    def test_dictionary_columns(self):
        rng = np.random.default_rng(7)
        n, cap = 400, 512
        words = ["alpha", "beta", "gamma", "delta", None]
        strs = Column.from_strings(
            [words[i % 5] for i in range(n)] + [None] * (cap - n)
        )
        key = Column.from_numpy(
            parse_type("bigint"), rng.integers(0, 25, n), capacity=cap
        )
        active = np.zeros(cap, dtype=np.bool_)
        active[:n] = True
        page = Page((key, strs), jnp.asarray(active))
        # hash by the STRING key too: dictionary value-key translation
        _roundtrip_vs_host(page, [0, 1], 5)
        # decoded frames carry a working dictionary
        cols, off, cnt = R.repartition_to_host(page, [0], 3)
        back = deserialize_page(serialize_page_slices(cols, off, cnt)[0])
        assert back.columns[1].dictionary is not None

    def test_long_decimal_lanes(self):
        rng = np.random.default_rng(9)
        from trino_tpu.ops.int128 import np_from_ints, np_to_ints

        n, cap = 200, 256
        vals = [int(x) for x in rng.integers(-(10**15), 10**15, n)]
        pad = np.zeros((cap, 2), dtype=np.int64)
        pad[:n] = np_from_ints(vals)
        active = np.zeros(cap, dtype=np.bool_)
        active[:n] = True
        dec = Column(parse_type("decimal(38,2)"), jnp.asarray(pad), jnp.asarray(active))
        key = Column.from_numpy(
            parse_type("bigint"), rng.integers(0, 9, n), capacity=cap
        )
        page = Page((key, dec), jnp.asarray(active))
        cols, off, cnt = R.repartition_to_host(page, [0], 4)
        got = []
        for f in serialize_page_slices(cols, off, cnt):
            p = deserialize_page(f)
            a = np.asarray(p.active)
            got.extend(np_to_ints(np.asarray(p.columns[1].data)[a]))
        assert sorted(v % 2**128 for v in vals) == sorted(v % 2**128 for v in got)

    def test_zero_row_page(self):
        page = _scalar_page("bigint")
        empty = Page(page.columns, jnp.zeros(page.capacity, dtype=jnp.bool_))
        cols, off, cnt = R.repartition_to_host(empty, [0], 3)
        assert cnt.sum() == 0
        for f in serialize_page_slices(cols, off, cnt):
            assert deserialize_page(f).to_pylist() == []

    def test_empty_partitions_decode_empty(self):
        # 1 distinct key + many partitions: most frames carry zero rows
        key = Column.from_numpy(parse_type("bigint"), np.full(64, 7), capacity=64)
        page = Page((key,), jnp.ones(64, dtype=jnp.bool_))
        cols, off, cnt = R.repartition_to_host(page, [0], 8)
        assert (cnt > 0).sum() == 1
        frames = serialize_page_slices(cols, off, cnt)
        sizes = [len(deserialize_page(f).to_pylist()) for f in frames]
        assert sorted(sizes, reverse=True) == [64] + [0] * 7

    def test_lazy_frame_header_and_padding(self):
        page = _scalar_page("bigint")
        cols, off, cnt = R.repartition_to_host(page, [0], 2)
        f = serialize_page_slices(cols, off, cnt)[0]
        lazy = LazyPageFrame(f)
        assert lazy.version == 2 and lazy.nrows == int(cnt[0])
        padded = lazy.to_page(capacity=4096)
        assert padded.capacity == 4096
        assert len(padded.to_pylist()) == int(cnt[0])

    def test_fused_frames_byte_identical_to_sliced(self):
        """repartition_frames (the fused per-partition production path) must
        emit the SAME bytes as the building-block contiguous-chunk path —
        the pool fan-out may only change which core builds a frame."""
        from trino_tpu.runtime.spiller import io_pool

        for tname in ("bigint", "double"):
            page = _scalar_page(tname, n=400)
            cols, off, cnt = R.repartition_to_host(page, [0], 6)
            want = serialize_page_slices(cols, off, cnt)
            got, got_cnt = R.repartition_frames(page, [0], 6, pool=io_pool())
            assert got == want
            assert list(got_cnt) == [int(c) for c in cnt]

    def test_v1_frames_still_decode(self):
        page = _scalar_page("double")
        blob = serialize_page(page)
        assert deserialize_page(blob).to_pylist() == page.to_pylist()
        lazy = LazyPageFrame(blob)
        assert lazy.version == 1
        assert lazy.to_page().to_pylist() == page.to_pylist()


class TestSerdeV2Rejection:
    def _frame(self):
        page = _scalar_page("bigint", n=400)
        cols, off, cnt = R.repartition_to_host(page, [0], 2)
        return serialize_page_slices(cols, off, cnt)[0]

    @needs_native
    def test_checksum_mismatch(self):
        f = bytearray(self._frame())
        f[-3] ^= 0xFF
        with pytest.raises(ValueError):
            deserialize_page(bytes(f))

    def test_truncated_frame(self):
        f = self._frame()
        for cut in (len(f) // 4, len(f) // 2, len(f) - 5):
            with pytest.raises(ValueError):
                deserialize_page(f[:cut])

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            deserialize_page(b"NOPE" + self._frame()[4:])


Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

Q13 = """
SELECT c_count, count(*) AS custdist
FROM (
  SELECT c_custkey, count(o_orderkey) AS c_count
  FROM customer LEFT JOIN orders ON c_custkey = o_custkey
    AND o_comment NOT LIKE '%special%requests%'
  GROUP BY c_custkey
) AS c_orders
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC
"""


class TestDeviceVsHostRepartition:
    """Distributed results must be BIT-IDENTICAL between the device epilogue
    and the legacy host path across repartitioned TPC-H plans."""

    def _run(self, sql: str, device: bool, monkeypatch) -> list:
        from trino_tpu.parallel.runner import DistributedQueryRunner

        monkeypatch.setenv(R.DEVICE_REPARTITION_ENV, "1" if device else "0")
        runner = DistributedQueryRunner.tpch(scale=SCALE, n_workers=4)
        runner.session.set("retry_policy", "TASK")
        return runner.execute(sql).rows

    @pytest.mark.parametrize("sql", [Q6, Q3, Q13], ids=["q6", "q3", "q13"])
    def test_fte_bit_identical(self, sql, monkeypatch):
        assert self._run(sql, True, monkeypatch) == self._run(
            sql, False, monkeypatch
        )


class TestOutputBufferAccounting:
    def _buffer(self, n=2):
        from trino_tpu.server.worker import OutputBuffer

        return OutputBuffer(n)

    def test_byte_counter_freed_on_ack(self):
        buf = self._buffer(1)
        for _ in range(3):
            buf.add(0, b"x" * 100)
        assert buf.buffered_bytes() == 300
        pages, token, _ = buf.get(0, 0, max_wait=0)
        assert len(pages) == 3
        buf.get(0, token, max_wait=0)  # token ack frees everything below
        assert buf.buffered_bytes() == 0

    def test_broadcast_charged_once_and_shared(self):
        buf = self._buffer(4)
        blob = b"y" * 1000
        buf.add_broadcast(blob)
        # charged once (split across buffers), NOT 4x
        assert buf.buffered_bytes() == 1000
        for b in range(4):
            pages, _, _ = buf.get(b, 0, max_wait=0)
            assert len(pages) == 1 and pages[0] is blob  # shared object

    def test_backpressure_wakes_on_ack(self, monkeypatch):
        from trino_tpu.server import worker as worker_mod

        monkeypatch.setattr(worker_mod, "MAX_UNACKED_BYTES", 100)
        buf = self._buffer(1)
        buf.add(0, b"a" * 101)  # over the limit: next add must block
        state = {"done": False}

        def producer():
            buf.add(0, b"b" * 10)
            state["done"] = True

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not state["done"], "add should block while consumer is behind"
        _, token, _ = buf.get(0, 0, max_wait=0)
        buf.get(0, token, max_wait=0)  # the ack frees bytes and notifies
        t.join(timeout=5)
        assert state["done"], "ack did not wake the blocked producer"

    def test_broadcast_backpressure_uses_shared_charge(self, monkeypatch):
        from trino_tpu.server import worker as worker_mod

        monkeypatch.setattr(worker_mod, "MAX_UNACKED_BYTES", 1000)
        buf = self._buffer(4)
        # old accounting charged each buffer the FULL blob -> blocked after
        # ~1 blob; shared accounting charges len/n per buffer, so 4 KiB of
        # distinct broadcast bytes fit before backpressure
        for _ in range(4):
            buf.add_broadcast(b"z" * 1000)  # must not block
        assert buf.buffered_bytes() == 4000


class TestBufferedSink:
    def test_part_sink_coalesces_and_skips_empty(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import ExchangeManager

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("q", 0)
        sink = ex.part_sink(0, 0)
        blobs = [bytes([i]) * (10 + i) for i in range(5)]
        for b in blobs:
            sink.add_part(0, b, rows=1)
        sink.add_part(2, b"last", rows=1)
        sink.commit()
        assert ex.source_part(0, 0) == blobs
        assert ex.source_part(0, 2) == [b"last"]
        assert ex.source_part(0, 1) == []  # never written -> no file
        assert ex.attempt_meta(0)["rows"] == 6

    def test_flush_at_target_keeps_open_handle(self, tmp_path, monkeypatch):
        from trino_tpu.runtime import exchange_spi

        monkeypatch.setattr(exchange_spi, "FLUSH_TARGET_BYTES", 64)
        mgr = exchange_spi.ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("q", 0)
        sink = ex.part_sink(0, 0)
        for i in range(10):
            sink.add_part(0, bytes([i]) * 40)
        sink.commit()
        assert ex.source_part(0, 0) == [bytes([i]) * 40 for i in range(10)]

    def test_streaming_read_is_lazy(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import ExchangeManager

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("q", 0)
        sink = ex.part_sink(0, 0)
        for i in range(4):
            sink.add_part(0, bytes([i]) * 8, rows=1)
        sink.commit()
        it = ex.iter_part(0, 0)
        assert next(it) == bytes([0]) * 8  # frames stream one at a time
        assert next(it) == bytes([1]) * 8
        it.close()

    def test_truncated_part_file_rejected(self, tmp_path):
        import os

        from trino_tpu.runtime.exchange_spi import ExchangeManager

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("q", 0)
        sink = ex.part_sink(0, 0)
        sink.add_part(0, b"payload-bytes", rows=1)
        sink.commit()
        path = os.path.join(
            ex.root, "p0", "attempt-0.parts", "part0.pages"
        )
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[:-4])
        with pytest.raises(ValueError, match="truncated"):
            ex.source_part(0, 0)


class TestExchangeFlightEvents:
    def test_repartition_serde_flush_events_paired(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import ExchangeManager
        from trino_tpu.runtime.fte_plane import emit_durable_output
        from trino_tpu.runtime.observability import (
            RECORDER,
            validate_chrome_trace,
        )

        page = _scalar_page("bigint", n=400)
        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("q", 0)
        RECORDER.clear()
        RECORDER.enable()
        try:
            emit_durable_output(
                {"dir": ex.root, "partition": 0, "attempt": 0, "n": 4,
                 "keys": ["k"], "symbols": ["k", "v"]},
                page,
            )
        finally:
            RECORDER.disable()
        trace = RECORDER.chrome_trace()
        assert validate_chrome_trace(trace) == []
        names = {e.get("name") for e in trace["traceEvents"]}
        assert {"repartition_kernel", "serde_encode", "exchange_flush"} <= names
        RECORDER.clear()
