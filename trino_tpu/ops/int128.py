"""Int128 arithmetic on TPU: two int64 limbs, pad-and-mask native.

Reference blueprint: core/trino-spi/src/main/java/io/trino/spi/type/
Int128.java:23 + Int128Math.java (the long-decimal representation behind
DECIMAL(p>18), TPC-DS's strict money type). The JVM carries a (high, low)
long pair per value; the TPU-native formulation carries the SAME two limbs
as a trailing axis of the column's data array — shape (cap, 2) = [hi, lo]
— so every row-level op is an elementwise int64 program (VPU-friendly, no
scalar loops) and permutation/slice/concat machinery works unchanged on
axis 0.

Conventions:
- hi is SIGNED (two's complement of the 128-bit value's top half); lo is
  the raw low 64 bits (int64 storage, unsigned semantics via xor-MIN
  comparisons).
- Division helpers require a divisor < 2**31 so schoolbook long division
  over 32-bit digits stays inside exact int64 — powers of ten chain in
  steps of 10**9 (Int128Math.rescale's divideRoundUp analogue).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

# numpy scalars, NOT jnp: module-level jnp scalars are device buffers that
# every closure captures as hoisted executable constants — two traces with
# identical HLO structure then disagree on parameter counts under the
# persistent compilation cache ("Execution supplied N buffers..."). numpy
# scalars inline as HLO literals.
_MIN64 = np.int64(np.iinfo(np.int64).min)
_MASK32 = np.int64(0xFFFFFFFF)


def hi(x: jnp.ndarray) -> jnp.ndarray:
    return x[..., 0]


def lo(x: jnp.ndarray) -> jnp.ndarray:
    return x[..., 1]


def make(hi_: jnp.ndarray, lo_: jnp.ndarray) -> jnp.ndarray:
    return jnp.stack(
        [hi_.astype(jnp.int64), lo_.astype(jnp.int64)], axis=-1
    )


def from_int64(x: jnp.ndarray) -> jnp.ndarray:
    x = x.astype(jnp.int64)
    return make(x >> jnp.int64(63), x)  # arithmetic shift sign-extends


def zeros(shape) -> jnp.ndarray:
    return jnp.zeros(tuple(shape) + (2,), dtype=jnp.int64)


def _ult(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Unsigned < over int64 storage."""
    return (a ^ _MIN64) < (b ^ _MIN64)


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    l = lo(a) + lo(b)  # wraps mod 2**64
    carry = _ult(l, lo(a)).astype(jnp.int64)
    return make(hi(a) + hi(b) + carry, l)


def negate(a: jnp.ndarray) -> jnp.ndarray:
    l = -lo(a)
    borrow = (lo(a) != 0).astype(jnp.int64)
    return make(-hi(a) - borrow, l)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return add(a, negate(b))


def is_negative(a: jnp.ndarray) -> jnp.ndarray:
    return hi(a) < 0


def abs_(a: jnp.ndarray) -> jnp.ndarray:
    neg = is_negative(a)
    n = negate(a)
    return make(jnp.where(neg, hi(n), hi(a)), jnp.where(neg, lo(n), lo(a)))


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (hi(a) == hi(b)) & (lo(a) == lo(b))


def lt(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return (hi(a) < hi(b)) | ((hi(a) == hi(b)) & _ult(lo(a), lo(b)))


def lte(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return lt(a, b) | eq(a, b)


def _shr32(x: jnp.ndarray) -> jnp.ndarray:
    """LOGICAL right shift by 32: the 32x32 partial products reach 2**64-2**33
    and wrap negative in int64 storage — an arithmetic shift would smear the
    sign bit over the high half."""
    import jax

    return jax.lax.shift_right_logical(x, jnp.int64(32))


def _mul_64x64(x: jnp.ndarray, y: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Unsigned 64x64 -> (hi, lo) via four 32x32 partial products (each an
    exact int64 multiply mod 2**64; carries recovered with logical shifts)."""
    x0, x1 = x & _MASK32, _shr32(x)
    y0, y1 = y & _MASK32, _shr32(y)
    p00 = x0 * y0
    p01 = x0 * y1
    p10 = x1 * y0
    p11 = x1 * y1
    mid = _shr32(p00) + (p01 & _MASK32) + (p10 & _MASK32)
    lo_ = (p00 & _MASK32) | ((mid & _MASK32) << jnp.int64(32))
    hi_ = p11 + _shr32(p01) + _shr32(p10) + _shr32(mid)
    return hi_, lo_


def mul_int64(a: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """int128 * int64 keeping the low 128 bits (results must fit p<=38)."""
    k = jnp.asarray(k, dtype=jnp.int64)
    ph, pl = _mul_64x64(lo(a), k)
    # _mul_64x64 treats lo(a) as unsigned (correct: lo IS unsigned) and k
    # as unsigned (k<0 overcounts by 2**64 * lo(a) — subtract it back);
    # hi(a)*k wraps mod 2**64, exactly the low-128 contribution
    h = ph + hi(a) * k - jnp.where(k < 0, lo(a), jnp.int64(0))
    return make(h, pl)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """int128 * int128 keeping the low 128 bits. No sign corrections are
    needed: (a_hi*2**64 + ulo_a)(b_hi*2**64 + ulo_b) mod 2**128 =
    ulo*ulo + 2**64*(a_hi*ulo_b + b_hi*ulo_a), and int64 wrap-multiply is
    exact mod 2**64 regardless of sign interpretation."""
    ph, pl = _mul_64x64(lo(a), lo(b))
    h = ph + hi(a) * lo(b) + lo(a) * hi(b)
    return make(h, pl)


def mul_i64_i64(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """Exact signed int64 * int64 -> int128 (the short*short product that
    overflows long: DECIMAL(18,s) * DECIMAL(18,s))."""
    ph, pl = _mul_64x64(x, y)
    # signed corrections for the unsigned partial product
    ph = ph - jnp.where(x < 0, y, jnp.int64(0)) - jnp.where(y < 0, x, jnp.int64(0))
    return make(ph, pl)


def divmod_u32(a: jnp.ndarray, d: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """NON-NEGATIVE int128 // d and remainder, d < 2**31: schoolbook long
    division over four 32-bit digits (remainder stays < 2**31, so every
    intermediate fits exact int64)."""
    assert 0 < d < (1 << 31), d
    dd = jnp.int64(d)
    digits = [
        (hi(a) >> jnp.int64(32)) & _MASK32,
        hi(a) & _MASK32,
        (lo(a) >> jnp.int64(32)) & _MASK32,
        lo(a) & _MASK32,
    ]
    r = jnp.zeros_like(hi(a))
    qs = []
    for dig in digits:
        cur = (r << jnp.int64(32)) | dig
        qs.append(cur // dd)
        r = cur - qs[-1] * dd
    q_hi = (qs[0] << jnp.int64(32)) | qs[1]
    q_lo = (qs[2] << jnp.int64(32)) | qs[3]
    return make(q_hi, q_lo), r


def div_round_pow10(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a / 10**k with round-half-up on the magnitude (Int128Math.rescale's
    divideRoundUp): chained 10**9 steps keep divisors < 2**31."""
    if k == 0:
        return a
    neg = is_negative(a)
    m = abs_(a)
    rem_scale = 1
    left = k
    while left > 0:
        step = min(left, 9)
        d = 10**step
        if left - step == 0:
            # final step: round half up using this step's remainder
            m, r = divmod_u32(m, d)
            m = add(m, from_int64((2 * r >= d).astype(jnp.int64)))
        else:
            m, _ = divmod_u32(m, d)
        left -= step
        rem_scale *= d
    n = negate(m)
    return make(
        jnp.where(neg, hi(n), hi(m)), jnp.where(neg, lo(n), lo(m))
    )


def div_int(a: jnp.ndarray, d: jnp.ndarray) -> jnp.ndarray:
    """a / d (round-half-up on magnitude) for POSITIVE array divisors
    d < 2**31 — the decimal AVG denominator (group counts)."""
    dd = jnp.maximum(d.astype(jnp.int64), 1)
    neg = is_negative(a)
    m = abs_(a)
    digits = [
        (hi(m) >> jnp.int64(32)) & _MASK32,
        hi(m) & _MASK32,
        (lo(m) >> jnp.int64(32)) & _MASK32,
        lo(m) & _MASK32,
    ]
    r = jnp.zeros_like(hi(m))
    qs = []
    for dig in digits:
        cur = (r << jnp.int64(32)) | dig
        qs.append(cur // dd)
        r = cur - qs[-1] * dd
    q = make((qs[0] << jnp.int64(32)) | qs[1], (qs[2] << jnp.int64(32)) | qs[3])
    q = add(q, from_int64((2 * r >= dd).astype(jnp.int64)))
    n = negate(q)
    return make(jnp.where(neg, hi(n), hi(q)), jnp.where(neg, lo(n), lo(q)))


def scale_up_pow10(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """a * 10**k (rescale to a larger scale), chained in exact steps."""
    left = k
    out = a
    while left > 0:
        step = min(left, 18)
        out = mul_int64(out, jnp.int64(10**step))
        left -= step
    return out


def to_float64(a: jnp.ndarray) -> jnp.ndarray:
    # sign-magnitude: summing the signed-hi and unsigned-lo terms directly
    # cancels catastrophically near zero (-1 -> -2**64 + (2**64-1) rounds
    # to 0.0); with a non-negative magnitude both terms round the same way
    neg = is_negative(a)
    m = abs_(a)
    ulo = lo(m).astype(jnp.float64) + jnp.where(
        lo(m) < 0, jnp.float64(2.0**64), jnp.float64(0.0)
    )
    f = hi(m).astype(jnp.float64) * jnp.float64(2.0**64) + ulo
    return jnp.where(neg, -f, f)


def fits_int64(a: jnp.ndarray) -> jnp.ndarray:
    """True where the value is representable as int64 (hi is pure sign
    extension of lo)."""
    return hi(a) == (lo(a) >> jnp.int64(63))


def order_key_pair(a: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(primary, secondary) int64 sort keys: signed hi, then lo shifted to
    signed order (unsigned lo compares via xor MIN)."""
    return hi(a), lo(a) ^ _MIN64


# ------------------------------------------------------------------ host side


def np_from_ints(vals) -> np.ndarray:
    """Host: iterable of python ints -> (n, 2) int64 limbs (values wrap to
    signed int64 storage)."""

    def signed(x: int) -> int:
        return (x + 2**63) % 2**64 - 2**63

    hi_ = np.array([signed(int(v) >> 64) for v in vals], dtype=np.int64)
    lo_ = np.array([signed(int(v) & ((1 << 64) - 1)) for v in vals], dtype=np.int64)
    return np.stack([hi_, lo_], axis=-1)


def np_to_ints(limbs: np.ndarray) -> list:
    """Host: (n, 2) limbs -> python ints."""
    out = []
    for h, l in limbs:
        out.append((int(h) << 64) | (int(l) & ((1 << 64) - 1)))
    return out
