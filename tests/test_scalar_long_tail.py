"""Round-3 scalar function batch.

Coverage model: the reference's operator/scalar tests — MathFunctions,
BitwiseFunctions, DateTimeFunctions (ISO week semantics), StringFunctions.
"""

import datetime
import math

import pytest

from trino_tpu.runtime import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


def one(runner, expr):
    return runner.execute(f"SELECT {expr}").rows[0][0]


class TestMath:
    def test_constants(self, runner):
        assert abs(one(runner, "pi()") - math.pi) < 1e-15
        assert abs(one(runner, "e()") - math.e) < 1e-15
        assert math.isnan(one(runner, "nan()"))
        assert math.isinf(one(runner, "infinity()"))

    def test_angle_and_hyperbolic(self, runner):
        assert abs(one(runner, "degrees(pi())") - 180.0) < 1e-12
        assert abs(one(runner, "radians(180.0)") - math.pi) < 1e-12
        assert abs(one(runner, "cosh(1.0)") - math.cosh(1)) < 1e-12
        assert abs(one(runner, "tanh(0.5)") - math.tanh(0.5)) < 1e-12

    def test_truncate(self, runner):
        assert one(runner, "truncate(3.789)") == 3.0
        assert abs(one(runner, "truncate(3.789, 2)") - 3.78) < 1e-12
        assert one(runner, "truncate(-3.789)") == -3.0

    def test_predicates(self, runner):
        assert one(runner, "is_nan(nan())") is True
        assert one(runner, "is_finite(1.0)") is True
        assert one(runner, "is_infinite(1.0 / 0.0)") in (True, None)

    def test_width_bucket(self, runner):
        assert one(runner, "width_bucket(5.0, 0.0, 10.0, 4)") == 3
        assert one(runner, "width_bucket(-1.0, 0.0, 10.0, 4)") == 0
        assert one(runner, "width_bucket(11.0, 0.0, 10.0, 4)") == 5

    def test_random_bounds(self, runner):
        rows = runner.execute(
            "SELECT min(r) >= 0.0, max(r) < 1.0 FROM "
            "(SELECT random() AS r FROM lineitem)"
        ).rows
        assert rows == [(True, True)]
        (distinct,) = runner.execute(
            "SELECT count(DISTINCT r) FROM (SELECT random() AS r FROM lineitem)"
        ).rows[0]
        assert distinct > 100


class TestBitwise:
    def test_basics(self, runner):
        assert one(runner, "bitwise_and(12, 10)") == 8
        assert one(runner, "bitwise_or(12, 10)") == 14
        assert one(runner, "bitwise_xor(12, 10)") == 6
        assert one(runner, "bitwise_not(0)") == -1
        assert one(runner, "bitwise_not(-1)") == 0

    def test_shifts(self, runner):
        assert one(runner, "bitwise_left_shift(1, 10)") == 1024
        assert one(runner, "bitwise_right_shift(1024, 3)") == 128
        # logical right shift of a negative (the reference's semantics)
        assert one(runner, "bitwise_right_shift(-1, 62)") == 3

    def test_bit_count(self, runner):
        assert one(runner, "bit_count(255)") == 8
        assert one(runner, "bit_count(0)") == 0
        assert one(runner, "bit_count(-1, 64)") == 64
        assert one(runner, "bit_count(-1, 8)") == 8


class TestDatetimeLongTail:
    def test_iso_week_edges(self, runner):
        # 2026-01-01 is a Thursday: week 1 of 2026
        assert one(runner, "week(DATE '2026-01-01')") == 1
        assert one(runner, "year_of_week(DATE '2026-01-01')") == 2026
        # 2021-01-01 is a Friday: ISO week 53 of 2020
        assert one(runner, "week(DATE '2021-01-01')") == 53
        assert one(runner, "yow(DATE '2021-01-01')") == 2020
        # 2024-12-30 is a Monday: week 1 of 2025
        assert one(runner, "week(DATE '2024-12-30')") == 1
        assert one(runner, "year_of_week(DATE '2024-12-30')") == 2025

    def test_week_against_python(self, runner):
        rows = runner.execute(
            "SELECT o_orderdate, week(o_orderdate), year_of_week(o_orderdate) "
            "FROM orders LIMIT 200"
        ).rows
        for d, w, wy in rows:
            iso = d.isocalendar()
            assert (wy, w) == (iso[0], iso[1]), d

    def test_last_day_of_month(self, runner):
        assert one(runner, "last_day_of_month(DATE '2024-02-10')") == datetime.date(2024, 2, 29)
        assert one(runner, "last_day_of_month(DATE '2023-02-10')") == datetime.date(2023, 2, 28)
        assert one(runner, "last_day_of_month(DATE '2026-12-31')") == datetime.date(2026, 12, 31)

    def test_aliases(self, runner):
        assert one(runner, "day_of_month(DATE '2026-07-30')") == 30
        assert one(runner, "dow(DATE '2026-07-30')") == 4  # Thursday
        assert one(runner, "doy(DATE '2026-02-01')") == 32


class TestStringLongTail:
    def test_split_part(self, runner):
        assert one(runner, "split_part('a,b,c', ',', 2)") == "b"
        assert one(runner, "split_part('a,b,c', ',', 9)") is None

    def test_translate(self, runner):
        assert one(runner, "translate('hello', 'el', 'ip')") == "hippo"
        # unmapped from-characters are deleted
        assert one(runner, "translate('abcd', 'bd', 'x')") == "axc"

    def test_codepoint(self, runner):
        assert one(runner, "codepoint('A')") == 65

    def test_distances_over_column(self, runner):
        rows = runner.execute(
            "SELECT n_name, levenshtein_distance(n_name, 'CHINA') FROM nation "
            "WHERE n_name IN ('CHINA', 'INDIA') ORDER BY n_name"
        ).rows
        assert rows == [("CHINA", 0), ("INDIA", 4)]
        assert one(runner, "hamming_distance('abc', 'abd')") == 1
        assert one(runner, "hamming_distance('abc', 'abcd')") is None


class TestRound4ScalarBatch:
    """Math CDFs, hash/encoding family (hex-string deviation noted in
    compiler), regexp counts, Wilson intervals, timezone extracts.
    ref: scalar/MathFunctions.java (normalCdf/inverseNormalCdf/betaCdf),
    WilsonInterval.java, VarbinaryFunctions.java, JoniRegexpFunctions."""

    def test_math_cdfs(self, runner):
        row = runner.execute(
            "SELECT log(2.0, 8.0), normal_cdf(0.0, 1.0, 1.96), "
            "inverse_normal_cdf(0.0, 1.0, 0.975), beta_cdf(2.0, 3.0, 0.5)"
        ).rows[0]
        for got, exp in zip(row, (3.0, 0.97500, 1.95996, 0.6875)):
            assert abs(got - exp) < 1e-4, (got, exp)

    def test_wilson_interval(self, runner):
        row = runner.execute(
            "SELECT wilson_interval_lower(10, 100, 1.96), "
            "wilson_interval_upper(10, 100, 1.96)"
        ).rows[0]
        for got, exp in zip(row, (0.05522, 0.17437)):
            assert abs(got - exp) < 1e-4, (got, exp)

    def test_hash_and_encoding(self, runner):
        rows = runner.execute(
            "SELECT md5('abc'), sha256(''), crc32('abc'), "
            "to_base64('hello'), from_base64('aGVsbG8='), "
            "to_hex('AB'), from_hex('4142')"
        ).rows
        assert rows == [(
            "900150983cd24fb0d6963f7d28e17f72",
            "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855",
            891568578, "aGVsbG8=", "hello", "4142", "AB",
        )]

    def test_regexp_count_position(self, runner):
        rows = runner.execute(
            "SELECT regexp_count('a1b2c3', '[0-9]'), "
            "regexp_position('xxy7', '[0-9]'), regexp_position('xxy', '[0-9]')"
        ).rows
        assert rows == [(3, 4, -1)]

    def test_luhn_and_iso_date(self, runner):
        import datetime

        rows = runner.execute(
            "SELECT luhn_check('79927398713'), luhn_check('79927398714'), "
            "from_iso8601_date('2001-08-22')"
        ).rows
        assert rows == [(True, False, datetime.date(2001, 8, 22))]

    def test_timezone_extracts(self, runner):
        rows = runner.execute(
            "SELECT timezone_hour(TIMESTAMP '2001-08-22 03:04:05.321 +07:09'), "
            "timezone_minute(TIMESTAMP '2001-08-22 03:04:05.321 +07:09')"
        ).rows
        assert rows == [(7, 9)]

    def test_normalize(self, runner):
        rows = runner.execute("SELECT normalize('café')").rows
        assert rows == [("café",)]


class TestRound5Cdfs:
    """Distribution CDFs vs scipy-free closed forms (MathFunctions.java)."""

    def test_symmetry_points(self, runner):
        assert abs(one(runner, "cauchy_cdf(0.0, 1.0, 0.0)") - 0.5) < 1e-12
        assert abs(one(runner, "laplace_cdf(0.0, 1.0, 0.0)") - 0.5) < 1e-12
        assert abs(one(runner, "t_cdf(10.0, 0.0)") - 0.5) < 1e-12

    def test_known_values(self, runner):
        # chi2(k=2) cdf at 2 = 1 - exp(-1)
        assert abs(one(runner, "chi_squared_cdf(2.0, 2.0)") - (1 - math.exp(-1))) < 1e-9
        # weibull(1,1) is exponential(1)
        assert abs(one(runner, "weibull_cdf(1.0, 1.0, 1.0)") - (1 - math.exp(-1))) < 1e-9
        # poisson cdf at k=large ~ 1
        assert abs(one(runner, "poisson_cdf(1.0, 100)") - 1.0) < 1e-9
        # binomial(10, 0.5) P(X<=5) known
        assert abs(one(runner, "binomial_cdf(10, 0.5, 5)") - 0.623046875) < 1e-6

    def test_inverse_round_trips(self, runner):
        assert abs(one(runner, "cauchy_cdf(1.0, 2.0, inverse_cauchy_cdf(1.0, 2.0, 0.3))") - 0.3) < 1e-9
        assert abs(one(runner, "laplace_cdf(1.0, 2.0, inverse_laplace_cdf(1.0, 2.0, 0.7))") - 0.7) < 1e-9
        assert abs(one(runner, "weibull_cdf(2.0, 3.0, inverse_weibull_cdf(2.0, 3.0, 0.4))") - 0.4) < 1e-9

    def test_t_pdf_integrates_to_cdf_slope(self, runner):
        # numeric: d/dx t_cdf ~= t_pdf at 0
        h = 1e-5
        slope = (one(runner, f"t_cdf(10.0, {h})") - one(runner, f"t_cdf(10.0, {-h})")) / (2 * h)
        assert abs(slope - one(runner, "t_pdf(10.0, 0.0)")) < 1e-5


class TestRound5Strings:
    def test_length_aliases_and_positions(self, runner):
        assert one(runner, "char_length('hello')") == 5
        assert one(runner, "character_length('hello')") == 5
        assert one(runner, "ends_with('hello', 'llo')") is True
        assert one(runner, "strrpos('ababa', 'a')") == 5
        assert one(runner, "strrpos('ababa', 'z')") == 0

    def test_soundex_known(self, runner):
        assert one(runner, "soundex('Robert')") == "R163"
        assert one(runner, "soundex('Rupert')") == "R163"
        assert one(runner, "soundex('Tymczak')") == "T522"

    def test_utf8_round_trip(self, runner):
        assert one(runner, "from_utf8(to_utf8('héllo'))") == "héllo"

    def test_hashes_known_vectors(self, runner):
        assert one(runner, "xxhash64('hello')") == "26c7827d889f6da3"
        import hmac as _hmac

        assert one(runner, "hmac_sha256('msg', 'key')") == _hmac.new(
            b"key", b"msg", "sha256"
        ).hexdigest()

    def test_split_family(self, runner):
        assert one(runner, "split('a,b,c', ',')") == ["a", "b", "c"]
        assert one(runner, "split('a,b,c', ',', 2)") == ["a", "b,c"]
        assert one(runner, "regexp_split('one1two2three', '[0-9]')") == [
            "one", "two", "three"
        ]
        assert one(runner, "regexp_extract_all('a1b22c', '[0-9]+')") == ["1", "22"]

    def test_split_on_dictionary_column(self, runner):
        rows = runner.execute(
            "SELECT c_mktsegment, split(c_mktsegment, 'I') FROM customer "
            "WHERE c_mktsegment = 'FURNITURE' LIMIT 1"
        ).rows
        assert rows[0][1] == ["FURN", "TURE"]


class TestRound5Datetime:
    def test_date_parse_mysql_tokens(self, runner):
        assert one(
            runner, "date_parse('2021-03-04 05:06:07', '%Y-%m-%d %H:%i:%s')"
        ) == datetime.datetime(2021, 3, 4, 5, 6, 7)

    def test_parse_datetime_joda(self, runner):
        assert one(
            runner, "parse_datetime('04/03/2021 05:06', 'dd/MM/yyyy HH:mm')"
        ) == datetime.datetime(2021, 3, 4, 5, 6)

    def test_iso_timestamp_with_zone_normalizes_to_utc(self, runner):
        assert one(
            runner, "from_iso8601_timestamp('2021-03-04T05:06:07+02:00')"
        ) == datetime.datetime(2021, 3, 4, 3, 6, 7)

    def test_parse_duration_units(self, runner):
        assert one(runner, "to_milliseconds(parse_duration('1.5 s'))") == 1500
        assert one(runner, "to_milliseconds(parse_duration('2h'))") == 7200000

    def test_folded_formatters(self, runner):
        assert one(runner, "to_iso8601(DATE '2021-03-04')") == "2021-03-04"
        assert one(
            runner, "date_format(TIMESTAMP '2021-03-04 05:06:07', '%Y/%m/%d %H:%i')"
        ) == "2021/03/04 05:06"
        assert one(runner, "format_datetime(TIMESTAMP '2021-03-04 05:06:07', 'yyyy-MM-dd')") == "2021-03-04"
        assert one(runner, "human_readable_seconds(93784)") == "1 day, 2 hours, 3 minutes, 4 seconds"
        assert one(runner, "chr(65)") == "A"
        assert one(runner, "to_base(255, 16)") == "ff"
        assert one(runner, "from_base('ff', 16)") == 255

    def test_date_cast_function(self, runner):
        assert one(runner, "date(TIMESTAMP '2021-03-04 05:06:07')") == datetime.date(2021, 3, 4)


class TestRound5Arrays:
    def test_set_operations(self, runner):
        assert one(runner, "array_except(ARRAY[1,2,3,2], ARRAY[2])") == [1, 3]
        assert one(runner, "array_intersect(ARRAY[1,2,3], ARRAY[3,2,9])") == [2, 3]
        assert one(runner, "array_union(ARRAY[1,2], ARRAY[2,3])") == [1, 2, 3]
        assert one(runner, "array_remove(ARRAY[1,2,1,3], 1)") == [2, 3]
        assert one(runner, "arrays_overlap(ARRAY[1,2], ARRAY[2,3])") is True
        assert one(runner, "arrays_overlap(ARRAY[1,2], ARRAY[5,9])") is False

    def test_null_element_semantics(self, runner):
        # no real match + a NULL element on either side -> NULL (unknown)
        assert one(runner, "arrays_overlap(ARRAY[1, NULL], ARRAY[5,9])") is None
        assert one(runner, "arrays_overlap(ARRAY[1, NULL], ARRAY[1])") is True

    def test_trim_repeat_sequence(self, runner):
        assert one(runner, "trim_array(ARRAY[1,2,3,4], 2)") == [1, 2]
        assert one(runner, "repeat('x', 3)") == ["x", "x", "x"]
        assert one(runner, "sequence(1, 5)") == [1, 2, 3, 4, 5]
        assert one(runner, "sequence(10, 2, -3)") == [10, 7, 4]

    def test_map_concat_later_wins(self, runner):
        got = one(
            runner,
            "map_concat(MAP(ARRAY[1,2], ARRAY['a','b']), MAP(ARRAY[2,3], ARRAY['c','d']))",
        )
        assert got == {1: "a", 2: "c", 3: "d"}


class TestRound5Misc:
    def test_bitwise_arithmetic_shift(self, runner):
        assert one(runner, "bitwise_right_shift_arithmetic(-8, 1)") == -4
        assert one(runner, "bitwise_right_shift(8, 1)") == 4

    def test_try_and_json(self, runner):
        assert one(runner, "try(1/0)") is None
        assert one(runner, "try(6/2)") == 3
        assert one(runner, "json_exists('{\"a\":1}', '$.a')") is True
        assert one(runner, "json_exists('{\"a\":1}', '$.b')") is False
        assert one(runner, "is_json_scalar('3')") is True
        assert one(runner, "is_json_scalar('[1]')") is False
        assert one(runner, "json_value('{\"a\":\"x\"}', '$.a')") == "x"

    def test_version_and_timezone(self, runner):
        assert "trino-tpu" in one(runner, "version()")
        assert one(runner, "current_timezone()") == "UTC"
