"""Spilling: HBM -> host offload of idle pages.

Reference blueprint: io.trino.spiller (FileSingleStreamSpiller/
GenericPartitioningSpiller with LZ4, SURVEY.md §5.7) — Trino spills operator
state to local disk under memory pressure. The TPU analogue's first memory tier
below HBM is host DRAM: spilled pages serialize through the page wire serde
(LZ4-compressed host bytes), freeing device memory; unspilling deserializes back
to device. Stage outputs parked between fragments are the natural spill unit.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..spi.page import Page
from .serde import deserialize_page, serialize_page


class Spiller:
    """Byte-budgeted page parking lot (SpillerFactory + SpillSpaceTracker rolled
    into one; disk tier arrives with multi-host)."""

    def __init__(self, trigger_bytes: int = 0, compress: bool = True):
        """``trigger_bytes``: device-resident budget for parked pages; pages
        beyond it spill to host (0 = never spill)."""
        self.trigger_bytes = trigger_bytes
        self.compress = compress
        self._lock = threading.Lock()
        self.spilled_bytes = 0
        self.spill_count = 0

    def maybe_spill(self, pages: List[Page]) -> List[object]:
        """Park a list of pages: returns entries that are either Pages (still
        device-resident) or spill handles, largest pages spilled first."""
        if not self.trigger_bytes:
            return list(pages)
        from .memory import page_bytes

        sized = [(page_bytes(p), i, p) for i, p in enumerate(pages)]
        total = sum(s for s, _, _ in sized)
        out: List[object] = list(pages)
        for size, i, p in sorted(sized, reverse=True):
            if total <= self.trigger_bytes:
                break
            out[i] = _SpilledPage(serialize_page(p, compress=self.compress))
            total -= size
            with self._lock:
                self.spilled_bytes += size
                self.spill_count += 1
        return out

    @staticmethod
    def load(entry: object) -> Page:
        if isinstance(entry, _SpilledPage):
            return deserialize_page(entry.data)
        return entry  # still a device Page


class _SpilledPage:
    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data
