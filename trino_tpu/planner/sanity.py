"""Plan sanity checkers: validate plan invariants between rewrites.

Reference blueprint: io.trino.sql.planner.sanity.PlanSanityChecker —
``validateIntermediatePlan`` after every IterativeOptimizer pass,
``validateFinalPlan`` before execution (ValidateDependenciesChecker,
NoDuplicatePlanNodeIdsValidator, TypeValidator, ValidateAggregationsWithDefault-
Values, ...). The same discipline makes tensor-compiler pipelines debuggable
(arXiv:2203.01877): validate the IR at every lowering step, so a rule that
drops a partition key or leaves a dangling symbol fails AT the rule, not as a
wrong answer or a deep executor crash three planes later.

Two entry points:

- :func:`validate_intermediate` — structural checkers, run after EVERY
  optimizer rule when the ``validate_plan`` session property is on (default:
  on under pytest, off on the production hot path — the gate is one flag
  check in ``optimizer.optimize``).
- :func:`validate_final` — the same structural checkers plus the
  estimate-sanity checker, ALWAYS run at the end of ``optimize()`` and again
  after ``add_exchanges`` (before fragmenting), because a corrupt plan must
  never reach an executor even in production.

A violation raises :class:`PlanSanityError` naming the violated checker, the
offending node path, and the optimizer rule (or phase) that produced the
plan. Each checker owns a disjoint invariant so the seeded-corruption
mutation suite (tests/test_static_analysis.py) can assert a given corruption
is caught by exactly the checker that owns it.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..spi.types import BOOLEAN, Type
from ..sql.ir import IrExpr, is_deterministic, references
from .plan import (
    AggregationNode,
    ExchangeNode,
    ExchangeScope,
    ExchangeType,
    FilterNode,
    JoinNode,
    LimitNode,
    LogicalPlan,
    OutputNode,
    PlanNode,
    ProjectNode,
    SemiJoinNode,
    SortNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    UnnestNode,
    VectorTopNNode,
    WindowNode,
    PatternRecognitionNode,
)

_FRAME_KINDS = {
    "UNBOUNDED_PRECEDING", "PRECEDING", "CURRENT_ROW",
    "FOLLOWING", "UNBOUNDED_FOLLOWING",
}


class PlanSanityError(AssertionError):
    """A plan violated an invariant between rewrites. Carries the checker id,
    the path of the offending node, and the rule/phase that produced the
    plan, so the failing rewrite is identified without a debugger."""

    def __init__(self, checker: str, message: str, node_path: str, rule: str):
        self.checker = checker
        self.node_path = node_path
        self.rule = rule
        super().__init__(
            f"[{checker}] {message} (at {node_path}; after rule {rule!r})"
        )


class Violation:
    __slots__ = ("checker", "message", "node_path")

    def __init__(self, checker: str, message: str, node_path: str):
        self.checker = checker
        self.message = message
        self.node_path = node_path


class SanityContext:
    """What the checkers may consult beyond the plan tree itself. Memoizes
    the (node, path) walk so a full checker pass costs ONE traversal — the
    always-on final validation must stay invisible next to the optimizer's
    own cost (BENCH_r12_sanity_ab.json)."""

    def __init__(self, types: Dict[str, Type], session=None, estimator=None):
        self.types = types or {}
        self.session = session
        self.estimator = estimator
        self._walked = None
        self._walked_root = None

    def walked(self, root: "PlanNode"):
        # value comparison, not `is`: two id() calls return distinct int
        # objects (the memoized list keeps root alive, so the id cannot be
        # reused for a different node while cached)
        if self._walked is None or self._walked_root != id(root):
            self._walked = list(_walk(root, _root_path(root)))
            self._walked_root = id(root)
        return self._walked

    def session_get(self, name: str, default):
        if self.session is None:
            return default
        try:
            return self.session.get(name)
        except KeyError:
            return default


def _walk(node: PlanNode, path: str):
    """Yield (node, path) pre-order; path names each edge, e.g.
    ``Output > Project > Join.left > TableScan``."""
    yield node, path
    sources = node.sources
    if isinstance(node, JoinNode):
        labels = (".left", ".right")
    elif isinstance(node, SemiJoinNode):
        labels = (".source", ".filtering")
    elif len(sources) > 1:
        labels = tuple(f"[{i}]" for i in range(len(sources)))
    else:
        labels = ("",) * len(sources)
    for src, lab in zip(sources, labels):
        name = type(src).__name__.replace("Node", "")
        yield from _walk(src, f"{path}{lab} > {name}")


def _root_path(root: PlanNode) -> str:
    return type(root).__name__.replace("Node", "")


# --------------------------------------------------------------------------- #
# checkers — each owns one disjoint invariant
# --------------------------------------------------------------------------- #


class Checker:
    id: str = ""
    # estimate-sanity needs an estimator: it only runs when the context has
    # one (final validation / the mutation suite), never per-rule
    needs_estimator = False

    def check(self, root: PlanNode, ctx: SanityContext) -> List[Violation]:
        raise NotImplementedError


class SymbolDependencyChecker(Checker):
    """Every symbol a node's expressions consume is produced by its children
    (ref: sanity/ValidateDependenciesChecker). Aggregation/window operand
    validity lives in their own checkers; this one owns filters, projections,
    join criteria, semi-join keys, sort/exchange keys, unnest inputs, and
    output references."""

    id = "symbol-dependencies"

    def check(self, root, ctx):
        out: List[Violation] = []

        def missing(needed, node, what: str, path: str):
            produced = set()
            for s in node.sources:
                produced.update(s.output_symbols)
            lost = sorted(set(needed) - produced)
            if lost:
                out.append(Violation(
                    self.id,
                    f"{what} references {lost} not produced by children",
                    path,
                ))

        for node, path in ctx.walked(root):
            if isinstance(node, FilterNode):
                missing(references(node.predicate), node, "filter predicate", path)
            elif isinstance(node, ProjectNode):
                needed = set()
                for _, e in node.assignments:
                    needed |= references(e)
                missing(needed, node, "projection", path)
            elif isinstance(node, JoinNode):
                left = set(node.left.output_symbols)
                right = set(node.right.output_symbols)
                for l, r in node.criteria:
                    if l not in left:
                        out.append(Violation(
                            self.id,
                            f"join criteria left symbol {l!r} not produced by the left side",
                            path,
                        ))
                    if r not in right:
                        out.append(Violation(
                            self.id,
                            f"join criteria right symbol {r!r} not produced by the right side",
                            path,
                        ))
                if node.filter is not None:
                    missing(references(node.filter), node, "join filter", path)
            elif isinstance(node, SemiJoinNode):
                if node.source_key not in set(node.source.output_symbols):
                    out.append(Violation(
                        self.id,
                        f"semi-join source key {node.source_key!r} not produced by source",
                        path,
                    ))
                if node.filtering_key not in set(node.filtering_source.output_symbols):
                    out.append(Violation(
                        self.id,
                        f"semi-join filtering key {node.filtering_key!r} not produced "
                        "by filtering source",
                        path,
                    ))
            elif isinstance(node, (SortNode, TopNNode)):
                missing({o.symbol for o in node.orderings}, node, "sort key", path)
            elif isinstance(node, VectorTopNNode):
                # the fused node's projection half consumes child symbols;
                # its orderings reference its OWN computed assignments
                needed = set()
                for _, e in node.assignments:
                    needed |= references(e)
                missing(needed, node, "fused top-k projection", path)
                produced = {s for s, _ in node.assignments}
                lost = sorted(
                    {o.symbol for o in node.orderings} - produced
                )
                if lost:
                    out.append(Violation(
                        self.id,
                        f"fused top-k sort key references {lost} not "
                        "computed by its own assignments",
                        path,
                    ))
            elif isinstance(node, UnnestNode):
                needed = set(node.replicate_symbols)
                needed |= {s for s, _ in node.unnest_symbols}
                missing(needed, node, "unnest input", path)
            elif isinstance(node, OutputNode):
                missing(set(node.symbols), node, "output", path)
            elif isinstance(node, PatternRecognitionNode):
                needed = set(node.partition_by)
                needed |= {o.symbol for o in node.order_by}
                missing(needed, node, "pattern partition/order key", path)
        return out


class NoDuplicateNodeChecker(Checker):
    """No plan node instance appears twice in the tree (the PlanNodeId
    analogue: object identity IS the node id here — the stats memo, the
    actuals plane, and per-node attribution all key on ``id(node)``, so an
    aliased subtree double-counts silently)."""

    id = "no-duplicate-plan-node-ids"

    def check(self, root, ctx):
        out: List[Violation] = []
        seen: Dict[int, str] = {}
        for node, path in ctx.walked(root):
            first = seen.get(id(node))
            if first is not None:
                out.append(Violation(
                    self.id,
                    f"node instance appears twice (first at {first})",
                    path,
                ))
            else:
                seen[id(node)] = path
        return out


class UniqueOutputSymbolsChecker(Checker):
    """A node's output symbols are unique (symbols are plan-wide unique
    names, Trino's SymbolAllocator contract)."""

    id = "unique-output-symbols"

    def check(self, root, ctx):
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            syms = node.output_symbols
            if len(set(syms)) != len(syms):
                dupes = sorted({s for s in syms if syms.count(s) > 1})
                out.append(Violation(
                    self.id, f"duplicate output symbols {dupes}", path
                ))
        return out


class TypeConsistencyChecker(Checker):
    """Types line up (ref: sanity/TypeValidator): every output symbol has a
    declared type in the plan's TypeProvider, boolean positions (filter
    predicates, join filters, aggregate FILTER masks) hold boolean-typed
    expressions, and tensor-plane expressions are statically well-shaped —
    a VECTOR dimension mismatch inside ``dot_product(a, b)`` (or a model
    call whose weight count disagrees with its bound features) must fail
    HERE, naming this checker, never inside a compiled kernel."""

    id = "type-consistency"

    def check(self, root, ctx):
        from ..ops.tensor import vector_dimension_problems

        out: List[Violation] = []
        types = ctx.types

        def bool_expr(e: Optional[IrExpr], what: str, path: str):
            if e is None:
                return
            t = e.type
            if t is not None and t != BOOLEAN:
                out.append(Violation(
                    self.id, f"{what} has type {t.display()}, expected boolean",
                    path,
                ))

        def vector_shapes(e: Optional[IrExpr], what: str, path: str):
            if e is None:
                return
            for msg in vector_dimension_problems(e):
                out.append(Violation(self.id, f"{what}: {msg}", path))

        for node, path in ctx.walked(root):
            for s in node.output_symbols:
                if s not in types:
                    out.append(Violation(
                        self.id, f"output symbol {s!r} has no declared type", path
                    ))
            if isinstance(node, FilterNode):
                bool_expr(node.predicate, "filter predicate", path)
                vector_shapes(node.predicate, "filter predicate", path)
            elif isinstance(node, ProjectNode):
                for sym, e in node.assignments:
                    vector_shapes(e, f"projection {sym!r}", path)
            elif isinstance(node, VectorTopNNode):
                for sym, e in node.assignments:
                    vector_shapes(e, f"fused top-k assignment {sym!r}", path)
            elif isinstance(node, JoinNode):
                bool_expr(node.filter, "join filter", path)
                vector_shapes(node.filter, "join filter", path)
            elif isinstance(node, AggregationNode):
                for sym, agg in node.aggregations:
                    if agg.filter is not None:
                        ft = types.get(agg.filter)
                        if ft is not None and ft != BOOLEAN:
                            out.append(Violation(
                                self.id,
                                f"aggregate {sym!r} FILTER symbol {agg.filter!r} "
                                f"has type {ft.display()}, expected boolean",
                                path,
                            ))
        return out


class AggregationChecker(Checker):
    """Aggregation operand validity (ref: ValidateAggregationsWithDefault-
    Values + ValidateDependenciesChecker's aggregation arm): group keys,
    aggregate args, FILTER masks, and WITHIN-GROUP ordering symbols all come
    from the source; DISTINCT aggregates take exactly one argument."""

    id = "aggregation-validity"

    def check(self, root, ctx):
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            if not isinstance(node, AggregationNode):
                continue
            produced = set(node.source.output_symbols)
            for k in node.group_keys:
                if k not in produced:
                    out.append(Violation(
                        self.id, f"group key {k!r} not produced by source", path
                    ))
            for sym, agg in node.aggregations:
                if not agg.function:
                    out.append(Violation(
                        self.id, f"aggregate {sym!r} has no function", path
                    ))
                for a in agg.args:
                    if a not in produced:
                        out.append(Violation(
                            self.id,
                            f"aggregate {sym!r} argument {a!r} not produced by source",
                            path,
                        ))
                if agg.filter is not None and agg.filter not in produced:
                    out.append(Violation(
                        self.id,
                        f"aggregate {sym!r} FILTER symbol {agg.filter!r} "
                        "not produced by source",
                        path,
                    ))
                for o in agg.ordering:
                    if o.symbol not in produced:
                        out.append(Violation(
                            self.id,
                            f"aggregate {sym!r} ordering symbol {o.symbol!r} "
                            "not produced by source",
                            path,
                        ))
                if agg.distinct and len(agg.args) != 1:
                    out.append(Violation(
                        self.id,
                        f"DISTINCT aggregate {sym!r} takes exactly one "
                        f"argument, got {len(agg.args)}",
                        path,
                    ))
        return out


class WindowChecker(Checker):
    """Window operand validity: partition/order keys and function arguments
    come from the source; frame kinds are well-formed."""

    id = "window-validity"

    def check(self, root, ctx):
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            if not isinstance(node, WindowNode):
                continue
            produced = set(node.source.output_symbols)
            for k in node.partition_by:
                if k not in produced:
                    out.append(Violation(
                        self.id, f"partition key {k!r} not produced by source", path
                    ))
            for o in node.order_by:
                if o.symbol not in produced:
                    out.append(Violation(
                        self.id,
                        f"order key {o.symbol!r} not produced by source", path
                    ))
            for sym, fn in node.functions:
                if not fn.function:
                    out.append(Violation(
                        self.id, f"window function {sym!r} has no function", path
                    ))
                for a in fn.args:
                    if a not in produced:
                        out.append(Violation(
                            self.id,
                            f"window function {sym!r} argument {a!r} "
                            "not produced by source",
                            path,
                        ))
                if fn.frame is not None:
                    if (fn.frame.start_kind not in _FRAME_KINDS
                            or fn.frame.end_kind not in _FRAME_KINDS):
                        out.append(Violation(
                            self.id,
                            f"window function {sym!r} frame kinds "
                            f"({fn.frame.start_kind}, {fn.frame.end_kind}) invalid",
                            path,
                        ))
        return out


class ExchangePartitioningChecker(Checker):
    """Exchange/partitioning invariants: a REPARTITION exchange carries hash
    keys and every key exists in the child's output (a dropped partition key
    silently degrades to a broken shuffle — the engine-wide splitmix64 key
    rule in ops/repartition.py can only hash columns that arrive); a
    REPARTITION_RANGE carries the driving sort order; GATHER/BROADCAST carry
    no partition keys."""

    id = "exchange-partitioning"

    def check(self, root, ctx):
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            if not isinstance(node, ExchangeNode):
                continue
            produced = set(node.source.output_symbols)
            if node.exchange_type == ExchangeType.REPARTITION:
                if not node.partition_keys:
                    out.append(Violation(
                        self.id, "REPARTITION exchange with no partition keys",
                        path,
                    ))
                for k in node.partition_keys:
                    if k not in produced:
                        out.append(Violation(
                            self.id,
                            f"partition key {k!r} not produced by child "
                            "(dropped repartition hash key)",
                            path,
                        ))
            elif node.exchange_type == ExchangeType.REPARTITION_RANGE:
                if not node.orderings:
                    out.append(Violation(
                        self.id,
                        "REPARTITION_RANGE exchange with no driving sort order",
                        path,
                    ))
                for o in node.orderings:
                    if o.symbol not in produced:
                        out.append(Violation(
                            self.id,
                            f"range-partition order key {o.symbol!r} "
                            "not produced by child",
                            path,
                        ))
                for k in node.partition_keys:
                    if k not in produced:
                        out.append(Violation(
                            self.id,
                            f"partition key {k!r} not produced by child", path
                        ))
            else:  # GATHER / BROADCAST
                if node.partition_keys:
                    out.append(Violation(
                        self.id,
                        f"{node.exchange_type.value} exchange carries "
                        f"partition keys {list(node.partition_keys)}",
                        path,
                    ))
                for o in node.orderings:
                    # merge-GATHER order must still be producible
                    if o.symbol not in produced:
                        out.append(Violation(
                            self.id,
                            f"merge order key {o.symbol!r} not produced by child",
                            path,
                        ))
        return out


class FteDeterminismChecker(Checker):
    """Under TASK retries, a nondeterministic expression below a retryable
    REMOTE exchange boundary is a correctness hazard: a retried or
    speculative attempt recomputes the fragment and may commit different
    rows than the attempt a consumer already read, unless the boundary
    materializes first (ref: Trino FTE's determinism requirements on
    exchange materialization). The checker flags nondeterministic
    projections/filters strictly below a REMOTE exchange when
    ``retry_policy=TASK``."""

    id = "fte-determinism"

    def check(self, root, ctx):
        if str(ctx.session_get("retry_policy", "NONE")) != "TASK":
            return []
        # mark everything strictly below a REMOTE exchange, then flag from
        # the shared walk (one labeling implementation, in _walk)
        below: set = set()

        def mark(node: PlanNode):
            for src in node.sources:
                if id(src) not in below:
                    below.add(id(src))
                    mark(src)

        remotes = [
            node for node, _ in ctx.walked(root)
            if isinstance(node, ExchangeNode)
            and node.scope == ExchangeScope.REMOTE
        ]
        if not remotes:
            return []
        for ex in remotes:
            mark(ex)
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            if id(node) not in below:
                continue
            exprs: List[Tuple[str, Optional[IrExpr]]] = []
            if isinstance(node, ProjectNode):
                exprs = [(f"projection {s!r}", e) for s, e in node.assignments]
            elif isinstance(node, FilterNode):
                exprs = [("filter predicate", node.predicate)]
            elif isinstance(node, JoinNode):
                exprs = [("join filter", node.filter)]
            for what, e in exprs:
                if e is not None and not is_deterministic(e):
                    out.append(Violation(
                        self.id,
                        f"nondeterministic {what} below a retryable "
                        "REMOTE exchange boundary",
                        path,
                    ))
        return out


class LimitSanityChecker(Checker):
    """Limit/TopN/TableFunction scalar sanity: non-negative counts and
    offsets (a negative count compiles into a nonsense static capacity)."""

    id = "limit-sanity"

    def check(self, root, ctx):
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            if isinstance(node, LimitNode):
                if node.count < 0:
                    out.append(Violation(
                        self.id, f"negative limit count {node.count}", path
                    ))
                if node.offset < 0:
                    out.append(Violation(
                        self.id, f"negative limit offset {node.offset}", path
                    ))
            elif isinstance(node, (TopNNode, VectorTopNNode)):
                if node.count < 0:
                    out.append(Violation(
                        self.id, f"negative topn count {node.count}", path
                    ))
            elif isinstance(node, TableScanNode):
                if node.limit is not None and node.limit < 0:
                    out.append(Violation(
                        self.id, f"negative scan limit {node.limit}", path
                    ))
        return out


class UnionConsistencyChecker(Checker):
    """Union shape: one symbol mapping per input, each mapping as wide as
    the union's output row, and every mapped symbol produced by its input."""

    id = "union-consistency"

    def check(self, root, ctx):
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            if not isinstance(node, UnionNode):
                continue
            if len(node.symbol_mapping) != len(node.inputs):
                out.append(Violation(
                    self.id,
                    f"{len(node.inputs)} inputs but "
                    f"{len(node.symbol_mapping)} symbol mappings",
                    path,
                ))
                continue
            for i, (inp, mapping) in enumerate(
                zip(node.inputs, node.symbol_mapping)
            ):
                if len(mapping) != len(node.symbols):
                    out.append(Violation(
                        self.id,
                        f"input {i} mapping has {len(mapping)} symbols, "
                        f"union outputs {len(node.symbols)}",
                        path,
                    ))
                produced = set(inp.output_symbols)
                for s in mapping:
                    if s not in produced:
                        out.append(Violation(
                            self.id,
                            f"input {i} mapped symbol {s!r} not produced "
                            "by that input",
                            path,
                        ))
        return out


class OutputArityChecker(Checker):
    """OutputNode names exactly as many columns as it outputs symbols."""

    id = "output-arity"

    def check(self, root, ctx):
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            if isinstance(node, OutputNode):
                if len(node.column_names) != len(node.symbols):
                    out.append(Violation(
                        self.id,
                        f"{len(node.column_names)} column names for "
                        f"{len(node.symbols)} output symbols",
                        path,
                    ))
        return out


class EstimateSanityChecker(Checker):
    """Estimate sanity (ref: PlanNodeStatsEstimate's invariants): after the
    stats overlay (history-based stats included), every node's estimated row
    count is unknown (None) or a finite non-negative number, and column NDVs
    are finite and non-negative — NaN/negative estimates silently invert
    every cost-based decision downstream."""

    id = "estimate-sanity"
    needs_estimator = True

    def check(self, root, ctx):
        if ctx.estimator is None:
            return []
        out: List[Violation] = []
        for node, path in ctx.walked(root):
            try:
                stats = ctx.estimator.stats(node)
            except Exception as e:  # estimator crash is itself a violation
                out.append(Violation(
                    self.id, f"estimator raised {type(e).__name__}: {e}", path
                ))
                continue
            rows = stats.rows
            if rows is not None and (math.isnan(rows) or rows < 0
                                     or math.isinf(rows)):
                out.append(Violation(
                    self.id, f"estimated rows {rows!r} not finite/non-negative",
                    path,
                ))
            for sym, col in stats.columns.items():
                ndv = getattr(col, "ndv", None)
                if ndv is not None and (math.isnan(ndv) or ndv < 0
                                        or math.isinf(ndv)):
                    out.append(Violation(
                        self.id,
                        f"column {sym!r} ndv {ndv!r} not finite/non-negative",
                        path,
                    ))
        return out


# ordered: cheap structural checks first
CHECKERS: Tuple[Checker, ...] = (
    NoDuplicateNodeChecker(),
    SymbolDependencyChecker(),
    UniqueOutputSymbolsChecker(),
    TypeConsistencyChecker(),
    AggregationChecker(),
    WindowChecker(),
    ExchangePartitioningChecker(),
    UnionConsistencyChecker(),
    LimitSanityChecker(),
    OutputArityChecker(),
    FteDeterminismChecker(),
    EstimateSanityChecker(),
)


def checker_ids() -> List[str]:
    return [c.id for c in CHECKERS]


def run_checkers(
    root: PlanNode, ctx: SanityContext, checkers=CHECKERS
) -> List[Violation]:
    """All violations from all (applicable) checkers — the mutation suite's
    entry point: it asserts a seeded corruption fires exactly its owner."""
    out: List[Violation] = []
    for c in checkers:
        if c.needs_estimator and ctx.estimator is None:
            continue
        out.extend(c.check(root, ctx))
    return out


def _raise(violations: List[Violation], rule: str) -> None:
    if not violations:
        return
    v = violations[0]
    extra = "" if len(violations) == 1 else f" (+{len(violations) - 1} more)"
    raise PlanSanityError(v.checker, v.message + extra, v.node_path, rule)


def validate_intermediate(
    root: PlanNode,
    types: Dict[str, Type],
    rule: str,
    session=None,
) -> None:
    """Structural validation after one optimizer rule (the
    validateIntermediatePlan analogue). Raises PlanSanityError naming the
    rule that produced the plan."""
    ctx = SanityContext(types, session=session)
    _raise(run_checkers(root, ctx), rule)


def validate_final(
    plan: LogicalPlan,
    metadata=None,
    session=None,
    stage: str = "final",
    with_estimates: Optional[bool] = None,
) -> None:
    """Full validation before fragmenting/execution (the validateFinalPlan
    analogue): all structural checkers, plus estimate sanity when the
    ``validate_plan`` knob is on (the estimator walk is the only non-trivial
    cost) — or when ``with_estimates`` explicitly asks."""
    estimator = None
    if with_estimates is None:
        with_estimates = False
        if session is not None:
            try:
                with_estimates = bool(session.get("validate_plan"))
            except KeyError:
                pass
    if with_estimates and metadata is not None:
        from .stats import make_estimator

        estimator = make_estimator(metadata, plan.types, session)
    ctx = SanityContext(plan.types, session=session, estimator=estimator)
    _raise(run_checkers(plan.root, ctx), stage)
