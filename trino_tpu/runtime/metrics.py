"""Metrics registry + Prometheus text exposition.

Reference blueprint: io.trino.spi.metrics (Metrics/Metric — connector and
operator metrics merged up the query tree) and the JMX metrics the reference
exposes per coordinator/worker (queued/running queries, memory pools, spill
bytes); the Prometheus text format replaces the JMX transport (the reference
ecosystem scrapes those beans the same way).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Thread-safety audit (task-executor threads inc/dec concurrently): every
# mutation below is a lock-guarded read-modify-write. `value` READS in
# render() are lock-free on purpose — a float read is atomic in CPython and
# a scrape racing an inc may see either side of it, which Prometheus
# semantics allow (the next scrape catches up; counters stay monotonic
# because no path ever decrements one).


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters are monotonic; inc() amount must be >= 0")
        with self._lock:
            self.value += amount


class Gauge:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


def _escape_label_value(s) -> str:
    """Prometheus text exposition label-value escaping: backslash,
    double-quote, newline (one helper for every metric type)."""
    return (
        str(s)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _format_value(v: float) -> str:
    """Full precision: %g truncates counters above ~1e6 and breaks scrape
    deltas — integral values render as ints, others via repr."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def exponential_buckets(start: float, factor: float, count: int) -> Tuple[float, ...]:
    """Fixed exponential bucket bounds (Prometheus client convention)."""
    out = []
    b = start
    for _ in range(count):
        out.append(b)
        b *= factor
    return tuple(out)


# default latency buckets: 1ms .. ~65s, 2x-spaced
DEFAULT_BUCKETS = exponential_buckets(0.001, 2.0, 17)


def histogram_quantile(
    buckets: Sequence[Tuple[float, int]], count: int, q: float
) -> Optional[float]:
    """Estimated q-quantile from cumulative buckets by linear interpolation
    inside the owning bucket (the promql ``histogram_quantile`` convention:
    the first bucket interpolates from 0; a rank landing in the +Inf bucket
    reports the highest finite bound). ``buckets``: [(le, cumulative), ...,
    (inf, total)] exactly as ``MetricsRegistry.collect`` emits them. None
    when the histogram is empty."""
    import math

    if count <= 0 or not buckets:
        return None
    rank = q * count
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        if cum >= rank:
            if math.isinf(le):
                return prev_le  # past the last finite bound
            if cum == prev_cum:
                return le
            return prev_le + (le - prev_le) * (rank - prev_cum) / (cum - prev_cum)
        prev_le, prev_cum = le, cum
    return prev_le


class Histogram:
    """Cumulative-bucket histogram with Prometheus text exposition
    (``name_bucket{le=...}`` / ``name_sum`` / ``name_count``). Buckets are
    fixed at construction; observe() is a lock-guarded O(log n) bisect."""

    __slots__ = ("buckets", "bucket_counts", "sum", "count", "_lock")

    def __init__(self, buckets: Optional[Sequence[float]] = None):
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_BUCKETS
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bs
        self.bucket_counts = [0] * len(bs)  # non-cumulative per-bucket
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        import bisect

        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            if i < len(self.bucket_counts):
                self.bucket_counts[i] += 1
            self.sum += value
            self.count += 1

    def render_into(self, lines: List[str], name: str, labels) -> None:
        with self._lock:
            counts = list(self.bucket_counts)
            total, s = self.count, self.sum
        base = ",".join(
            f'{k}="{_escape_label_value(v)}"' for k, v in labels
        )
        prefix = base + "," if base else ""
        cum = 0
        for bound, c in zip(self.buckets, counts):
            cum += c
            le = f"{bound:g}"
            lines.append(f'{name}_bucket{{{prefix}le="{le}"}} {cum}')
        lines.append(f'{name}_bucket{{{prefix}le="+Inf"}} {total}')
        suffix = f"{{{base}}}" if base else ""
        lines.append(f"{name}_sum{suffix} {_format_value(s)}")
        lines.append(f"{name}_count{suffix} {total}")


class MetricsRegistry:
    """Name+labels -> metric; renders Prometheus text exposition format."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._types: Dict[str, str] = {}
        self._help: Dict[str, str] = {}

    _TYPE_NAMES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}

    def _get(self, cls, name: str, labels: Dict[str, str], help_: str, **kw):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(**kw)
                self._metrics[key] = m
                self._types[name] = self._TYPE_NAMES[cls]
                # don't let a later help-less registration of another label
                # set clobber the name's HELP line
                if help_ or name not in self._help:
                    self._help[name] = help_
            return m

    def counter(self, name: str, labels: Dict[str, str] = None, help: str = "") -> Counter:
        return self._get(Counter, name, labels or {}, help)

    def gauge(self, name: str, labels: Dict[str, str] = None, help: str = "") -> Gauge:
        return self._get(Gauge, name, labels or {}, help)

    def histogram(
        self,
        name: str,
        labels: Dict[str, str] = None,
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        h = self._get(Histogram, name, labels or {}, help, buckets=buckets)
        if buckets is not None and tuple(sorted(buckets)) != h.buckets:
            # an existing series can't change its bucket layout — silently
            # returning the old bounds would scatter observations into
            # unexpected le= bounds on the scrape side
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{h.buckets}"
            )
        return h

    def collect(self) -> List[dict]:
        """Structured snapshot of every series (the system.metrics table
        adapter; render() stays the Prometheus wire format). One dict per
        series: name/labels/type/help plus ``value`` for counters+gauges or
        ``buckets`` [(le, cumulative), ..., (inf, total)] / ``sum`` /
        ``count`` for histograms."""
        import math

        with self._lock:
            items = sorted(self._metrics.items())
            types = dict(self._types)
            helps = dict(self._help)
        out: List[dict] = []
        for (name, labels), metric in items:
            entry = {
                "name": name,
                "labels": dict(labels),
                "type": types.get(name, "gauge"),
                "help": helps.get(name, ""),
            }
            if isinstance(metric, Histogram):
                with metric._lock:
                    counts = list(metric.bucket_counts)
                    total, s = metric.count, metric.sum
                buckets = []
                cum = 0
                for bound, c in zip(metric.buckets, counts):
                    cum += c
                    buckets.append((float(bound), cum))
                buckets.append((math.inf, total))
                entry.update(buckets=buckets, sum=s, count=total)
            else:
                entry["value"] = metric.value
            out.append(entry)
        return out

    def render(self) -> str:
        """Prometheus text format, grouped by metric name."""
        with self._lock:
            items = sorted(self._metrics.items())
            types = dict(self._types)
            helps = dict(self._help)
        lines: List[str] = []
        seen = set()
        for (name, labels), metric in items:
            if name not in seen:
                seen.add(name)
                if helps.get(name):
                    lines.append(f"# HELP {name} {helps[name]}")
                lines.append(f"# TYPE {name} {types.get(name, 'gauge')}")
            if isinstance(metric, Histogram):
                metric.render_into(lines, name, labels)
                continue
            text = _format_value(metric.value)
            if labels:
                lbl = ",".join(
                    f'{k}="{_escape_label_value(val)}"' for k, val in labels
                )
                lines.append(f"{name}{{{lbl}}} {text}")
            else:
                lines.append(f"{name} {text}")
        return "\n".join(lines) + "\n"


# process-wide registry (the coordinator/worker expose it at /v1/metrics)
REGISTRY = MetricsRegistry()
