"""Failure injection + query retry policy.

Reference blueprint: execution/FailureInjector.java:35 (InjectedFailureType:51)
— fault injection is built into the engine and driven by tests (SURVEY.md §4
BaseFailureRecoveryTest) — and RetryPolicy.QUERY (SqlQueryExecution.java:536:
re-run the whole query on failure; task-level FTE is the round-2+ tier).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional


class InjectedFailure(RuntimeError):
    pass


class RetryableQueryError(RuntimeError):
    """A failure the QUERY retry policy may recover from by re-running the
    whole query (e.g. a worker task failed or a worker died mid-query)."""


class FailureInjector:
    """Injects failures into operator evaluation, keyed by plan-node type.

    Usage (tests): injector.fail_once("AggregationNode"); attach to a
    PlanExecutor subclass or the retrying runner below.
    """

    _tls = threading.local()

    def __init__(self):
        self._remaining: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.injected = 0
        self._prev: Optional["FailureInjector"] = None

    def fail_once(self, node_type: str, times: int = 1) -> None:
        with self._lock:
            self._remaining[node_type] = self._remaining.get(node_type, 0) + times

    def maybe_fail(self, node_type: str) -> None:
        with self._lock:
            n = self._remaining.get(node_type, 0)
            if n > 0:
                self._remaining[node_type] = n - 1
                self.injected += 1
                raise InjectedFailure(f"injected failure at {node_type}")

    def __enter__(self):
        # thread-local + save/restore: concurrent queries on other threads are
        # unaffected, and nested contexts restore the outer injector
        self._prev = getattr(FailureInjector._tls, "current", None)
        FailureInjector._tls.current = self
        return self

    def __exit__(self, *exc):
        FailureInjector._tls.current = self._prev
        return False

    @staticmethod
    def current() -> Optional["FailureInjector"]:
        return getattr(FailureInjector._tls, "current", None)


def execute_with_retry(execute: Callable[[str], object], sql: str,
                       retry_policy: str = "NONE", max_retries: int = 1):
    """RetryPolicy.QUERY: re-run the whole query on retryable failure
    (ref: SqlQueryExecution.java:536-560 scheduler selection by retry policy)."""
    attempts = 0
    while True:
        try:
            return execute(sql)
        except (InjectedFailure, RetryableQueryError):
            attempts += 1
            if retry_policy != "QUERY" or attempts > max_retries:
                raise
