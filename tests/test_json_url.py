"""JSON + URL scalar families (dictionary-LUT transforms).

Model: the reference's TestJsonFunctions/TestUrlFunctions
(operator/scalar/JsonFunctions.java, UrlFunctions.java, io.trino.jsonpath) —
evaluated here as once-per-dictionary host transforms.
"""

import pytest


@pytest.fixture(scope="module")
def runner():
    from trino_tpu.runtime import LocalQueryRunner

    return LocalQueryRunner.tpch(scale=0.0005)


def one(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


class TestJson:
    def test_extract_scalar(self, runner):
        assert one(runner, """SELECT json_extract_scalar('{"a": {"b": 7}}', '$.a.b')""") == ("7",)
        assert one(runner, """SELECT json_extract_scalar('{"a": "hi"}', '$["a"]')""") == ("hi",)
        assert one(runner, """SELECT json_extract_scalar('{"a": 1}', '$.missing')""") == (None,)
        # objects/arrays are not scalars
        assert one(runner, """SELECT json_extract_scalar('{"a": [1]}', '$.a')""") == (None,)

    def test_extract_json(self, runner):
        assert one(runner, """SELECT json_extract('{"a": [1,2,{"c":3}]}', '$.a[2]')""") == ('{"c":3}',)
        assert one(runner, """SELECT json_array_get('[10, 20, 30]', 1)""") == ("20",)

    def test_lengths_and_sizes(self, runner):
        assert one(runner, "SELECT json_array_length('[1,2,3]')") == (3,)
        assert one(runner, """SELECT json_array_length('{"x":1}')""") == (None,)
        assert one(runner, """SELECT json_size('{"a": {"b":1,"c":2}}', '$.a')""") == (2,)
        assert one(runner, """SELECT json_size('{"a": 5}', '$.a')""") == (0,)

    def test_array_contains(self, runner):
        assert one(
            runner,
            "SELECT json_array_contains('[1,2,3]', 2), "
            "json_array_contains('[1,2,3]', 9), "
            "json_array_contains('[\"x\"]', 'x'), "
            "json_array_contains('[1.5]', 1.5)",
        ) == (True, False, True, True)
        assert one(runner, "SELECT json_array_contains('5', 5)") == (None,)

    def test_parse_and_format(self, runner):
        assert one(runner, """SELECT json_parse('{"b": 1,  "a": 2}')""") == ('{"b":1,"a":2}',)
        assert one(runner, "SELECT json_parse('not json')") == (None,)

    def test_over_table_column(self, runner):
        # transform applies per dictionary entry over a real column pipeline
        rows = runner.execute(
            "SELECT DISTINCT json_extract_scalar("
            "'{\"m\": \"' || l_shipmode || '\"}', '$.m') FROM lineitem "
            "ORDER BY 1 LIMIT 3"
        ).rows
        assert [r[0] for r in rows] == ["AIR", "FOB", "MAIL"]


class TestUrl:
    def test_extract_parts(self, runner):
        url = "'https://example.com:8080/p/a?q=1&r=two#frag'"
        assert one(
            runner,
            f"SELECT url_extract_protocol({url}), url_extract_host({url}), "
            f"url_extract_path({url}), url_extract_query({url}), "
            f"url_extract_fragment({url})",
        ) == ("https", "example.com", "/p/a", "q=1&r=two", "frag")

    def test_extract_parameter(self, runner):
        url = "'https://e.com/?q=1&r=two'"
        assert one(
            runner,
            f"SELECT url_extract_parameter({url}, 'r'), "
            f"url_extract_parameter({url}, 'zz')",
        ) == ("two", None)

    def test_encode_decode(self, runner):
        assert one(runner, "SELECT url_encode('a b/c'), url_decode('a%20b%2Fc')") == (
            "a%20b%2Fc",
            "a b/c",
        )
