"""Filesystem abstraction: object-store-shaped path API.

Reference blueprint: lib/trino-filesystem/src/main/java/io/trino/filesystem/
TrinoFileSystem.java:60 — the engine never touches java.io directly; every
reader/writer goes through a Location + TrinoFileSystem pair resolved per
scheme (s3/gcs/azure/hdfs/local implementations). This module is the same
contract shaped for the TPU engine's host side:

- a :class:`Location` is ``scheme://host/path``; schemes resolve through the
  :class:`FileSystemManager` registry.
- the API is OBJECT-STORE-shaped: no mkdir/rename primitives in the read
  path, listing is BY PREFIX, writes are whole-object puts with an atomic
  commit (temp + rename locally; multipart-put semantics on a real store).
  Code written against it ports to s3:// by registering another factory.

Only the local implementation ships (the image has no object-store creds);
the contract is what the lakehouse connector and the metastore build on.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple


@dataclass(frozen=True)
class Location:
    """Parsed storage location (ref: filesystem/Location.java)."""

    scheme: str
    path: str  # scheme-relative, no leading slash

    @staticmethod
    def parse(uri: str) -> "Location":
        if "://" not in uri:
            # bare paths are local (the reference maps them to file://)
            return Location("local", uri.lstrip("/"))
        scheme, _, rest = uri.partition("://")
        return Location(scheme.lower(), rest.lstrip("/"))

    def uri(self) -> str:
        return f"{self.scheme}://{self.path}"

    def child(self, *parts: str) -> "Location":
        path = "/".join([self.path.rstrip("/")] + [p.strip("/") for p in parts])
        return Location(self.scheme, path)

    @property
    def name(self) -> str:
        return self.path.rsplit("/", 1)[-1]


@dataclass(frozen=True)
class FileEntry:
    location: Location
    length: int


class TrinoFileSystem:
    """The per-scheme filesystem contract (TrinoFileSystem.java:60)."""

    def read(self, location: Location) -> bytes:
        raise NotImplementedError

    def write(self, location: Location, data: bytes) -> None:
        """Whole-object put, atomic: readers never observe partial objects."""
        raise NotImplementedError

    def write_if_absent(self, location: Location, data: bytes) -> bool:
        """Atomic create-EXCLUSIVE put: False when the object already
        exists (the optimistic-commit primitive — S3 If-None-Match / GCS
        precondition; iceberg-style metadata swaps race on it)."""
        raise NotImplementedError

    def delete(self, location: Location) -> None:
        raise NotImplementedError

    def exists(self, location: Location) -> bool:
        raise NotImplementedError

    def list_files(self, prefix: Location) -> Iterator[FileEntry]:
        """All objects whose path starts with ``prefix`` (recursive — the
        object-store model has no directories)."""
        raise NotImplementedError


class LocalFileSystem(TrinoFileSystem):
    """local:// filesystem rooted at a directory (filesystem/local/
    LocalFileSystem.java). Writes are temp-file + rename — the local stand-in
    for an object store's atomic put."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)

    def _os_path(self, location: Location) -> str:
        p = os.path.normpath(os.path.join(self.root, location.path))
        if p != self.root and not p.startswith(self.root + os.sep):
            raise ValueError(f"path escapes filesystem root: {location.uri()}")
        return p

    def read(self, location: Location) -> bytes:
        with open(self._os_path(location), "rb") as f:
            return f.read()

    def write(self, location: Location, data: bytes) -> None:
        p = self._os_path(location)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)

    def write_if_absent(self, location: Location, data: bytes) -> bool:
        p = self._os_path(location)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        try:
            fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
        except FileExistsError:
            return False
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        return True

    def delete(self, location: Location) -> None:
        try:
            os.unlink(self._os_path(location))
        except FileNotFoundError:
            pass

    def exists(self, location: Location) -> bool:
        return os.path.exists(self._os_path(location))

    def list_files(self, prefix: Location) -> Iterator[FileEntry]:
        base = self._os_path(prefix)
        if os.path.isfile(base):
            yield FileEntry(prefix, os.path.getsize(base))
            return
        for root, dirs, files in os.walk(base):
            dirs.sort()
            for fn in sorted(files):
                if fn.endswith(".tmp"):
                    continue
                full = os.path.join(root, fn)
                rel = os.path.relpath(full, self.root).replace(os.sep, "/")
                yield FileEntry(
                    Location(prefix.scheme, rel), os.path.getsize(full)
                )


class FileSystemManager:
    """Scheme -> filesystem registry (the FileSystemFactory set the
    reference assembles from catalog config)."""

    def __init__(self):
        self._factories: Dict[str, Callable[[], TrinoFileSystem]] = {}
        self._instances: Dict[str, TrinoFileSystem] = {}
        self._lock = threading.Lock()

    def register(self, scheme: str, factory: Callable[[], TrinoFileSystem]) -> None:
        with self._lock:
            self._factories[scheme.lower()] = factory
            self._instances.pop(scheme.lower(), None)

    def for_location(self, location: Location) -> TrinoFileSystem:
        with self._lock:
            fs = self._instances.get(location.scheme)
            if fs is None:
                factory = self._factories.get(location.scheme)
                if factory is None:
                    raise ValueError(
                        f"no filesystem registered for scheme {location.scheme!r}"
                    )
                fs = factory()
                self._instances[location.scheme] = fs
            return fs
