"""Python client for the coordinator REST protocol.

Reference blueprint: client/trino-client StatementClientV1.java:75 — POST the
statement, then follow ``nextUri`` (advance():397) until the query drains,
accumulating row batches. Uses stdlib urllib (no extra deps).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Iterator, List, Optional


class ClientError(RuntimeError):
    pass


@dataclass
class StatementResult:
    query_id: str
    columns: List[str]
    rows: List[list]
    stats: dict = field(default_factory=dict)


class StatementClient:
    def __init__(self, base_url: str, timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, url: str, body: Optional[bytes] = None,
                 headers: Optional[dict] = None) -> dict:
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers or {})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read().decode())
            except Exception:
                detail = {"error": str(e)}
            raise ClientError(f"HTTP {e.code}: {detail}") from None

    def _fetch_segments(self, segments: list, encoding: str) -> List[list]:
        """Fetch + decode + ack spooled segments (protocol/spooling client)."""
        rows: List[list] = []
        for seg in segments:
            req = urllib.request.Request(seg["uri"])
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                data = resp.read()
            if encoding == "json+lz4":
                from ..native import lz4_decompress

                data = lz4_decompress(data, seg["uncompressedSize"])
            rows.extend(json.loads(data.decode()))
            # acknowledge: the server may free the segment
            ack = urllib.request.Request(seg["uri"], method="DELETE")
            try:
                urllib.request.urlopen(ack, timeout=self.timeout)
            except urllib.error.HTTPError:
                pass
        return rows

    def execute(self, sql: str, data_encoding: Optional[str] = None) -> StatementResult:
        headers = (
            {"X-Trino-Query-Data-Encoding": data_encoding} if data_encoding else None
        )
        payload = self._request(
            "POST", f"{self.base_url}/v1/statement", sql.encode(), headers=headers
        )
        columns: List[str] = []
        rows: List[list] = []
        query_id = payload.get("id", "")
        deadline = time.time() + self.timeout
        while True:
            if "error" in payload:
                err = payload["error"]
                raise ClientError(f"{err.get('errorName')}: {err.get('message')}")
            if "columns" in payload:
                columns = [c["name"] for c in payload["columns"]]
            if "segments" in payload:
                # spooled protocol: fetch each segment out-of-band, then ack
                rows.extend(
                    self._fetch_segments(
                        payload["segments"], payload.get("dataEncoding", "json")
                    )
                )
            rows.extend(payload.get("data", []))
            next_uri = payload.get("nextUri")
            if next_uri is None:
                return StatementResult(
                    query_id=query_id,
                    columns=columns,
                    rows=rows,
                    stats=payload.get("stats", {}),
                )
            if time.time() > deadline:
                raise ClientError(f"query {query_id} timed out")
            payload = self._request("GET", next_uri)

    def query_info(self, query_id: str) -> dict:
        return self._request("GET", f"{self.base_url}/v1/query/{query_id}")

    def server_info(self) -> dict:
        return self._request("GET", f"{self.base_url}/v1/info")
