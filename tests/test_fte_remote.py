"""FTE over REMOTE workers + multi-part distributed sort on the DCN tiers.

Round-3 verdict items 3: retry_policy=TASK previously raised with remote
workers (the fault-tolerance story only covered in-process execution, where
tasks rarely die), and FIXED_RANGE fragments ran single-part on the staged
tier. ref: EventDrivenFaultTolerantQueryScheduler.java:209 (tasks re-run
from durable inputs after REMOTE loss), BaseFailureRecoveryTest (kill a
worker mid-query, results must be exact), benchto distributed_sort suite.
"""

import pytest

from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.metadata import CatalogManager, Session
from trino_tpu.parallel.runner import DistributedQueryRunner
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.server.worker import WorkerServer

SCALE = 0.0005
SECRET = "fte-remote-secret"

SORT_SQL = (
    "SELECT o_orderkey, o_totalprice FROM orders "
    "ORDER BY o_totalprice DESC, o_orderkey"
)
AGG_SQL = (
    "SELECT l_returnflag, count(*) c, sum(l_quantity) s "
    "FROM lineitem GROUP BY 1 ORDER BY 1"
)
JOIN_SQL = "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey"


def _worker_catalogs():
    c = CatalogManager()
    c.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    return c


def _make_dist(urls, n_workers=3):
    dist = DistributedQueryRunner(
        Session(catalog="tpch", schema="sf0_0005"),
        n_workers=n_workers,
        worker_urls=urls,
        secret=SECRET,
    )
    dist.catalogs.register("tpch", TpchConnector(scale=SCALE, split_target_rows=512))
    dist.session.set("retry_policy", "TASK")
    return dist


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner.tpch(scale=SCALE)


class TestFteRemoteWorkers:
    def test_fte_query_on_remote_workers(self, local):
        ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(2)]
        try:
            dist = _make_dist([f"http://{w.address}" for w in ws])
            res = dist.execute(AGG_SQL)
            assert dist.last_tier == "fte"
            assert res.rows == local.execute(AGG_SQL).rows
        finally:
            for w in ws:
                w.stop()

    def test_worker_killed_mid_query_task_retries(self, local):
        # kill one worker BETWEEN stages (after its source tasks committed
        # durably, before the consumer stage dispatches): the consumer task
        # attempt against the dead worker fails with a transport error and
        # must retry on a survivor — query completes, no query-level restart
        ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(3)]
        alive = ws[:]
        dist = _make_dist([f"http://{w.address}" for w in ws])
        orig = dist._adaptive_join_modes_durable
        killed = []

        calls = []

        def kill_then_modes(*args, **kwargs):
            # runs once per stage; kill on the SECOND stage so the first
            # stage's tasks have committed durably and the consumer stage's
            # attempt against the dead worker must retry on a survivor
            calls.append(True)
            if len(calls) == 2 and not killed:
                ws[0].stop()
                killed.append(True)
            return orig(*args, **kwargs)

        dist._adaptive_join_modes_durable = kill_then_modes
        try:
            res = dist.execute(JOIN_SQL)
            assert killed, "kill hook never fired (query had no stages?)"
            assert res.rows == local.execute(JOIN_SQL).rows
            # at least one task needed a second attempt
            assert any(a >= 1 for a in dist.last_task_attempts.values())
        finally:
            for w in alive[1:]:
                w.stop()

    def test_all_workers_dead_raises(self):
        w = WorkerServer(_worker_catalogs(), secret=SECRET).start()
        dist = _make_dist([f"http://{w.address}"])
        w.stop()
        with pytest.raises(Exception):
            dist.execute(AGG_SQL)

    def test_exchange_payload_never_transits_coordinator(self, local):
        # round-5 data plane (ref: FileSystemExchangeManager): workers read
        # inputs from and commit outputs to the shared durable store
        # directly; the coordinator ships descriptors and reads metadata.
        # fte_coordinator_payload_bytes counts every exchange byte routed
        # through the coordinator — hash/gather/broadcast plans must be 0.
        ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(2)]
        try:
            dist = _make_dist([f"http://{w.address}" for w in ws])
            # ORDER BY under distributed_sort plans a REPARTITION_RANGE
            # exchange — the documented coordinator fallback; pin it off so
            # these plans are pure hash/gather
            dist.session.set("distributed_sort", False)
            for sql in (AGG_SQL, JOIN_SQL):
                res = dist.execute(sql)
                assert res.rows == local.execute(sql).rows
                assert dist.fte_coordinator_payload_bytes == 0, sql
        finally:
            for w in ws:
                w.stop()

    def test_range_exchange_fallback_is_counted(self, local):
        # distributed sort still materializes range cuts through the
        # coordinator (global quantiles over a stream) — documented
        # exception, observable in the same counter
        ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(2)]
        try:
            dist = _make_dist([f"http://{w.address}" for w in ws])
            dist.session.set("target_partition_rows", 10)
            res = dist.execute(SORT_SQL)
            assert res.rows == local.execute(SORT_SQL).rows
            assert dist.fte_coordinator_payload_bytes > 0
        finally:
            for w in ws:
                w.stop()


class TestDistributedSortStaged:
    def test_order_by_runs_range_partitioned(self, local):
        dist = DistributedQueryRunner.tpch(scale=SCALE, n_workers=3)
        dist.session.set("use_ici_exchange", False)  # pin the staged tier
        # tiny test tables would legitimately collapse to one partition under
        # DeterminePartitionCount — force fan-out to exercise the range shuffle
        dist.session.set("target_partition_rows", 10)
        res = dist.execute(SORT_SQL)
        assert dist.last_tier == "staged"
        assert res.rows == local.execute(SORT_SQL).rows
        # the FIXED_RANGE fragment must have run multi-part
        from trino_tpu.planner.fragmenter import Partitioning

        sub = dist.plan_distributed(SORT_SQL)
        range_frags = [
            f.fragment_id
            for f in sub.fragments
            if f.partitioning == Partitioning.FIXED_RANGE
        ]
        assert range_frags, "plan has no FIXED_RANGE fragment"
        assert all(
            dist.last_partition_counts.get(fid) == 3 for fid in range_frags
        )

    def test_order_by_nulls_and_desc(self, local):
        sql = (
            "SELECT o_orderkey, CASE WHEN o_orderkey % 7 = 0 THEN NULL "
            "ELSE o_orderpriority END p FROM orders "
            "ORDER BY p DESC NULLS FIRST, o_orderkey"
        )
        dist = DistributedQueryRunner.tpch(scale=SCALE, n_workers=3)
        assert dist.execute(sql).rows == local.execute(sql).rows

    def test_fte_tier_order_by_range_partitioned(self, local):
        dist = DistributedQueryRunner.tpch(scale=SCALE, n_workers=3)
        dist.session.set("retry_policy", "TASK")
        res = dist.execute(SORT_SQL)
        assert dist.last_tier == "fte"
        assert res.rows == local.execute(SORT_SQL).rows

    def test_fte_remote_order_by(self, local):
        ws = [WorkerServer(_worker_catalogs(), secret=SECRET).start() for _ in range(2)]
        try:
            dist = _make_dist([f"http://{w.address}" for w in ws], n_workers=2)
            res = dist.execute(SORT_SQL)
            assert res.rows == local.execute(SORT_SQL).rows
        finally:
            for w in ws:
                w.stop()
