"""Active-active coordinator fleet plane (runtime/fleet.py).

The r20 tentpole's test surface: deterministic consistent-hash ownership
(a dead member's range moves, every other key keeps its owner),
partitioned admission over REAL coordinators (redirect and proxy modes),
follower reads (system.*-only statements, status-board polls), the
client's bounded-hop 307 following with a clear redirect-loop error, and
the default-off contract (no fleet object, no heartbeat, no routing
branch — poisoning-style)."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from trino_tpu.client.client import ClientError, StatementClient
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.fleet import (
    FleetMember,
    HashRing,
    is_system_read,
    partition_key,
)

SCALE = 0.0005


class TestHashRing:
    def test_deterministic(self):
        a = HashRing(["n1", "n2", "n3"])
        b = HashRing(["n3", "n1", "n2"])
        keys = [f"session:u{i}@" for i in range(64)]
        assert [a.owner(k) for k in keys] == [b.owner(k) for k in keys]

    def test_dead_member_moves_only_its_own_range(self):
        members = ["n1", "n2", "n3", "n4"]
        full = HashRing(members)
        keys = [f"session:user{i:03d}@src" for i in range(300)]
        before = {k: full.owner(k) for k in keys}
        survivors = HashRing([m for m in members if m != "n3"])
        for k in keys:
            after = survivors.owner(k)
            if before[k] == "n3":
                assert after != "n3"  # reassigned to a survivor
            else:
                assert after == before[k]  # everyone else keeps its owner

    def test_every_member_owns_something(self):
        ring = HashRing(["n1", "n2", "n3", "n4"])
        owners = {ring.owner(f"session:user{i:03d}@") for i in range(400)}
        assert owners == {"n1", "n2", "n3", "n4"}

    def test_empty_ring(self):
        assert HashRing([]).owner("anything") is None


class TestPartitionKey:
    def test_session_identity_default(self):
        assert partition_key("alice", "cli") == "session:alice@cli"

    def test_group_override(self, monkeypatch):
        monkeypatch.setenv("TRINO_TPU_FLEET_PARTITION_BY", "group")
        assert partition_key("alice", "cli", "global.etl") == \
            "group:global.etl"
        # no resolved group: fall back to the session identity
        assert partition_key("alice", "cli", "") == "session:alice@cli"


class TestSystemReadClassifier:
    def test_system_only_select(self):
        assert is_system_read("SELECT * FROM system.runtime.nodes")
        assert is_system_read(
            "select a.node_id from system.metrics.counters a "
            "join system.runtime.nodes b on 1=1"
        )

    def test_anything_else_routes_to_owner(self):
        assert not is_system_read("SELECT count(*) FROM nation")
        assert not is_system_read(
            "SELECT * FROM system.runtime.nodes, tpch.nation"
        )
        assert not is_system_read("INSERT INTO system.x VALUES (1)")
        assert not is_system_read("SELECT 1")  # no targets: owner decides


class TestMembership:
    def test_heartbeat_ttl_and_deregister(self, tmp_path):
        m1 = FleetMember(str(tmp_path), "n1", "http://h:1",
                         heartbeat_secs=0.2)
        m2 = FleetMember(str(tmp_path), "n2", "http://h:2",
                         heartbeat_secs=0.2)
        m1.publish_heartbeat()
        m2.publish_heartbeat()
        assert sorted(m1.live_members(now=time.time())) == ["n1", "n2"]
        # a lapsed heartbeat drops out without any delete
        assert sorted(m1.live_members(now=time.time() + 10)) == []
        # graceful stop deregisters immediately
        m2.stop(deregister=True)
        assert sorted(m1.live_members(now=time.time())) == ["n1"]

    def test_owner_of_self_when_alone(self, tmp_path):
        m = FleetMember(str(tmp_path), "n1", "http://h:1",
                        heartbeat_secs=0.2)
        assert m.owner_of("session:any@")["node_id"] == "n1"

    def test_status_board_round_trip(self, tmp_path):
        m1 = FleetMember(str(tmp_path), "n1", "http://h:1",
                         heartbeat_secs=0.2)
        m2 = FleetMember(str(tmp_path), "n2", "http://h:2",
                         heartbeat_secs=0.2)
        m1.publish_status("q_x", {"queryId": "q_x", "state": "FINISHED"})
        board = m2.read_status("q_x")
        assert board["state"] == "FINISHED"
        assert board["fleet_owner"] == "n1"
        assert m2.read_status("q_missing") is None

    def test_heartbeat_carries_bounded_metrics(self, tmp_path):
        from trino_tpu.runtime.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_queries_submitted_total", help="queries submitted"
        ).inc(0)
        m = FleetMember(str(tmp_path), "n1", "http://h:1",
                        heartbeat_secs=0.2)
        m.publish_heartbeat()
        rec = m.live_members(now=time.time())["n1"]
        names = {s.get("name") for s in rec["metrics"]}
        assert "trino_tpu_queries_submitted_total" in names
        assert isinstance(rec["queue_depth"], int)


def _fleet_pair(tmp_path, monkeypatch, route="redirect"):
    monkeypatch.setenv("TRINO_TPU_FLEET_DIR", str(tmp_path))
    monkeypatch.setenv("TRINO_TPU_FLEET_HEARTBEAT_SECS", "0.2")
    monkeypatch.setenv("TRINO_TPU_FLEET_ROUTE", route)
    from trino_tpu.server.coordinator import CoordinatorServer

    c1 = CoordinatorServer(
        LocalQueryRunner.tpch(scale=SCALE), node_id="n1"
    ).start()
    c2 = CoordinatorServer(
        LocalQueryRunner.tpch(scale=SCALE), node_id="n2"
    ).start()
    # one user owned by each coordinator (deterministic ring, so scan)
    users = {}
    for i in range(64):
        user = f"user{i:02d}"
        owner = c1.fleet.owner_of(partition_key(user, ""))["node_id"]
        users.setdefault(owner, user)
        if len(users) == 2:
            break
    assert set(users) == {"n1", "n2"}
    return c1, c2, users


class TestPartitionedAdmission:
    def test_non_owner_redirects_and_client_follows(
        self, tmp_path, monkeypatch
    ):
        c1, c2, users = _fleet_pair(tmp_path, monkeypatch)
        try:
            # raw protocol: a statement for n2's user POSTed at n1 is 307
            req = urllib.request.Request(
                f"http://{c1.address}/v1/statement",
                data=b"SELECT count(*) FROM nation", method="POST",
                headers={"X-Trino-User": users["n2"]},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 307
            assert ei.value.headers["Location"] == \
                f"http://{c2.host}:{c2.port}/v1/statement"
            assert ei.value.headers["X-Trino-Fleet-Owner"] == "n2"
            # the client follows it transparently
            cl = StatementClient(f"http://{c1.address}", user=users["n2"])
            assert cl.execute("SELECT count(*) FROM nation").rows == [[25]]
            # the owner's own user passes straight through
            cl_own = StatementClient(
                f"http://{c1.address}", user=users["n1"]
            )
            assert cl_own.execute(
                "SELECT count(*) FROM nation"
            ).rows == [[25]]
        finally:
            c1.stop()
            c2.stop()

    def test_proxy_mode_serves_without_redirect(self, tmp_path, monkeypatch):
        c1, c2, users = _fleet_pair(tmp_path, monkeypatch, route="proxy")
        try:
            req = urllib.request.Request(
                f"http://{c1.address}/v1/statement",
                data=b"SELECT count(*) FROM region", method="POST",
                headers={"X-Trino-User": users["n2"]},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
                payload = json.loads(resp.read())
            # the proxied intake came back from the owner; paging then
            # goes DIRECT to the owner's address
            next_uri = payload.get("nextUri", "")
            if next_uri:
                assert f"{c2.host}:{c2.port}" in next_uri
            # the owner holds the query, the proxy does not
            assert (
                c2.manager.get(payload["id"]) is not None
                or c2.fleet.read_status(payload["id"]) is not None
            )
            assert c1.manager.get(payload["id"]) is None
        finally:
            c1.stop()
            c2.stop()

    def test_follower_reads_served_locally(self, tmp_path, monkeypatch):
        c1, c2, users = _fleet_pair(tmp_path, monkeypatch)
        try:
            # system.*-only statement for n2's user served by n1 directly
            cl = StatementClient(f"http://{c1.address}", user=users["n2"])
            res = cl.execute("SELECT node_id FROM system.runtime.nodes")
            assert res.rows
            assert c1.manager.get(res.query_id) is not None
            # status poll for an owner-side query answered by the follower
            run = cl.execute("SELECT count(*) FROM nation")
            deadline = time.time() + 5
            board = None
            while time.time() < deadline:
                board = c1._fleet_board_status(run.query_id)
                if board is not None and board.get("state") == "FINISHED":
                    break
                time.sleep(0.05)
            assert board is not None
            assert board["fleet_owner"] == "n2"
        finally:
            c1.stop()
            c2.stop()

    def test_crashed_owner_range_reassigns(self, tmp_path, monkeypatch):
        c1, c2, users = _fleet_pair(tmp_path, monkeypatch)
        try:
            c2.stop(crash=True)  # membership record left to lapse
            # after the TTL the ring serves n2's old range from n1
            deadline = time.time() + 5
            while time.time() < deadline:
                live = c1.fleet.live_members(now=time.time())
                if "n2" not in live:
                    break
                time.sleep(0.05)
            assert "n2" not in c1.fleet.live_members(now=time.time())
            cl = StatementClient(f"http://{c1.address}", user=users["n2"])
            assert cl.execute("SELECT count(*) FROM nation").rows == [[25]]
        finally:
            c1.stop()


class _Redirector(BaseHTTPRequestHandler):
    """Stub coordinator that 307s every statement to a configured peer."""

    peer = ""

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self.send_response(307)
        self.send_header("Location", f"{self.peer}/v1/statement")
        self.send_header("Content-Length", "0")
        self.end_headers()


class TestClientRedirects:
    def test_two_coordinator_redirect_loop_is_a_clear_error(self):
        class A(_Redirector):
            pass

        class B(_Redirector):
            pass

        sa = ThreadingHTTPServer(("127.0.0.1", 0), A)
        sb = ThreadingHTTPServer(("127.0.0.1", 0), B)
        A.peer = f"http://127.0.0.1:{sb.server_port}"
        B.peer = f"http://127.0.0.1:{sa.server_port}"
        threads = [
            threading.Thread(target=s.serve_forever, daemon=True)
            for s in (sa, sb)
        ]
        for t in threads:
            t.start()
        try:
            cl = StatementClient(f"http://127.0.0.1:{sa.server_port}")
            with pytest.raises(ClientError) as ei:
                cl.execute("SELECT 1")
            assert "redirect loop" in str(ei.value)
            assert str(sa.server_port) in str(ei.value)
            assert str(sb.server_port) in str(ei.value)
        finally:
            for s in (sa, sb):
                s.shutdown()
                s.server_close()

    def test_hop_bound(self):
        # a chain longer than MAX_REDIRECT_HOPS of DISTINCT targets
        class Chain(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0))
                )
                self.send_response(307)
                # a fresh path every hop: never a loop, only depth
                n = int(self.path.rsplit("=", 1)[-1]) if "=" in self.path \
                    else 0
                self.send_header(
                    "Location",
                    f"http://127.0.0.1:{self.server.server_port}"
                    f"/v1/statement?hop={n + 1}",
                )
                self.send_header("Content-Length", "0")
                self.end_headers()

        s = ThreadingHTTPServer(("127.0.0.1", 0), Chain)
        threading.Thread(target=s.serve_forever, daemon=True).start()
        try:
            cl = StatementClient(f"http://127.0.0.1:{s.server_port}")
            with pytest.raises(ClientError) as ei:
                cl.execute("SELECT 1")
            assert "too many redirects" in str(ei.value)
        finally:
            s.shutdown()
            s.server_close()

    def test_redirect_without_location_is_an_error(self):
        class NoLoc(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def do_POST(self):
                self.rfile.read(
                    int(self.headers.get("Content-Length", 0))
                )
                self.send_response(307)
                self.send_header("Content-Length", "0")
                self.end_headers()

        s = ThreadingHTTPServer(("127.0.0.1", 0), NoLoc)
        threading.Thread(target=s.serve_forever, daemon=True).start()
        try:
            cl = StatementClient(f"http://127.0.0.1:{s.server_port}")
            with pytest.raises(ClientError) as ei:
                cl.execute("SELECT 1")
            assert "redirect without Location" in str(ei.value)
        finally:
            s.shutdown()
            s.server_close()


class TestOffPathByteIdentity:
    """Default-off contract: with $TRINO_TPU_FLEET_DIR unset there is no
    fleet object, no heartbeat thread, no routing branch — and the fleet
    plane may not even be TOUCHED (poisoning-style)."""

    def test_no_fleet_without_the_knob(self, monkeypatch):
        monkeypatch.delenv("TRINO_TPU_FLEET_DIR", raising=False)
        from trino_tpu.server.coordinator import CoordinatorServer

        c = CoordinatorServer(LocalQueryRunner.tpch(scale=SCALE))
        assert c.fleet is None
        assert c._front_server is None

    def test_off_path_poisoned_fleet_untouched(self, monkeypatch):
        monkeypatch.delenv("TRINO_TPU_FLEET_DIR", raising=False)
        from trino_tpu.runtime import fleet as fleet_mod
        from trino_tpu.server.coordinator import CoordinatorServer

        def poisoned(*a, **k):
            raise AssertionError("fleet plane touched on the off path")

        monkeypatch.setattr(fleet_mod.FleetMember, "__init__", poisoned)
        monkeypatch.setattr(fleet_mod.FleetMember, "owner_of", poisoned)
        monkeypatch.setattr(fleet_mod, "is_system_read", poisoned)
        monkeypatch.setattr(fleet_mod, "partition_key", poisoned)

        # REGISTRY is process-global (earlier on-path tests register the
        # fleet series): the off-path contract is that the VALUES never
        # move, not that the names are absent from a shared registry
        from trino_tpu.runtime.metrics import REGISTRY

        def fleet_series():
            return [
                line for line in REGISTRY.render().splitlines()
                if line.startswith("trino_tpu_fleet_")
            ]

        before = fleet_series()
        c = CoordinatorServer(LocalQueryRunner.tpch(scale=SCALE)).start()
        try:
            cl = StatementClient(f"http://{c.address}", user="alice")
            assert cl.execute("SELECT count(*) FROM nation").rows == [[25]]
            assert fleet_series() == before
        finally:
            c.stop()
