from .coordinator import CoordinatorServer
