"""Tests for the type system and Page/Column substrate (SURVEY.md §2.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trino_tpu import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    VARCHAR,
    Column,
    Dictionary,
    Page,
    decimal_type,
    parse_type,
)
from trino_tpu.spi.types import common_super_type, varchar_type


class TestTypes:
    def test_storage_dtypes(self):
        assert BIGINT.storage_dtype == np.int64
        assert INTEGER.storage_dtype == np.int32
        assert DOUBLE.storage_dtype == np.float64
        assert BOOLEAN.storage_dtype == np.bool_
        assert DATE.storage_dtype == np.int32
        assert VARCHAR.storage_dtype == np.int32
        assert decimal_type(12, 2).storage_dtype == np.int64

    def test_parse_type(self):
        assert parse_type("bigint") == BIGINT
        assert parse_type("decimal(12,2)") == decimal_type(12, 2)
        assert parse_type("varchar(25)") == varchar_type(25)
        assert parse_type("DOUBLE") == DOUBLE

    def test_common_super_type(self):
        assert common_super_type(INTEGER, BIGINT) == BIGINT
        assert common_super_type(BIGINT, DOUBLE) == DOUBLE
        d = common_super_type(decimal_type(12, 2), INTEGER)
        assert d.scale == 2
        assert common_super_type(varchar_type(3), varchar_type(7)) == varchar_type(7)
        assert common_super_type(BOOLEAN, BIGINT) is None


class TestDictionary:
    def test_sorted_codes_preserve_order(self):
        d = Dictionary.from_strings(["pear", "apple", "mango"])
        codes = [d.code_of(s) for s in ["apple", "mango", "pear"]]
        assert codes == sorted(codes)  # lexicographic order == code order
        assert d.code_of("absent") == -1

    def test_searchsorted_for_ranges(self):
        d = Dictionary.from_strings(["a", "c", "e"])
        assert d.searchsorted("b") == 1  # codes >= 1 are strings >= 'b'
        assert d.searchsorted("c") == 1
        assert d.searchsorted("c", side="right") == 2


class TestPage:
    def test_roundtrip(self):
        page = Page.from_arrays(
            [BIGINT, DOUBLE],
            [np.array([1, 2, 3]), np.array([1.5, 2.5, 3.5])],
            capacity=8,
        )
        assert page.capacity == 8
        assert int(page.num_rows()) == 3
        assert page.to_pylist() == [(1, 1.5), (2, 2.5), (3, 3.5)]

    def test_nulls(self):
        col = Column.from_numpy(
            BIGINT, np.array([1, 2, 3]), valid=np.array([True, False, True])
        )
        page = Page(columns=(col,), active=jnp.array([True, True, True]))
        assert page.to_pylist() == [(1,), (None,), (3,)]

    def test_mask_no_compaction(self):
        page = Page.from_arrays([BIGINT], [np.arange(4)])
        filtered = page.mask(jnp.array([True, False, True, False]))
        assert filtered.capacity == 4  # static shape preserved
        assert int(filtered.num_rows()) == 2
        assert filtered.to_pylist() == [(0,), (2,)]

    def test_string_column(self):
        col = Column.from_strings(["b", None, "a", "b"])
        page = Page(columns=(col,), active=jnp.ones(4, dtype=bool))
        assert [r[0] for r in page.to_pylist()] == ["b", None, "a", "b"]

    def test_page_is_pytree(self):
        page = Page.from_arrays([BIGINT], [np.arange(5)], capacity=8)

        @jax.jit
        def double_col(p: Page) -> Page:
            c = p.column(0)
            out = Column(c.type, c.data * 2, c.valid, c.dictionary)
            return p.with_columns([out])

        out = double_col(page)
        assert out.to_pylist() == [(0,), (2,), (4,), (6,), (8,)]

    def test_decimal_decode(self):
        col = Column.from_numpy(decimal_type(10, 2), np.array([150, 299]))
        page = Page(columns=(col,), active=jnp.ones(2, dtype=bool))
        assert page.to_pylist() == [(1.5,), (2.99,)]
