"""The estimate<->actual statistics feedback plane (runtime/statstore.py).

Covers: per-operator actuals collection across the TPC-H corpus (finite
q-errors, every executed plan node reported), EXPLAIN ANALYZE est->actual
rendering, the history-based stats store (canonical keys, file round-trip
"through coordinator restart", HistoryBasedStatsEstimator overlay changing
a Q5-shape join order with ORACLE-verified bit-identical results),
mis-estimate flight events/metrics, the system.runtime.operator_stats /
system.optimizer.stats_history tables, FTE attribution (only the WINNING
attempt of a speculative pair folds into query-level stats — the
double-counting regression), and 16-client concurrent collector safety.

ref: Presto HBO (HistoryBasedPlanStatisticsCalculator) + io.trino.cost.
"""

import threading

import pytest

from trino_tpu.planner.plan import (
    FilterNode,
    JoinNode,
    OutputNode,
    TableScanNode,
    visit_plan,
)
from trino_tpu.runtime import statstore
from trino_tpu.runtime.local import LocalQueryRunner

SCALE = 0.001

Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
       avg(l_extendedprice) AS avg_price, count(*) AS count_order
FROM lineitem WHERE l_shipdate <= DATE '1998-09-02'
GROUP BY l_returnflag, l_linestatus ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01' AND l_shipdate < DATE '1995-01-01'
  AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24
"""

Q13 = """
SELECT c_count, count(*) AS custdist
FROM (
  SELECT c_custkey, count(o_orderkey) AS c_count
  FROM customer LEFT JOIN orders ON c_custkey = o_custkey
    AND o_comment NOT LIKE '%special%requests%'
  GROUP BY c_custkey
) AS c_orders
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC
"""


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


class TestQError:
    def test_finite_and_symmetric(self):
        assert statstore.q_error(100, 100) == 1.0
        assert statstore.q_error(200, 100) == 2.0
        assert statstore.q_error(100, 200) == 2.0
        # zero actual/estimate floors at one row instead of diverging
        assert statstore.q_error(1000, 0) == 1000.0
        assert statstore.q_error(0, 0) == 1.0
        assert statstore.q_error(None, 5) is None


class TestActualsCollection:
    @pytest.mark.parametrize("sql", [Q1, Q3, Q6, Q13], ids=["q1", "q3", "q6", "q13"])
    def test_every_plan_node_reports_actuals(self, runner, sql):
        """Acceptance: every Q1/Q3/Q6/Q13 plan node reports actuals with a
        finite q-error wherever an estimate exists."""
        res = runner.execute(sql)
        nodes = res.query_stats["planNodes"]
        assert nodes, "no plan-node actuals collected"
        # the executed plan has the same preorder shape as a fresh planning
        plan = runner.plan_sql(sql)
        expected_keys = set()
        ordered = []
        visit_plan(plan.root, ordered.append)
        for idx, node in enumerate(ordered):
            if isinstance(node, OutputNode):
                continue  # the root names columns; it is never executed
            expected_keys.add(f"{idx}:{type(node).__name__}")
        assert expected_keys == set(nodes)
        import math

        for key, ent in nodes.items():
            assert ent["actualRows"] >= 0, key
            if ent["estimatedRows"] is not None:
                assert ent["qError"] is not None and math.isfinite(ent["qError"]), key
                assert ent["qError"] >= 1.0, key

    def test_scan_actual_matches_row_count(self, runner):
        expected = runner.execute("SELECT count(*) FROM nation").rows[0][0]
        res = runner.execute("SELECT max(n_nationkey) FROM nation")
        scans = [
            v for k, v in res.query_stats["planNodes"].items()
            if k.endswith("TableScanNode")
        ]
        assert len(scans) == 1
        assert scans[0]["actualRows"] == expected
        assert scans[0]["nullFraction"] == 0.0

    def test_join_reports_build_side_and_dynamic_filter(self, runner):
        res = runner.execute(
            "SELECT count(*) FROM supplier JOIN nation "
            "ON s_nationkey = n_nationkey"
        )
        joins = [
            v for k, v in res.query_stats["planNodes"].items()
            if k.endswith("JoinNode")
        ]
        assert len(joins) == 1
        assert joins[0]["buildRows"] is not None and joins[0]["buildRows"] > 0
        sel = joins[0]["dynamicFilterSelectivity"]
        assert sel is None or 0.0 <= sel <= 1.0

    def test_feedback_disabled_collects_nothing(self):
        r = LocalQueryRunner.tpch(scale=SCALE)
        r.session.set("statistics_feedback", False)
        res = r.execute("SELECT count(*) FROM nation")
        assert res.query_stats["planNodes"] == {}


class TestExplainAnalyze:
    def test_est_actual_qerror_rendered(self, runner):
        res = runner.execute(
            "EXPLAIN ANALYZE SELECT n_name, count(*) FROM supplier, nation "
            "WHERE s_nationkey = n_nationkey GROUP BY n_name"
        )
        text = "\n".join(line for (line,) in res.rows)
        assert "rows: est " in text and "-> actual " in text
        assert "(q=" in text
        # the verbose attribution columns still render on top
        res2 = runner.execute(
            "EXPLAIN ANALYZE VERBOSE SELECT count(*) FROM nation"
        )
        text2 = "\n".join(line for (line,) in res2.rows)
        assert "rows: est " in text2 and "device=" in text2

    def test_constant_query_analyzes(self, runner):
        res = runner.execute("EXPLAIN ANALYZE SELECT 1")
        text = "\n".join(line for (line,) in res.rows)
        assert "actual 1" in text


class TestCanonicalKeys:
    def _scan(self, runner, sql):
        plan = runner.plan_sql(sql)
        scans = []
        visit_plan(
            plan.root,
            lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
        )
        return plan, scans

    def test_leaf_key_symbol_independent(self, runner):
        """The same filtered-scan shape keys identically across plannings
        (symbol allocation differs between queries in one statement vs two)."""
        p1 = runner.plan_sql(
            "SELECT count(*) FROM orders WHERE o_comment LIKE '%x%'"
        )
        p2 = runner.plan_sql(
            "SELECT count(*) FROM orders o, nation "
            "WHERE o_comment LIKE '%x%' AND o_orderkey = n_nationkey"
        )

        def filter_keys(plan):
            out = []
            visit_plan(
                plan.root,
                lambda n: out.append(statstore.leaf_key_for(n))
                if isinstance(n, FilterNode) else None,
            )
            return [k for k in out if k]

        k1, k2 = filter_keys(p1), filter_keys(p2)
        assert k1, "no canonical leaf key for the filtered scan"
        # the 2-table plan's orders leaf carries the same LIKE conjunct
        assert set(k1) & set(k2)

    def test_different_predicates_key_differently(self, runner):
        p1, _ = self._scan(runner, "SELECT * FROM nation WHERE n_nationkey = 1")
        p2, _ = self._scan(runner, "SELECT * FROM nation WHERE n_nationkey = 2")

        def first_filter_key(plan):
            out = []
            visit_plan(
                plan.root,
                lambda n: out.append(statstore.leaf_key_for(n))
                if isinstance(n, FilterNode) else None,
            )
            return next((k for k in out if k), None)

        assert first_filter_key(p1) != first_filter_key(p2)

    def test_constrained_scan_keys_differently_from_bare_scan(self, runner):
        """A scan with an absorbed TupleDomain emits fewer rows than a bare
        scan; recording its actual under the bare-scan key would poison
        unfiltered-scan estimates (review finding)."""
        _, bare = self._scan(runner, "SELECT n_name FROM nation")
        plan, constrained = self._scan(
            runner, "SELECT n_name FROM nation WHERE n_nationkey = 3"
        )
        with_constraint = [s for s in constrained if s.constraint.domains]
        assert with_constraint, "pushdown_into_scans left no constraint"
        assert statstore.leaf_key_for(bare[0]) != statstore.leaf_key_for(
            with_constraint[0]
        )

    def test_node_fingerprint_stable(self, runner):
        _, scans1 = self._scan(runner, "SELECT n_name FROM nation")
        _, scans2 = self._scan(runner, "SELECT n_name FROM nation")
        assert statstore.node_fingerprint(scans1[0]) == statstore.node_fingerprint(
            scans2[0]
        )
        assert statstore.node_fingerprint(scans1[0]).startswith("s:")


class TestHistoryStore:
    def test_memory_roundtrip_and_run_counter(self, monkeypatch):
        monkeypatch.delenv(statstore.ENV_VAR, raising=False)
        statstore.clear_memory()
        statstore.record_history({"s:abc": {"kind": "FilterNode", "actual": 7,
                                            "estimate": 100.0, "runs": 1}})
        statstore.record_history({"s:abc": {"kind": "FilterNode", "actual": 9,
                                            "estimate": 100.0, "runs": 1}})
        ent = statstore.lookup("s:abc")
        assert ent["actual"] == 9  # latest actual wins
        assert ent["runs"] == 2    # observation count accumulates
        statstore.clear_memory()
        assert statstore.lookup("s:abc") is None

    def test_file_store_survives_restart(self, tmp_path, monkeypatch):
        """Acceptance: the history store round-trips through a coordinator
        restart — the file is the durable contract; a fresh process (here: a
        cleared in-memory state) reloads every record."""
        path = str(tmp_path / "stats_history.json")
        monkeypatch.setenv(statstore.ENV_VAR, path)
        r = LocalQueryRunner.tpch(scale=SCALE)
        r.execute("SELECT count(*) FROM orders WHERE o_comment LIKE '%never%'")
        on_disk = statstore.load_history()
        assert on_disk, "execution recorded nothing to the history file"
        # "restart": wipe all in-process state; the file alone must serve
        statstore.clear_memory()
        reloaded = statstore.load_history()
        assert reloaded == on_disk
        assert any(e.get("actual") is not None for e in reloaded.values())

    def test_memory_store_bounded(self, monkeypatch):
        monkeypatch.delenv(statstore.ENV_VAR, raising=False)
        statstore.clear_memory()
        statstore.record_history({
            f"s:{i:04d}": {"kind": "x", "actual": i, "runs": 1}
            for i in range(statstore._MAX_MEMORY_ENTRIES + 100)
        })
        assert len(statstore.load_history()) <= statstore._MAX_MEMORY_ENTRIES
        statstore.clear_memory()


QH = """
SELECT c_name, sum(l_extendedprice) AS revenue
FROM lineitem, orders, customer
WHERE l_orderkey = o_orderkey AND o_custkey = c_custkey
  AND o_comment LIKE '%slyly%pending%'
GROUP BY c_name ORDER BY revenue DESC, c_name
"""


class TestHistoryOverlay:
    """The Presto-HBO acceptance path: cold run records actuals, warm run
    of the same Q5-shape multi-join plans a different (better-costed) join
    order, oracle-verified bit-identical."""

    def _leaves(self, plan):
        out = []
        visit_plan(
            plan.root,
            lambda n: out.append(n.table.schema_table.table)
            if isinstance(n, TableScanNode) else None,
        )
        return out

    def test_warm_run_changes_join_order_bit_identical(self, tmp_path, monkeypatch):
        # file-backed history: the warm planning may happen after a restart
        monkeypatch.setenv(statstore.ENV_VAR, str(tmp_path / "hbo.json"))
        r = LocalQueryRunner.tpch(scale=0.01)
        r.session.set("history_based_stats", True)
        cold_plan = r.plan_sql(QH)
        cold = r.execute(QH)
        assert cold.rows, "the history-demo query must return rows"
        # the cold estimator treated the LIKE filter as ~unknown selectivity;
        # the recorded actual must expose the mis-estimate
        entries = [
            e for e in statstore.load_history().values()
            if e.get("kind") == "FilterNode" and e.get("actual") is not None
        ]
        assert any(
            e["estimate"] is not None
            and e["estimate"] > 50 * max(e["actual"], 1)
            for e in entries
        ), f"no recorded filter mis-estimate in {entries}"
        # "coordinator restart": a FRESH runner (new catalogs, new planner
        # state) reads the history file and plans differently
        statstore.clear_memory()
        r2 = LocalQueryRunner.tpch(scale=0.01)
        r2.session.set("history_based_stats", True)
        warm_plan = r2.plan_sql(QH)
        assert self._leaves(warm_plan) != self._leaves(cold_plan), (
            "history overlay did not change the join order: "
            f"{self._leaves(cold_plan)}"
        )
        warm = r2.execute(QH)
        assert warm.rows == cold.rows  # bit-identical, oracle = cold run
        # ... and against the independent pandas oracle
        import re

        from tests.oracle import assert_rows_equal, tpch_df

        df_l = tpch_df("lineitem", 0.01)
        df_o = tpch_df("orders", 0.01)
        df_c = tpch_df("customer", 0.01)
        o = df_o[df_o["o_comment"].str.match(re.compile(".*slyly.*pending.*"))]
        j = df_l.merge(o, left_on="l_orderkey", right_on="o_orderkey").merge(
            df_c, left_on="o_custkey", right_on="c_custkey"
        )
        exp = (
            j.groupby("c_name")["l_extendedprice"].sum().reset_index()
            .sort_values(["l_extendedprice", "c_name"], ascending=[False, True])
        )
        assert_rows_equal(
            warm.rows, list(exp.itertuples(index=False, name=None)),
            float_tol=1e-6,
        )

    def test_overlay_off_by_default(self, tmp_path, monkeypatch):
        """Without history_based_stats the same history must NOT change
        plans (the Presto default: recording on, consumption opt-in)."""
        monkeypatch.setenv(statstore.ENV_VAR, str(tmp_path / "hbo2.json"))
        r = LocalQueryRunner.tpch(scale=0.01)
        plain_before = r.plan_sql(QH)
        r.execute(QH)  # records history
        plain_after = r.plan_sql(QH)
        assert self._leaves(plain_before) == self._leaves(plain_after)

    def test_join_graph_order_consults_history(self, runner):
        """Unit: filtered_leaf_rows short-circuits the selectivity model."""
        from trino_tpu.planner.stats import HistoryBasedStatsEstimator

        plan = runner.plan_sql("SELECT count(*) FROM orders")
        scans = []
        visit_plan(
            plan.root,
            lambda n: scans.append(n) if isinstance(n, TableScanNode) else None,
        )
        key = statstore.leaf_key_for(scans[0])
        est = HistoryBasedStatsEstimator(
            runner.metadata, plan.types, {key: {"actual": 3.0}}
        )
        assert est.filtered_leaf_rows(scans[0], []) == 3.0
        assert est.rows(scans[0]) == 3.0  # stats() overlays too


class TestMisestimateDetection:
    def test_flight_event_and_counter(self):
        from trino_tpu.runtime.metrics import REGISTRY
        from trino_tpu.runtime.observability import RECORDER

        r = LocalQueryRunner.tpch(scale=SCALE)
        r.session.set("qerror_threshold", 1.5)
        counter = REGISTRY.counter(
            "trino_tpu_cardinality_misestimates_total",
            help="plan nodes whose actual rows exceeded the q-error threshold",
        )
        before = counter.value
        RECORDER.clear()
        RECORDER.enable()
        try:
            r.execute(
                "SELECT count(*) FROM orders "
                "WHERE o_comment LIKE '%no such text anywhere%'"
            )
        finally:
            RECORDER.disable()
        events = [
            e for e in RECORDER.events()
            if e.get("name") == "cardinality_misestimate"
        ]
        RECORDER.clear()
        assert events, "forced mis-estimate emitted no flight event"
        args = events[0].get("args") or {}
        assert args["q"] > 1.5 and args["actual"] == 0
        assert counter.value > before

    def test_threshold_respected(self):
        from trino_tpu.runtime.observability import RECORDER

        r = LocalQueryRunner.tpch(scale=SCALE)
        r.session.set("qerror_threshold", 1e9)  # nothing can trip it
        RECORDER.clear()
        RECORDER.enable()
        try:
            r.execute(
                "SELECT count(*) FROM orders WHERE o_comment LIKE '%zzz%'"
            )
        finally:
            RECORDER.disable()
        events = [
            e for e in RECORDER.events()
            if e.get("name") == "cardinality_misestimate"
        ]
        RECORDER.clear()
        assert events == []


class TestSystemTables:
    def test_operator_stats_live(self, runner):
        runner.execute("SELECT count(*) FROM supplier")
        res = runner.execute(
            "SELECT plan_node, actual_rows, q_error, ts "
            "FROM system.runtime.operator_stats WHERE plan_node = 'TableScanNode'"
        )
        assert res.rows
        for plan_node, actual, q, ts in res.rows:
            assert isinstance(actual, int) and actual >= 0
            assert q is None or q >= 1.0
            assert ts > 0

    def test_stats_history_table(self, runner):
        runner.execute("SELECT count(*) FROM supplier")
        res = runner.execute(
            "SELECT key, plan_node, actual_rows, runs "
            "FROM system.optimizer.stats_history"
        )
        assert res.rows
        kinds = {k[:2] for (k, _, _, _) in res.rows}
        assert "s:" in kinds  # structural keys
        assert "l:" in kinds  # canonical leaf keys
        assert all(runs >= 1 for (_, _, _, runs) in res.rows)


class TestFteAttribution:
    """Satellite: operator actuals under FTE speculation/retries — only the
    winning attempt of each task folds into query-level stats."""

    SCALE = 0.0005

    def _runner(self):
        from trino_tpu.parallel.runner import DistributedQueryRunner

        runner = DistributedQueryRunner.tpch(scale=self.SCALE, n_workers=4)
        runner.session.set("retry_policy", "TASK")
        runner.session.set("join_distribution_type", "PARTITIONED")
        runner.session.set("target_partition_rows", 200)
        return runner

    def _node_rows(self, res):
        return {
            k: v["actualRows"] for k, v in res.query_stats["planNodes"].items()
        }

    def test_speculative_sibling_does_not_double_count(self):
        """Regression: a task_stall-forced speculative sibling completes as
        well as its primary; its rows must NOT fold into the query rollup a
        second time. Ground truth = the chaos-free run of the same query."""
        from trino_tpu.runtime.failure import ChaosInjector

        clean = self._runner().execute(Q3)
        baseline = self._node_rows(clean)
        assert baseline, "FTE run collected no plan-node actuals"

        runner = self._runner()
        runner.session.set("fte_speculation_min_secs", 0.3)
        runner.session.set("fte_speculation_quantile", 0.0)
        runner.session.set("fte_speculation_multiplier", 1.0)
        with ChaosInjector() as chaos:
            chaos.arm("task_stall", times=1, match="_p0_a0", delay=6.0)
            res = runner.execute(Q3)
        assert chaos.fired.get("task_stall") == 1
        sched = runner.last_fte_scheduler
        assert sched.stats["speculative"] >= 1, "no speculation happened"
        assert res.rows == clean.rows
        assert self._node_rows(res) == baseline, (
            "losing speculative attempt folded its rows into operatorSummaries"
        )
        # drain the abandoned stalled sibling: its daemon thread wakes after
        # the stall and would emit flight spans into a LATER test's recorder
        # window (observed as unpaired-span flakes in the fte smoke)
        import time

        deadline = time.time() + 30
        for t in threading.enumerate():
            if t.name.startswith("fte-") and t is not threading.current_thread():
                t.join(max(0.0, deadline - time.time()))

    def test_failed_retry_does_not_double_count(self):
        from trino_tpu.runtime.failure import ChaosInjector

        clean = self._runner().execute(Q13)
        baseline = self._node_rows(clean)
        runner = self._runner()
        with ChaosInjector() as chaos:
            chaos.arm("task_crash_mid_execute", times=1)
            res = runner.execute(Q13)
        assert chaos.fired.get("task_crash_mid_execute") == 1
        assert res.rows == clean.rows
        assert self._node_rows(res) == baseline


class TestConcurrentCollectors:
    def test_sixteen_client_replay(self):
        """Thread-safety under the 16-client replay harness: concurrent
        per-query collectors never cross-contaminate — each query's scan
        actuals match its own tables."""
        r = LocalQueryRunner.tpch(scale=SCALE)
        workload = [
            ("SELECT count(*) FROM nation", "nation"),
            ("SELECT count(*) FROM supplier", "supplier"),
            ("SELECT count(*) FROM customer", "customer"),
            ("SELECT count(*) FROM region", "region"),
        ]
        expected = {
            table: r.execute(sql).rows[0][0] for sql, table in workload
        }
        errors = []

        def client(i):
            sql, table = workload[i % len(workload)]
            try:
                res = r.execute(sql)
                assert res.rows[0][0] == expected[table]
                scans = [
                    v for k, v in res.query_stats["planNodes"].items()
                    if k.endswith("TableScanNode")
                ]
                assert len(scans) == 1
                assert scans[0]["actualRows"] == expected[table], (
                    f"client {i}: {table} actual {scans[0]['actualRows']} "
                    f"!= {expected[table]}"
                )
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(f"client {i}: {e!r}")

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(16)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errors, errors

    def test_concurrent_history_recording(self):
        statstore.clear_memory()
        errors = []

        def writer(i):
            try:
                statstore.record_history({
                    f"s:thread{i}": {"kind": "x", "actual": i, "runs": 1}
                })
                for _ in range(20):
                    statstore.load_history()
                    statstore.lookup(f"s:thread{i}")
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=writer, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors, errors
        hist = statstore.load_history()
        assert all(f"s:thread{i}" in hist for i in range(16))
        statstore.clear_memory()
