#!/usr/bin/env python
"""Observability smoke check (tier-1): one TPC-H query, flight recorder on.

Runs a small TPC-H join query with the pipeline flight recorder enabled,
exports the Chrome/Perfetto trace JSON via tools/query_trace.py, and
validates it against the minimal schema contract:

- monotonic timestamps per (pid, tid) track
- paired B/E duration events (no unclosed/unopened spans)
- every event's pid/tid declared by process_name/thread_name metadata
- the events the plane promises are actually present (operator or bucket
  spans, and an XLA compile on a cold cache)

Exit code 0 = pass. Wired into the tier-1 suite as a fast test
(tests/test_observability.py::TestSmokeCheck) and runnable standalone:

    JAX_PLATFORMS=cpu python tools/obs_smoke.py
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

SMOKE_SQL = """
SELECT n.n_name, count(*) AS suppliers
FROM supplier s JOIN nation n ON s.s_nationkey = n.n_nationkey
GROUP BY n.n_name
ORDER BY suppliers DESC, n.n_name
LIMIT 5
"""


def run_smoke(scale: float = 0.001, ooc: bool = False) -> List[str]:
    """Returns a list of problems; [] means the smoke check passed."""
    import os

    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import query_trace  # sibling module

    trace, stats, rows = query_trace.run_query_trace(
        SMOKE_SQL, scale=scale, ooc=ooc
    )
    problems = query_trace.validate(trace)
    if rows == 0:
        problems.append("smoke query returned no rows")
    events = trace.get("traceEvents", [])
    cats = {e.get("cat") for e in events}
    if not ({"operator", "bucket"} & cats):
        problems.append(
            f"no operator/bucket spans recorded (cats={sorted(c for c in cats if c)})"
        )
    if ooc and "prefetch" not in cats and "transfer" not in cats:
        problems.append("ooc run recorded no prefetch/transfer events")
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    ooc = bool(argv and "--ooc" in argv)
    problems = run_smoke(ooc=ooc)
    if problems:
        for p in problems:
            print(f"SMOKE FAIL: {p}", file=sys.stderr)
        return 1
    print("observability smoke check passed", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
