"""TPC-H connector: SPI implementation over the deterministic generator.

Reference blueprint: plugin/trino-tpch — TpchConnectorFactory.java:30,
TpchMetadata, TpchSplitManager.java:38 (splits = row ranges any node can
generate), TpchPageSourceProvider.java:53. Schemas are scale-factor-named
(``tiny``=0.01, ``sf1``, ``sf100``...) as in the reference.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ...spi.page import Column, Dictionary, Page
from ...spi.predicate import TupleDomain
from ...spi.types import parse_type
from . import generator as g

SCHEMA_SCALES = {
    "tiny": 0.01,
    "sf1": 1.0,
    "sf10": 10.0,
    "sf100": 100.0,
    "sf1000": 1000.0,
}


# generation order per table: primary key ascending (lineitem rows follow
# their order keys; see generator.py chunk_range_for_split)
_SORT_ORDER = {
    "lineitem": ("l_orderkey", "l_linenumber"),
    "orders": ("o_orderkey",),
    "customer": ("c_custkey",),
    "part": ("p_partkey",),
    "supplier": ("s_suppkey",),
    "partsupp": ("ps_partkey", "ps_suppkey"),
    "nation": ("n_nationkey",),
    "region": ("r_regionkey",),
}


def _scale_for_schema(schema: str) -> Optional[float]:
    if schema in SCHEMA_SCALES:
        return SCHEMA_SCALES[schema]
    if schema.startswith("sf"):
        try:
            # dots are not valid in unquoted identifiers: sf0_001 == scale 0.001
            return float(schema[2:].replace("_", "."))
        except ValueError:
            return None
    return None


class TpchConnector(Connector):
    name = "tpch"

    def __init__(self, scale: Optional[float] = None, split_target_rows: int = 1 << 20):
        """``scale``: if set, a single default scale used when instantiating the
        connector programmatically (schema name still wins)."""
        self.default_scale = scale
        self.split_target_rows = split_target_rows
        self._dictionaries: Dict[tuple, Dictionary] = {}
        self._capacities: Dict[tuple, int] = {}
        self._meta = _TpchMetadata(self)
        self._splits = _TpchSplitManager(self)
        self._pages = _TpchPageSourceProvider(self)

    def metadata(self):
        return self._meta

    def cache_table_version(self, schema: str, table: str):
        """Warm-path cache plane hook (runtime/cachestore.py): generated
        data is deterministic per RESOLVED scale, so the token carries it —
        two connectors mounting the same non-scale-encoded schema name
        ('tiny') at different default scales must never alias. None (scale
        unresolvable) degrades to the unversioned TTL-or-bypass path."""
        s = _scale_for_schema(schema)
        if s is None:
            s = self.default_scale
        if s is None:
            return None
        return f"static-{schema}-sf{s:g}"

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    # ------------------------------------------------------------------ utils

    def scale_of(self, handle: TableHandle) -> float:
        s = _scale_for_schema(handle.schema_table.schema)
        if s is None:
            s = self.default_scale
        if s is None:
            raise ValueError(f"unknown tpch schema: {handle.schema_table.schema}")
        return s

    def dictionary(self, table: str, column: str, scale: float) -> Optional[Dictionary]:
        key = (table, column, round(scale * 1e6))
        if key not in self._dictionaries:
            vocab = g.vocab_for(table, column, scale)
            # setdefault: concurrent page-source threads (OOC scan prefetch)
            # racing a cold key must all end up with ONE Dictionary object —
            # dictionaries hash by identity, so a duplicate would force a
            # spurious XLA retrace of every program keyed on the loser
            self._dictionaries.setdefault(
                key,
                Dictionary(np.asarray(vocab, dtype=object)) if vocab is not None else None,
            )
        return self._dictionaries[key]

    def split_count(self, table: str, scale: float) -> int:
        base_rows = g.row_count("orders" if table == "lineitem" else table, scale)
        rows = base_rows * 4 if table == "lineitem" else base_rows
        wanted = max(1, math.ceil(rows / self.split_target_rows))
        # a split is a contiguous range of canonical generation chunks
        n_chunks = (base_rows + g.canonical_chunk_rows(base_rows) - 1) // g.canonical_chunk_rows(base_rows)
        return min(wanted, n_chunks)

    def split_capacity(self, table: str, scale: float, total_splits: int) -> int:
        """Fixed page capacity for every split of this table (static shapes).

        Rounded up to a power of two (capped at 1M-row granularity) so pages
        from different tables share shapes — XLA-compiled operator programs are
        cached per shape, so uniform capacities turn per-table compiles into
        cache hits. Memoized: the lineitem path draws per-chunk rng streams."""
        key = (table, round(scale * 1e6), total_splits)
        cached = self._capacities.get(key)
        if cached is not None:
            return cached
        if table == "lineitem":
            rows = max(
                g.lineitem_split_rows(scale, s, total_splits)
                for s in range(total_splits)
            )
        else:
            n = g.row_count(table, scale)
            rows = 1
            for s in range(total_splits):
                first, end, chunk, _ = g.chunk_range_for_split(n, s, total_splits)
                rows = max(rows, min(end * chunk, n) - first * chunk)
        cap = 64
        while cap < rows and cap < (1 << 20):
            cap *= 2
        if cap < rows:  # beyond 1M: multiples of 1M, not powers of two
            cap = math.ceil(rows / (1 << 20)) << 20
        self._capacities[key] = cap
        return cap


class _TpchMetadata(ConnectorMetadata):
    def __init__(self, connector: TpchConnector):
        self.connector = connector

    def list_schemas(self):
        schemas = set(SCHEMA_SCALES)
        # a non-canonical default scale (e.g. 0.01 -> sf0_01) is queryable,
        # so it must be discoverable too (information_schema reads this)
        scale = self.connector.default_scale
        if scale is not None:
            schemas.add("sf" + f"{scale:g}".replace(".", "_"))
        return sorted(schemas)

    def list_tables(self, schema: Optional[str] = None):
        schemas = [schema] if schema else self.list_schemas()
        return [
            SchemaTableName(s, t) for s in schemas for t in sorted(g.TPCH_TABLES)
        ]

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        if name.table not in g.TPCH_TABLES:
            return None
        if _scale_for_schema(name.schema) is None and self.connector.default_scale is None:
            return None
        cols = tuple(
            ColumnMetadata(c.name, parse_type(c.type_name))
            for c in g.TPCH_TABLES[name.table]
        )
        # the generator emits each table ordered by its primary key (splits
        # cover ascending chunk ranges, generator.py chunk_range_for_split) —
        # declared so grouped aggregation can stream without sorting
        sorted_by = _SORT_ORDER.get(name.table, ())
        return TableMetadata(name, cols, sorted_by=sorted_by)

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        scale = self.connector.scale_of(handle)
        table = handle.schema_table.table
        if table == "lineitem":
            rows = g.row_count("orders", scale) * 4.0
        else:
            rows = float(g.row_count(table, scale))
        return TableStatistics(
            row_count=rows, columns=_column_statistics(table, scale)
        )

    def apply_filter(self, handle: TableHandle, domain: TupleDomain) -> Optional[TableHandle]:
        # absorb the domain for key-range split pruning (primary keys are
        # range-partitioned across splits)
        return TableHandle(handle.catalog, handle.schema_table, connector_handle=domain)


_KEY_COLUMNS = {
    "orders": "o_orderkey",
    "lineitem": "l_orderkey",
    "customer": "c_custkey",
    "part": "p_partkey",
    "supplier": "s_suppkey",
}


def _column_statistics(table: str, scale: float):
    """Per-column (ndv, low, high) from the generator's closed-form value
    distributions — the CBO's stats source (ref: the tpch connector's
    TpchMetadata.getTableStatistics, which likewise derives exact stats from
    dbgen formulas instead of scanning). Decimal columns report storage-scaled
    values; dates epoch days; dictionary strings code space."""
    from ...spi.connector import ColumnStatistics as CS

    S = float(g.row_count("supplier", scale))
    C = float(g.row_count("customer", scale))
    P = float(g.row_count("part", scale))
    O = float(g.row_count("orders", scale))  # noqa: E741
    date_lo, date_hi = float(g.MIN_ORDER_DATE), float(g.MAX_ORDER_DATE)
    stats: dict = {}

    def put(col, ndv, low=None, high=None):
        stats[col] = CS(
            ndv=float(ndv),
            low=None if low is None else float(low),
            high=None if high is None else float(high),
        )

    if table == "region":
        put("r_regionkey", 5, 0, 4)
    elif table == "nation":
        put("n_nationkey", 25, 0, 24)
        put("n_regionkey", 5, 0, 4)
    elif table == "supplier":
        put("s_suppkey", S, 1, S)
        put("s_nationkey", 25, 0, 24)
        put("s_acctbal", min(S, 1099997), -99999, 999998)
    elif table == "customer":
        put("c_custkey", C, 1, C)
        put("c_nationkey", 25, 0, 24)
        put("c_acctbal", min(C, 1099997), -99999, 999998)
    elif table == "part":
        put("p_partkey", P, 1, P)
        put("p_size", 50, 1, 50)
        put("p_retailprice", min(P, 10000), 90000, 200000)
    elif table == "partsupp":
        put("ps_partkey", P, 1, P)
        put("ps_suppkey", S, 1, S)
        put("ps_availqty", 9999, 1, 9999)
        put("ps_supplycost", 99901, 100, 100000)
    elif table == "orders":
        put("o_orderkey", O, 1, O)
        put("o_custkey", C - C // 3, 1, C)
        put("o_orderdate", date_hi - 121 - date_lo, date_lo, date_hi - 121)
        put("o_totalprice", min(O, 55465500), 90000, 55555499)
    elif table == "lineitem":
        put("l_orderkey", O, 1, O)
        put("l_partkey", P, 1, P)
        put("l_suppkey", S, 1, S)
        put("l_linenumber", 7, 1, 7)
        put("l_quantity", 50, 100, 5000)
        put("l_extendedprice", min(O * 4, 1000000), 90000, 1100000)
        put("l_discount", 11, 0, 10)
        put("l_tax", 9, 0, 8)
        put("l_shipdate", date_hi + 121 - date_lo, date_lo, date_hi + 121)
        put("l_commitdate", date_hi + 121 - date_lo, date_lo, date_hi + 121)
        put("l_receiptdate", date_hi + 151 - date_lo, date_lo, date_hi + 151)
    # dictionary-coded columns: ndv == vocab size, code space [0, |vocab|)
    for col in g.TPCH_TABLES[table]:
        if col.name not in stats:
            vocab = g.vocab_for(table, col.name, scale)
            if vocab is not None:
                stats[col.name] = CS(
                    ndv=float(len(vocab)), low=0.0, high=float(len(vocab) - 1)
                )
    return stats


class _TpchSplitManager(ConnectorSplitManager):
    def __init__(self, connector: TpchConnector):
        self.connector = connector

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        scale = self.connector.scale_of(handle)
        table = handle.schema_table.table
        total = self.connector.split_count(table, scale)
        splits = [Split(handle, i, total) for i in range(total)]
        # key-range split pruning from the pushed-down TupleDomain
        constraint = handle.connector_handle
        key_col = _KEY_COLUMNS.get(table)
        if isinstance(constraint, TupleDomain) and key_col is not None:
            dom = constraint.domain_for(key_col)
            n = g.row_count("orders" if table == "lineitem" else table, scale)
            kept = []
            for s in splits:
                first, end, chunk, _ = g.chunk_range_for_split(n, s.split_id, total)
                lo = first * chunk + 1
                hi = min(end * chunk, n)
                if hi >= lo and dom.overlaps_range(lo, hi):
                    kept.append(s)
            splits = kept
        return splits


class _TpchPageSourceProvider(ConnectorPageSourceProvider):
    def __init__(self, connector: TpchConnector):
        self.connector = connector

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        handle = split.table
        scale = self.connector.scale_of(handle)
        table = handle.schema_table.table
        data = g.generate_split(table, scale, split.split_id, split.total_splits)
        capacity = self.connector.split_capacity(table, scale, split.total_splits)
        schema = g.TPCH_TABLES[table]
        cols = []
        for idx in column_indexes:
            cm = schema[idx]
            type_ = parse_type(cm.type_name)
            arr = data.columns[cm.name]
            dictionary = self.connector.dictionary(table, cm.name, scale)
            cols.append(
                Column.from_numpy(type_, arr, None, capacity, dictionary)
            )
        active = np.zeros(capacity, dtype=np.bool_)
        active[: data.count] = True
        import jax.numpy as jnp

        return Page(tuple(cols), jnp.asarray(active))
