"""Fault-tolerant execution v0: durable exchange + task-level retry.

ref: spi/exchange/ExchangeManager.java:39, FileSystemExchangeSink (atomic
commit), EventDrivenFaultTolerantQueryScheduler (task re-attempts from stored
inputs), BaseFailureRecoveryTest (SURVEY.md §4 — FailureInjector kills a task
mid-query; results must still be correct WITHOUT a whole-query restart).
"""

import pytest

from trino_tpu.parallel.runner import DistributedQueryRunner
from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime.failure import FailureInjector, InjectedFailure

SCALE = 0.0005


@pytest.fixture()
def fte_runner():
    r = DistributedQueryRunner.tpch(scale=SCALE, n_workers=4)
    r.session.set("retry_policy", "TASK")
    return r


@pytest.fixture(scope="module")
def local():
    return LocalQueryRunner.tpch(scale=SCALE)


SQL = "SELECT l_returnflag, count(*) c, sum(l_quantity) FROM lineitem GROUP BY 1 ORDER BY 1"
JOIN_SQL = "SELECT count(*) FROM lineitem JOIN orders ON l_orderkey = o_orderkey"


class TestExchangeSpi:
    def test_atomic_commit_and_dedup(self, tmp_path):
        from trino_tpu.runtime.exchange_spi import ExchangeManager

        mgr = ExchangeManager(str(tmp_path))
        ex = mgr.create_exchange("q1", 0)
        # attempt 0 dies before commit: invisible
        s0 = ex.sink(0, 0)
        s0.add(b"partial")
        s0.abort()
        assert ex.committed_attempt(0) is None
        # attempt 1 commits; a later duplicate attempt never mixes in
        s1 = ex.sink(0, 1)
        s1.add(b"page-a")
        s1.add(b"page-b")
        s1.commit()
        s2 = ex.sink(0, 2)
        s2.add(b"dup")
        s2.commit()
        assert ex.committed_attempt(0) == 1
        assert ex.source(0) == [b"page-a", b"page-b"]
        mgr.remove_query("q1")
        with pytest.raises(FileNotFoundError):
            ex.source(0)


class TestTaskRetry:
    def test_injected_task_failure_recovers(self, fte_runner, local):
        inj = FailureInjector()
        inj.fail_once("AggregationNode")
        with inj:
            res = fte_runner.execute(SQL)
        assert inj.injected == 1
        assert res.rows == local.execute(SQL).rows
        # exactly ONE task re-attempted; everything else ran once
        attempts = fte_runner.last_task_attempts
        assert sorted(attempts.values())[-1] == 1
        assert list(attempts.values()).count(1) == 1

    def test_join_query_recovers(self, fte_runner, local):
        inj = FailureInjector()
        inj.fail_once("JoinNode")
        with inj:
            res = fte_runner.execute(JOIN_SQL)
        assert inj.injected == 1
        assert res.rows == local.execute(JOIN_SQL).rows

    def test_exhausted_attempts_fail(self, fte_runner):
        inj = FailureInjector()
        inj.fail_once("AggregationNode", times=10)
        with inj:
            with pytest.raises(InjectedFailure):
                fte_runner.execute(SQL)

    def test_no_failure_single_attempts(self, fte_runner, local):
        res = fte_runner.execute(SQL)
        assert res.rows == local.execute(SQL).rows
        assert set(fte_runner.last_task_attempts.values()) == {0}


class TestAdaptiveReplanning:
    """Stage-boundary re-optimization from actual sizes (ref:
    planner/AdaptivePlanner.java:87, rule/AdaptiveReorderPartitionedJoin):
    a partitioned join whose durable build output is small re-plans to
    broadcast build + no-shuffle probe, with identical results."""

    def _fte_runner(self, threshold):
        runner = DistributedQueryRunner.tpch(scale=SCALE, n_workers=4)
        runner.session.set("retry_policy", "TASK")
        runner.session.set("broadcast_join_threshold_rows", threshold)
        # force the planner to choose PARTITIONED up front so the adaptive
        # pass has something to flip
        runner.session.set("join_distribution_type", "PARTITIONED")
        return runner

    def test_small_build_flips_to_broadcast(self):
        runner = self._fte_runner(1_000_000)
        sql = ("SELECT n_name, count(*) FROM lineitem "
               "JOIN supplier ON l_suppkey = s_suppkey "
               "JOIN nation ON s_nationkey = n_nationkey "
               "GROUP BY n_name ORDER BY n_name")
        want = LocalQueryRunner.tpch(scale=SCALE).execute(sql).rows
        got = runner.execute(sql).rows
        assert got == want
        assert any(
            d["rule"] == "partitioned_join_to_broadcast"
            for d in runner.last_adaptive
        ), runner.last_adaptive

    def test_threshold_zero_disables(self):
        runner = self._fte_runner(0)
        sql = ("SELECT count(*) FROM lineitem "
               "JOIN orders ON l_orderkey = o_orderkey")
        want = LocalQueryRunner.tpch(scale=SCALE).execute(sql).rows
        assert runner.execute(sql).rows == want
        assert runner.last_adaptive == []
