"""Iceberg-lite: snapshot-versioned tables over the lakehouse storage stack.

Reference blueprint: plugin/trino-iceberg (IcebergMetadata.java — snapshot
log, manifest-driven scans, optimistic metadata commits) shrunk to the
mechanism that matters on this storage stack:

- every INSERT/CTAS commit appends ONE snapshot JSON
  (`<table>/_iceberg/snap-%012d.json`) listing the table's COMPLETE data
  file set (manifest inlined — "lite": no manifest-list indirection),
- commits are optimistic: the snapshot object is created with the
  filesystem's atomic create-EXCLUSIVE put (`fs.write_if_absent`; the
  S3 If-None-Match / GCS precondition primitive). Two writers racing on
  the same parent snapshot produce ONE winner; the loser raises
  CommitConflict and its freshly written (uuid-named) data objects stay
  unreferenced — invisible to every reader, exactly iceberg's failed-
  commit garbage,
- reads resolve the CURRENT snapshot (or `FOR VERSION AS OF n`) and scan
  exactly its manifest — concurrent writers never tear a read.

Builds on the lake connector's partitioned-Parquet writer/metastore; the
schema evolution/delete-file/compaction surface of real iceberg is out of
scope and recorded as such in STATUS.md.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

from ..fs import Location
from ..spi.connector import Split, TableHandle
from .lake import LakeConnector, _LakeMetadata, _LakeSplitManager

_SNAP_DIR = "_iceberg"


class CommitConflict(RuntimeError):
    """Another writer committed the same parent snapshot first."""


def _snap_name(snapshot_id: int) -> str:
    return f"snap-{snapshot_id:012d}.json"


class IcebergLiteConnector(LakeConnector):
    name = "iceberg_lite"

    def metadata(self):
        if not isinstance(self._meta, _IcebergMetadata):
            self._meta = _IcebergMetadata(self)
        return self._meta

    def split_manager(self):
        if not isinstance(self._splits, _IcebergSplitManager):
            self._splits = _IcebergSplitManager(self)
        return self._splits

    # ------------------------------------------------------------ snapshots

    def _table_loc(self, schema: str, table: str) -> Optional[Location]:
        t = self.metastore.get_table(schema, table)
        return Location.parse(t.location) if t is not None else None

    def snapshots(self, schema: str, table: str) -> List[int]:
        loc = self._table_loc(schema, table)
        if loc is None:
            return []
        fs = self._fs(loc)
        ids = []
        for entry in fs.list_files(loc.child(_SNAP_DIR)):
            base = entry.location.path.rsplit("/", 1)[-1]
            if base.startswith("snap-") and base.endswith(".json"):
                ids.append(int(base[len("snap-"):-len(".json")]))
        return sorted(ids)

    def current_snapshot_id(self, schema: str, table: str) -> int:
        ids = self.snapshots(schema, table)
        return ids[-1] if ids else 0

    def cache_table_version(self, schema: str, table: str):
        """Warm-path cache plane hook (runtime/cachestore.py): the current
        snapshot id, QUALIFIED by the table's storage location — snapshot
        ids are sequential per table (parent+1), so two warehouses holding
        a same-named table at the same snapshot count must never alias.
        Every DML commit appends a snapshot, so a bump invalidates exactly
        and only the entries it should; the location is stable across
        processes, so persisted entries stay valid after a restart."""
        loc = self._table_loc(schema, table)
        if loc is None:
            return None  # unknown table: TTL-or-bypass, never a guess
        return f"{loc.uri()}@{self.current_snapshot_id(schema, table)}"

    def read_snapshot(self, schema: str, table: str, snapshot_id: int) -> dict:
        loc = self._table_loc(schema, table)
        path = loc.child(_SNAP_DIR, _snap_name(snapshot_id))
        return json.loads(self._fs(loc).read(path))

    def _commit_snapshot(
        self, schema: str, table: str, parent: int, files: List[dict], op: str
    ) -> int:
        """Optimistic append of snapshot parent+1; raises CommitConflict on
        a concurrent commit (the caller's data objects stay unreferenced)."""
        loc = self._table_loc(schema, table)
        snap = {
            "snapshot_id": parent + 1,
            "parent": parent or None,
            "operation": op,
            "files": files,
        }
        target = loc.child(_SNAP_DIR, _snap_name(parent + 1))
        if not self._fs(loc).write_if_absent(
            target, json.dumps(snap, indent=1).encode()
        ):
            raise CommitConflict(
                f"snapshot {parent + 1} of {schema}.{table} was committed "
                "by a concurrent writer"
            )
        return parent + 1

    # ---------------------------------------------------------------- write

    def insert(self, name, page) -> int:
        n, written = self._insert_pages(name, page)
        if n == 0:
            return 0
        parent = self.current_snapshot_id(name.schema, name.table)
        base = (
            self.read_snapshot(name.schema, name.table, parent)["files"]
            if parent
            else []
        )
        self._commit_snapshot(
            name.schema, name.table, parent, base + written, "append"
        )
        return n


class _IcebergMetadata(_LakeMetadata):
    def apply_filter(self, handle, domain):
        # connector_handle is reserved for the snapshot pin; partition
        # pruning under time travel is future work ("lite")
        return None

    def apply_version(self, handle: TableHandle, version: int) -> Optional[TableHandle]:
        name = handle.schema_table
        if version not in self.connector.snapshots(name.schema, name.table):
            raise ValueError(
                f"snapshot {version} of {name} does not exist"
            )
        return TableHandle(
            catalog=handle.catalog,
            schema_table=name,
            connector_handle={"snapshot_id": version},
        )


class _IcebergSplitManager(_LakeSplitManager):
    def get_splits(self, handle: TableHandle) -> List[Split]:
        name = handle.schema_table
        ch = getattr(handle, "connector_handle", None)
        if isinstance(ch, dict) and "snapshot_id" in ch:
            sid = int(ch["snapshot_id"])
        else:
            sid = self.connector.current_snapshot_id(name.schema, name.table)
        if sid == 0:
            return []  # no committed snapshot: an empty (or new) table
        files = self.connector.read_snapshot(name.schema, name.table, sid)["files"]
        return [
            Split(table=handle, split_id=i, total_splits=len(files), info=f)
            for i, f in enumerate(files)
        ]
