"""Spool SPI: spooled query results fetched out-of-band as segments.

Reference blueprint: io.trino.spi.spool (SpoolingManager.java — create/
finish/get/delete spooled segments, segment handles + ack tokens) and the
client protocol's spooled encoding (protocol/spooling/: results above a
threshold go to storage segments; the JSON response carries segment
descriptors the client fetches and acknowledges out-of-band instead of
inline data pages).

The filesystem implementation stores each segment as one LZ4-framed page
file through the existing wire serde — the same bytes a worker exchange
would ship — so spooling and the exchange tier share one codec.
"""

from __future__ import annotations

import os
import tempfile
import threading
import uuid
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class SpooledSegmentHandle:
    segment_id: str
    rows: int
    size_bytes: int


class SpoolingManager:
    """spi/spool/SpoolingManager contract."""

    def create_segment(self, data: bytes, rows: int) -> SpooledSegmentHandle:
        raise NotImplementedError

    def get_segment(self, segment_id: str) -> Optional[bytes]:
        raise NotImplementedError

    def delete_segment(self, segment_id: str) -> None:
        raise NotImplementedError


class FileSystemSpoolingManager(SpoolingManager):
    """Segments as files under a spool directory (the reference's
    filesystem spooling plugin); TTL eviction like its segment pruner."""

    def __init__(self, directory: Optional[str] = None, ttl_secs: float = 900.0):
        self._dir = directory or tempfile.mkdtemp(prefix="trino_tpu_spool_")
        os.makedirs(self._dir, exist_ok=True)
        self._ttl = ttl_secs
        self._lock = threading.Lock()
        self._segments: Dict[str, Tuple[str, float]] = {}  # id -> (path, created)

    def create_segment(self, data: bytes, rows: int) -> SpooledSegmentHandle:
        import time

        seg_id = uuid.uuid4().hex
        path = os.path.join(self._dir, seg_id + ".seg")
        with open(path, "wb") as f:
            f.write(data)
        with self._lock:
            self._segments[seg_id] = (path, time.time())
            self._evict_expired_locked()
        return SpooledSegmentHandle(seg_id, rows, len(data))

    def get_segment(self, segment_id: str) -> Optional[bytes]:
        with self._lock:
            entry = self._segments.get(segment_id)
        if entry is None:
            return None
        try:
            with open(entry[0], "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete_segment(self, segment_id: str) -> None:
        with self._lock:
            entry = self._segments.pop(segment_id, None)
        if entry is not None:
            try:
                os.unlink(entry[0])
            except FileNotFoundError:
                pass

    def _evict_expired_locked(self) -> None:
        import time

        now = time.time()
        expired = [
            sid for sid, (_, created) in self._segments.items()
            if now - created > self._ttl
        ]
        for sid in expired:
            path, _ = self._segments.pop(sid)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass

    def list_segments(self) -> List[str]:
        with self._lock:
            return list(self._segments)

    def close(self) -> None:
        """Delete every segment and the spool directory itself."""
        import shutil

        with self._lock:
            self._segments.clear()
        shutil.rmtree(self._dir, ignore_errors=True)
