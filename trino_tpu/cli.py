"""Interactive SQL CLI.

Reference blueprint: client/trino-cli Console.java:84 — a REPL that talks the
client protocol to a coordinator, or runs embedded (the PlanTester-style
in-process mode). `python -m trino_tpu.cli --catalog tpch --schema sf0.01`.
"""

from __future__ import annotations

import argparse
import sys
import time


def format_table(columns, rows, max_width: int = 40) -> str:
    def fmt(v):
        if v is None:
            return "NULL"
        s = str(v)
        return s if len(s) <= max_width else s[: max_width - 1] + "…"

    str_rows = [[fmt(v) for v in row] for row in rows]
    widths = [len(c) for c in columns]
    for row in str_rows:
        for i, v in enumerate(row):
            widths[i] = max(widths[i], len(v))
    sep = "-+-".join("-" * w for w in widths)
    header = " | ".join(c.ljust(w) for c, w in zip(columns, widths))
    lines = [header, sep]
    for row in str_rows:
        lines.append(" | ".join(v.ljust(w) for v, w in zip(row, widths)))
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="trino-tpu", description=__doc__)
    parser.add_argument("--server", help="coordinator URL (omit for embedded mode)")
    parser.add_argument("--catalog", default="tpch")
    parser.add_argument("--schema", default=None, help="defaults to sf<scale>")
    parser.add_argument("--scale", type=float, default=0.01, help="embedded tpch scale")
    parser.add_argument("--execute", "-e", help="run one statement and exit")
    args = parser.parse_args(argv)

    if args.server:
        from .client import StatementClient

        client = StatementClient(args.server)

        def run(sql):
            res = client.execute(sql)
            return res.columns, res.rows
    else:
        from .connectors.memory import BlackHoleConnector, MemoryConnector
        from .runtime import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=args.scale, schema=args.schema)  # schema=None derives sf<scale>
        runner.register_catalog("memory", MemoryConnector())
        runner.register_catalog("blackhole", BlackHoleConnector())

        def run(sql):
            res = runner.execute(sql)
            return res.column_names, res.rows

    def execute_and_print(sql: str) -> None:
        t0 = time.time()
        try:
            columns, rows = run(sql)
        except Exception as e:  # noqa: BLE001 — REPL surfaces all engine errors
            print(f"error: {e}", file=sys.stderr)
            return
        print(format_table(columns, rows))
        print(f"({len(rows)} row{'s' if len(rows) != 1 else ''} in {time.time() - t0:.2f}s)")

    if args.execute:
        execute_and_print(args.execute)
        return 0

    print(f"trino-tpu CLI ({'server ' + args.server if args.server else 'embedded'})")
    print("Type a SQL statement ending with ';', or 'quit'.")
    buffer: list = []
    while True:
        try:
            prompt = "trino-tpu> " if not buffer else "        -> "
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if line.strip().lower() in ("quit", "exit") and not buffer:
            return 0
        buffer.append(line)
        if line.rstrip().endswith(";"):
            sql = "\n".join(buffer).rstrip().rstrip(";")
            buffer = []
            if sql.strip():
                execute_and_print(sql)


if __name__ == "__main__":
    sys.exit(main())
