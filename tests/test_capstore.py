"""Tuned-capacity persistence (runtime/capstore.py).

Round-5 mechanism: AdaptiveQuery fixpoints are stored keyed by a structural
plan fingerprint, so a repeat of the same query (same process, a later
session, or a bench child) seeds the exact tuned capacities and pays ONE
compile (which additionally hits the persistent XLA cache) instead of the
grow/shrink loop. ref: sql/gen/PageFunctionCompiler.java:103 (generated-class
result cache) is the reference's analogous amortization.
"""

import json
import os

import numpy as np
import pytest

from trino_tpu.runtime import LocalQueryRunner
from trino_tpu.runtime import capstore
from trino_tpu.runtime.adaptive import AdaptiveQuery

SCALE = 0.01

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(autouse=True)
def fresh_store(monkeypatch):
    monkeypatch.delenv(capstore.ENV_VAR, raising=False)
    capstore.clear_memory()
    yield
    capstore.clear_memory()


def test_fingerprint_stable_across_plans(runner):
    fp1 = capstore.plan_fingerprint(runner.plan_sql(Q3))
    fp2 = capstore.plan_fingerprint(runner.plan_sql(Q3))
    assert fp1 and fp1 == fp2


def test_fingerprint_distinguishes_plans(runner):
    fp1 = capstore.plan_fingerprint(runner.plan_sql(Q3))
    fp2 = capstore.plan_fingerprint(
        runner.plan_sql("SELECT count(*) FROM lineitem")
    )
    assert fp1 != fp2


def test_second_instance_skips_tuning(runner):
    q1 = AdaptiveQuery(runner.plan_sql(Q3), runner.metadata, runner.session)
    assert not q1.seeded_from_store
    page1, _ = q1.tune()

    q2 = AdaptiveQuery(runner.plan_sql(Q3), runner.metadata, runner.session)
    assert q2.seeded_from_store
    page2, _ = q2.tune()
    assert q2.compiles == 1  # seeded at the fixpoint: no grow, no shrink

    rows1 = np.asarray(page1.active).sum()
    rows2 = np.asarray(page2.active).sum()
    assert rows1 == rows2
    # seeded caps reproduce the exact tuned program shapes
    assert page2.capacity == page1.capacity


def test_file_store_round_trip(tmp_path, monkeypatch, runner):
    path = tmp_path / "caps.json"
    monkeypatch.setenv(capstore.ENV_VAR, str(path))

    q1 = AdaptiveQuery(runner.plan_sql(Q3), runner.metadata, runner.session)
    q1.tune()
    assert path.exists()
    data = json.loads(path.read_text())
    assert q1.fingerprint in data
    caps = data[q1.fingerprint]
    assert all(c is None or c >= 1024 for c in caps)

    # a "new process": in-memory store cleared, file survives
    capstore.clear_memory()
    q2 = AdaptiveQuery(runner.plan_sql(Q3), runner.metadata, runner.session)
    assert q2.seeded_from_store
    q2.tune()
    assert q2.compiles == 1


def test_stale_vector_length_ignored(runner):
    plan = runner.plan_sql(Q3)
    fp = capstore.plan_fingerprint(plan)
    capstore.save(fp, [2048])  # wrong arity: must not be applied
    q = AdaptiveQuery(plan, runner.metadata, runner.session)
    assert not q.seeded_from_store


def test_atomic_write_tolerates_garbage_file(tmp_path, monkeypatch, runner):
    path = tmp_path / "caps.json"
    path.write_text("{not json")
    monkeypatch.setenv(capstore.ENV_VAR, str(path))
    q = AdaptiveQuery(runner.plan_sql(Q3), runner.metadata, runner.session)
    assert not q.seeded_from_store  # garbage treated as empty
    q.tune()
    data = json.loads(path.read_text())  # rewritten valid
    assert q.fingerprint in data
