from .connector import TpchConnector, SCHEMA_SCALES

__all__ = ["TpchConnector", "SCHEMA_SCALES"]
