from . import types
from .page import Column, Dictionary, Page
from .types import Type
