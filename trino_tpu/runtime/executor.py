"""Plan executor: evaluates optimized plans as vectorized device programs.

Reference blueprint: the worker hot path (SURVEY.md §3.2) — LocalExecutionPlanner
(LocalExecutionPlanner.java:412) turning fragments into operator pipelines, and the
operators of §2.5 (ScanFilterAndProjectOperator, HashAggregationOperator,
HashBuilder/LookupJoinOperator, TopNOperator, WindowOperator...).

TPU-first redesign: instead of Trino's page-at-a-time pull loop (Driver.java:372
moving 4KB pages between operators), each operator is a *whole-split vectorized
transform* Page -> Page with static shapes; a split is one fused XLA program's
worth of data (SURVEY.md §7: morsel = split, pad-and-mask everywhere). Pipeline
breakers (agg/join/sort) consume concatenated split pages.

Each operator evaluation is one cached jit program (the compilation caching model
of PageFunctionCompiler: cache per (plan-node structure, input layout); plan nodes
are frozen dataclasses, so they hash as static jit arguments directly). Joins are
two programs with a host sync between them to pick the static output capacity
(SURVEY.md §7 "fixed-capacity bucketed batches").
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..metadata import Metadata, Session
from . import kernelcost
from .device_scheduler import on_program_launch
from .failure import FailureInjector
from .observability import on_spill_read, on_spill_write
from ..ops import kernels as K
from ..ops.compiler import CVal, ColumnLayout, CompileError, compile_expression
from ..spi.connector import Split
from ..spi.page import Column, Dictionary, Page
from ..spi.types import (
    BIGINT,
    BOOLEAN,
    DOUBLE,
    DecimalType,
    Type,
    is_floating,
    is_integral,
    is_string,
)
from ..sql.ir import Reference
from ..planner.plan import (
    Aggregation,
    AggregationNode,
    AggregationStep,
    EnforceSingleRowNode,
    ExchangeNode,
    FilterNode,
    JoinKind,
    JoinNode,
    LimitNode,
    LogicalPlan,
    OutputNode,
    PlanNode,
    ProjectNode,
    SemiJoinNode,
    SortNode,
    TableFunctionNode,
    TableScanNode,
    TopNNode,
    UnionNode,
    ValuesNode,
    VectorTopNNode,
    WindowNode,
)


class ExecutionError(RuntimeError):
    pass


def _null_column(c: Column, cap: int) -> Column:
    """An all-NULL column shaped like ``c`` with row capacity ``cap`` (every
    array leaf zeroed — validity masks become all-False)."""
    return jax.tree_util.tree_map(
        lambda a: jnp.zeros((cap,) + tuple(a.shape[1:]), a.dtype), c
    )


def _permute_column(c: Column, perm) -> Column:
    """Row-gather a column by ``perm`` (nested parts ride along on axis 0)."""
    return Column(
        c.type, c.data[perm], c.valid[perm], c.dictionary,
        lengths=None if c.lengths is None else c.lengths[perm],
        elem_valid=None if c.elem_valid is None else c.elem_valid[perm],
        children=tuple(_permute_column(k, perm) for k in c.children),
    )


def _slice_column(c: Column, n: int) -> Column:
    return Column(
        c.type, c.data[:n], c.valid[:n], c.dictionary,
        lengths=None if c.lengths is None else c.lengths[:n],
        elem_valid=None if c.elem_valid is None else c.elem_valid[:n],
        children=tuple(_slice_column(k, n) for k in c.children),
    )


def _cval_of(c: Column) -> CVal:
    return CVal(
        c.data, c.valid, c.dictionary, c.lengths, c.elem_valid,
        tuple(_cval_of(k) for k in c.children),
    )


def _child_dicts(c: Column) -> tuple:
    """Nested dictionary tree for ColumnLayout.child_dicts (tuple per map/row
    child, Dictionary/None per scalar/array child)."""
    return tuple(
        _child_dicts(k) if k.children else k.dictionary for k in c.children
    )


def _column_of(type_, v: CVal, fallback_dict=None) -> Column:
    """CVal -> Column, rebuilding nested children with their declared types."""
    kid_types = type_.child_types() if hasattr(type_, "child_types") else ()
    kids = tuple(_column_of(kt, kv) for kt, kv in zip(kid_types, v.children))
    return Column(
        type_, v.data, v.valid, v.dictionary or fallback_dict,
        lengths=v.lengths, elem_valid=v.elem_valid, children=kids,
    )


@dataclass
class Relation:
    """A Page plus the plan symbols its columns carry.

    ``sorted_by``: symbols the rows are ordered by (a physical data property
    propagated from connector-declared sort order through order-preserving
    operators — scan/filter/project/probe-major join/compact; ref
    sql/planner LocalProperties + spi/connector sort-order metadata). Grouped
    aggregation uses it to skip the group sort; the fast path SELF-VERIFIES
    monotonicity on device and falls back, so a wrong declaration costs one
    pass, never correctness."""

    page: Page
    symbols: Tuple[str, ...]
    sorted_by: Tuple[str, ...] = ()

    def env(self) -> Dict[str, CVal]:
        return {
            s: _cval_of(c) for s, c in zip(self.symbols, self.page.columns)
        }

    def layout(self) -> Dict[str, ColumnLayout]:
        return {
            s: ColumnLayout(c.type, c.dictionary, _child_dicts(c))
            for s, c in zip(self.symbols, self.page.columns)
        }

    def column_for(self, symbol: str) -> Column:
        return self.page.columns[self.symbols.index(symbol)]

    @property
    def capacity(self) -> int:
        return self.page.capacity


def _concat_pages(pages: List[Page]) -> Page:
    """Concatenate split pages; string columns with differing dictionaries are
    re-encoded into a merged sorted dictionary (codes are only comparable
    within one dictionary); nested columns pad/recurse via _concat_cols."""
    if len(pages) == 1:
        return pages[0]
    cols = [
        _concat_cols([p.columns[i] for p in pages], pages[0].columns[i].type)
        for i in range(pages[0].num_columns)
    ]
    active = jnp.concatenate([p.active for p in pages])
    return Page(tuple(cols), active)


class _KeyView:
    """column_for shim over resolved group-key source columns — the
    direct-indexed domain computation consults only the key columns'
    type/dictionary, so the fused planner can run it before the joined
    page exists."""

    def __init__(self, cols: Dict[str, Column]):
        self._cols = cols

    def column_for(self, symbol: str) -> Column:
        return self._cols[symbol]


@dataclass
class OperatorStats:
    """Per-plan-node execution stats (ref: operator/OperatorStats.java — the
    numbers EXPLAIN ANALYZE and the web UI surface, SURVEY.md §5.1).

    Time attribution (sync mode: every operator is fenced with
    block_until_ready, so the splits are exact): ``device_secs`` is the
    post-dispatch drain (exclusive — children are fenced before the parent
    dispatches), ``compile_secs`` is XLA backend-compile time attributed by
    the jax.monitoring listener (inclusive of children, like ``wall_secs``).
    Host time is DERIVED by consumers as exclusive wall - device - compile,
    not stored — one formula, no second number to drift."""

    node: PlanNode
    wall_secs: float
    output_rows: int
    output_capacity: int
    device_secs: float = 0.0
    compile_secs: float = 0.0


class PlanExecutor:
    """Evaluates a LogicalPlan bottom-up. One instance per query execution."""

    # False in traced subclasses: no host syncs (join sizing, dynamic filters)
    # may happen mid-plan — everything stays inside one XLA program.
    allow_host_sync = True

    def _choose_join_capacity(self, emit, probe_cap: int, build_cap: int) -> int:
        """Join output capacity: host-sync the exact emitted row count (the
        operator-at-a-time model; traced executors override with a static
        bound + overflow accounting)."""
        total = int(jnp.sum(emit))
        return _round_capacity(max(total, 1))

    def __init__(
        self,
        plan: LogicalPlan,
        metadata: Metadata,
        session: Session,
        collect_stats: bool = False,
    ):
        self.plan = plan
        self.metadata = metadata
        self.session = session
        self.types = plan.types
        self.collect_stats = collect_stats
        self.stats: Dict[int, OperatorStats] = {}  # keyed by id(node)
        # statistics feedback plane (runtime/statstore.py): per-node deferred
        # actuals. Off by default — the feedback-plane entry points (local
        # runner, fragment executors) flip it on; traced/OOC executors, whose
        # pages are tracers or per-bucket slices, keep it off.
        self.collect_actuals = False
        self.actuals: Dict[int, dict] = {}  # keyed by id(node)
        # warm-path cache plane (runtime/cachestore.py): entry points that
        # opt in set a FragmentBinding here; eval() then serves cacheable
        # scan->filter->(partial-)agg subtrees from the committed
        # materialization instead of re-executing them
        self.fragment_cache = None
        self.fragment_cache_hits = 0
        # device batching plane (runtime/device_scheduler.py): entry points
        # that opt in (device_batching knob) set a BatchBinding here; eval()
        # then submits batchable subtrees as work items that pack with
        # compatible fragments from concurrent queries into one ragged
        # launch, and leaf scans dedup through shared-scan elimination
        self.device_batching = None
        # id(node) -> provenance text ("fragment reused from query q-17")
        # rendered by EXPLAIN ANALYZE
        self.cache_provenance: Dict[int, str] = {}
        # ANN index tier (connectors/vector_index.py): id(scan node) ->
        # {"probed", "total", "nprobe"} for pruned IVF scans — read by the
        # recall sampler in run_vector_topn and by EXPLAIN ANALYZE
        self.ann_probe_stats: Dict[int, dict] = {}
        # join node -> (synthetic dynamic-filter node id, probe node id)
        self.dyn_filters: Dict[int, Tuple[int, int]] = {}
        self._pinned: List[PlanNode] = []  # synthetic nodes the keys above reference
        from .memory import query_memory_context

        limit = int(session.get("query_max_memory_bytes") or 0) or None
        # attaches to the active memory scope's pool (QueryManager execution:
        # blocking backpressure + killer); plain accounting otherwise
        self.memory = query_memory_context(limit)
        # operator-state spill stats (io.trino.spiller SpillMetrics analogue)
        self.spill_count = 0
        self.spilled_bytes = 0
        # megakernel plane: the launch site (server/worker.py) plants the
        # fragment's output partitioning here — (key_symbols, n_parts) — so
        # a fused root can run the repartition epilogue as its output stage
        self.repartition_hint = None
        # kernel cost plane (runtime/kernelcost.py): id(node) -> aggregated
        # XLA cost-model attribution for this query's launches. Only filled
        # in stats mode with the kernel_cost session property on (EXPLAIN
        # ANALYZE VERBOSE forces it) — otherwise the cost hook never fires
        # and the execution path is byte-identical.
        self.kernel_cost_enabled = kernelcost.session_enabled(session)
        self.kernel_costs: Dict[int, dict] = {}
        self._kc_seq = 0
        self._kc_plan_fp: Optional[str] = None

    # ------------------------------------------------------------------ entry

    def execute(self) -> Tuple[List[str], Page]:
        root = self.plan.root
        assert isinstance(root, OutputNode)
        rel = self.eval(root.source)
        cols = [rel.column_for(s) for s in root.symbols]
        return list(root.column_names), Page(tuple(cols), rel.page.active)

    # ------------------------------------------------------------------ nodes

    def eval(self, node: PlanNode) -> Relation:
        if self.fragment_cache is not None and isinstance(node, AggregationNode):
            rel = self.fragment_cache.fetch_or_execute(self, node)
            if id(node) in self.cache_provenance:
                # served from the fragment tier: children never ran — book
                # only this node's output (stats for EXPLAIN ANALYZE, memory
                # accounting, actuals for the feedback plane)
                if self.collect_stats:
                    rows = int(jnp.sum(rel.page.active.astype(jnp.int32)))
                    self.stats[id(node)] = OperatorStats(
                        node=node, wall_secs=0.0, output_rows=rows,
                        output_capacity=rel.capacity, device_secs=0.0,
                        compile_secs=0.0,
                    )
                if self.collect_actuals:
                    self._stash_actual(node, rel)
                self._account(node, rel)
            return rel
        if (
            self.device_batching is not None
            and isinstance(
                node, (AggregationNode, SortNode, TopNNode, VectorTopNNode)
            )
            and not self.collect_stats
        ):
            # device batching plane: submit the subtree as a work item;
            # None = not batchable here, fall through to plain execution.
            # Like a fragment-cache hit, only the subtree ROOT is booked
            # (intermediate chain nodes ran inside the packed launch) —
            # unless the scheduler ran the subtree through _eval_node
            # itself (subsumption winner), which booked everything.
            rel = self.device_batching.execute(self, node)
            if rel is not None:
                if getattr(self, "_batch_root_booked", None) is node:
                    self._batch_root_booked = None
                    return rel
                if self.collect_actuals:
                    self._stash_actual(node, rel)
                self._account(node, rel)
                return rel
        return self._eval_node(node)

    def _eval_node(self, node: PlanNode) -> Relation:
        method = getattr(self, "_exec_" + type(node).__name__, None)
        if method is None:
            raise ExecutionError(f"no executor for {type(node).__name__}")
        if self.device_batching is not None and isinstance(node, TableScanNode):
            # shared-scan elimination: overlapping leaf scans of concurrent
            # queries subsume into one execution (stats/actuals/chaos for
            # this node still book normally around the wrapped method)
            inner = method
            method = (
                lambda n, _inner=inner:
                self.device_batching.shared_scan(self, n, _inner)
            )
        if self.allow_host_sync and not (
            self.device_batching is not None
            and isinstance(node, TableScanNode)
        ):
            # device-program launch accounting at the operator boundary
            # (the batching A/B metric; a packed ragged launch books once
            # inside the scheduler instead). Traced executors
            # (allow_host_sync=False) run inside ONE fused program — their
            # per-node walk is a trace, not a launch. Scans under the
            # batching plane book inside shared_scan: a scan SERVED from a
            # concurrent overlapping scan uploads nothing and launches
            # nothing.
            on_program_launch()
        injector = FailureInjector.current()
        if injector is not None:
            injector.maybe_fail(type(node).__name__)
        if not self.collect_stats:
            # kernel_cost session property: attribute on the regular path
            # too (no fences, so no measured device_secs — ledger rows
            # carry classification but not pct-of-roofline). With the
            # property off this is a nullcontext: byte-identical execution.
            with self._kernel_cost_scope(node):
                rel = method(node)
            if self.collect_actuals:
                self._stash_actual(node, rel)
            self._account(node, rel)
            return rel
        import time as _time

        from .observability import RECORDER, compile_window

        t0 = _time.perf_counter()
        with RECORDER.span(type(node).__name__, "operator"):
            with compile_window() as cw:
                with self._kernel_cost_scope(node):
                    rel = method(node)
            t1 = _time.perf_counter()
            # sync fence: exact device/host attribution needs the drain
            # isolated from the next dispatch (the opt-in cost of stats mode)
            jax.block_until_ready(rel.page.active)
        t2 = _time.perf_counter()
        rows = int(jnp.sum(rel.page.active.astype(jnp.int32)))
        self.stats[id(node)] = OperatorStats(
            node=node,
            wall_secs=t2 - t0,
            output_rows=rows,
            output_capacity=rel.capacity,
            device_secs=t2 - t1,
            compile_secs=cw.seconds,
        )
        if self.collect_actuals:
            self._stash_actual(node, rel)
        self._account(node, rel)
        return rel

    def _kernel_cost_scope(self, node: PlanNode):
        """Recording scope for the kernel cost plane: every jitted program
        launched while this node's method runs attributes its XLA cost
        analysis to this node (scopes nest with evaluation, innermost wins,
        so a child evaluated mid-method books to the child)."""
        import contextlib

        if not self.kernel_cost_enabled:
            return contextlib.nullcontext()
        from . import capstore, statstore
        from .observability import current_collector

        if self._kc_plan_fp is None:
            try:
                self._kc_plan_fp = capstore.plan_fingerprint(self.plan)
            except Exception:  # noqa: BLE001 — keying only, never fail eval
                self._kc_plan_fp = "plan"
        self._kc_seq += 1
        kind = type(node).__name__
        # cross-process-stable node key: stats-mode evaluation order is
        # deterministic for a given plan, so the sequence number
        # disambiguates same-kind siblings without a preorder walk
        node_key = f"{self._kc_plan_fp}:{self._kc_seq}:{kind}"
        agg = self.kernel_costs.setdefault(
            id(node),
            {"flops": 0.0, "bytes_accessed": 0.0, "peak_hbm_bytes": 0,
             "programs": 0, "unavailable": 0},
        )
        collector = current_collector()

        def sink(record: dict) -> None:
            agg["programs"] += 1
            if record.get("status") == "ok":
                agg["flops"] += float(record.get("flops") or 0.0)
                agg["bytes_accessed"] += float(
                    record.get("bytes_accessed") or 0.0
                )
                if record.get("peak_hbm_bytes"):
                    # programs launch serially within one operator: the
                    # node watermark is the largest single launch
                    agg["peak_hbm_bytes"] = max(
                        agg["peak_hbm_bytes"], int(record["peak_hbm_bytes"])
                    )
            else:
                agg["unavailable"] += 1
            if collector is not None:
                collector.add_kernel_cost(kind, record)

        return kernelcost.attributing(
            node_key, kind, sink,
            query_id=statstore.current_query_id() or "",
        )

    # ------------------------------------------------ cardinality actuals

    # valid-mask retention bound for NULL-fraction sampling: beyond this
    # capacity the masks would pin real device memory until query end, so
    # null_frac degrades to None instead (the row COUNT is a pinned 4-byte
    # device scalar either way — large pages never pin their masks)
    _NULL_FRAC_CAP = 1 << 20

    def _stash_actual(self, node: PlanNode, rel: Relation) -> None:
        """Defer this node's actual row count: dispatch ONE tiny async
        reduction per operator page and pin only its 4-byte device scalar —
        pinning the mask itself would hold a byte per row of every
        intermediate until query end. Scans/filters (the nodes selectivity
        estimation is calibrated on) additionally keep their column valid
        masks for NULL fractions, bounded by _NULL_FRAC_CAP. Host syncs
        happen ONCE in finalize_actuals after the result has drained."""
        ent = self.actuals.get(id(node))
        if ent is None:
            ent = self.actuals[id(node)] = {
                "counts": [], "valids": [], "capacity": 0, "bytes": 0,
            }
        ent["counts"].append(jnp.sum(rel.page.active, dtype=jnp.int32))
        ent["capacity"] += rel.capacity
        if (
            isinstance(node, (TableScanNode, FilterNode))
            and rel.page.columns
            and rel.capacity <= self._NULL_FRAC_CAP
        ):
            ent["valids"].append(
                (rel.page.active, tuple(c.valid for c in rel.page.columns))
            )

    def finalize_actuals(self) -> Dict[int, dict]:
        """Resolve the deferred per-node actuals to plain ints — called once
        after the query drained (statstore.observe_query's input). Counting
        runs in NUMPY on the host (np.asarray of a drained mask is free on
        the CPU backend, one small D2H elsewhere) — jnp reductions here
        would dispatch a fresh XLA program per mask and dominate the plane's
        cost (the Q6 A/B regression that numpy counting removes)."""
        import numpy as np

        out: Dict[int, dict] = {}
        for key, ent in self.actuals.items():
            rows = sum(int(np.asarray(c)) for c in ent["counts"])
            null_frac = None
            if ent["valids"] and rows > 0:
                nulls = cells = 0
                for active, valids in ent["valids"]:
                    a = np.asarray(active)
                    page_rows = int(np.count_nonzero(a))
                    for v in valids:
                        nulls += int(np.count_nonzero(a & ~np.asarray(v)))
                        cells += page_rows  # THIS page's rows, not the total
                null_frac = (nulls / cells) if cells else None
            out[key] = {
                "rows": rows,
                "capacity": ent["capacity"],
                "bytes": ent["bytes"],
                "null_frac": null_frac,
            }
        # dynamic-filter hit rate resolves HERE, per executor: the synthetic
        # filter node only exists in this executor's lifetime, and pre/post
        # rows from different partitions must pair up before any summing
        # (post[last partition] / pre[all partitions] would understate the
        # selectivity by the partition count)
        for join_id, (fnode_id, probe_id) in self.dyn_filters.items():
            ent = out.get(join_id)
            post = out.get(fnode_id)
            pre = out.get(probe_id)
            if ent is not None and post is not None and pre is not None:
                ent["dyn_post"] = post["rows"]
                ent["dyn_pre"] = pre["rows"]
        return out

    def _account(self, node: PlanNode, rel: Relation) -> None:
        """Memory accounting per operator output (lib/trino-memory-context)."""
        from .memory import page_bytes

        nbytes = page_bytes(rel.page)
        ctx = self.memory.new_local(type(node).__name__)
        ctx.set_bytes(nbytes)
        if self.collect_actuals:
            ent = self.actuals.get(id(node))
            if ent is not None:
                ent["bytes"] += nbytes

    def _exec_TableScanNode(self, node: TableScanNode) -> Relation:
        connector = self.metadata.connector_for(node.table)
        handle = node.table
        if node.constraint.domains:
            absorbed = self.metadata.apply_filter(handle, node.constraint)
            if absorbed is not None:
                handle = absorbed
        splits = connector.split_manager().get_splits(handle)
        ch = handle.connector_handle
        if isinstance(ch, dict) and "ann_probe" in ch and splits:
            # ANN centroid pre-pass pruned the IVF cluster splits — surface
            # it like partition pruning (EXPLAIN ANALYZE + recall sampler)
            info = splits[0].info if isinstance(splits[0].info, dict) else {}
            probed = len(splits)
            total = int(info.get("total_clusters", probed))
            nprobe = int(ch["ann_probe"].get("nprobe", probed))
            self.ann_probe_stats[id(node)] = {
                "probed": probed, "total": total, "nprobe": nprobe,
            }
            self.cache_provenance[id(node)] = (
                f"ann: probed {probed}/{total} clusters (nprobe={nprobe})"
            )
        symbols = tuple(s for s, _ in node.assignments)
        meta = self.metadata.get_table_metadata(node.table)
        col_indexes = [meta.column_index(c) for _, c in node.assignments]
        if not splits:
            # all splits pruned: 1-row page with nothing active (zero-capacity
            # arrays break .at[0] initializers in downstream kernels).
            # empty_page_for keeps multi-lane storage (vectors, long
            # decimals) and the string dictionary sentinel layout-correct.
            from ..spi.host_pages import empty_page_for

            page = empty_page_for(symbols, {s: self.types[s] for s in symbols})
            return Relation(page, symbols)
        provider = connector.page_source_provider()
        counts = None  # per-page active rows, only when something computed it
        if node.limit is not None and len(splits) > 1:
            # stop-early scan (PushLimitIntoTableScan): read splits until the
            # row target is covered; the LimitNode above enforces exactness
            pages = []
            counts = []
            rows = 0
            for sp in splits:
                p = provider.create_page_source(sp, col_indexes)
                pages.append(p)
                counts.append(int(jnp.sum(p.active.astype(jnp.int32))))
                rows += counts[-1]
                if rows >= node.limit:
                    break
            splits = splits[: len(pages)]
        else:
            pages = _load_splits(provider, splits, col_indexes, self.session)
        # split boundary: SplitCompletedEvent dispatch (spi/eventlistener) —
        # one thread-local read when no listener asked for split events; the
        # limit branch's counts are reused (no second device sync per split)
        from .events import split_event_sink

        sink = split_event_sink()
        if sink is not None:
            if counts is None:
                counts = [
                    int(jnp.sum(p.active.astype(jnp.int32))) for p in pages
                ]
            for sp, p, n in zip(splits, pages, counts):
                sink({
                    "catalog": handle.catalog,
                    "table": str(handle.schema_table),
                    "splitId": sp.split_id,
                    "totalSplits": sp.total_splits,
                    "rows": n,
                })
        # connector-declared sort order -> symbol space (splits are generated
        # over ascending key ranges, so the concat preserves it)
        col_to_sym = {c: s for s, c in node.assignments}
        sorted_by = []
        for col in getattr(meta, "sorted_by", ()):
            sym = col_to_sym.get(col)
            if sym is None:
                break
            sorted_by.append(sym)
        return Relation(_concat_pages(pages), symbols, tuple(sorted_by))

    def _exec_FilterNode(self, node: FilterNode) -> Relation:
        rel = self.eval(node.source)
        fn, _ = compile_expression(node.predicate, rel.layout(), rel.capacity)
        page = _jit_filter(fn, rel.env(), rel.page)
        # masking never reorders rows
        return Relation(page, rel.symbols, rel.sorted_by)

    def _exec_ProjectNode(self, node: ProjectNode) -> Relation:
        rel = self.eval(node.source)
        return self._project_relation(node, rel)

    def _compile_assignments(self, assignments, rel: Relation):
        """Compile a projection's (symbol, expr) assignments against an
        evaluated relation — ONE implementation shared by the project walk
        and the fused top-k node, so the fused path's 'same compiled
        closures as the serial pair' bit-identity guarantee is structural."""
        layout = rel.layout()
        compiled = []
        for sym, expr in assignments:
            fn, out_dict = compile_expression(expr, layout, rel.capacity)
            type_ = self.types.get(sym) or expr.type
            compiled.append((fn, type_, out_dict))
        return tuple(compiled)

    def _project_relation(self, node: ProjectNode, rel: Relation) -> Relation:
        """Project an already-evaluated relation (shared by the standard walk
        and the megakernel plane's serial-finish fallback, which must not
        re-evaluate the project's source subtree)."""
        compiled = self._compile_assignments(node.assignments, rel)
        symbols = []
        alias_of = {}  # output symbol -> input symbol (identity projections)
        for sym, expr in node.assignments:
            symbols.append(sym)
            if isinstance(expr, Reference):
                alias_of[expr.symbol] = sym
        from ..ops import tensor as _tensor

        vinfo = _tensor.assignments_vector_info(node.assignments)
        if vinfo is None:
            page = _jit_project(tuple(compiled), rel.env(), rel.page)
        else:
            # a similarity/model projection: one MXU-shaped launch — book it
            # on the tensor plane's counter with the paired kernel span
            with _tensor.vector_kernel_span(rel.capacity, vinfo[1]):
                page = _jit_project(tuple(compiled), rel.env(), rel.page)
            _tensor.on_vector_kernel()
        sorted_by = []
        for s in rel.sorted_by:
            out = alias_of.get(s)
            if out is None:
                break
            sorted_by.append(out)
        payload = rel.page.__dict__.get("_megakernel_epilogue")
        if payload and payload.get("keys"):
            # a fused source computed the exchange dest in-kernel; a
            # projection is row-preserving (active rides through unchanged),
            # so the dest stays valid as long as every partition key passes
            # through as an identity reference — carry it to the new page
            # under the aliased names
            renamed = tuple(alias_of.get(k) for k in payload["keys"])
            if all(r is not None for r in renamed):
                from ..ops.megakernels import attach_epilogue

                attach_epilogue(
                    page, payload["dest"],
                    tuple(symbols.index(r) for r in renamed),
                    payload["n_parts"], keys=renamed,
                )
        return Relation(page, tuple(symbols), tuple(sorted_by))

    def _exec_UnnestNode(self, node) -> Relation:
        """UNNEST: flatten [cap, W] element lanes to a [cap*W] row grid (ref
        operator/unnest/UnnestOperator.java — its per-position appendRange loop
        becomes one static reshape; rows past each array's length stay
        inactive)."""
        from ..spi.types import ArrayType as _At

        rel = self.eval(node.source)
        unnest_cols = [rel.column_for(s) for s, _ in node.unnest_symbols]
        w = 1
        for c in unnest_cols:
            arr = c if isinstance(c.type, _At) else c.children[0]
            w = max(w, int(arr.data.shape[1]) if arr.data.ndim > 1 else 1)
        page = _jit_unnest(
            tuple(rel.symbols.index(s) for s in node.replicate_symbols),
            tuple(rel.symbols.index(s) for s, _ in node.unnest_symbols),
            w,
            node.ordinality_symbol is not None,
            rel.page,
        )
        return Relation(page, tuple(node.output_symbols))

    # ------------------------------------------------------------ aggregation

    def _exec_AggregationNode(self, node: AggregationNode) -> Relation:
        distinct_aggs = [a for _, a in node.aggregations if a.distinct]
        if distinct_aggs:
            return self._exec_distinct_aggregation(node)
        fused = self._try_fused_join_aggregate(node)
        if fused is not None:
            return fused
        rel = self.eval(node.source)
        thresh = self._spill_threshold()
        if thresh and self.allow_host_sync and node.group_keys:
            from .memory import page_bytes

            total = page_bytes(rel.page)
            if total > thresh:
                return self._spill_partitioned_aggregate(rel, node, total, thresh)
        return aggregate_relation(rel, node, self.types, self._pallas_mode())

    def _pallas_mode(self) -> str:
        """Resolve the pallas_aggregation session property to a static mode:
        'tpu' (compiled kernels), 'interpret' (pl.pallas_call interpret mode —
        the CPU test hook), or 'off'. THE policy (why AUTO keeps the XLA
        formulation, with the v5e measurements) lives in the central knob
        registry: knobs.resolve_pallas_aggregation."""
        try:
            mode = self.session.get("pallas_aggregation")
        except KeyError:
            mode = "auto"
        return knobs.resolve_pallas_aggregation(mode)

    # ------------------------------------------------- megakernel plane

    def _fusion_enabled(self) -> bool:
        """pallas_fusion session gate. Off (the default) keeps the execution
        path byte-identical to the serial op-chain (the device_batching
        contract). Stats mode stays serial so EXPLAIN ANALYZE attributes
        per-operator time; traced executors (allow_host_sync=False) run one
        fused XLA program already and host-sync nothing mid-plan."""
        if not self.allow_host_sync or self.collect_stats:
            return False
        try:
            return bool(self.session.get("pallas_fusion"))
        except KeyError:
            return False

    def _fusion_interpret(self) -> bool:
        try:
            mode = self.session.get("pallas_interpret")
        except KeyError:
            mode = "auto"
        return knobs.resolve_pallas_interpret(mode, jax.default_backend())

    def _epilogue_spec_for(self, symbols: Tuple[str, ...]):
        """(key_idx, n_parts) when this fragment's output feeds a hash
        exchange whose keys the produced symbols cover (the launch site —
        server/worker.py — plants ``repartition_hint`` before execution), so
        the megakernel computes the exchange destination as its output stage
        and ops/repartition skips the standalone hash program."""
        hint = getattr(self, "repartition_hint", None)
        if not hint:
            return None
        keys, n_parts = hint
        if not keys or n_parts <= 1:
            return None
        if not all(k in symbols for k in keys):
            return None
        return tuple(symbols.index(k) for k in keys), int(n_parts)

    def _fused_join_spec(self, kind, node: JoinNode, probe, build,
                         pkeys, bkeys):
        """Shared shape gate: compiler recognition + physical key check.
        Returns the MegakernelSpec or None (fallback ticked)."""
        from ..ops import megakernels as MK
        from ..ops.compiler import megakernel_key_check, plan_megakernel

        spec, reason = plan_megakernel(
            kind, node.criteria, node.filter is not None,
            probe.page, build.page,
        )
        if spec is None:
            MK.on_pallas_fallback(reason)
            return None
        for cols in (pkeys, bkeys):
            ok, reason = megakernel_key_check(cols)
            if not ok:
                MK.on_pallas_fallback(reason)
                return None
        return spec

    def _try_fused_join(
        self, kind, node: JoinNode, probe: Relation, build: Relation,
        pkeys, bkeys, luts,
    ) -> Optional[Relation]:
        """Attempt the fused hash-join megakernel for an already-normalized
        (RIGHT-swapped) join: ops/compiler.plan_megakernel recognizes the
        shape, ops/megakernels runs build+probe+expand (+ the repartition
        dest) as Pallas launches. Returns the fused Relation, or None after
        a labeled fallback tick — the caller runs the serial op-chain."""
        from ..ops import megakernels as MK

        spec = self._fused_join_spec(kind, node, probe, build, pkeys, bkeys)
        if spec is None:
            return None
        interp = self._fusion_interpret()
        out_symbols = probe.symbols + build.symbols
        try:
            pr = MK.probe_phase(
                pkeys, bkeys, luts, probe.page.active, build.page.active,
                spec.left_outer, interp,
            )
            if pr is None:
                return None  # bucket skew; fallback already ticked
            out_capacity = self._choose_join_capacity(
                pr["emit"], probe.capacity, build.capacity
            )
            epi_spec = self._epilogue_spec_for(out_symbols)
            page, dest = MK.expand_phase(
                pr, pkeys, bkeys, luts, probe.page, build.page,
                out_capacity, out_symbols, None, None, epi_spec, interp,
            )
        except Exception:
            # an unexpected kernel failure must degrade to the serial path,
            # never fail the query — the counter + flight instant surface it
            MK.on_pallas_fallback("kernel_error")
            return None
        if dest is not None:
            MK.attach_epilogue(
                page, dest, epi_spec[0], epi_spec[1],
                keys=(self.repartition_hint or ((),))[0],
            )
        # probe-major expansion preserves the probe side's order (the serial
        # join's out_sorted rule for non-FULL kinds)
        return Relation(page, out_symbols, probe.sorted_by)

    def _try_fused_join_aggregate(self, node: AggregationNode) -> Optional[Relation]:
        """join -> [project] -> partial-agg fusion: when a (non-distinct,
        grouped) aggregation sits on a fused-eligible join — possibly with
        one elementwise ProjectNode in between (the shape the optimizer
        emits for every sum(expr)-over-join fragment) — build, probe,
        expansion, the projected expressions, and the group stage all run
        inside megakernel launches; the join output never materializes
        between operators, and the whole fragment books ONE device program
        where the serial walk books two or three.

        Group strategy mirrors aggregate_relation exactly: direct-indexed
        (small static dictionary/boolean domains) runs entirely inside the
        expand kernel; every other shape takes the sort path — group-sort +
        boundary detection inside the expand kernel, one host sync for the
        group count (the sync the serial path performs too), then the
        reduction stage as the aggregate kernel. Returns the aggregated
        Relation, or None for the standard walk."""
        if not self._fusion_enabled():
            return None
        proj = None
        src = node.source
        if isinstance(src, ProjectNode) and isinstance(src.source, JoinNode):
            proj, src = src, src.source
        if not isinstance(src, JoinNode) or not node.group_keys:
            return None
        if self._spill_threshold():
            return None  # the spill paths host-sync sizes — serial only
        if self._pallas_mode() != "off":
            return None  # the limb kernels cannot nest inside the fused kernel
        if any(
            a.distinct or a.ordering or a.function in _LANE_AGGS
            for _, a in node.aggregations
        ):
            # lane-valued aggregates host-sync their static lane width;
            # aggregate ORDER BY pre-sorts the whole relation — serial only
            return None
        from ..ops import megakernels as MK

        pre = self._join_inputs(src)
        if isinstance(pre, Relation):
            # the operator-state spill path ran the whole join (it cannot
            # trigger with spill_operator_threshold_bytes unset, but stay
            # safe against future gates): finish serially
            return self._serial_agg_finish(node, proj, pre)
        left, right = pre
        kind, src_n, probe, build, pkeys, bkeys, luts = self._join_sides(
            src, left, right
        )

        def serial_finish() -> Relation:
            # ONE spelling of the fallback: serial join (fusion already
            # declined — don't re-attempt), booked like _eval_node would
            return self._serial_agg_finish(
                node, proj,
                self._join_relations(src, left, right, allow_fusion=False),
                book_join=True,
            )

        spec = self._fused_join_spec(kind, src_n, probe, build, pkeys, bkeys)
        if spec is None:
            return serial_finish()
        base_symbols = probe.symbols + build.symbols
        view = Relation(
            Page(
                tuple(probe.page.columns) + tuple(build.page.columns),
                probe.page.active,
            ),
            base_symbols,
            probe.sorted_by,
        )
        interp = self._fusion_interpret()
        try:
            pr = MK.probe_phase(
                pkeys, bkeys, luts, probe.page.active, build.page.active,
                spec.left_outer, interp,
            )
            if pr is None:
                return serial_finish()
            out_capacity = self._choose_join_capacity(
                pr["emit"], probe.capacity, build.capacity
            )
            # fold the intermediate projection into the kernel: the same
            # compiled expression closures the serial _project_impl runs
            # (compile_expression caches on (expr, layout, capacity), so the
            # jit static key is stable across executions)
            proj_spec = None
            post_symbols = base_symbols
            post_sorted = view.sorted_by
            key_sources: Dict[str, Column] = {}
            if proj is not None:
                layout = view.layout()
                compiled = []
                symbols = []
                alias_of = {}
                for sym, expr in proj.assignments:
                    fn, out_dict = compile_expression(expr, layout, out_capacity)
                    type_ = self.types.get(sym) or expr.type
                    compiled.append((fn, type_, out_dict))
                    symbols.append(sym)
                    if isinstance(expr, Reference):
                        alias_of[expr.symbol] = sym
                        key_sources[sym] = view.column_for(expr.symbol)
                proj_spec = (tuple(compiled), tuple(symbols))
                post_symbols = tuple(symbols)
                post_sorted = []
                for s in view.sorted_by:
                    out = alias_of.get(s)
                    if out is None:
                        break
                    post_sorted.append(out)
                post_sorted = tuple(post_sorted)
            else:
                key_sources = {s: view.column_for(s) for s in node.group_keys
                               if s in base_symbols}
            agg_symbols = node.group_keys + tuple(s for s, _ in node.aggregations)
            epi_spec = self._epilogue_spec_for(agg_symbols)
            domains = None
            if all(k in key_sources for k in node.group_keys) and not any(
                a.function not in _DIRECT_AGG_FUNCS for _, a in node.aggregations
            ):
                domains = _direct_agg_domains(_KeyView(key_sources), node)
            if domains is not None:
                agg_spec = ("direct", (
                    tuple(node.group_keys), tuple(node.aggregations),
                    tuple(domains), tuple(post_symbols),
                ))
                page, dest = MK.expand_phase(
                    pr, pkeys, bkeys, luts, probe.page, build.page,
                    out_capacity, base_symbols, proj_spec, agg_spec,
                    epi_spec, interp,
                )
            else:
                needed = _needed_agg_symbols(node)
                presorted = bool(post_sorted) and (
                    post_sorted[0] == node.group_keys[0]
                )
                if presorted and any(
                    a.function in _RESORT_AGGS for _, a in node.aggregations
                ):
                    # serial would _force_dense here — a no-op for joined
                    # pages (the expansion emits a dense active prefix),
                    # so the presorted grouping is safe to take as-is
                    pass
                mode = "presorted" if presorted else "sort"
                agg_spec = (mode, (
                    tuple(node.group_keys), tuple(needed), tuple(post_symbols),
                ))
                if presorted:
                    # the serial presorted fast path, fused: the expand
                    # kernel verifies sortedness in-program; a violation
                    # re-groups through one extra kernel — the exact
                    # decision (and cost) of the serial path
                    joined, p, ng, n_grp, viol = MK.expand_phase(
                        pr, pkeys, bkeys, luts, probe.page, build.page,
                        out_capacity, base_symbols, proj_spec, agg_spec,
                        None, interp,
                    )
                    if bool(viol):
                        sorted_page, new_group, num_groups = MK.group_sort_phase(
                            tuple(node.group_keys), tuple(needed),
                            tuple(post_symbols), joined, interp,
                        )
                    else:
                        sorted_page, new_group, num_groups = p, ng, n_grp
                else:
                    sorted_page, new_group, num_groups = MK.expand_phase(
                        pr, pkeys, bkeys, luts, probe.page, build.page,
                        out_capacity, base_symbols, proj_spec, agg_spec,
                        None, interp,
                    )
                # the group-count host sync the serial sort path performs
                out_cap = min(
                    _round_capacity(max(int(num_groups), 1), base=16),
                    max(out_capacity, 16),
                )
                page, dest = MK.aggregate_phase(
                    tuple(node.group_keys), tuple(node.aggregations),
                    tuple(needed), out_cap, sorted_page, new_group,
                    num_groups, epi_spec, interp,
                )
        except Exception:
            MK.on_pallas_fallback("kernel_error")
            return serial_finish()
        if dest is not None:
            MK.attach_epilogue(
                page, dest, epi_spec[0], epi_spec[1],
                keys=(self.repartition_hint or ((),))[0],
            )
        return Relation(page, agg_symbols)

    def _serial_agg_finish(self, node: AggregationNode, proj,
                           join_rel: Relation, book_join: bool = False) -> Relation:
        """Finish an attempted fused join+agg fragment on the serial path
        WITHOUT re-evaluating the join inputs, booking the intermediate
        nodes the way _eval_node would have."""
        if book_join:
            on_program_launch()
            if self.collect_actuals:
                self._stash_actual(node.source if proj is None else proj.source,
                                   join_rel)
            self._account(node.source if proj is None else proj.source, join_rel)
        rel = join_rel
        if proj is not None:
            on_program_launch()
            rel = self._project_relation(proj, rel)
            if self.collect_actuals:
                self._stash_actual(proj, rel)
            self._account(proj, rel)
        return aggregate_relation(rel, node, self.types, self._pallas_mode())

    def _exec_distinct_aggregation(self, node: AggregationNode) -> Relation:
        """x(DISTINCT col): dedup on (group keys, col) first, then aggregate.
        (Trino: MarkDistinct + masked accumulators; same two-phase idea.)
        A mix of DISTINCT and plain aggregates evaluates as two aggregations
        over the same input — both paths group by the same keys through the
        same machinery, so their group rows align 1:1 (asserted) and the
        outputs merge columnwise (the MarkDistinct-masked-accumulator effect
        without per-aggregate masks)."""
        distinct_cols = {a.args[0] for _, a in node.aggregations if a.distinct}
        if len(distinct_cols) > 1:
            raise ExecutionError(
                "multiple DISTINCT aggregates over different columns not supported yet"
            )
        rel = self.eval(node.source)
        dcol = next(iter(distinct_cols))
        dedup_node = AggregationNode(
            source=node.source,
            group_keys=tuple(node.group_keys) + (dcol,),
            aggregations=(),
            step=AggregationStep.SINGLE,
        )
        deduped = aggregate_relation(rel, dedup_node, self.types, self._pallas_mode())
        dist_part = AggregationNode(
            source=node.source,  # unused
            group_keys=node.group_keys,
            aggregations=tuple(
                (s, Aggregation(a.function, a.args, False, a.filter, a.output_type))
                for s, a in node.aggregations
                if a.distinct
            ),
            step=node.step,
        )
        dist_rel = aggregate_relation(
            deduped, dist_part, self.types, self._pallas_mode()
        )
        plain_aggs = tuple(
            (s, a) for s, a in node.aggregations if not a.distinct
        )
        if not plain_aggs:
            return dist_rel
        plain_part = AggregationNode(
            source=node.source,  # unused
            group_keys=node.group_keys,
            aggregations=plain_aggs,
            step=node.step,
        )
        plain_rel = aggregate_relation(
            rel, plain_part, self.types, self._pallas_mode()
        )
        # both outputs order groups identically (same keys, same machinery —
        # group rows sit compacted at the front) but their CAPACITIES differ
        # (the distinct side aggregated the smaller deduped relation): verify
        # the active group rows match, then slice both to a common capacity
        act_a = np.asarray(dist_rel.page.active)
        act_b = np.asarray(plain_rel.page.active)
        ga, gb = int(act_a.sum()), int(act_b.sum())
        same = ga == gb
        if same and node.group_keys:
            # EVERY key column must align — a single-key check would accept
            # mismatched group orders whose first key happens to collide —
            # and NULL keys align on the valid mask with data compared only
            # where valid (invalid slots hold unspecified storage values)
            for k in node.group_keys:
                a, b = dist_rel.column_for(k), plain_rel.column_for(k)
                va = np.asarray(a.valid)[act_a]
                vb = np.asarray(b.valid)[act_b]
                da = np.asarray(a.data)[act_a]
                db = np.asarray(b.data)[act_b]
                # NaN is a valid non-NULL float group key and groups with
                # itself — it must compare equal here, not abort the query
                eq_nan = da.dtype.kind == "f"
                same = np.array_equal(va, vb) and np.array_equal(
                    da[va], db[vb], equal_nan=eq_nan
                )
                if not same:
                    break
        if not same:
            raise ExecutionError(
                "distinct/plain aggregation group alignment failed"
            )
        target = min(dist_rel.capacity, plain_rel.capacity)
        cols = {}
        for s in node.group_keys:
            cols[s] = _slice_column(dist_rel.column_for(s), target)
        for s, a in node.aggregations:
            src = dist_rel if a.distinct else plain_rel
            cols[s] = _slice_column(src.column_for(s), target)
        symbols = tuple(node.group_keys) + tuple(s for s, _ in node.aggregations)
        page = Page(tuple(cols[s] for s in symbols), dist_rel.page.active[:target])
        return Relation(page, symbols)

    # ----------------------------------------------------------------- joins

    def _exec_JoinNode(self, node: JoinNode) -> Relation:
        pre = self._join_inputs(node)
        if isinstance(pre, Relation):
            return pre  # the operator-state spill path ran the whole join
        left, right = pre
        return self._join_relations(node, left, right)

    def _join_inputs(self, node: JoinNode):
        """Shared join preamble — dynamic filtering, input compaction, the
        operator-state spill gate — factored out so the megakernel plane
        (join -> partial-agg fusion) evaluates inputs exactly the way the
        serial path does. Returns ``(left, right)`` Relations, or a finished
        Relation when the spill-partitioned path executed the join itself."""
        # dynamic filtering (ref: server/DynamicFilterService.java:101 +
        # DynamicFilterSourceOperator): evaluate the build side first, collect
        # its key ranges, and AND them into the probe subtree as a filter so
        # the probe is pruned before the join. Inner joins only (an outer
        # probe must keep unmatched rows).
        dynamic_filter = None
        if (
            node.kind == JoinKind.INNER
            and node.criteria
            and self.allow_host_sync
            and self.session.get("enable_dynamic_filtering")
        ):
            right = self.eval(node.right)
            dynamic_filter = self._dynamic_filter_predicate(node, right)
            if dynamic_filter is not None:
                fnode = FilterNode(source=node.left, predicate=dynamic_filter)
                left = self.eval(fnode)
                if self.collect_actuals:
                    # probe rows before vs after the build-derived range
                    # filter = the dynamic-filter hit rate statstore reports.
                    # fnode must stay referenced: actuals are keyed by id(),
                    # and a collected synthetic node's id could be reused
                    self._pinned.append(fnode)
                    self.dyn_filters[id(node)] = (id(fnode), id(node.left))
            else:
                left = self.eval(node.left)
        else:
            left = self.eval(node.left)
            right = self.eval(node.right)
        if self.allow_host_sync:
            left = _maybe_compact(left)
            right = _maybe_compact(right)
        # operator-state spill (ref: spilling HashBuilderOperator.java:68 +
        # MemoryRevokingScheduler.java:48): a build side larger than the
        # budget revokes to host as hash partitions, joined one at a time
        thresh = self._spill_threshold()
        if (
            thresh
            and self.allow_host_sync
            and node.criteria
            and node.kind != JoinKind.CROSS
        ):
            from .memory import page_bytes

            total = page_bytes(left.page) + page_bytes(right.page)
            if total > thresh:
                return self._spill_partitioned_join(node, left, right, total, thresh)
        return left, right

    def _join_sides(self, node: JoinNode, left: Relation, right: Relation):
        """RIGHT-swap + key/LUT extraction shared by the serial join and the
        fused megakernel path: returns (kind, node, probe, build, pkeys,
        bkeys, luts) with RIGHT normalized to LEFT (sides swapped; output
        symbols reorder by symbol lookup, so the swap is free)."""
        kind = node.kind
        if kind == JoinKind.RIGHT:
            node = JoinNode(
                left=node.right,
                right=node.left,
                kind=JoinKind.LEFT,
                criteria=tuple((r, l) for l, r in node.criteria),
                filter=node.filter,
                distribution=node.distribution,
            )
            left, right = right, left
            kind = JoinKind.LEFT
        probe, build = left, right
        if kind == JoinKind.CROSS:
            pkeys, bkeys, luts = (), (), ()
        else:
            pkeys = tuple(
                (probe.column_for(l).data, probe.column_for(l).valid)
                for l, _ in node.criteria
            )
            bkeys = tuple(
                (build.column_for(r).data, build.column_for(r).valid)
                for _, r in node.criteria
            )
            # cross-dictionary key translation for string join keys
            luts = _string_key_luts(node, probe, build)
        return kind, node, probe, build, pkeys, bkeys, luts

    def _join_relations(
        self, node: JoinNode, left: Relation, right: Relation,
        allow_fusion: bool = True,
    ) -> Relation:
        kind, node, probe, build, pkeys, bkeys, luts = self._join_sides(
            node, left, right
        )
        left_outer = kind in (JoinKind.LEFT, JoinKind.FULL)
        if allow_fusion and self._fusion_enabled():
            rel = self._try_fused_join(
                kind, node, probe, build, pkeys, bkeys, luts
            )
            if rel is not None:
                return rel

        emit, count, lo, perm_b = _jit_join_match(
            left_outer, pkeys, bkeys, luts, probe.page.active, build.page.active
        )
        out_capacity = self._choose_join_capacity(emit, probe.capacity, build.capacity)
        page = _jit_join_expand(
            out_capacity, emit, count, lo, perm_b, probe.page, build.page
        )

        if kind == JoinKind.FULL:
            # append unmatched build rows with a null probe side (the join is
            # symmetric: a LEFT expansion plus the build side's anti set)
            extra = _jit_full_join_tail(
                pkeys, bkeys, luts, probe.page, build.page
            )
            page = _concat_pages([page, extra])
        # match expansion emits probe-major output (expand_matches: slot ->
        # last probe row with start <= slot), so the probe side's sort order
        # survives INNER/LEFT joins; the FULL tail breaks it
        out_sorted = probe.sorted_by if kind != JoinKind.FULL else ()
        out = Relation(page, probe.symbols + build.symbols, out_sorted)

        if node.filter is not None:
            if kind == JoinKind.FULL:
                raise ExecutionError(
                    "FULL JOIN with non-equi residual not supported yet"
                )
            fn, _ = compile_expression(node.filter, out.layout(), out.capacity)
            if not left_outer:
                page = _jit_filter(fn, out.env(), out.page)
                out = Relation(page, out.symbols, out.sorted_by)
            else:
                # LEFT semantics: the residual is part of the ON clause — rows
                # failing it drop, and probe rows left without any surviving
                # match re-emit one null-padded row
                page = _jit_left_join_residual(
                    fn,
                    out.symbols,
                    out_capacity,
                    emit,
                    count,
                    lo,
                    perm_b,
                    probe.page,
                    build.page,
                )
                out = Relation(page, out.symbols, out.sorted_by)
        self._tag_vector_broadcast(build, out)
        return out

    def _tag_vector_broadcast(self, build: Relation, out: Relation) -> None:
        """Embedding-JOIN detection (vector serving plane): a build side
        that is exactly ONE active row carrying vector columns makes
        ``sim(probe.v, build.v)`` above this join a constant-query scoring —
        tag the joined page with the broadcast vector symbols so a
        VectorTopN root routes through the vector serving tier's stacked
        path (runtime/device_scheduler.py). The lane body stays this query's
        own compiled einsum closures, so bit-identity vs the serial einsum
        is structural; the tag only affects routing."""
        if not self.allow_host_sync:
            return
        from ..spi.types import is_vector

        bsyms = frozenset(
            s for s in build.symbols
            if is_vector(build.column_for(s).type)
        )
        if not bsyms:
            return
        if int(jnp.sum(build.page.active.astype(jnp.int32))) != 1:
            return
        out.page._vector_broadcast = bsyms

    # ------------------------------------------------- operator-state spill

    def _spill_threshold(self) -> int:
        try:
            return int(self.session.get("spill_operator_threshold_bytes") or 0)
        except KeyError:
            return 0

    def _hash_partition_spill(
        self, rel: Relation, key_symbols: Tuple[str, ...], nparts: int
    ) -> List[bytes]:
        """Revoke a relation to host as LZ4 hash partitions by key value.

        The partition id is a deterministic function of the key VALUE
        (dictionary columns hash through content-stable value keys), so the
        same key lands in the same partition on both join sides and a group
        never spans partitions — the invariant Trino's partitioned spill
        relies on (GenericPartitioningSpiller, SpillableHashAggregationBuilder).

        Runs as the compiled repartition epilogue (ops/repartition.py): one
        hash + stable cosort + one D2H yields a partition-contiguous buffer
        that serde slices into nparts frames — the old path ran one masked
        compaction program + serialization per partition (nparts device
        round-trips). Nested layouts keep the legacy per-partition path.
        """
        from ..ops.repartition import (
            device_repartition_enabled,
            hash_key_columns,
            partition_ids,
            repartition_frames,
            supports_device_repartition,
        )
        from .serde import serialize_page

        blobs: List[bytes] = []
        if device_repartition_enabled() and supports_device_repartition(rel.page):
            key_idx = [rel.symbols.index(s) for s in key_symbols]
            # pool=None: spill can run inside OOC pool jobs — fanning out
            # from a pool thread deadlocks a saturated executor
            blobs, _ = repartition_frames(rel.page, key_idx, nparts, compress=True)
        else:
            cols = [rel.column_for(s) for s in key_symbols]
            pid = partition_ids(hash_key_columns(cols), nparts)
            for p in range(nparts):
                mask = rel.page.active & (pid == p)
                n = int(jnp.sum(mask.astype(jnp.int32)))
                part = _jit_compact(
                    _round_capacity(max(n, 1)), Page(rel.page.columns, mask)
                )
                blobs.append(serialize_page(part, compress=True))
        for b in blobs:
            self.spill_count += 1
            self.spilled_bytes += len(b)
            on_spill_write(len(b))
        return blobs

    def _unspill(self, blob: bytes, template: Relation) -> Relation:
        """Host bytes -> device Relation, re-attaching the parent's dictionary
        OBJECTS (same content): dictionaries are identity-hashed in the jit
        cache, so fresh objects per partition would force a recompile each.
        v2 frames land on a canonical capacity class (v1 frames carry their
        own rounded capacity) — varying partition sizes share compiled
        programs downstream."""
        from .serde import LazyPageFrame

        on_spill_read(len(blob))
        frame = LazyPageFrame(blob)
        page = frame.to_page(capacity=_round_capacity(max(frame.nrows, 1)))
        cols = tuple(
            Column(c.type, c.data, c.valid, t.dictionary, c.lengths,
                   c.elem_valid, c.children)
            if t.dictionary is not None
            else c
            for c, t in zip(page.columns, template.page.columns)
        )
        return Relation(Page(cols, page.active), template.symbols)

    @staticmethod
    def _spill_parts(total_bytes: int, thresh: int) -> int:
        nparts = 2
        while nparts * thresh < total_bytes and nparts < 64:
            nparts *= 2
        return nparts

    def _spill_partitioned_join(
        self, node: JoinNode, left: Relation, right: Relation,
        total_bytes: int, thresh: int,
    ) -> Relation:
        nparts = self._spill_parts(total_bytes, thresh)
        lkeys = tuple(l for l, _ in node.criteria)
        rkeys = tuple(r for _, r in node.criteria)
        lparts = self._hash_partition_spill(left, lkeys, nparts)
        rparts = self._hash_partition_spill(right, rkeys, nparts)
        outs: List[Relation] = []
        for lb, rb in zip(lparts, rparts):
            outs.append(
                self._join_relations(node, self._unspill(lb, left), self._unspill(rb, right))
            )
        page = _concat_pages([o.page for o in outs])
        return Relation(page, outs[0].symbols)

    def _spill_partitioned_aggregate(
        self, rel: Relation, node: AggregationNode, total_bytes: int, thresh: int
    ) -> Relation:
        """Partitioned aggregation under memory pressure (ref:
        SpillableHashAggregationBuilder.java): groups are disjoint across hash
        partitions, so per-partition aggregation outputs concatenate."""
        nparts = self._spill_parts(total_bytes, thresh)
        parts = self._hash_partition_spill(rel, node.group_keys, nparts)
        outs: List[Relation] = []
        for blob in parts:
            outs.append(
                aggregate_relation(
                    self._unspill(blob, rel), node, self.types, self._pallas_mode()
                )
            )
        page = _concat_pages([o.page for o in outs])
        return Relation(page, outs[0].symbols)

    def _dynamic_filter_predicate(self, node: JoinNode, build: Relation):
        """min/max range of the build keys as an IR predicate on probe symbols."""
        from ..sql.ir import Call as IrCall, Constant as IrConstant
        from ..spi.types import BOOLEAN as B, is_string as _is_str

        conjuncts = []
        for probe_sym, build_sym in node.criteria:
            bc = build.column_for(build_sym)
            if _is_str(bc.type):
                continue  # code spaces differ across dictionaries; skip strings
            w = build.page.active & bc.valid
            n = int(jnp.sum(w.astype(jnp.int32)))
            if n == 0:
                continue
            info_min = jnp.where(w, bc.data, bc.data.max()).min()
            info_max = jnp.where(w, bc.data, bc.data.min()).max()
            lo, hi = bc.type.storage_dtype.type(info_min).item(), bc.type.storage_dtype.type(info_max).item()
            ptype = self.types[probe_sym]
            ref = Reference(probe_sym, ptype)
            conjuncts.append(
                IrCall(
                    "$and",
                    (
                        IrCall("$gte", (ref, IrConstant(bc.type, lo)), B),
                        IrCall("$lte", (ref, IrConstant(bc.type, hi)), B),
                    ),
                    B,
                )
            )
        if not conjuncts:
            return None
        pred = conjuncts[0]
        for c in conjuncts[1:]:
            pred = IrCall("$and", (pred, c), B)
        return pred

    def _exec_SemiJoinNode(self, node: SemiJoinNode) -> Relation:
        source = self.eval(node.source)
        filtering = self.eval(node.filtering_source)
        skey = source.column_for(node.source_key)
        fkey = filtering.column_for(node.filtering_key)
        lut = _translate_lut(skey.dictionary, fkey.dictionary)
        page = _jit_semijoin(
            skey, fkey, lut, source.page, filtering.page.active, node.null_aware
        )
        return Relation(page, source.symbols + (node.output,))

    # ------------------------------------------------------------- sort/limit

    def _exec_SortNode(self, node: SortNode) -> Relation:
        rel = self.eval(node.source)
        if self.allow_host_sync:
            rel = _maybe_compact(rel)
        page = _jit_sort(node.orderings, rel.symbols, None, rel.page)
        return Relation(page, rel.symbols)

    def _exec_TopNNode(self, node: TopNNode) -> Relation:
        rel = self.eval(node.source)
        if self.allow_host_sync:
            rel = _maybe_compact(rel)
        page = _jit_sort(node.orderings, rel.symbols, node.count, rel.page)
        return Relation(page, rel.symbols)

    def _exec_VectorTopNNode(self, node) -> Relation:
        rel = self.eval(node.source)
        if self.allow_host_sync:
            rel = _maybe_compact(rel)
        return self.run_vector_topn(node, rel)

    def run_vector_topn(self, node, rel: Relation) -> Relation:
        """Tensor plane: the fused scores->top-k program over an already
        evaluated (and compacted) source — the scoring projection's closures
        and the stable top-k permutation dispatch as ONE device program (one
        launch where the serial pair books two). Shared by the serial walk
        and the vector serving tier's per-lane fallback
        (runtime/device_scheduler.py), so both paths compute the same bytes.
        A runtime failure falls back to the serial Project + TopN pair with
        a labeled counter tick; the query still answers."""
        from ..ops import tensor as T
        from ..planner.plan import ProjectNode as _PN

        symbols = tuple(s for s, _ in node.assignments)
        try:
            compiled = self._compile_assignments(node.assignments, rel)
            info = T.assignments_vector_info(node.assignments) or (0, 0)
            with T.topk_fusion_span(rel.capacity, info[1], node.count):
                page = _jit_vector_topn(
                    compiled, symbols, node.orderings, node.count,
                    rel.env(), rel.page,
                )
            T.on_vector_kernel()
            out = Relation(page, symbols)
            self._maybe_sample_ann_recall(node, out)
            return out
        except Exception:
            T.on_topk_fallback("kernel_error")
            proj = self._project_relation(
                _PN(source=node.source, assignments=node.assignments), rel
            )
            page = _jit_sort(
                node.orderings, proj.symbols, node.count, proj.page
            )
            return Relation(page, proj.symbols)

    def _maybe_sample_ann_recall(self, node, approx: Relation) -> None:
        """ANN recall monitoring: re-run a deterministic sample of pruned
        vector top-k executions against the unpruned exact oracle (the SAME
        fused program over ALL cluster splits) and record measured recall@k
        to the system.runtime.ann_recall ring. Measurement only — the
        sampled query's result is untouched, and a failed oracle run never
        fails the query."""
        from ..ops import tensor as T

        if not self.allow_host_sync:
            return
        stats = self.ann_probe_stats.get(id(node.source))
        if stats is None or stats["probed"] >= stats["total"]:
            return
        try:
            rate = float(self.session.get("ann_recall_sample_rate") or 0.0)
        except KeyError:
            rate = 0.0
        if rate <= 0.0 or not T.ann_sample_due(rate):
            return
        try:
            import dataclasses as _dc

            scan = node.source
            handle = scan.table
            exact_handle = _dc.replace(
                handle,
                connector_handle={
                    k: v for k, v in handle.connector_handle.items()
                    if k != "ann_probe"
                } or None,
            )
            oracle_rel = self._exec_TableScanNode(
                _dc.replace(scan, table=exact_handle)
            )
            oracle_rel = _maybe_compact(oracle_rel)
            symbols = tuple(s for s, _ in node.assignments)
            compiled = self._compile_assignments(node.assignments, oracle_rel)
            exact_page = _jit_vector_topn(
                compiled, symbols, node.orderings, node.count,
                oracle_rel.env(), oracle_rel.page,
            )
            from collections import Counter

            got = Counter(_result_row_keys(approx.page))
            want = Counter(_result_row_keys(exact_page))
            k_eff = sum(want.values())
            recall = (
                sum((got & want).values()) / k_eff if k_eff else 1.0
            )
            T.record_ann_recall(
                str(scan.table.schema_table), node.count, stats["nprobe"],
                recall, stats["probed"], stats["total"],
            )
        except Exception:
            T.on_ann_oracle_error()  # monitoring only, never a query failure

    def _exec_LimitNode(self, node: LimitNode) -> Relation:
        rel = self.eval(node.source)
        page = _jit_limit(node.count, node.offset, rel.page)
        return Relation(page, rel.symbols)

    # ------------------------------------------------------------------ misc

    def _exec_TableFunctionNode(self, node: TableFunctionNode) -> Relation:
        if node.function == "sequence":
            start, stop, step = node.args
            n = max((stop - start) // step + 1, 0)
            cap = _round_capacity(max(n, 1), base=16)
            data = jnp.int64(start) + jnp.arange(cap, dtype=jnp.int64) * jnp.int64(step)
            active = jnp.arange(cap) < n
            col = Column(BIGINT, data, active)
            return Relation(Page((col,), active), node.symbols)
        raise ExecutionError(f"table function {node.function} not implemented")

    def _exec_ValuesNode(self, node: ValuesNode) -> Relation:
        n = len(node.rows)
        cols = []
        for i, sym in enumerate(node.symbols):
            type_ = self.types[sym]
            vals = [row[i] for row in node.rows]
            from ..spi.types import VectorType as _VecT

            if is_string(type_):
                col = Column.from_strings(vals, type_)
            elif isinstance(type_, _VecT):
                # vector literals (folded CAST(ARRAY[...] AS vector(n))):
                # host tuples -> the dense (rows, n) lane buffer
                dim = type_.dimension
                arr = np.zeros((len(vals), dim), dtype=np.float64)
                valid = np.zeros(len(vals), dtype=np.bool_)
                for j, v in enumerate(vals):
                    if v is None:
                        continue
                    if len(v) != dim:
                        raise ExecutionError(
                            f"vector literal of length {len(v)} for "
                            f"{type_.display()}"
                        )
                    arr[j] = np.asarray(v, dtype=np.float64)
                    valid[j] = True
                col = Column.from_numpy(type_, arr, valid)
            elif getattr(type_, "storage_lanes", None) == 2:
                # long decimals: python ints -> two int64 limbs
                from ..ops.int128 import np_from_ints

                arr = np_from_ints([0 if v is None else int(v) for v in vals])
                valid = np.array([v is not None for v in vals], dtype=np.bool_)
                col = Column.from_numpy(type_, arr, valid)
            else:
                arr = np.array(
                    [0 if v is None else v for v in vals], dtype=type_.storage_dtype
                )
                valid = np.array([v is not None for v in vals], dtype=np.bool_)
                col = Column.from_numpy(type_, arr, valid)
            cols.append(col)
        active = jnp.ones((max(n, 1),), dtype=jnp.bool_)
        if n == 0:
            active = jnp.zeros((1,), dtype=jnp.bool_)
            cols = [
                Column(
                    self.types[s],
                    jnp.zeros((1,), dtype=self.types[s].storage_dtype),
                    jnp.zeros((1,), dtype=jnp.bool_),
                )
                for s in node.symbols
            ]
        return Relation(Page(tuple(cols), active), node.symbols)

    def _exec_UnionNode(self, node: UnionNode) -> Relation:
        pages = []
        for inp, in_syms in zip(node.inputs, node.symbol_mapping):
            rel = self.eval(inp)
            cols = tuple(rel.column_for(s) for s in in_syms)
            pages.append(Page(cols, rel.page.active))
        merged = _concat_union_pages(pages, [self.types[s] for s in node.symbols])
        return Relation(merged, node.symbols)

    def _exec_EnforceSingleRowNode(self, node: EnforceSingleRowNode) -> Relation:
        rel = self.eval(node.source)
        n = int(jnp.sum(rel.page.active.astype(jnp.int32)))
        if n > 1:
            raise ExecutionError("scalar subquery returned more than one row")
        if n == 1:
            return rel
        # empty -> single null row (SQL scalar subquery semantics)
        cols = tuple(
            _null_column(c, 1) for c in rel.page.columns
        )
        return Relation(Page(cols, jnp.ones((1,), dtype=jnp.bool_)), rel.symbols)

    def _exec_ExchangeNode(self, node: ExchangeNode) -> Relation:
        # single-process local execution: exchanges are pass-through;
        # the distributed engine (parallel/) overrides this.
        return self.eval(node.source)

    def _exec_WindowNode(self, node: WindowNode) -> Relation:
        from .window import execute_window

        rel = self.eval(node.source)
        return execute_window(self, rel, node)

    def _exec_PatternRecognitionNode(self, node) -> Relation:
        from .match_recognize import execute_match_recognize

        rel = self.eval(node.source)
        return execute_match_recognize(self, rel, node)


# --------------------------------------------------------------------------- #
# aggregation core (shared with distinct path)
# --------------------------------------------------------------------------- #


def _load_splits(provider, splits, col_indexes, session) -> List[Page]:
    """Intra-node source parallelism (the LocalExchange.java:66 /
    AddLocalExchanges analogue for this engine): the device is ONE driver, so
    local parallelism lives at the source boundary — `task_concurrency` host
    threads decode/generate splits concurrently, overlapping host work with
    each other and with device uploads (numpy releases the GIL; jnp.asarray
    dispatch is async). Split order is preserved, so connector-declared sort
    order survives exactly as in the serial path."""
    try:
        workers = int(session.get("task_concurrency") or 1)
    except KeyError:
        workers = 1
    if workers <= 1 or len(splits) <= 1:
        return [provider.create_page_source(sp, col_indexes) for sp in splits]
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=min(workers, len(splits))) as pool:
        return list(
            pool.map(lambda sp: provider.create_page_source(sp, col_indexes), splits)
        )


def _maybe_compact(rel: Relation, density: int = 4, min_cap: int = 8192) -> Relation:
    """Drop inactive rows when fewer than 1/``density`` of capacity is live.

    One stable single-key sort pass (active rows first, no gathers) replacing
    the many full-capacity sort passes a sparse group-by/sort would otherwise
    pay. Host-syncs the active count — callers are pipeline breakers that
    already host-sync their output capacity."""
    cap = rel.capacity
    if cap <= min_cap:
        return rel
    n = int(jnp.sum(rel.page.active.astype(jnp.int32)))
    if n * density > cap:
        return rel
    new_cap = _round_capacity(max(n, 1))
    page = _jit_compact(new_cap, rel.page)
    # compaction is a stable partition by activity — order preserved
    return Relation(page, rel.symbols, rel.sorted_by)


@partial(kernelcost.jit, static_argnums=(0,))
def _jit_compact(new_cap: int, page: Page) -> Page:
    if any(c.children or c.data.ndim > 1 for c in page.columns):
        # nested lanes can't ride lax.sort payloads (shape mismatch) —
        # permutation-gather instead
        perm = jnp.argsort((~page.active).astype(jnp.int8))
        cols = tuple(_slice_column(_permute_column(c, perm), new_cap) for c in page.columns)
        return Page(cols, page.active[perm][:new_cap])
    key = (~page.active).astype(jnp.int8)
    payloads: List[jnp.ndarray] = []
    for c in page.columns:
        payloads.append(c.data)
        payloads.append(c.valid)
    payloads.append(page.active)
    _, sorted_payloads = K.cosort([key], payloads)
    cols = tuple(
        Column(
            c.type,
            sorted_payloads[2 * i][:new_cap],
            sorted_payloads[2 * i + 1][:new_cap],
            c.dictionary,
        )
        for i, c in enumerate(page.columns)
    )
    return Page(cols, sorted_payloads[-1][:new_cap])


def _needed_agg_symbols(node: AggregationNode) -> Tuple[str, ...]:
    needed: List[str] = []
    for k in node.group_keys:
        if k not in needed:
            needed.append(k)
    for _, a in node.aggregations:
        for s in a.args:
            if s not in needed:
                needed.append(s)
        if a.filter and a.filter not in needed:
            needed.append(a.filter)
    return tuple(needed)


# Functions the direct-indexed path supports (approx_distinct and DISTINCT
# need per-group value sorting and stay on the sort path).
_DIRECT_AGG_FUNCS = frozenset(
    {
        "count", "count_if", "sum", "avg", "min", "max", "bool_and", "every",
        "bool_or", "arbitrary", "any_value", "stddev", "stddev_samp",
        "stddev_pop", "variance", "var_samp", "var_pop", "$fsum", "$fsumsq",
    }
)
# Above this many candidate groups the [G, n] broadcast reduction loses to the
# sort path (each extra group re-reads the data lane-parallel).
DIRECT_GROUP_LIMIT = 256


def _direct_agg_domains(rel: Relation, node: AggregationNode):
    """Static per-key domain sizes when every group key has a small, statically
    known domain (dictionary-coded strings, booleans) — the condition for the
    sort-free direct-indexed aggregation (BigintGroupByHash fast-path analogue,
    GroupByHash.java:82-98). Returns None when the sort path must be used."""
    if not node.group_keys:
        return None
    if any(
        a.function not in _DIRECT_AGG_FUNCS or a.distinct
        for _, a in node.aggregations
    ):
        return None
    domains = []
    for k in node.group_keys:
        c = rel.column_for(k)
        if c.dictionary is not None:
            domains.append(len(c.dictionary) + 1)  # +1: null slot
        elif c.type == BOOLEAN:
            domains.append(3)
        else:
            return None
    total = 1
    for d in domains:
        total *= d
    if not 1 <= total <= DIRECT_GROUP_LIMIT:
        return None
    return tuple(domains)


def aggregate_relation(
    rel: Relation,
    node: AggregationNode,
    types: Dict[str, Type],
    pallas_mode: str = "off",
) -> Relation:
    """Grouped aggregation, two strategies (ref GroupByHash.java:82-98 — the
    engine picks a hash strategy per key shape; here per domain knowledge):

    - direct-indexed (small static key domains): gid computed elementwise from
      dictionary codes, one fused bandwidth-bound pass — no sort, no host sync.
    - sort-based: (1) co-sort the needed columns by the group keys inside
      lax.sort (no permutation gathers — they cost ~60ns/element on TPU),
      host-sync the group count, (2) reduction program with a bucketed static
      output capacity, segment sums via cumsum-at-boundaries."""
    domains = _direct_agg_domains(rel, node)
    if domains is not None:
        page = _jit_direct_aggregate(
            node.group_keys, node.aggregations, domains, rel.symbols, rel.page,
            pallas_mode,
        )
        return Relation(page, node.group_keys + tuple(s for s, _ in node.aggregations))
    # sparse inputs (a selective filter upstream) would drag dead rows through
    # every multi-pass sort — compact first (this path host-syncs anyway).
    # ref: Trino pages are always dense (PageProcessor compacts per batch);
    # our mask design defers compaction to exactly these pipeline breakers.
    rel = _maybe_compact(rel)
    # aggregate ORDER BY (array_agg(x ORDER BY y), listagg WITHIN GROUP): the
    # group sort is stable, so pre-sorting the whole relation by the aggregate
    # ordering fixes each group's element order (ref: AggregationNode
    # orderingScheme -> operator/aggregation ordered accumulators)
    orderings: Tuple = ()
    for _, a in node.aggregations:
        if a.ordering:
            if orderings and a.ordering != orderings:
                raise ExecutionError(
                    "multiple distinct aggregate ORDER BY clauses in one "
                    "aggregation are not supported"
                )
            orderings = a.ordering
    if orderings:
        rel = Relation(_jit_sort(orderings, rel.symbols, None, rel.page), rel.symbols)
    needed = _needed_agg_symbols(node)
    if node.group_keys:
        # pre-sorted fast path: input ordered on the first group key skips
        # the multi-pass group sort entirely (self-verifying, see
        # _jit_presorted_group)
        sorted_page = None
        if rel.sorted_by and rel.sorted_by[0] == node.group_keys[0]:
            if any(a.function in _RESORT_AGGS for _, a in node.aggregations):
                # these aggregates re-sort internally and rely on group
                # segments staying at fixed positions — that needs a dense
                # active prefix, so compact any interleaved inactive rows
                rel = _force_dense(rel)
            p, ng, n_grp, viol = _jit_presorted_group(
                node.group_keys, needed, rel.symbols, rel.page
            )
            if not bool(viol):
                sorted_page, new_group, num_groups = p, ng, n_grp
        if sorted_page is None:
            sorted_page, new_group, num_groups = _jit_group_sort(
                node.group_keys, needed, rel.symbols, rel.page
            )
        out_cap = min(
            _round_capacity(max(int(num_groups), 1), base=16), max(rel.capacity, 16)
        )
    else:
        # global aggregation: no sort at all — select the needed columns
        cols = tuple(rel.column_for(s) for s in needed)
        sorted_page = Page(cols, rel.page.active)
        new_group, num_groups, out_cap = None, 1, 1
    # lane-valued aggregates (array_agg, map_agg, histogram, multimap_agg,
    # listagg) need a static lane width = the largest group's row count
    # (host-synced like num_groups; ref operator/aggregation/ArrayAggregation)
    agg_w = 0
    if any(a.function in _LANE_AGGS for _, a in node.aggregations):
        if node.group_keys:
            agg_w = int(_jit_max_run(new_group, sorted_page.active))
        else:
            agg_w = int(jnp.sum(sorted_page.active.astype(jnp.int32)))
        agg_w = _round_capacity(max(agg_w, 1), base=8)
    page = _jit_aggregate(
        node.group_keys,
        node.aggregations,
        needed,
        out_cap,
        agg_w,
        sorted_page,
        new_group,
        num_groups if node.group_keys else jnp.int32(1),
    )
    # host finalization for string/nested-valued aggregates: listagg joins the
    # gathered lanes into new dictionary strings; multimap_agg regroups the
    # (key, value) lanes into map<K, array(V)> (strings/nested construction is
    # a host concern in this engine — same as dictionary LUT transforms)
    fin = [
        i
        for i, (_, a) in enumerate(node.aggregations)
        if a.function in ("listagg", "multimap_agg")
    ]
    if fin:
        cols = list(page.columns)
        nk = len(node.group_keys)
        for i in fin:
            _, agg = node.aggregations[i]
            if agg.function == "listagg":
                sep = ""
                if len(agg.args) > 1:
                    sepcol = rel.column_for(agg.args[1])
                    vals = sepcol.decode(np.asarray(rel.page.active))
                    nonnull = [v for v in vals if v is not None]
                    sep = nonnull[0] if nonnull else ""
                cols[nk + i] = _finalize_listagg(cols[nk + i], sep)
            else:
                cols[nk + i] = _finalize_multimap(cols[nk + i], agg.output_type)
        page = Page(tuple(cols), page.active)
    out_symbols = node.group_keys + tuple(s for s, _ in node.aggregations)
    return Relation(page, out_symbols)


# aggregates whose per-group state is a padded lane grid [out_cap, agg_w]
_LANE_AGGS = frozenset(
    {"array_agg", "map_agg", "multimap_agg", "histogram", "listagg"}
)

# aggregates whose evaluation re-sorts rows by gid and reuses the group
# bounds positionally (distinct-count cosorts, percentile rank gathers,
# map-lane scatters) — the presorted fast path must hand them a dense
# active prefix
_RESORT_AGGS = frozenset(
    {
        "approx_distinct", "approx_percentile", "tdigest_agg", "qdigest_agg",
        "map_agg", "histogram", "multimap_agg", "listagg",
    }
)


def _force_dense(rel: Relation) -> Relation:
    """Compact unless active rows already form a dense prefix."""
    n = int(jnp.sum(rel.page.active.astype(jnp.int32)))
    if n == rel.capacity or bool(jnp.all(rel.page.active[:n])):
        return rel
    page = _jit_compact(_round_capacity(max(n, 1)), rel.page)
    return Relation(page, rel.symbols, rel.sorted_by)


def _finalize_listagg(col: Column, sep: str) -> Column:
    """listagg lanes -> joined strings with a fresh dictionary (host).

    Rows outside the produced group count decode with padded lanes (None
    elements) — skip those elements; the page's active mask hides the rows."""
    lists = col.children[0].decode(None)
    strings = [
        None if x is None else sep.join(e for e in x if e is not None)
        for x in lists
    ]
    return Column.from_strings(strings, col.type)


def _finalize_multimap(col: Column, out_type) -> Column:
    """multimap_agg (key, value) lanes -> map<K, array(V)> (host regroup)."""
    karr, varr = col.children
    klists = karr.decode(None)
    vlists = varr.decode(None)
    dicts: List[Optional[dict]] = []
    for ks, vs in zip(klists, vlists):
        if ks is None:
            dicts.append(None)
            continue
        d: dict = {}
        for k, v in zip(ks, vs):
            if k is not None:
                d.setdefault(k, []).append(v)
        dicts.append(d)
    return Column.from_nested(out_type, dicts)


def _presorted_group_impl(group_keys, needed, symbols, page: Page):
    """Grouping WITHOUT sorting for inputs already ordered on the first group
    key (ref: the reference's streaming aggregation over pre-sorted local
    properties — AddExchanges keeps grouped/sorted data properties so
    HashAggregationOperator can stream). Rows stay in place; inactive rows may
    be interleaved (last-active-prev scans bridge the gaps).

    Returns (page over ``needed``, new_group, num_groups, violation) where
    ``violation`` is True when the data is NOT actually sorted on key1 (any
    active row's key1 decreases) or secondary keys vary within a key1 run —
    the caller falls back to the sorting path, so a wrong or stale sortedness
    declaration can never produce wrong results."""
    rel = Relation(page, symbols)
    active = page.active
    k1 = rel.column_for(group_keys[0])
    k1n = jnp.where(k1.valid, K.order_key(k1.data), jnp.int64(K.INT64_MAX))
    prev_k1, has_prev = K.last_active_prev(k1n, active)
    first_active = active & ~has_prev
    new_group = active & (first_active | (k1n != prev_k1))
    violation = jnp.any(active & has_prev & (k1n < prev_k1))
    for k in group_keys[1:]:
        c = rel.column_for(k)
        kn = jnp.where(c.valid, K.order_key(c.data), jnp.int64(K.INT64_MAX))
        prev_k, _ = K.last_active_prev(kn, active)
        # a secondary key changing inside a key1 run means the run holds
        # multiple groups interleaved — only a sort can separate them
        violation = violation | jnp.any(
            active & has_prev & ~new_group & (kn != prev_k)
        )
    num_groups = jnp.sum(new_group.astype(jnp.int32))
    cols = tuple(rel.column_for(s) for s in needed)
    return Page(cols, active), new_group, num_groups, violation


_jit_presorted_group = partial(kernelcost.jit, static_argnums=(0, 1, 2))(
    _presorted_group_impl
)


def _group_sort_impl(group_keys, needed, symbols, page: Page):
    """Phase 1: co-sort needed columns by group keys; detect group boundaries.
    Returns (sorted Page over ``needed`` symbols, new_group mask, num_groups).
    Plain body — ops/megakernels.py re-traces it inside the fused join
    kernel's sort-path aggregation stage (bit-identity by construction)."""
    rel = Relation(page, symbols)
    pass_keys: List[jnp.ndarray] = []
    # least-significant first; each key contributes (norm, validity-bit) passes
    for k in reversed(group_keys):
        c = rel.column_for(k)
        if c.data.ndim == 2:  # Int128 limbs: lo pass then hi pass
            from ..ops import int128 as i128

            h, l = i128.order_key_pair(c.data)
            pass_keys.append(jnp.where(c.valid, l, jnp.int64(K.INT64_MAX)))
            pass_keys.append(jnp.where(c.valid, h, jnp.int64(K.INT64_MAX)))
        else:
            norm = jnp.where(c.valid, K.order_key(c.data), jnp.int64(K.INT64_MAX))
            pass_keys.append(norm)
        pass_keys.append(c.valid.astype(jnp.int8))
    pass_keys.append((~page.active).astype(jnp.int8))  # inactive rows last

    payloads: List[jnp.ndarray] = []
    lanes: List[int] = []  # payloads per column's data (Int128 limbs ride as 2)
    for s in needed:
        c = rel.column_for(s)
        if c.data.ndim == 2:
            for j in range(c.data.shape[1]):
                payloads.append(c.data[:, j])
            lanes.append(c.data.shape[1])
        else:
            payloads.append(c.data)
            lanes.append(1)
        payloads.append(c.valid)
    payloads.append(page.active)

    sorted_keys, sorted_payloads = K.cosort(pass_keys, payloads)
    active_s = sorted_payloads[-1]
    cap = page.capacity
    diff = jnp.zeros(cap, dtype=bool)
    for k in sorted_keys[:-1]:
        diff = diff | (k != jnp.roll(k, 1))
    first = jnp.zeros(cap, dtype=bool).at[0].set(True)
    prev_active = jnp.roll(active_s, 1).at[0].set(False)
    new_group = active_s & (first | diff | ~prev_active)
    num_groups = jnp.sum(new_group.astype(jnp.int32))

    cols = []
    pos = 0
    for s, nl in zip(needed, lanes):
        c = rel.column_for(s)
        if nl == 1:
            data = sorted_payloads[pos]
        else:
            data = jnp.stack(sorted_payloads[pos : pos + nl], axis=-1)
        cols.append(Column(c.type, data, sorted_payloads[pos + nl], c.dictionary))
        pos += nl + 1
    return Page(tuple(cols), active_s), new_group, num_groups


_jit_group_sort = partial(kernelcost.jit, static_argnums=(0, 1, 2))(_group_sort_impl)


@kernelcost.jit
def _jit_max_run(new_group, active):
    """Largest group's row count (group-sorted input): distance from each row
    to its group's first row, maxed over active rows."""
    n = new_group.shape[0]
    idx = jnp.arange(n)
    start_pos = jax.lax.associative_scan(jnp.maximum, jnp.where(new_group, idx, -1))
    return jnp.max(jnp.where(active, idx - start_pos + 1, 0))


def _aggregate_impl(
    group_keys: Tuple[str, ...],
    aggregations: Tuple[Tuple[str, Aggregation], ...],
    symbols: Tuple[str, ...],
    out_cap: int,
    agg_w: int,  # static array_agg lane width (0 when unused)
    page: Page,  # already sorted by group keys (or unsorted for global)
    new_group,
    num_groups,
) -> Page:
    rel = Relation(page, symbols)
    global_agg = len(group_keys) == 0
    active_s = page.active
    n = page.capacity

    bounds = None
    gid = None
    if not global_agg:
        starts = K.boundary_positions(new_group, out_cap)  # n-padded
        ends = jnp.concatenate([starts[1:], jnp.array([n])]) - 1
        bounds = (starts, ends)
        safe_starts = jnp.clip(starts, 0, n - 1)
        # min/max/arbitrary/approx_* need dense gids (scatter/sort paths)
        if any(
            a.function
            in (
                "min", "max", "arbitrary", "any_value", "approx_distinct",
                "approx_percentile", "tdigest_agg", "qdigest_agg", "array_agg",
                "map_agg", "histogram", "multimap_agg", "listagg", "min_by",
                "max_by", "bitwise_and_agg", "bitwise_or_agg",
                "bitwise_xor_agg",
            )
            for _, a in aggregations
        ):
            # max(…, 0): presorted (unsorted-layout) inputs may have inactive
            # rows before the first group start; they never participate but
            # their gid must stay a valid segment id
            gid = jnp.maximum(
                K.cumsum(new_group.astype(jnp.int32)) - 1, 0
            ).astype(jnp.int32)

    out_cols: List[Column] = []
    # group key outputs: gather the first row of each group (out_cap gathers)
    for k in group_keys:
        c = rel.column_for(k)
        in_range = jnp.arange(out_cap) < num_groups
        out_cols.append(
            Column(
                c.type,
                c.data[safe_starts],
                c.valid[safe_starts] & in_range,
                c.dictionary,
            )
        )

    if global_agg:
        # exactly one output row even over empty input
        group_exists = jnp.ones((1,), dtype=jnp.bool_)
    else:
        group_exists = jnp.arange(out_cap) < num_groups

    def reduce_fn(vals, w, kind):
        if kind in ("sum", "count"):
            return K.segment_reduce(vals, w, gid, out_cap, kind, new_group, bounds)
        g = gid if gid is not None else jnp.zeros(active_s.shape, dtype=jnp.int32)
        return K.segment_reduce(vals, w, g, out_cap, kind)

    def first_fn(vals, w):
        g = gid if gid is not None else jnp.zeros(active_s.shape, dtype=jnp.int32)
        return K.scatter_first(vals, w, g, out_cap)

    def distinct_count_fn(vals_s, w):
        # count distinct via sorted adjacency within each group; rows are
        # group-sorted so re-sorting by (gid primary, value) keeps each group's
        # segment at the same positions (stable sort) — bounds stay valid
        g = gid if gid is not None else jnp.zeros(active_s.shape, dtype=jnp.int32)
        keys2, payloads2 = K.cosort([K.order_key(vals_s), g.astype(jnp.int64)], [w])
        v2 = keys2[0]
        g2 = keys2[1].astype(jnp.int32)
        w2 = payloads2[0]
        prev_same = (v2 == jnp.roll(v2, 1)) & (g2 == jnp.roll(g2, 1))
        prev_same = prev_same.at[0].set(False)
        ws = w2 & ~prev_same
        return K.segment_reduce(
            ws.astype(jnp.int64), ws, g2, out_cap, "count", new_group, bounds
        )

    # HLL replaces the exact cosort when the register state fits; with MANY
    # groups each group has few rows, so the exact path is the cheap one anyway
    # (ref operator/aggregation/ApproximateCountDistinctAggregations)
    hll_fn = None
    if out_cap * (1 << K.HLL_BITS) <= (1 << 23):

        def hll_fn(vals_s, w):  # noqa: F811
            g = gid if gid is not None else jnp.zeros(active_s.shape, dtype=jnp.int32)
            return K.hll_estimate(K.hll_registers(vals_s, w, g, out_cap))

    def percentile_fn(vals_s, w, q_g, nonempty):
        # exact per-group quantile: re-sort by (gid primary, participates,
        # value); stable sort keeps each group's segment at the same positions
        # so ``bounds`` starts stay valid, then one gather at the rank offset
        g = gid if gid is not None else jnp.zeros(active_s.shape, dtype=jnp.int32)
        _, payloads2 = K.cosort(
            [K.order_key(vals_s), (~w).astype(jnp.int8), g.astype(jnp.int64)],
            [vals_s],
        )
        v2 = payloads2[0]
        cap_n = active_s.shape[0]
        starts = bounds[0] if bounds is not None else jnp.zeros((1,), dtype=jnp.int64)
        # clamp the rank to the group's participant prefix: an out-of-range q
        # must never gather across the group boundary
        idx = jnp.floor(
            q_g * jnp.maximum(nonempty - 1, 0).astype(jnp.float64)
        ).astype(jnp.int64)
        idx = jnp.clip(idx, 0, jnp.maximum(nonempty - 1, 0))
        pos = jnp.clip(starts.astype(jnp.int64) + idx, 0, cap_n - 1)
        return v2[pos]

    def tdigest_fn(vals_s, w, nonempty):
        # fixed-K t-digest (TDigestAggregationFunction.java:33, TPU-native):
        # participants sort to each group's segment front; the within-group
        # rank maps through the k1 (arcsine) scale so centroid resolution
        # biases toward the tails, then ONE segment-sum per lane builds all
        # groups' centroids at once
        from ..spi.types import TDIGEST_CENTROIDS as KC

        g = gid if gid is not None else jnp.zeros(active_s.shape, dtype=jnp.int32)
        _, payloads2 = K.cosort(
            [K.order_key(vals_s), (~w).astype(jnp.int8), g.astype(jnp.int64)],
            [vals_s, w],
        )
        v2, w2 = payloads2
        cap_n = active_s.shape[0]
        starts = bounds[0] if bounds is not None else jnp.zeros((1,), dtype=jnp.int64)
        rank = jnp.arange(cap_n, dtype=jnp.int64) - starts[g].astype(jnp.int64)
        n_g = jnp.maximum(nonempty[g], 1).astype(jnp.float64)
        q = (rank.astype(jnp.float64) + 0.5) / n_g
        scale = 0.5 + jnp.arcsin(jnp.clip(2.0 * q - 1.0, -1.0, 1.0)) / jnp.pi
        bucket = jnp.clip((scale * KC).astype(jnp.int32), 0, KC - 1)
        seg = jnp.where(w2, g * KC + bucket, out_cap * KC).astype(jnp.int32)
        sums = jax.ops.segment_sum(
            jnp.where(w2, v2.astype(jnp.float64), 0.0), seg,
            num_segments=out_cap * KC + 1,
        )[: out_cap * KC].reshape(out_cap, KC)
        cnts = jax.ops.segment_sum(
            w2.astype(jnp.float64), seg, num_segments=out_cap * KC + 1
        )[: out_cap * KC].reshape(out_cap, KC)
        means = jnp.where(cnts > 0, sums / jnp.maximum(cnts, 1.0), 0.0)
        return jnp.concatenate([means, cnts], axis=-1)

    def array_agg_fn(vals_s, part, elem_ok, dictionary):
        # scatter each participating row into its group's lane grid
        # [out_cap, agg_w]; lane index = rank among the group's participants
        n = active_s.shape[0]
        g = gid if gid is not None else jnp.zeros((n,), dtype=jnp.int32)
        starts = (
            jnp.clip(bounds[0], 0, n - 1)
            if bounds is not None
            else jnp.zeros((1,), dtype=jnp.int64)
        )
        c = K.cumsum(part.astype(jnp.int32))
        spg = starts[g]
        rank = c - (c[spg] - part[spg].astype(jnp.int32)) - 1
        flat = jnp.where(
            part & (rank < agg_w), g.astype(jnp.int64) * agg_w + rank, out_cap * agg_w
        ).astype(jnp.int32)
        zeros = jnp.zeros((out_cap * agg_w + 1,), dtype=vals_s.dtype)
        data = zeros.at[flat].set(vals_s, mode="drop")[:-1].reshape(out_cap, agg_w)
        evf = jnp.zeros((out_cap * agg_w + 1,), dtype=jnp.bool_)
        ev = evf.at[flat].set(elem_ok, mode="drop")[:-1].reshape(out_cap, agg_w)
        lengths = jnp.minimum(
            reduce_fn(part.astype(jnp.int64), part, "count"), agg_w
        ).astype(jnp.int32)
        return data, ev, lengths

    def map_lanes_fn(kvals, part, vvals, vok, kind):
        """Distinct-key lane grids for map_agg/histogram: re-sort each group's
        participants by key (stable — group segments stay at the same
        positions, so ``bounds`` stays valid), mark the first row of each
        (group, key) run, and scatter keys/values/counts into [out_cap, agg_w]
        (ref operator/aggregation/MapAggAggregation, histogram/Histogram)."""
        n = active_s.shape[0]
        g = gid if gid is not None else jnp.zeros((n,), dtype=jnp.int32)
        starts = (
            jnp.clip(bounds[0], 0, n - 1)
            if bounds is not None
            else jnp.zeros((1,), dtype=jnp.int64)
        )
        payloads = [kvals, part] + ([vvals, vok] if vvals is not None else [])
        keys2, payloads2 = K.cosort(
            [K.order_key(kvals), (~part).astype(jnp.int8), g.astype(jnp.int64)],
            payloads,
        )
        k2, part2 = payloads2[0], payloads2[1]
        knorm2 = keys2[0]
        g2 = keys2[2].astype(jnp.int32)
        prev_same = (
            (knorm2 == jnp.roll(knorm2, 1))
            & (g2 == jnp.roll(g2, 1))
            & jnp.roll(part2, 1)
        )
        prev_same = prev_same.at[0].set(False)
        first = part2 & ~prev_same
        c = K.cumsum(first.astype(jnp.int32))
        spg = starts[g2]
        rank = c - (c[spg] - first[spg].astype(jnp.int32)) - 1
        in_lane = rank < agg_w
        oob = out_cap * agg_w
        flat_first = jnp.where(
            first & in_lane, g2.astype(jnp.int64) * agg_w + rank, oob
        ).astype(jnp.int32)
        kdata = (
            jnp.zeros((oob + 1,), dtype=kvals.dtype)
            .at[flat_first].set(k2, mode="drop")[:-1]
            .reshape(out_cap, agg_w)
        )
        kev = (
            jnp.zeros((oob + 1,), dtype=jnp.bool_)
            .at[flat_first].set(True, mode="drop")[:-1]
            .reshape(out_cap, agg_w)
        )
        lengths = (
            jnp.zeros((out_cap,), dtype=jnp.int32)
            .at[g2].add((first & in_lane).astype(jnp.int32), mode="drop")
        )
        if kind == "histogram":
            flat_all = jnp.where(
                part2 & in_lane, g2.astype(jnp.int64) * agg_w + rank, oob
            ).astype(jnp.int32)
            counts = (
                jnp.zeros((oob + 1,), dtype=jnp.int64)
                .at[flat_all].add(1, mode="drop")[:-1]
                .reshape(out_cap, agg_w)
            )
            return kdata, kev, counts, kev, lengths
        v2, vok2 = payloads2[2], payloads2[3]
        vdata = (
            jnp.zeros((oob + 1,), dtype=v2.dtype)
            .at[flat_first].set(v2, mode="drop")[:-1]
            .reshape(out_cap, agg_w)
        )
        vev = (
            jnp.zeros((oob + 1,), dtype=jnp.bool_)
            .at[flat_first].set(vok2, mode="drop")[:-1]
            .reshape(out_cap, agg_w)
        )
        return kdata, kev, vdata, vev, lengths

    for sym, agg in aggregations:
        out_type = agg.output_type
        col = _eval_aggregate(
            rel, agg, out_type, active_s, out_cap, reduce_fn, first_fn,
            distinct_count_fn, hll_fn, percentile_fn, tdigest_fn,
            array_agg_fn if agg_w else None,
            map_lanes_fn if agg_w else None,
            broadcast_fn=lambda g: g[
                gid if gid is not None
                else jnp.zeros(active_s.shape, dtype=jnp.int32)
            ],
        )
        out_cols.append(col)

    return Page(tuple(out_cols), group_exists)


_jit_aggregate = partial(kernelcost.jit, static_argnums=(0, 1, 2, 3, 4))(
    _aggregate_impl
)


def _direct_aggregate_impl(
    group_keys: Tuple[str, ...],
    aggregations: Tuple[Tuple[str, Aggregation], ...],
    domains: Tuple[int, ...],
    symbols: Tuple[str, ...],
    page: Page,
    pallas_mode: str = "off",
) -> Page:
    """Direct-indexed aggregation for small-domain group keys: gid computed
    elementwise from dictionary codes / bools — NO sort, NO scatter, no host
    sync; every aggregate is one fused [G, n] masked reduction. NULL keys take
    each domain's last slot. Empty key combinations stay inactive rows.
    (ref: BigintGroupByHash small-domain fast path, GroupByHash.java:82-98)"""
    rel = Relation(page, symbols)
    active = page.active
    G = 1
    for d in domains:
        G *= d
    gid = jnp.zeros(page.capacity, dtype=jnp.int32)
    for k, D in zip(group_keys, domains):
        c = rel.column_for(k)
        size = D - 1
        code = jnp.where(
            c.valid, jnp.clip(c.data.astype(jnp.int32), 0, max(size - 1, 0)), size
        )
        gid = gid * D + code

    out_cols: List[Column] = []
    # reconstruct key values from the flat group index (code order)
    codes_rev = []
    rem = jnp.arange(G, dtype=jnp.int32)
    for D in reversed(domains):
        codes_rev.append(rem % D)
        rem = rem // D
    for k, D, code_g in zip(group_keys, domains, codes_rev[::-1]):
        c = rel.column_for(k)
        out_cols.append(
            Column(c.type, code_g.astype(c.data.dtype), code_g < D - 1, c.dictionary)
        )

    # Pallas kernel tier (ops/pallas_kernels.py grouped sums): exact int64
    # sums/counts via 16-bit limb accumulation in native int32 — ONE data pass
    # per reduction instead of int64-emulated [G, n] reductions. min/max and
    # float sums stay on the XLA formulation.
    from ..ops import pallas_kernels as PK

    use_pallas = pallas_mode != "off" and G <= PK.PALLAS_GROUP_LIMIT
    interp = pallas_mode == "interpret"
    if pallas_mode == "tpu" and page.capacity < 32768:
        use_pallas = False  # launch overhead beats the win on tiny pages

    def reduce_fn(vals, w, kind):
        if use_pallas and kind == "count":
            return PK.grouped_sum_i32(w.astype(jnp.int32), w, gid, G, interpret=interp)
        if (
            use_pallas
            and kind == "sum"
            and not jnp.issubdtype(vals.dtype, jnp.floating)
        ):
            return PK.grouped_sum_i64(
                vals.astype(jnp.int64), w, gid, G, interpret=interp
            )
        return K.direct_group_reduce(vals, w, gid, G, kind)

    group_exists = reduce_fn(active.astype(jnp.int64), active, "count") > 0

    def first_fn(vals, w):
        return K.direct_group_first(vals, w, gid, G)

    for sym, agg in aggregations:
        out_cols.append(
            _eval_aggregate(
                rel, agg, agg.output_type, active, G, reduce_fn, first_fn,
                broadcast_fn=lambda g: g[gid],
            )
        )
    return Page(tuple(out_cols), group_exists)


# the plain body stays importable: ops/megakernels.py re-traces it INSIDE the
# fused join kernel (join -> partial-agg fusion), which is what makes the
# fused aggregation bit-identical to this serial formulation by construction
_jit_direct_aggregate = partial(kernelcost.jit, static_argnums=(0, 1, 2, 3, 5))(
    _direct_aggregate_impl
)


def _eval_aggregate(
    rel: Relation,
    agg: Aggregation,
    out_type: Type,
    active_s: jnp.ndarray,
    out_cap: int,
    reduce_fn,
    first_fn,
    distinct_count_fn=None,
    hll_fn=None,
    percentile_fn=None,
    tdigest_fn=None,
    array_agg_fn=None,
    map_lanes_fn=None,
    broadcast_fn=None,
) -> Column:
    """One aggregate, strategy-agnostic: ``reduce_fn(vals, weight, kind)``
    produces the per-group reduction (sort path: cumsum-at-boundaries /
    gid scatter; direct path: [G, n] masked reduce), ``first_fn`` an arbitrary
    participating row (ref: operator/aggregation/*, the Accumulator bodies)."""
    name = agg.function
    fmask = active_s
    if agg.filter is not None:
        fcol = rel.column_for(agg.filter)
        fmask = fmask & (fcol.data.astype(jnp.bool_) & fcol.valid)

    if name == "count" and not agg.args:
        data = reduce_fn(fmask.astype(jnp.int64), fmask, "count")
        return Column(BIGINT, data, jnp.ones((out_cap,), dtype=jnp.bool_))

    arg = rel.column_for(agg.args[0])
    vals_s = arg.data
    valid_s = arg.valid
    w = fmask & valid_s
    nonempty = reduce_fn(w.astype(jnp.int64), w, "count")

    if name == "count":
        return Column(BIGINT, nonempty, jnp.ones((out_cap,), dtype=jnp.bool_))
    if name == "count_if":
        ws = w & vals_s.astype(jnp.bool_)
        data = reduce_fn(ws.astype(jnp.int64), ws, "count")
        return Column(BIGINT, data, jnp.ones((out_cap,), dtype=jnp.bool_))
    if name in ("$fsum", "$fsumsq"):
        # float64 partial states for distributed stddev/variance (fragmenter)
        x = vals_s.astype(jnp.float64)
        if isinstance(arg.type, DecimalType):
            x = x / float(10**arg.type.scale)
        if name == "$fsumsq":
            x = x * x
        data = reduce_fn(x, w, "sum")
        return Column(DOUBLE, data, jnp.ones((out_cap,), dtype=jnp.bool_))
    if name in ("sum", "avg"):
        acc_dtype = jnp.float64 if is_floating(arg.type) else jnp.int64
        data = reduce_fn(vals_s.astype(acc_dtype), w, "sum")
        if name == "avg":
            if isinstance(out_type, DecimalType):
                # decimal avg keeps scale: round-half-up division
                half = nonempty // 2
                denom = jnp.maximum(nonempty, 1)
                data = jnp.where(
                    data >= 0, (data + half) // denom, -((-data + half) // denom)
                )
            else:
                data = data.astype(jnp.float64) / jnp.maximum(nonempty, 1)
                if isinstance(arg.type, DecimalType):
                    data = data / float(10**arg.type.scale)
        return Column(out_type, data.astype(out_type.storage_dtype), nonempty > 0)
    if name in ("min", "max") and vals_s.ndim == 2:
        # Int128 limbs (DECIMAL p>18): per-group extreme of the hi key, then
        # the lo extreme among rows TIED on hi — the min_by broadcast trick
        # (Int128.compareTo semantics, two int64 reduction passes)
        if broadcast_fn is None:
            raise ExecutionError(
                f"{name} over DECIMAL(p>18) needs a group-broadcast strategy"
            )
        from ..ops import int128 as i128

        h, ulo = i128.order_key_pair(vals_s)
        if name == "max":  # order-reversing complement: one code path
            h, ulo = ~h, ~ulo
        sent = jnp.iinfo(jnp.int64).max
        h_ext = reduce_fn(jnp.where(w, h, sent), jnp.ones_like(w), "min")
        tied = w & (h == broadcast_fn(h_ext))
        l_ext = reduce_fn(jnp.where(tied, ulo, sent), jnp.ones_like(w), "min")
        if name == "max":
            h_ext, l_ext = ~h_ext, ~l_ext
        data = i128.make(h_ext, l_ext ^ jnp.int64(jnp.iinfo(jnp.int64).min))
        return Column(out_type, data, nonempty > 0)
    if name in ("min", "max"):
        sent = (
            jnp.iinfo(jnp.int64).max if name == "min" else jnp.iinfo(jnp.int64).min
        )
        if jnp.issubdtype(vals_s.dtype, jnp.floating):
            sentf = jnp.inf if name == "min" else -jnp.inf
            masked = jnp.where(w, vals_s, sentf)
        elif vals_s.dtype == jnp.bool_:
            masked = jnp.where(w, vals_s, name == "min")
        else:
            masked = jnp.where(w, vals_s.astype(jnp.int64), sent)
        data = reduce_fn(masked, jnp.ones_like(w), name)
        return Column(
            out_type, data.astype(out_type.storage_dtype), nonempty > 0, arg.dictionary
        )
    if name in ("bool_and", "every"):
        ws = w & ~vals_s.astype(jnp.bool_)
        anyfalse = reduce_fn(ws.astype(jnp.int64), ws, "count")
        return Column(BOOLEAN, anyfalse == 0, nonempty > 0)
    if name == "bool_or":
        ws = w & vals_s.astype(jnp.bool_)
        anytrue = reduce_fn(ws.astype(jnp.int64), ws, "count")
        return Column(BOOLEAN, anytrue > 0, nonempty > 0)
    if name in ("arbitrary", "any_value"):
        # any participating row of each group
        data = first_fn(vals_s, w)
        return Column(out_type, data, nonempty > 0, arg.dictionary)
    if name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
        x = vals_s.astype(jnp.float64)
        if isinstance(arg.type, DecimalType):
            x = x / float(10**arg.type.scale)
        s1 = reduce_fn(x, w, "sum")
        s2 = reduce_fn(x * x, w, "sum")
        n = jnp.maximum(nonempty, 1).astype(jnp.float64)
        mean = s1 / n
        var_pop = jnp.maximum(s2 / n - mean * mean, 0.0)
        if name in ("var_pop", "stddev_pop"):
            var = var_pop
            valid = nonempty > 0
        else:
            var = var_pop * n / jnp.maximum(n - 1, 1)
            valid = nonempty > 1
        data = jnp.sqrt(var) if name.startswith("stddev") else var
        return Column(DOUBLE, data, valid)
    if name == "approx_distinct" and (hll_fn or distinct_count_fn):
        # HyperLogLog sketch (bounded [G, m] state, one scatter-max) when the
        # register state fits; exact sorted-adjacency count otherwise
        fn = hll_fn if hll_fn is not None else distinct_count_fn
        data = fn(vals_s, w)
        return Column(BIGINT, data, jnp.ones((out_cap,), dtype=jnp.bool_))
    if name in ("tdigest_agg", "qdigest_agg") and tdigest_fn is not None:
        if vals_s.ndim == 2:
            raise ExecutionError(
                "tdigest_agg over DECIMAL(p>18) not supported yet "
                "(cast to DOUBLE or a short decimal)"
            )
        x = vals_s.astype(jnp.float64)
        if isinstance(arg.type, DecimalType):
            x = x / float(10**arg.type.scale)
        data = tdigest_fn(x, w, nonempty)
        return Column(out_type, data, nonempty > 0)
    if name == "approx_percentile" and percentile_fn is not None:
        qcol = rel.column_for(agg.args[1])
        q = qcol.data.astype(jnp.float64)
        if isinstance(qcol.type, DecimalType):
            q = q / float(10**qcol.type.scale)
        # a row participates only if BOTH value and percentile are non-null —
        # the rank count must match the sort's participant mask exactly
        wq = w & qcol.valid
        nq = reduce_fn(wq.astype(jnp.int64), wq, "count")
        q_g = first_fn(q, wq)
        data = percentile_fn(vals_s, wq, q_g, nq)
        return Column(
            out_type, data.astype(out_type.storage_dtype), nq > 0, arg.dictionary
        )
    if name == "array_agg" and array_agg_fn is not None:
        # NULL elements are kept (Trino default); empty groups yield NULL
        data, ev, lengths = array_agg_fn(vals_s, fmask, fmask & valid_s, arg.dictionary)
        return Column(
            out_type, data, lengths > 0, arg.dictionary,
            lengths=lengths, elem_valid=ev,
        )
    if name in ("map_agg", "histogram") and map_lanes_fn is not None:
        from ..spi.types import ArrayType as _At

        # NULL keys are skipped (Trino map_agg/histogram); groups with no
        # non-null key yield NULL (same convention as array_agg above)
        part = w  # fmask & key validity
        if name == "map_agg":
            varg = rel.column_for(agg.args[1])
            kdata, kev, vdata, vev, lengths = map_lanes_fn(
                vals_s, part, varg.data, varg.valid & part, "map_agg"
            )
            vtype, vdict = varg.type, varg.dictionary
        else:
            kdata, kev, vdata, vev, lengths = map_lanes_fn(
                vals_s, part, None, None, "histogram"
            )
            vtype, vdict = BIGINT, None
        karr = Column(
            _At(element=arg.type), kdata, lengths > 0, arg.dictionary,
            lengths=lengths, elem_valid=kev,
        )
        varr = Column(
            _At(element=vtype), vdata, lengths > 0, vdict,
            lengths=lengths, elem_valid=vev,
        )
        return Column(
            out_type, jnp.zeros((out_cap,), dtype=jnp.int8), lengths > 0,
            lengths=lengths, children=(karr, varr),
        )
    if name == "multimap_agg" and array_agg_fn is not None:
        from ..spi.types import ArrayType as _At

        varg = rel.column_for(agg.args[1])
        kdata, kev, lengths = array_agg_fn(vals_s, w, w, arg.dictionary)
        vdata, vev, _ = array_agg_fn(varg.data, w, w & varg.valid, varg.dictionary)
        karr = Column(
            _At(element=arg.type), kdata, lengths > 0, arg.dictionary,
            lengths=lengths, elem_valid=kev,
        )
        varr = Column(
            _At(element=varg.type), vdata, lengths > 0, varg.dictionary,
            lengths=lengths, elem_valid=vev,
        )
        # placeholder carrying raw lanes; aggregate_relation regroups on host
        return Column(
            out_type, jnp.zeros((out_cap,), dtype=jnp.int8), lengths > 0,
            lengths=lengths, children=(karr, varr),
        )
    if name == "listagg" and array_agg_fn is not None:
        from ..spi.types import ArrayType as _At

        # NULL values are skipped (Trino listagg default ON OVERFLOW ERROR
        # semantics aside); host pass joins lanes with the separator
        data, ev, lengths = array_agg_fn(vals_s, w, w, arg.dictionary)
        lanes = Column(
            _At(element=arg.type), data, lengths > 0, arg.dictionary,
            lengths=lengths, elem_valid=ev,
        )
        return Column(
            out_type, jnp.zeros((out_cap,), dtype=jnp.int32), lengths > 0,
            children=(lanes,),
        )
    def _f64(col, weight):
        x = col.data.astype(jnp.float64)
        if isinstance(col.type, DecimalType):
            x = x / float(10**col.type.scale)
        return jnp.where(weight, x, 0.0)

    if name in ("min_by", "max_by") and broadcast_fn is not None:
        # value of arg0 at the row where arg1 is extremal (ref:
        # operator/aggregation/minmaxby/) — reduce the key's order-key, then
        # pick any row matching the group extreme
        kcol = rel.column_for(agg.args[1])
        wk = fmask & kcol.valid
        key = K.encode_sort_column(kcol.data, kcol.valid, True, False)
        key = jnp.where(wk, key, K.INT64_MAX if name == "min_by" else K.INT64_MIN)
        extreme = reduce_fn(key, wk, "min" if name == "min_by" else "max")
        at = wk & (key == broadcast_fn(extreme))
        data = first_fn(vals_s, at)
        valid_out = (reduce_fn(wk.astype(jnp.int64), wk, "count") > 0) & first_fn(
            valid_s, at
        )
        return Column(out_type, data, valid_out, arg.dictionary)
    if name in (
        "corr", "covar_samp", "covar_pop", "regr_slope", "regr_intercept",
        "regr_count", "regr_avgx", "regr_avgy", "regr_sxx", "regr_syy",
        "regr_sxy", "regr_r2",
    ):
        # two-column moments (ref: operator/aggregation/ CorrelationAggregation,
        # CovarianceAggregation, RegressionAggregation): trino argument order
        # is (y, x) with x the independent variable
        xcol = rel.column_for(agg.args[1])
        w2 = fmask & valid_s & xcol.valid
        y = _f64(arg, w2)
        x = _f64(xcol, w2)
        n2 = reduce_fn(w2.astype(jnp.int64), w2, "count")
        n = jnp.maximum(n2, 1).astype(jnp.float64)
        sx = reduce_fn(x, w2, "sum")
        sy = reduce_fn(y, w2, "sum")
        sxy = reduce_fn(x * y, w2, "sum")
        sxx = reduce_fn(x * x, w2, "sum")
        syy = reduce_fn(y * y, w2, "sum")
        cov_pop = sxy / n - (sx / n) * (sy / n)
        varx = jnp.maximum(sxx / n - (sx / n) ** 2, 0.0)
        vary = jnp.maximum(syy / n - (sy / n) ** 2, 0.0)
        if name == "covar_pop":
            data, valid_out = cov_pop, n2 > 0
        elif name == "covar_samp":
            data = cov_pop * n / jnp.maximum(n - 1, 1.0)
            valid_out = n2 > 1
        elif name == "corr":
            denom = jnp.sqrt(varx * vary)
            data = cov_pop / jnp.where(denom > 0, denom, 1.0)
            valid_out = (n2 > 1) & (denom > 0)
        elif name == "regr_slope":
            data = cov_pop / jnp.where(varx > 0, varx, 1.0)
            valid_out = (n2 > 1) & (varx > 0)
        elif name == "regr_intercept":
            slope = cov_pop / jnp.where(varx > 0, varx, 1.0)
            data = sy / n - slope * (sx / n)
            valid_out = (n2 > 1) & (varx > 0)
        elif name == "regr_count":
            return Column(BIGINT, n2, jnp.ones_like(n2, dtype=jnp.bool_))
        elif name == "regr_avgx":
            data, valid_out = sx / n, n2 > 0
        elif name == "regr_avgy":
            data, valid_out = sy / n, n2 > 0
        elif name == "regr_sxx":
            data, valid_out = varx * n, n2 > 0
        elif name == "regr_syy":
            data, valid_out = vary * n, n2 > 0
        elif name == "regr_sxy":
            data, valid_out = cov_pop * n, n2 > 0
        else:  # regr_r2: corr^2; 1.0 when y is constant, NULL when x is
            r2 = jnp.where(
                vary > 0,
                (cov_pop * cov_pop) / jnp.where(
                    varx * vary > 0, varx * vary, 1.0
                ),
                1.0,
            )
            data = r2
            valid_out = (n2 > 0) & (varx > 0)
        return Column(DOUBLE, data, valid_out)
    if name == "entropy":
        # log2 entropy of per-row counts (ref: operator/aggregation/
        # EntropyAggregation): E = log2(S) - sum(c*log2(c)) / S
        c = jnp.maximum(_f64(arg, w), 0.0)
        s = reduce_fn(c, w, "sum")
        clogc = jnp.where(c > 0, c * jnp.log2(jnp.where(c > 0, c, 1.0)), 0.0)
        sl = reduce_fn(clogc, w, "sum")
        pos = s > 0
        data = jnp.where(
            pos, jnp.log2(jnp.where(pos, s, 1.0)) - sl / jnp.where(pos, s, 1.0), 0.0
        )
        return Column(DOUBLE, jnp.maximum(data, 0.0), nonempty > 0)
    if name in ("bitwise_and_agg", "bitwise_or_agg", "bitwise_xor_agg"):
        kind = {"bitwise_and_agg": "band", "bitwise_or_agg": "bor",
                "bitwise_xor_agg": "bxor"}[name]
        data = reduce_fn(vals_s.astype(jnp.int64), w, kind)
        return Column(BIGINT, data, nonempty > 0)
    if name in ("skewness", "kurtosis"):
        # central moments from raw power sums (CentralMomentsAggregation)
        x = _f64(arg, w)
        n2 = nonempty
        n = jnp.maximum(n2, 1).astype(jnp.float64)
        s1 = reduce_fn(x, w, "sum")
        s2 = reduce_fn(x * x, w, "sum")
        s3 = reduce_fn(x * x * x, w, "sum")
        m = s1 / n
        M2 = s2 - s1 * m
        M3 = s3 - 3 * s2 * m + 2 * s1 * m * m
        if name == "skewness":
            denom = jnp.power(jnp.maximum(M2, 1e-300), 1.5)
            data = jnp.sqrt(n) * M3 / denom
            valid_out = (n2 > 2) & (M2 > 0)
        else:
            s4 = reduce_fn(x * x * x * x, w, "sum")
            M4 = s4 - 4 * s3 * m + 6 * s2 * m * m - 3 * s1 * m * m * m
            m2sq = jnp.maximum(M2 * M2, 1e-300)
            data = (n * (n + 1) / jnp.maximum((n - 1) * (n - 2) * (n - 3), 1.0)) * (
                n * M4 / m2sq
            ) - 3 * (n - 1) * (n - 1) / jnp.maximum((n - 2) * (n - 3), 1.0)
            valid_out = (n2 > 3) & (M2 > 0)
        return Column(DOUBLE, data, valid_out)
    if name == "geometric_mean":
        x = _f64(arg, w)
        logs = jnp.where(w, jnp.log(jnp.where(w, x, 1.0)), 0.0)
        s = reduce_fn(logs, w, "sum")
        n = jnp.maximum(nonempty, 1).astype(jnp.float64)
        return Column(DOUBLE, jnp.exp(s / n), nonempty > 0)
    if name == "checksum":
        # order-insensitive content hash: wrapping sum of mixed value bits
        # (ref ChecksumAggregationFunction; BIGINT here, varbinary there)
        v = vals_s
        if arg.dictionary is not None:
            lut = jnp.asarray(arg.dictionary.value_keys())
            v = lut[jnp.clip(v, 0, lut.shape[0] - 1)]
        hashed = K.splitmix64(K.order_key(v))
        hashed = jnp.where(w, hashed, jnp.int64(0x9E3779B9))
        data = reduce_fn(jnp.where(fmask, hashed, 0), fmask, "sum")
        # zero-ROW groups return NULL (ref ChecksumAggregationFunction) —
        # but NULL input rows still update the state (the 0x9E3779B9 term
        # above), so the mask counts fmask rows, not non-null ones
        any_rows = reduce_fn(fmask.astype(jnp.int64), fmask, "count")
        return Column(BIGINT, data, any_rows > 0)
    raise ExecutionError(f"aggregate {name} not implemented")


# --------------------------------------------------------------------------- #
# jitted operator programs (cached per (static plan piece, page layout))
# --------------------------------------------------------------------------- #


def _repeat_column(c: Column, w: int) -> Column:
    return Column(
        c.type,
        jnp.repeat(c.data, w, axis=0),
        jnp.repeat(c.valid, w, axis=0),
        c.dictionary,
        lengths=None if c.lengths is None else jnp.repeat(c.lengths, w, axis=0),
        elem_valid=None if c.elem_valid is None else jnp.repeat(c.elem_valid, w, axis=0),
        children=tuple(_repeat_column(k, w) for k in c.children),
    )


def _flatten_array_col(c: Column, w: int, parent_valid) -> Column:
    """[cap, Wc] array lanes -> [cap*w] element column (pad lanes to w)."""
    wc = c.data.shape[1]
    data = c.data if wc == w else jnp.pad(c.data, ((0, 0), (0, w - wc)))
    ev = c.elem_valid if wc == w else jnp.pad(c.elem_valid, ((0, 0), (0, w - wc)))
    el_t = c.type.element
    return Column(
        el_t,
        data.reshape(-1),
        ev.reshape(-1) & jnp.repeat(parent_valid & c.valid, w),
        c.dictionary,
    )


@partial(kernelcost.jit, static_argnums=(0, 1, 2, 3))
def _jit_unnest(rep_idx, un_idx, w: int, with_ord: bool, page: Page) -> Page:
    from ..spi.types import ArrayType as _At

    cap = page.capacity
    maxlen = jnp.zeros(cap, dtype=jnp.int32)
    for i in un_idx:
        c = page.columns[i]
        lengths = c.lengths if isinstance(c.type, _At) else c.children[0].lengths
        maxlen = jnp.maximum(maxlen, jnp.where(c.valid, lengths, 0))
    lane = jnp.tile(jnp.arange(w, dtype=jnp.int64), cap)
    active = jnp.repeat(page.active, w) & (lane < jnp.repeat(maxlen, w))

    cols: List[Column] = []
    for i in rep_idx:
        cols.append(_repeat_column(page.columns[i], w))
    for i in un_idx:
        c = page.columns[i]
        if isinstance(c.type, _At):
            cols.append(_flatten_array_col(c, w, jnp.ones_like(c.valid)))
        else:  # map -> key, value columns
            keys, vals = c.children
            kc = Column(_At(element=c.type.key), keys.data, c.valid,
                        keys.dictionary, keys.lengths, keys.elem_valid)
            vc = Column(_At(element=c.type.value), vals.data, c.valid,
                        vals.dictionary, vals.lengths, vals.elem_valid)
            cols.append(_flatten_array_col(kc, w, c.valid))
            cols.append(_flatten_array_col(vc, w, c.valid))
    if with_ord:
        cols.append(Column(BIGINT, lane + 1, jnp.ones_like(active)))
    return Page(tuple(cols), active)


@partial(kernelcost.jit, static_argnums=(0,))
def _jit_filter(fn, env: Dict[str, CVal], page: Page) -> Page:
    v = fn(env)
    keep = v.valid & v.data.astype(jnp.bool_)
    return page.mask(keep)


def _project_impl(compiled, env: Dict[str, CVal], page: Page) -> Page:
    cols = []
    for fn, type_, out_dict in compiled:
        v = fn(env)
        dt = type_.storage_dtype
        data = v.data if v.data.dtype == dt else v.data.astype(dt)
        v = CVal(data, v.valid, v.dictionary, v.lengths, v.elem_valid, v.children)
        cols.append(_column_of(type_, v, out_dict))
    return Page(tuple(cols), page.active)


_jit_project = partial(kernelcost.jit, static_argnums=(0,))(_project_impl)


@partial(kernelcost.jit, static_argnums=(0,))
def _jit_join_match(left_outer: bool, pkeys, bkeys, luts, probe_active, build_active):
    """Join phase 1: key normalization + sorted-build matching + emit counts."""
    if not pkeys:  # cross join: all-equal keys
        probe_key = jnp.zeros(probe_active.shape, dtype=jnp.int64)
        build_key = jnp.zeros(build_active.shape, dtype=jnp.int64)
        probe_valid = jnp.ones(probe_active.shape, dtype=jnp.bool_)
        build_valid = jnp.ones(build_active.shape, dtype=jnp.bool_)
    else:
        aligned = []
        for (pd, pv), lut in zip(pkeys, luts):
            if lut is not None:
                mapped = lut[jnp.clip(pd, 0, lut.shape[0] - 1)]
                pd, pv = mapped, pv & (mapped >= 0)
            aligned.append((pd, pv))
        probe_key, probe_valid, build_key, build_valid = K.pack_key_pair(
            aligned, list(bkeys)
        )
    pa = probe_active & probe_valid
    ba = build_active & build_valid
    perm_b, lo, hi, count = K.join_match(build_key, ba, probe_key, pa)
    if left_outer:
        emit = jnp.where(probe_active, jnp.maximum(count, 1), 0)
    else:
        emit = count
    return emit, count, lo, perm_b


@partial(kernelcost.jit, static_argnums=(0,))
def _jit_join_expand(
    out_capacity: int, emit, count, lo, perm_b, probe_page: Page, build_page: Page
) -> Page:
    probe_idx, build_pos, matched, out_active, _ = K.expand_matches(
        emit, count, lo, perm_b, out_capacity
    )
    cols = []
    for c in probe_page.columns:
        cols.append(_permute_column(c, probe_idx))
    for c in build_page.columns:
        pc = _permute_column(c, build_pos)
        cols.append(replace(pc, valid=pc.valid & matched))
    return Page(tuple(cols), out_active)


@partial(kernelcost.jit, static_argnums=(0, 1, 2))
def _jit_left_join_residual(
    residual_fn,
    symbols: Tuple[str, ...],
    out_capacity: int,
    emit,
    count,
    lo,
    perm_b,
    probe_page: Page,
    build_page: Page,
) -> Page:
    """LEFT JOIN with an ON residual: filter the expanded matches, then append
    one null-padded row for every probe row whose matches all failed (including
    rows that never matched — their placeholder also fails the residual)."""
    probe_idx, build_pos, matched, out_active, _ = K.expand_matches(
        emit, count, lo, perm_b, out_capacity
    )
    cols = []
    for c in probe_page.columns:
        cols.append(_permute_column(c, probe_idx))
    for c in build_page.columns:
        pc = _permute_column(c, build_pos)
        cols.append(replace(pc, valid=pc.valid & matched))
    env = {s: _cval_of(c) for s, c in zip(symbols, cols)}
    v = residual_fn(env)
    keep = out_active & matched & v.valid & v.data.astype(jnp.bool_)
    expanded = Page(tuple(cols), keep)

    # surviving matches per probe row (probe capacity is small relative to the
    # expansion; scatter-add over probe_idx)
    pcap = probe_page.capacity
    ids = jnp.where(keep, probe_idx, pcap).astype(jnp.int32)
    survivors = (
        jnp.zeros((pcap + 1,), dtype=jnp.int32).at[ids].add(1, mode="drop")[:pcap]
    )
    tail_active = probe_page.active & (survivors == 0)
    tail_cols = list(probe_page.columns)
    for c in build_page.columns:
        tail_cols.append(_null_column(c, pcap))  # tree_map keeps type/dictionary
    tail = Page(tuple(tail_cols), tail_active)
    return _concat_pages([expanded, tail])


@kernelcost.jit
def _jit_full_join_tail(pkeys, bkeys, luts, probe_page: Page, build_page: Page) -> Page:
    """Unmatched-build-rows segment of a FULL OUTER JOIN: build rows whose key
    has no active probe match, with an all-null probe side."""
    aligned = []
    for (pd, pv), lut in zip(pkeys, luts):
        if lut is not None:
            mapped = lut[jnp.clip(pd, 0, lut.shape[0] - 1)]
            pd, pv = mapped, pv & (mapped >= 0)
        aligned.append((pd, pv))
    probe_key, probe_valid, build_key, build_valid = K.pack_key_pair(
        aligned, list(bkeys)
    )
    matched_b = K.semijoin_mask(
        probe_key,
        probe_page.active & probe_valid,
        build_key,
        build_page.active & build_valid,
    )
    active = build_page.active & ~matched_b
    cap = build_page.capacity
    cols = []
    for c in probe_page.columns:  # null probe side, build-capacity shaped
        cols.append(_null_column(c, cap))
    cols.extend(build_page.columns)
    return Page(tuple(cols), active)


@partial(kernelcost.jit, static_argnums=(5,))
def _jit_semijoin(
    skey: Column, fkey: Column, lut, source_page: Page, filtering_active,
    null_aware: bool = False,
):
    sdata = skey.data
    # match_ok gates matching only; a probe string absent from the filtering
    # dictionary (lut -> -1) is a real value that is simply unmatched, not NULL
    match_ok = skey.valid
    if lut is not None:
        sdata = lut[jnp.clip(sdata, 0, lut.shape[0] - 1)]
        match_ok = match_ok & (sdata >= 0)
    mask = K.semijoin_mask(
        K.order_key(fkey.data),
        filtering_active & fkey.valid,
        K.order_key(sdata),
        source_page.active & match_ok,
    )
    if null_aware:
        # IN 3VL: unmatched is NULL when the probe key is NULL or the filtering
        # side contains NULL; x IN (empty) is FALSE even for NULL x.
        has_any = jnp.any(filtering_active)
        has_null = jnp.any(filtering_active & ~fkey.valid)
        valid = mask | ~has_any | (skey.valid & ~has_null)
    else:
        valid = jnp.ones(source_page.active.shape, dtype=jnp.bool_)
    match_col = Column(BOOLEAN, mask, valid)
    return source_page.append_column(match_col)


def _sort_impl(orderings, symbols, count, page: Page) -> Page:
    rel = Relation(page, symbols)
    keys = []
    for o in orderings:
        c = rel.column_for(o.symbol)
        keys.extend(K.encode_sort_columns(c.data, c.valid, o.ascending, o.nulls_first))
    perm, out_active = K.topn_perm(keys, page.active, count)
    if count is not None:
        # slice the permutation BEFORE gathering: TopN gathers `count` rows
        # per column, not full capacity (gathers cost ~60ns/element on TPU)
        n = min(count, page.capacity)
        perm, out_active = perm[:n], out_active[:n]
    cols = tuple(_permute_column(c, perm) for c in page.columns)
    return Page(cols, out_active)


_jit_sort = partial(kernelcost.jit, static_argnums=(0, 1, 2))(_sort_impl)


@partial(kernelcost.jit, static_argnums=(0, 1, 2, 3))
def _jit_vector_topn(compiled, symbols, orderings, count, env, page: Page) -> Page:
    """The tensor plane's fused scores->top-k program: the scoring
    projection's compiled closures AND the stable top-k permutation in ONE
    device program (ref arXiv:2306.08367 — similarity matmul + selection in
    one launch). Composes the exact serial bodies (_project_impl +
    _sort_impl), so the unfused Project + TopN pair is the bit-identity
    oracle by construction."""
    proj = _project_impl(compiled, env, page)
    return _sort_impl(orderings, symbols, count, proj)


@partial(kernelcost.jit, static_argnums=(0,))
def _jit_vector_topn_lanes(specs, envs, pages):
    """Query-matrix batched vector serving (runtime/device_scheduler.py's
    vector lane tier): the statically-unrolled per-lane fused bodies of a
    whole lane group in ONE device program. Each lane's compiled closures
    close over that lane's OWN query constant — the same trace-time-constant
    environment the serial ``_jit_vector_topn`` folds — and compose the
    exact serial impls, so every lane's output is bit-identical to its own
    serial launch. A runtime ``(n, q)`` stacked query operand is deliberately
    NOT used: XLA constant-folds the constant-query normalization (cosine's
    query norm) differently from the runtime-operand arithmetic in the last
    ulp, which would break the bit-identity contract."""
    out = []
    for (compiled, symbols, orderings, count), env, page in zip(
        specs, envs, pages
    ):
        proj = _project_impl(compiled, env, page)
        out.append(_sort_impl(orderings, symbols, count, proj))
    return tuple(out)


def _result_row_keys(page: Page) -> list:
    """Active rows of a (small, drained) result page as hashable row keys —
    dictionary codes decode to their string values, so pages whose merged
    dictionaries differ (an ANN-pruned read sees fewer splits) still compare
    by content. Host-side; used only by the recall sampler."""
    act = np.asarray(page.active)
    idx = np.nonzero(act)[0]
    cols = []
    for c in page.columns:
        cols.append((np.asarray(c.data), np.asarray(c.valid), c.dictionary))
    keys = []
    for i in idx:
        parts = []
        for data, valid, dic in cols:
            if not valid[i]:
                parts.append(None)
            elif dic is not None:
                parts.append(dic.values[int(data[i])])
            else:
                parts.append(np.asarray(data[i]).tobytes())
        keys.append(tuple(parts))
    return keys


@partial(kernelcost.jit, static_argnums=(0, 1))
def _jit_limit(count: int, offset: int, page: Page) -> Page:
    keep = K.limit_mask(page.active, count, offset)
    return page.mask(keep)


# --------------------------------------------------------------------------- #
# helpers
# --------------------------------------------------------------------------- #


def _round_capacity(n: int, base: int = 1024) -> int:
    """Bucket output capacities to limit recompilation (powers of two)."""
    cap = base
    while cap < n:
        cap *= 2
    return cap


def _translate_lut(from_dict, to_dict):
    """Host LUT translating codes of ``from_dict`` into ``to_dict`` code space
    (exact match; unmatched -> -1, which never equals a real code)."""
    if from_dict is None or to_dict is None or from_dict is to_dict:
        return None
    lut = np.array([to_dict.code_of(s) for s in from_dict.values], dtype=np.int64)
    return jnp.asarray(lut)


def _string_key_luts(node, probe: Relation, build: Relation):
    luts = []
    for l, r in node.criteria:
        pc = probe.column_for(l)
        bc = build.column_for(r)
        luts.append(_translate_lut(pc.dictionary, bc.dictionary))
    return tuple(luts)


def _concat_cols(cols: List[Column], type_: Type) -> Column:
    """Concatenate column chunks: merges differing string dictionaries, pads
    array lanes to the widest W, and recurses into map/row children."""
    from ..spi.types import ArrayType as _At, MapType as _Mt, RowType as _Rt

    dicts = [c.dictionary for c in cols]
    real = [d for d in dicts if d is not None]
    if real and (
        len({id(d) for d in dicts}) > 1 and len({d.fingerprint() for d in real}) > 1
    ):
        merged_values = sorted(set().union(*[list(d.values) for d in real]))
        dictionary = Dictionary(np.asarray(merged_values, dtype=object))
        code_of = {s: c for c, s in enumerate(merged_values)}
        datas = []
        for c in cols:
            if c.dictionary is None:
                # dictionary-less string chunk (e.g. all-NULL branch of a
                # grouping-sets union): codes are meaningless, map to 0
                datas.append(jnp.zeros_like(c.data))
                continue
            lut = np.array([code_of[s] for s in c.dictionary.values], dtype=np.int32)
            datas.append(jnp.asarray(lut)[jnp.clip(c.data, 0, len(lut) - 1)])
    else:
        dictionary = next((d for d in dicts if d is not None), None)
        datas = [c.data for c in cols]
    valids = [c.valid for c in cols]

    if isinstance(type_, _At):
        w = max(d.shape[1] for d in datas)
        datas = [
            d if d.shape[1] == w else jnp.pad(d, ((0, 0), (0, w - d.shape[1])))
            for d in datas
        ]
        evs = [
            c.elem_valid
            if c.elem_valid.shape[1] == w
            else jnp.pad(c.elem_valid, ((0, 0), (0, w - c.elem_valid.shape[1])))
            for c in cols
        ]
        return Column(
            type_, jnp.concatenate(datas), jnp.concatenate(valids), dictionary,
            lengths=jnp.concatenate([c.lengths for c in cols]),
            elem_valid=jnp.concatenate(evs),
        )
    if isinstance(type_, (_Mt, _Rt)):
        kid_types = type_.child_types()
        kids = tuple(
            _concat_cols([c.children[k] for c in cols], kt)
            for k, kt in enumerate(kid_types)
        )
        lengths = (
            None
            if cols[0].lengths is None
            else jnp.concatenate([c.lengths for c in cols])
        )
        return Column(
            type_, jnp.concatenate(datas), jnp.concatenate(valids), None,
            lengths=lengths, children=kids,
        )
    return Column(type_, jnp.concatenate(datas), jnp.concatenate(valids), dictionary)


def _concat_union_pages(pages: List[Page], types: List[Type]) -> Page:
    cols = [
        _concat_cols([p.columns[i] for p in pages], type_)
        for i, type_ in enumerate(types)
    ]
    active = jnp.concatenate([p.active for p in pages])
    return Page(tuple(cols), active)
