"""Hierarchical memory accounting + query memory limits.

Reference blueprint: lib/trino-memory-context (AggregatedMemoryContext /
LocalMemoryContext, SURVEY.md §2.8) and io.trino.memory's per-query limits with
ExceededMemoryLimitException. Device HBM is the scarce resource here; operators
account their output pages and the query fails fast past its limit (spill-to-host
offload replaces failure in a later round — §5.7).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np


class ExceededMemoryLimitError(RuntimeError):
    pass


class LocalMemoryContext:
    """One operator's reservation (ref: LocalMemoryContext.java)."""

    def __init__(self, parent: "AggregatedMemoryContext", tag: str):
        self._parent = parent
        self.tag = tag
        self._bytes = 0

    def set_bytes(self, n: int) -> None:
        delta = n - self._bytes
        self._bytes = n
        self._parent._update(delta, self.tag)

    def get_bytes(self) -> int:
        return self._bytes


class AggregatedMemoryContext:
    """Tree of reservations with a limit at the root (ref:
    AggregatedMemoryContext.java)."""

    def __init__(self, limit_bytes: Optional[int] = None, tag: str = "query"):
        self._limit = limit_bytes
        self.tag = tag
        self._bytes = 0
        self._peak = 0
        self._lock = threading.Lock()

    def new_local(self, tag: str) -> LocalMemoryContext:
        return LocalMemoryContext(self, tag)

    def _update(self, delta: int, tag: str) -> None:
        with self._lock:
            self._bytes += delta
            self._peak = max(self._peak, self._bytes)
            if self._limit is not None and self._bytes > self._limit:
                raise ExceededMemoryLimitError(
                    f"query exceeded memory limit: {self._bytes:,} > "
                    f"{self._limit:,} bytes (while reserving for {tag})"
                )

    @property
    def reserved_bytes(self) -> int:
        return self._bytes

    @property
    def peak_bytes(self) -> int:
        return self._peak


def page_bytes(page) -> int:
    """Device bytes held by a Page (data + validity + active mask)."""
    total = int(np.asarray(page.active.shape[0]))  # active mask (bool)
    for c in page.columns:
        total += c.data.size * c.data.dtype.itemsize
        total += c.valid.size  # bool
    return total
