"""Memory connector: writable in-memory tables (device-resident pages).

Reference blueprint: plugin/trino-memory (MemoryConnector/MemoryMetadata/
MemoryPagesStore — SURVEY.md §2.9 "Benchmark/test connectors"). Tables live as
lists of device Pages; CREATE TABLE AS / INSERT append, scans concatenate.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..spi.connector import (
    ColumnMetadata,
    Connector,
    ConnectorMetadata,
    ConnectorPageSourceProvider,
    ConnectorSplitManager,
    SchemaTableName,
    Split,
    TableHandle,
    TableMetadata,
    TableStatistics,
)
from ..spi.page import Column, Page


@dataclass
class _StoredTable:
    columns: Tuple[ColumnMetadata, ...]
    pages: List[Page] = field(default_factory=list)

    def row_count(self) -> int:
        return sum(int(np.asarray(p.active).sum()) for p in self.pages)


class MemoryConnector(Connector):
    name = "memory"

    def __init__(self):
        self._tables: Dict[SchemaTableName, _StoredTable] = {}
        # reentrant: DML holds mutation_guard() across a read-compute-swap
        # that itself calls the locked replace_pages
        self._lock = threading.RLock()
        self._meta = _MemoryMetadata(self)
        self._splits = _MemorySplitManager(self)
        self._pages = _MemoryPageSourceProvider(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        return self._splits

    def page_source_provider(self):
        return self._pages

    # ------------------------------------------------------------------- DML

    def create_table(self, name: SchemaTableName, columns: Sequence[ColumnMetadata]) -> None:
        with self._lock:
            if name in self._tables:
                raise ValueError(f"table already exists: {name}")
            self._tables[name] = _StoredTable(tuple(columns))

    def drop_table(self, name: SchemaTableName, if_exists: bool = False) -> None:
        with self._lock:
            if name not in self._tables:
                if if_exists:
                    return
                raise ValueError(f"table not found: {name}")
            del self._tables[name]

    def insert(self, name: SchemaTableName, page: Page) -> int:
        """Append a page (the ConnectorPageSink.appendPage analogue)."""
        with self._lock:
            table = self._tables.get(name)
            if table is None:
                raise ValueError(f"table not found: {name}")
            if page.num_columns != len(table.columns):
                raise ValueError(
                    f"column count mismatch: {page.num_columns} vs {len(table.columns)}"
                )
            table.pages.append(page)
            return int(np.asarray(page.active).sum())

    def table(self, name: SchemaTableName) -> Optional[_StoredTable]:
        with self._lock:
            return self._tables.get(name)

    def mutation_guard(self):
        """Hold the table lock across a read-compute-swap so a concurrent
        INSERT can't land between reading ``pages`` and ``replace_pages``
        (rows it appended would be silently discarded)."""
        return self._lock

    def replace_pages(self, name: SchemaTableName, pages: List[Page]) -> None:
        """Swap a table's pages atomically (row-level DELETE/UPDATE/MERGE —
        the ConnectorMergeSink.storeMergedRows analogue for an in-memory
        store)."""
        with self._lock:
            table = self._tables.get(name)
            if table is None:
                raise ValueError(f"table not found: {name}")
            table.pages = list(pages)


class _MemoryMetadata(ConnectorMetadata):
    def __init__(self, connector: MemoryConnector):
        self.connector = connector

    def list_schemas(self):
        return sorted({n.schema for n in self.connector._tables} | {"default"})

    def list_tables(self, schema: Optional[str] = None):
        return sorted(
            (n for n in self.connector._tables if schema is None or n.schema == schema),
            key=str,
        )

    def get_table_metadata(self, name: SchemaTableName) -> Optional[TableMetadata]:
        t = self.connector.table(name)
        if t is None:
            return None
        return TableMetadata(name, t.columns)

    def get_table_statistics(self, handle: TableHandle) -> TableStatistics:
        t = self.connector.table(handle.schema_table)
        return TableStatistics(row_count=float(t.row_count()) if t else 0.0)


class _MemorySplitManager(ConnectorSplitManager):
    def __init__(self, connector: MemoryConnector):
        self.connector = connector

    def get_splits(self, handle: TableHandle, desired_splits: int = 1) -> List[Split]:
        t = self.connector.table(handle.schema_table)
        if t is None or not t.pages:
            return []
        return [Split(handle, i, len(t.pages)) for i in range(len(t.pages))]


class _MemoryPageSourceProvider(ConnectorPageSourceProvider):
    def __init__(self, connector: MemoryConnector):
        self.connector = connector

    def create_page_source(self, split: Split, column_indexes: Sequence[int]) -> Page:
        t = self.connector.table(split.table.schema_table)
        page = t.pages[split.split_id]
        cols = tuple(page.columns[i] for i in column_indexes)
        return Page(cols, page.active)


class BlackHoleConnector(Connector):
    """plugin/trino-blackhole analogue: accepts writes, reads return nothing."""

    name = "blackhole"

    def __init__(self):
        self._schemas: Dict[SchemaTableName, Tuple[ColumnMetadata, ...]] = {}
        self._meta = _BlackHoleMetadata(self)

    def metadata(self):
        return self._meta

    def split_manager(self):
        class _NoSplits(ConnectorSplitManager):
            def get_splits(self, handle, desired_splits=1):
                return []

        return _NoSplits()

    def page_source_provider(self):
        class _NoPages(ConnectorPageSourceProvider):
            def create_page_source(self, split, column_indexes):
                raise RuntimeError("blackhole has no data")

        return _NoPages()

    def create_table(self, name, columns):
        self._schemas[name] = tuple(columns)

    def drop_table(self, name, if_exists=False):
        if name not in self._schemas and not if_exists:
            raise ValueError(f"table not found: {name}")
        self._schemas.pop(name, None)

    def insert(self, name, page) -> int:
        return int(np.asarray(page.active).sum())  # swallowed


class _BlackHoleMetadata(ConnectorMetadata):
    def __init__(self, connector: BlackHoleConnector):
        self.connector = connector

    def list_schemas(self):
        return ["default"]

    def list_tables(self, schema=None):
        return sorted(self.connector._schemas, key=str)

    def get_table_metadata(self, name):
        cols = self.connector._schemas.get(name)
        return TableMetadata(name, cols) if cols else None
